"""Tier-path benchmark: hot device-resident read vs the cold decode path.

The comparison the tier exists for, at the host surface and with no
relay dependency:

* **hot**: the object's shard-major block is tier-resident; a read is
  one D2H of the data rows + the logical transpose
  (``ECBackend._tier_read``'s exact recipe).
* **cold**: the pre-tier miss path -- per-shard ``np.frombuffer``
  ingest of the stored shard bytes (what the messenger reply hands the
  primary), survivors selected with ``erasures`` data shards withheld,
  codec reconstruction, logical reassembly
  (``ecutil.decode_concat``).

Bit-exactness is gated BEFORE timing: both paths must round-trip every
payload byte-identically or the stage raises.  Promotion itself is also
exercised batched (``put_many``: one concatenated device transfer for
the whole object set).  Used by bench.py (``tier_path_host_*`` JSON
fields), ``tools/ec_benchmark.py --workload tier-path`` and the tier-1
smoke gate in tests/test_tier.py.
"""

from __future__ import annotations

import time
from typing import Dict, List

import numpy as np

from ceph_tpu.osd import ecutil
from ceph_tpu.tier.device_tier import (DeviceByteAccount, DeviceTierStore,
                                       reassemble_data_rows)


def run_tier_path_bench(ec, *, n_objects: int = 64,
                        obj_bytes: int = 1 << 16, iters: int = 2,
                        erasures: int = 2, seed: int = 1234) -> dict:
    """Returns the JSON-ready comparison dict; raises on any byte
    mismatch between the two paths."""
    k = ec.get_data_chunk_count()
    km = ec.get_chunk_count()
    m = km - k
    sinfo = ecutil.StripeInfo(k, k * ec.get_chunk_size(1))
    erased = list(range(min(m, erasures)))
    rng = np.random.RandomState(seed)
    payloads: List[bytes] = [
        rng.randint(0, 256, size=obj_bytes, dtype=np.uint8).tobytes()
        for _ in range(n_objects)
    ]

    # -- commit every object: shard store (cold source) + tier items ------
    store: Dict[str, bytes] = {}
    items = []
    for idx, data in enumerate(payloads):
        padded = sinfo.logical_to_next_stripe_offset(len(data))
        buf = np.zeros(padded, dtype=np.uint8)
        buf[: len(data)] = np.frombuffer(data, dtype=np.uint8)
        enc = ecutil.encode(sinfo, ec, buf, range(km))
        for s in range(km):
            store[f"obj{idx}@{s}"] = enc[s].tobytes()
        block = np.stack([np.asarray(enc[s], np.uint8) for s in range(km)])
        items.append(("bench", f"obj{idx}", block, (1, "bench"),
                      len(data)))

    # private ledger: the bench must not charge the process budget the
    # real OSD tiers share (and must never be evicted mid-timing)
    tier = DeviceTierStore(account=DeviceByteAccount(), budget=1 << 62)
    try:
        promoted = tier.put_many(items)
        if promoted != n_objects:
            raise AssertionError(
                f"tier-path: promoted {promoted}/{n_objects}")

        chunk_size = sinfo.chunk_size
        pos = ecutil.data_positions(ec)

        def hot_read(idx: int) -> bytes:
            ent = tier.lookup("bench", f"obj{idx}")
            if pos == list(range(k)):
                rows = np.asarray(ent.block[:k])
            else:
                host = np.asarray(ent.block)
                rows = np.stack([host[p] for p in pos])
            return reassemble_data_rows(rows, chunk_size)[:ent.logical_size]

        def cold_read(idx: int) -> bytes:
            chunks = {
                s: np.frombuffer(store[f"obj{idx}@{s}"], dtype=np.uint8)
                for s in range(km) if s not in erased
            }
            data = ecutil.decode_concat(sinfo, ec, chunks)
            return bytes(data[: len(payloads[idx])])

        # -- bit-exactness gate (untimed) ---------------------------------
        for idx, payload in enumerate(payloads):
            if hot_read(idx) != payload:
                raise AssertionError(f"tier-path: hot read of obj{idx} "
                                     "mismatched the payload")
            if cold_read(idx) != payload:
                raise AssertionError(f"tier-path: cold decode of obj{idx} "
                                     "mismatched the payload")

        nbytes = sum(len(p) for p in payloads)

        def timed(fn) -> float:
            fn(0)  # warm (device slice materialization / decode tables)
            best = None
            for _ in range(max(1, iters)):
                t0 = time.perf_counter()
                for idx in range(n_objects):
                    fn(idx)
                dt = time.perf_counter() - t0
                best = dt if best is None else min(best, dt)
            return nbytes / best / (1 << 30)

        hot = timed(hot_read)
        cold = timed(cold_read)

        return {
            "n_objects": n_objects,
            "obj_bytes": obj_bytes,
            "k": k,
            "m": m,
            "erasures": len(erased),
            "bit_exact": True,  # the gate raised otherwise
            "resident_bytes": tier.resident_bytes,
            "tier_hits": tier.hits,
            "hot_read_GiBs": hot,
            "cold_read_GiBs": cold,
            "read_speedup": round(hot / cold, 3) if cold else None,
        }
    finally:
        tier.clear()
