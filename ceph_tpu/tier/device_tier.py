"""Byte-budgeted device-resident shard store + the process HBM ledger.

The tier keeps hot objects' encoded shards as ONE shard-major device
array per object ([km, shard_len] uint8, row s = shard s), so a read
hit costs a single D2H of the data rows plus the logical transpose --
no per-shard messenger round-trips, no ``np.frombuffer`` ingest, and a
degraded acting set never forces a decode (every position is resident).

Accounting is exact and shared: every device byte the storage layer
retains -- tier entries here, the content-addressed H2D stripe cache in
``ops/pipeline.py`` -- is charged to one :class:`DeviceByteAccount`
ledger bounded by ``osd_tier_hbm_bytes``.  The pipeline cache evicts to
its own sub-allocation (``osd_tier_h2d_cache_bytes``); the tier evicts
to keep the TOTAL under budget, i.e. the tier yields device memory to
the codec's working set, never the other way around.  cephlint's
``jax-device-bytes-unaccounted`` rule keeps retention outside these two
seams from creeping in.

Eviction is LRU + temperature: the coldest (hit-set temperature, then
least-recently-used) CLEAN entries go first; dirty entries (a
write-through put whose fan-out has not committed yet) are never
evicted -- the agent flushes them instead (`TierAgent.tick`).
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np


def _to_device(arr: np.ndarray):
    """One H2D transfer through the counted residency seam; falls back
    to the host array when no jax backend is importable (the tier then
    degrades to a host cache with identical semantics -- tests and
    codec-only tools keep working)."""
    from ceph_tpu.analysis import residency

    return residency.device_put(arr)


class DeviceByteAccount:
    """Ledger of device (HBM) bytes the storage layer holds, partitioned
    by owner ("tier" shard blocks, "h2d" pipeline stripe cache).  The
    total budget is ``osd_tier_hbm_bytes``; consumers charge/release on
    every retention change so the sum is exact, never estimated."""

    def __init__(self):
        self._lock = threading.Lock()
        self._used: Dict[str, int] = {}

    def charge(self, owner: str, nbytes: int) -> None:
        with self._lock:
            self._used[owner] = self._used.get(owner, 0) + int(nbytes)

    def release(self, owner: str, nbytes: int) -> None:
        with self._lock:
            self._used[owner] = max(0, self._used.get(owner, 0) - int(nbytes))

    def used(self, owner: Optional[str] = None) -> int:
        with self._lock:
            if owner is not None:
                return self._used.get(owner, 0)
            return sum(self._used.values())

    @staticmethod
    def budget() -> int:
        """Total device-byte budget (osd_tier_hbm_bytes)."""
        from ceph_tpu.utils.config import get_config

        return int(get_config().get_val("osd_tier_hbm_bytes"))

    @staticmethod
    def h2d_budget() -> int:
        """The pipeline H2D stripe cache's sub-allocation: capped by the
        total budget (a sub-allocation cannot exceed the whole)."""
        from ceph_tpu.utils.config import get_config

        cfg = get_config()
        return min(int(cfg.get_val("osd_tier_h2d_cache_bytes")),
                   int(cfg.get_val("osd_tier_hbm_bytes")))


_account: Optional[DeviceByteAccount] = None
_account_lock = threading.Lock()


def device_byte_account() -> DeviceByteAccount:
    """The process-wide ledger (all OSD shards in one process share the
    one device, so they share the one budget)."""
    global _account
    with _account_lock:
        if _account is None:
            _account = DeviceByteAccount()
        return _account


class TierEntry:
    """One resident object: the shard-major device block + metadata."""

    __slots__ = ("pool", "oid", "block", "version", "logical_size",
                 "dirty", "nbytes", "last_access", "mesh_slice")

    def __init__(self, pool: str, oid: str, block, version: tuple,
                 logical_size: int, dirty: bool, nbytes: int,
                 mesh_slice: Optional[int] = None):
        self.pool = pool
        self.oid = oid
        self.block = block          # device array [km, shard_len] u8
        self.version = version      # (counter, writer) vt tuple
        self.logical_size = logical_size
        self.dirty = dirty
        self.nbytes = nbytes
        self.last_access = 0        # store-sequence LRU stamp
        #: mesh device slot owning this object's PG slice under the
        #: mesh data plane (osd_mesh_data_plane); None single-device.
        #: Keyed so per-slice residency is exact ledger data, not a
        #: re-derivation from placement at read time.
        self.mesh_slice = mesh_slice


class DeviceTierStore:
    """Per-OSD device-resident cache keyed by (pool, oid).

    ``temp_fn(pool, oid) -> float`` supplies hit-set temperature for
    eviction ordering (late-bound so a replaced HitSetTracker is picked
    up); ``budget`` overrides the config-driven global budget (bench
    isolation).  Thread-safe; device transfers happen outside no lock
    longer than necessary.
    """

    OWNER = "tier"

    def __init__(self, perf=None,
                 temp_fn: Optional[Callable[[str, str], float]] = None,
                 account: Optional[DeviceByteAccount] = None,
                 budget: Optional[int] = None):
        self.perf = perf
        self._temp_fn = temp_fn
        self._account = account if account is not None \
            else device_byte_account()
        self._budget = budget
        self._lock = threading.Lock()
        self._entries: "OrderedDict[Tuple[str, str], TierEntry]" = \
            OrderedDict()
        self._seq = 0
        self._resident_bytes = 0
        #: live invalidation-watch sets (the promotion agent's
        #: stale-gather coherence hook, see watch_invalidations)
        self._invalidation_watchers: List[set] = []
        self.hits = 0
        self.misses = 0

    # -- introspection -----------------------------------------------------

    @property
    def resident_bytes(self) -> int:
        return self._resident_bytes

    def budget(self) -> int:
        if self._budget is not None:
            return self._budget
        return self._account.budget()

    def _over_budget(self) -> bool:
        if self._budget is not None:
            return self._resident_bytes > self._budget
        # global invariant: EVERY retained device byte (all tier stores
        # + the pipeline H2D cache) stays under osd_tier_hbm_bytes
        return self._account.used() > self._account.budget()

    def contains(self, pool: Optional[str], oid: str) -> bool:
        with self._lock:
            return (pool, oid) in self._entries

    def status(self) -> dict:
        with self._lock:
            by_slice: Dict[str, int] = {}
            for e in self._entries.values():
                key = "unsliced" if e.mesh_slice is None \
                    else str(e.mesh_slice)
                by_slice[key] = by_slice.get(key, 0) + e.nbytes
            return {
                "resident_bytes": self._resident_bytes,
                "budget": self.budget(),
                "entries": len(self._entries),
                "dirty": sum(1 for e in self._entries.values() if e.dirty),
                "hit": self.hits,
                "miss": self.misses,
                # resident bytes grouped by owning mesh slice (the mesh
                # data plane's PG-slice ownership; "unsliced" =
                # single-device inserts)
                "by_mesh_slice": by_slice,
                "objects": [
                    {"pool": e.pool, "oid": e.oid, "bytes": e.nbytes,
                     "dirty": e.dirty, "version": list(e.version),
                     "mesh_slice": e.mesh_slice}
                    for e in self._entries.values()
                ],
            }

    # -- lookup ------------------------------------------------------------

    def lookup(self, pool: Optional[str], oid: str) -> Optional[TierEntry]:
        """Resident entry or None.  Dirty entries read as misses: their
        bytes are not commit-confirmed yet, and a cache must never serve
        data the shards could still refuse (read-after-ack)."""
        with self._lock:
            ent = self._entries.get((pool, oid))
            if ent is None or ent.dirty:
                self.misses += 1
                if self.perf is not None:
                    self.perf.inc("tier_miss")
                return None
            self._seq += 1
            ent.last_access = self._seq
            self._entries.move_to_end((pool, oid))
            self.hits += 1
        if self.perf is not None:
            self.perf.inc("tier_hit")
        return ent

    # -- insertion / promotion ---------------------------------------------

    def put(self, pool: Optional[str], oid: str, block, version: tuple,
            logical_size: int, dirty: bool = False,
            resident_origin: bool = False,
            promote_from_recovery: bool = False,
            mesh_slice: Optional[int] = None) -> TierEntry:
        """Insert/replace one object's shard-major block (host blocks are
        transferred; device arrays from ``put_many`` slicing are taken
        as-is), then evict to budget.

        ``resident_origin=True`` marks a promote-from-encode insert: the
        block is the encode pipeline's still-device-resident [km, bs]
        output, so this put moves ZERO bytes over the bus (counted
        separately -- ``tier_promote_from_encode`` is the write lane's
        "no re-upload" proof counter).  ``promote_from_recovery=True``
        marks the background plane's promote-on-recovery insert: the
        block was already assembled by the rebuild's fused decode, so
        the promote costs no extra shard reads (counted as
        ``tier_promote_from_recovery``, the recovery lane's twin)."""
        if promote_from_recovery and self.perf is not None:
            self.perf.inc("tier_promote_from_recovery")
        if isinstance(block, np.ndarray):
            block = _to_device(block)
        elif resident_origin and self.perf is not None:
            self.perf.inc("tier_promote_from_encode")
        # timeline attribution: a traced op that paid (or saved) a tier
        # insert on its path shows it as a named event
        from ceph_tpu.utils import trace

        trace.event("tier_put_resident" if resident_origin
                    else "tier_put")
        ent = self._insert(pool, oid, block, version, logical_size, dirty,
                           mesh_slice=mesh_slice)
        self.evict_to_budget()
        return ent

    def recovery_refresh(self, oid: str, version: tuple) -> bool:
        """Coherence check for a same-versioned RECOVERY push: True iff
        every resident copy of ``oid`` already holds ``version`` (then
        their recency is bumped and -- crucially -- NO invalidation is
        noted to the agent's watchers: a recovery push propagates an
        existing version, so an in-flight promotion gather of the
        rebuilt object stays valid; dropping it on every push window
        was the rebuilt-object-goes-cold bug).  Vacuously True with
        nothing resident.  False (a stale copy exists) sends the caller
        down the normal invalidate path."""
        with self._lock:
            ents = [self._entries[k] for k in self._entries
                    if k[1] == oid]
            if any(e.version != tuple(version) for e in ents):
                return False
            for e in ents:
                self._seq += 1
                e.last_access = self._seq
        return True

    def put_many(self, items: List[tuple]) -> int:
        """Batched promotion: ``items`` = [(pool, oid, host_block,
        version, logical_size), ...].  Blocks with the same shard count
        are concatenated along the byte axis and shipped as ONE device
        transfer (the tick's single H2D), then split back into
        per-object device slices."""
        groups: Dict[int, List[tuple]] = {}
        for it in items:
            blk = it[2]
            if blk is None or blk.size == 0:
                continue
            groups.setdefault(blk.shape[0], []).append(it)
        from ceph_tpu.analysis.residency import resident_section
        from ceph_tpu.utils import trace
        from ceph_tpu.utils.perf import stage_histogram

        t0 = time.monotonic()
        n = 0
        for grp in groups.values():
            big = np.concatenate(
                [np.asarray(it[2], dtype=np.uint8) for it in grp], axis=1
            )
            # the promote cut: ONE upload per group, then per-object
            # device slices -- nothing may pull the freshly promoted
            # block back to host between the transfer and the inserts
            # (statically + transfer-guard enforced)
            # cephlint: device-resident-section tier-promote-transfer
            with resident_section("tier-promote-transfer"):
                dev = _to_device(big)
                col = 0
                for pool, oid, blk, version, logical_size in grp:
                    width = blk.shape[1]
                    self._insert(pool, oid, dev[:, col:col + width],
                                 version, logical_size, dirty=False,
                                 promoted=True)
                    col += width
                    n += 1
            # cephlint: end-device-resident-section
        if n:
            # the batched promote is a shared stage too: one histogram
            # observation for the whole transfer (latency x bytes), and
            # an event on whatever span drove the tick
            stage_histogram("tier.promote_usec").inc(
                (time.monotonic() - t0) * 1e6,
                sum(g[2].nbytes for grp in groups.values() for g in grp))
            trace.event("tier_promote_batch")
            self.evict_to_budget()
        return n

    def _insert(self, pool, oid, block, version, logical_size,
                dirty, promoted: bool = False,
                mesh_slice: Optional[int] = None) -> TierEntry:
        nbytes = int(block.shape[0]) * int(block.shape[1])
        with self._lock:
            old = self._entries.pop((pool, oid), None)
            if old is not None:
                self._resident_bytes -= old.nbytes
                self._account.release(self.OWNER, old.nbytes)
            ent = TierEntry(pool, oid, block, tuple(version),
                            logical_size, dirty, nbytes,
                            mesh_slice=mesh_slice)
            self._seq += 1
            ent.last_access = self._seq
            self._entries[(pool, oid)] = ent
            self._resident_bytes += nbytes
            self._account.charge(self.OWNER, nbytes)
            hw = self._resident_bytes
        if self.perf is not None:
            if promoted:
                self.perf.inc("tier_promote_ops")
                self.perf.inc("tier_promote_bytes", nbytes)
            self.perf.hwm("tier_resident_bytes_hwm", hw)
        return ent

    # -- dirty lifecycle ---------------------------------------------------

    def mark_clean(self, pool: Optional[str], oid: str,
                   version: Optional[tuple] = None) -> bool:
        """Commit confirmation for a write-through put; version-checked
        so a racing newer put's state is never mislabeled."""
        with self._lock:
            ent = self._entries.get((pool, oid))
            if ent is None:
                return False
            if version is not None and ent.version != tuple(version):
                return False
            ent.dirty = False
        return True

    def flush_dirty(self) -> int:
        """Drop every dirty entry (the agent's flush): a put left dirty
        past its write's lifetime belongs to a failed/abandoned fan-out,
        and the authoritative bytes live on the shards -- reads fall
        back there.  Returns entries flushed."""
        with self._lock:
            stale = [key for key, e in self._entries.items() if e.dirty]
            for key in stale:
                ent = self._entries.pop(key)
                self._resident_bytes -= ent.nbytes
                self._account.release(self.OWNER, ent.nbytes)
        if stale and self.perf is not None:
            self.perf.inc("tier_flush_ops", len(stale))
        return len(stale)

    # -- invalidation ------------------------------------------------------

    def watch_invalidations(self) -> set:
        """Start collecting invalidated oids into a fresh set (returned;
        stop with :meth:`unwatch`).  The promotion agent's coherence
        hook: its consistent-cut gathers span awaits, and an
        invalidation landing in that window would otherwise no-op (the
        entry is not resident yet) and let ``put_many`` insert a stale
        block right after -- the asyncsan rmw-across-await class at the
        tier layer."""
        watch: set = set()
        self._invalidation_watchers.append(watch)
        return watch

    def unwatch(self, watch: set) -> None:
        try:
            self._invalidation_watchers.remove(watch)
        except ValueError:
            pass

    def _note_invalidated(self, oid: str) -> None:
        for watch in self._invalidation_watchers:
            watch.add(oid)

    def invalidate(self, pool: Optional[str], oid: str) -> bool:
        with self._lock:
            ent = self._entries.pop((pool, oid), None)
            self._note_invalidated(oid)
            if ent is None:
                return False
            self._resident_bytes -= ent.nbytes
            self._account.release(self.OWNER, ent.nbytes)
        if self.perf is not None:
            self.perf.inc("tier_invalidate")
        return True

    def invalidate_oid(self, oid: str,
                       keep_version: Optional[tuple] = None) -> int:
        """Drop ``oid`` across every pool unless the resident version
        matches ``keep_version`` -- the sub-write coherence hook: the
        primary's own write-through put (same versioned write) survives,
        any other applied write proves the copy stale."""
        dropped = 0
        with self._lock:
            # watchers hear about the oid even when nothing is resident
            # (the whole point: an in-flight promotion gather must drop
            # it); a conservative false drop only defers the promotion
            # to the next agent tick
            self._note_invalidated(oid)
            for key in [k for k in self._entries if k[1] == oid]:
                ent = self._entries[key]
                if keep_version is not None and \
                        ent.version == tuple(keep_version):
                    continue
                del self._entries[key]
                self._resident_bytes -= ent.nbytes
                self._account.release(self.OWNER, ent.nbytes)
                dropped += 1
        if dropped and self.perf is not None:
            self.perf.inc("tier_invalidate", dropped)
        return dropped

    # -- eviction ----------------------------------------------------------

    def evict_to_budget(self) -> int:
        """Evict coldest-first until under budget; returns bytes freed.
        Ordering: (hit-set temperature, LRU stamp) ascending -- the
        reference agent's evict_mode ranking reduced to the two signals
        we have.  Dirty entries are skipped (flush owns them)."""
        freed = 0
        evicted = 0
        while self._over_budget():
            with self._lock:
                cands = [(key, ent) for key, ent in self._entries.items()
                         if not ent.dirty]
                if not cands:
                    break
                if self._temp_fn is not None:
                    key, ent = min(
                        cands,
                        key=lambda kv: (self._temp_fn(kv[1].pool,
                                                      kv[1].oid),
                                        kv[1].last_access),
                    )
                else:
                    key, ent = min(cands,
                                   key=lambda kv: kv[1].last_access)
                del self._entries[key]
                self._resident_bytes -= ent.nbytes
                self._account.release(self.OWNER, ent.nbytes)
                freed += ent.nbytes
                evicted += 1
        if evicted and self.perf is not None:
            self.perf.inc("tier_evict_ops", evicted)
            self.perf.inc("tier_evict_bytes", freed)
        return freed

    def clear(self) -> None:
        """Drop everything and settle the ledger (process restart
        semantics: device memory does not survive the daemon, so a
        revived OSD always cold-starts -- tests simulate restarts with
        this, and the ledger must read zero afterwards)."""
        with self._lock:
            for ent in self._entries.values():
                self._account.release(self.OWNER, ent.nbytes)
            self._entries.clear()
            self._resident_bytes = 0


def reassemble_data_rows(data_rows: np.ndarray, chunk_size: int) -> bytes:
    """[k, shard_len] host data rows -> logical bytes (the one transpose
    of the hit path; mirrors ecutil._reassemble without the dict)."""
    k, shard_len = data_rows.shape
    n_stripes = shard_len // chunk_size
    return data_rows.reshape(k, n_stripes, chunk_size).transpose(
        1, 0, 2
    ).tobytes()
