"""Client-side data-path helpers (reference: src/osdc -- Objecter/
Striper/ObjectCacher).  The Objecter's placement+retry role is fused
into ECBackend; Striper lives here."""

from ceph_tpu.osdc.striper import FileLayout, Striper

__all__ = ["FileLayout", "Striper"]
