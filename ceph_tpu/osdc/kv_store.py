"""key_value_store: a sorted KV index over RADOS omap buckets.

Reference: src/key_value_store (KvFlatBtreeAsync, ~4.2k LoC) -- a flat
one-level B-tree: an index object maps each bucket's HIGH key to the
bucket object holding that key range in its omap; buckets split when
they outgrow ``max_per_bucket`` and merge with a neighbor when they
empty.  Reads are two hops (index, then bucket); scans walk buckets in
index order, which keeps enumeration sorted without a global object.

The reference makes split/merge crash-safe with prefixed index markers;
here a rebalance writes the new bucket objects FIRST, then routes the
low half by adding its index key (readers stay consistent at every
step), and finally CAS-flips the old high key -- a lost CAS means a
concurrent rebalance won, and the loser rolls its buckets back.  A
crash mid-split leaves the old (oversized but correct) state.  Bucket
names come from a CAS-allocated sequence persisted in the index, so a
reopened store never reuses a live bucket name.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ceph_tpu.utils.encoding import Decoder, Encoder

#: index omap: key = high key of the bucket ("\xff..." for the last),
#: value = encoded bucket object name
HIGH_LAST = "\xff"


def _enc(v) -> bytes:
    return Encoder().value(v).bytes()


def _dec(b):
    return Decoder(b).value() if b else None


class KvStore:
    SEQ_KEY = "_seq"

    def __init__(self, backend, name: str, max_per_bucket: int = 64):
        self.backend = backend
        self.name = name
        self.max_per_bucket = max_per_bucket

    @property
    def _index(self) -> str:
        return f"kvs.{self.name}.index"

    async def _new_bucket(self) -> str:
        """CAS-allocated bucket name persisted in the index: a reopened
        store must never hand out a LIVE bucket's name (an in-memory
        counter restarting at 0 would merge a future split into a
        foreign bucket, or delete it)."""
        while True:
            cur = await self.backend.omap_get(self._index, [self.SEQ_KEY])
            raw = cur.get(self.SEQ_KEY)
            n = (_dec(raw) or 0) + 1
            ok, _ = await self.backend.omap_cas(
                self._index, self.SEQ_KEY, raw, _enc(n))
            if ok:
                return f"kvs.{self.name}.b{n:08d}"

    async def _index_map(self) -> Dict[str, str]:
        try:
            omap = await self.backend.omap_get(self._index)
        except (FileNotFoundError, IOError):
            omap = {}
        out = {k: _dec(v) for k, v in omap.items()
               if k not in (self.SEQ_KEY, self.LOCK_KEY)}
        if not out:
            b = await self._new_bucket()
            ok, _ = await self.backend.omap_cas(
                self._index, HIGH_LAST, None, _enc(b))
            if not ok:  # racing first writer created the terminal bucket
                omap = await self.backend.omap_get(self._index)
                return {k: _dec(v) for k, v in omap.items()
                        if k not in (self.SEQ_KEY, self.LOCK_KEY)}
            out = {HIGH_LAST: b}
        return out

    def _bucket_for(self, index: Dict[str, str], key: str) -> Tuple[str, str]:
        """(high, bucket) whose range covers ``key``: the smallest high
        key >= key (the B-tree descent)."""
        for high in sorted(index):
            if key <= high or high == HIGH_LAST:
                return high, index[high]
        high = max(index)
        return high, index[high]

    # -- point ops ---------------------------------------------------------
    #
    # Concurrency model (a reduction vs the reference's prefixed index
    # markers, documented): any number of READERS run against live
    # rebalances -- a stale index resolution retries through the fresh
    # index, and writes re-validate their bucket against the index
    # after landing.  Concurrent WRITERS to the same key range are
    # last-writer-wins, like the backend omap they ride on.

    async def _bucket_put(self, bucket: str, key: str,
                          value: bytes) -> None:
        """Per-key CAS write: the backend's plain omap_set is a
        full-state last-writer-wins RMW, so two concurrent writers to
        one bucket would silently clobber each other's keys; omap_cas
        is the backend's atomicity primitive."""
        for _ in range(16):
            cur = (await self.backend.omap_get(bucket, [key])).get(key)
            ok, _c = await self.backend.omap_cas(bucket, key, cur, value)
            if ok:
                return
        raise IOError(f"bucket put contended: {key!r}")

    async def _bucket_rm(self, bucket: str, key: str) -> None:
        for _ in range(16):
            cur = (await self.backend.omap_get(bucket, [key])).get(key)
            if cur is None:
                return
            ok, _c = await self.backend.omap_cas(bucket, key, cur, None)
            if ok:
                return
        raise IOError(f"bucket rm contended: {key!r}")

    async def set(self, key: str, value: bytes) -> None:
        if not key or key >= HIGH_LAST:
            raise ValueError(f"key out of range: {key!r}")
        for _ in range(8):
            index = await self._index_map()
            high, bucket = self._bucket_for(index, key)
            await self._bucket_put(bucket, key, bytes(value))
            # re-validate: a concurrent split may have deleted the
            # bucket between our resolve and the write, destroying it
            fresh = await self._index_map()
            cur_high = next((h for h, b in fresh.items() if b == bucket),
                            None)
            if cur_high is None:
                continue  # bucket rebalanced away: redo via fresh index
            entries = await self.backend.omap_get(bucket)
            if len(entries) > self.max_per_bucket:
                await self._split(fresh, cur_high, bucket, entries)
            return
        raise IOError(f"set {key!r} kept losing to rebalances")

    async def get(self, key: str) -> bytes:
        for attempt in range(2):
            index = await self._index_map()
            _high, bucket = self._bucket_for(index, key)
            try:
                omap = await self.backend.omap_get(bucket, [key])
            except (FileNotFoundError, IOError):
                omap = {}
            if key in omap:
                return omap[key]
            if attempt == 0:
                continue  # maybe a stale index mid-split: re-resolve
        raise KeyError(key)

    async def remove(self, key: str) -> None:
        removed_once = False
        for _ in range(8):
            index = await self._index_map()
            high, bucket = self._bucket_for(index, key)
            omap = await self.backend.omap_get(bucket, [key])
            if key not in omap:
                if removed_once:
                    return  # our removal stuck through the rebalance
                # a rebalance may have moved it mid-resolve: one
                # re-resolve before declaring it missing
                fresh = await self._index_map()
                if self._bucket_for(fresh, key)[1] != bucket:
                    continue
                raise KeyError(key)
            await self._bucket_rm(bucket, key)
            removed_once = True
            fresh = await self._index_map()
            if bucket not in fresh.values():
                continue  # a split may have carried the key: re-check
            if len(fresh) > 1:
                rest = await self.backend.omap_get(bucket)
                if not rest:
                    await self._drop_bucket(fresh, high, bucket)
            return
        raise IOError(f"remove {key!r} kept losing to rebalances")

    # -- scans (sorted by construction) ------------------------------------

    async def items(self, prefix: str = "") -> List[Tuple[str, bytes]]:
        for _ in range(4):
            result = await self._items_once(prefix)
            if result is not None:
                return result
        raise IOError("scan kept losing to rebalances")

    async def _items_once(self, prefix: str):
        """One scan pass; None when a bucket vanished mid-scan (a split
        deleted it after our index read -- its keys live on in the new
        buckets, so the whole enumeration must restart on the fresh
        index rather than silently omit them)."""
        index = await self._index_map()
        out: List[Tuple[str, bytes]] = []
        prev_high = ""
        for high in sorted(index):
            # range pruning: a bucket covers (prev_high, high]; skip
            # buckets entirely below the prefix range, stop once a
            # previous high sorts after every possible "prefix*" key
            if prefix and high != HIGH_LAST and high < prefix:
                prev_high = high
                continue
            if prefix and prev_high > prefix and \
                    not prev_high.startswith(prefix):
                break
            omap = await self.backend.omap_get(index[high])
            if not omap:
                fresh = await self._index_map()
                if index[high] not in fresh.values():
                    return None  # bucket rebalanced away mid-scan
            for k in sorted(omap):
                if k.startswith(prefix):
                    out.append((k, omap[k]))
            prev_high = high
        return out

    async def keys(self, prefix: str = "") -> List[str]:
        return [k for k, _ in await self.items(prefix)]

    # -- rebalance (KvFlatBtreeAsync split / rebalance) --------------------

    LOCK_KEY = "_rebalance_lock"
    LOCK_TTL = 30.0

    async def _try_rebalance_lock(self) -> Optional[bytes]:
        """Opportunistic CAS lock serializing rebalances: two
        concurrent splits of overlapping ranges can strand a landed
        write inside a rolled-back bucket, so only one rebalance runs
        at a time; a loser simply defers (an oversized bucket is
        correct, merely unbalanced -- the next set retries).  A crashed
        holder's lock is stolen after LOCK_TTL."""
        import time as _time

        token = _enc({"t": _time.time()})
        ok, cur = await self.backend.omap_cas(
            self._index, self.LOCK_KEY, None, token)
        if ok:
            return token
        held = _dec(cur) if cur else None
        if held and _time.time() - held.get("t", 0) > self.LOCK_TTL:
            ok, _ = await self.backend.omap_cas(
                self._index, self.LOCK_KEY, cur, token)
            if ok:
                return token
        return None

    async def _unlock_rebalance(self, token: bytes) -> None:
        await self.backend.omap_cas(
            self._index, self.LOCK_KEY, token, None)

    async def _rollback_new_bucket(self, new_bucket: str,
                                   planned: Dict[str, bytes],
                                   old_bucket: str) -> None:
        """Undo an uncommitted split bucket.  A writer may have landed
        in it during its brief index visibility (including a stolen-
        lock race): anything beyond the planned copy is carried back to
        the still-live old bucket before the object goes."""
        try:
            cur = await self.backend.omap_get(new_bucket)
        except (FileNotFoundError, IOError):
            cur = {}
        for k, v in cur.items():
            if planned.get(k) != v:
                await self._bucket_put(old_bucket, k, v)
        await self._delete_bucket_obj(new_bucket)

    async def _delete_bucket_obj(self, bucket: str) -> None:
        await self.backend.omap_clear(bucket)
        try:
            await self.backend.remove_object(bucket)
        except (FileNotFoundError, IOError):
            pass

    async def _split(self, index: Dict[str, str], high: str,
                     bucket: str, entries: Dict[str, bytes]) -> None:
        token = await self._try_rebalance_lock()
        if token is None:
            return  # another rebalance is live: defer (stay oversized)
        try:
            await self._split_locked(index, high, bucket, entries)
        finally:
            await self._unlock_rebalance(token)

    async def _split_locked(self, index: Dict[str, str], high: str,
                            bucket: str,
                            entries: Dict[str, bytes]) -> None:
        ordered = sorted(entries)
        mid = len(ordered) // 2
        low_keys, high_keys = ordered[:mid], ordered[mid:]
        lo_bucket = await self._new_bucket()
        hi_bucket = await self._new_bucket()
        # 1. new buckets first (no reader can see them yet)
        await self.backend.omap_set(
            lo_bucket, {k: entries[k] for k in low_keys})
        await self.backend.omap_set(
            hi_bucket, {k: entries[k] for k in high_keys})
        # 2. route the low half: readers now find low keys in lo_bucket
        #    and everything else still in the (complete) old bucket
        ok, _ = await self.backend.omap_cas(
            self._index, low_keys[-1], None, _enc(lo_bucket))
        if not ok:
            # a concurrent rebalance created this boundary: yield
            await self._rollback_new_bucket(lo_bucket, entries, bucket)
            await self._rollback_new_bucket(hi_bucket, entries, bucket)
            return
        # 3. commit point: CAS the old high key to the new high bucket;
        #    a loser rolls everything back (the old state was correct,
        #    merely oversized)
        ok, _ = await self.backend.omap_cas(
            self._index, high, _enc(bucket), _enc(hi_bucket))
        if not ok:
            await self.backend.omap_cas(
                self._index, low_keys[-1], _enc(lo_bucket), None)
            await self._rollback_new_bucket(lo_bucket, entries, bucket)
            await self._rollback_new_bucket(hi_bucket, entries, bucket)
            return
        # writes that slipped into the OLD bucket between our copy and
        # the commit (and passed their validation against the
        # still-present index entry) must be carried over, not
        # destroyed with the bucket
        late = await self.backend.omap_get(bucket)
        extra = {k: v for k, v in late.items()
                 if entries.get(k) != v}
        for k, v in extra.items():
            dst = lo_bucket if k <= low_keys[-1] else hi_bucket
            await self._bucket_put(dst, k, v)  # CAS: writers may be live
        # late DELETIONS too: a key removed from the old bucket during
        # the window is absent from `late`, but its snapshot copy sits
        # in a new bucket -- without this it silently resurrects
        for k in set(entries) - set(late):
            dst = lo_bucket if k <= low_keys[-1] else hi_bucket
            await self._bucket_rm(dst, k)
        await self._delete_bucket_obj(bucket)

    async def _drop_bucket(self, index: Dict[str, str], high: str,
                           bucket: str) -> None:
        """An emptied bucket merges away: its range folds into the next
        bucket up (or the last bucket absorbs the tail range)."""
        if high == HIGH_LAST:
            return  # the terminal bucket always exists
        token = await self._try_rebalance_lock()
        if token is None:
            return  # defer: an empty bucket is correct, merely wasteful
        try:
            await self._drop_bucket_locked(high, bucket)
        finally:
            await self._unlock_rebalance(token)

    async def _drop_bucket_locked(self, high: str, bucket: str) -> None:
        ok, _ = await self.backend.omap_cas(
            self._index, high, _enc(bucket), None)
        if not ok:
            return  # the range moved under us
        # a write may have slipped in between our emptiness check and
        # the index removal (and validated against the still-present
        # entry): re-check, and restore the range instead of destroying
        # the key -- writes landing after the removal fail their own
        # validation and retry elsewhere
        rest = await self.backend.omap_get(bucket)
        if rest:
            await self.backend.omap_cas(
                self._index, high, None, _enc(bucket))
            return
        await self._delete_bucket_obj(bucket)

    async def stats(self) -> dict:
        index = await self._index_map()
        sizes = {}
        for high in sorted(index):
            omap = await self.backend.omap_get(index[high])
            sizes[index[high]] = len(omap)
        return {"buckets": len(index), "entries": sum(sizes.values()),
                "per_bucket": sizes}
