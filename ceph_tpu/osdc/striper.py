"""RAID-0 style striping of a logical byte space over RADOS objects.

Reference: src/osdc/Striper.cc + ``file_layout_t`` (stripe_unit,
stripe_count, object_size) -- used by librbd, CephFS and libradosstriper
to map file/image extents onto object extents and back.

Layout model (identical to the reference):
  * the byte space is cut into *stripe units* of ``su`` bytes;
  * consecutive units go round-robin across ``stripe_count`` objects of
    the current *object set*;
  * each object holds ``object_size / su`` units per pass; when every
    object of the set is full, the next object set begins.

``object_no = set * stripe_count + (unit % stripe_count)`` and the unit's
offset inside its object advances by ``su`` per pass.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Tuple


@dataclasses.dataclass(frozen=True)
class FileLayout:
    object_size: int = 1 << 22   # 4 MiB
    stripe_unit: int = 1 << 22   # == object_size -> simple striping
    stripe_count: int = 1

    def __post_init__(self):
        if self.object_size % self.stripe_unit != 0:
            raise ValueError("object_size must be a multiple of stripe_unit")
        if self.stripe_count < 1:
            raise ValueError("stripe_count >= 1")


class Striper:
    def __init__(self, layout: FileLayout):
        self.layout = layout

    def map_extent(
        self, offset: int, length: int
    ) -> List[Tuple[int, int, int]]:
        """Logical [offset, offset+length) -> [(object_no, obj_off, len)],
        in logical order (Striper::file_to_extents)."""
        lo = self.layout
        su, sc, osz = lo.stripe_unit, lo.stripe_count, lo.object_size
        units_per_obj = osz // su
        out: List[Tuple[int, int, int]] = []
        pos = offset
        end = offset + length
        while pos < end:
            unit = pos // su
            off_in_unit = pos - unit * su
            take = min(su - off_in_unit, end - pos)
            obj_set, in_set = divmod(unit, sc * units_per_obj)
            pass_no, obj_idx = divmod(in_set, sc)
            object_no = obj_set * sc + obj_idx
            obj_off = pass_no * su + off_in_unit
            out.append((object_no, obj_off, take))
            pos += take
        return out

    def coalesce(
        self, extents: List[Tuple[int, int, int]]
    ) -> Dict[int, List[Tuple[int, int]]]:
        """Group per object and merge adjacent extents
        (Striper::file_to_extents' extent map shape)."""
        by_obj: Dict[int, List[Tuple[int, int]]] = {}
        for object_no, obj_off, length in extents:
            lst = by_obj.setdefault(object_no, [])
            if lst and lst[-1][0] + lst[-1][1] == obj_off:
                lst[-1] = (lst[-1][0], lst[-1][1] + length)
            else:
                lst.append((obj_off, length))
        return by_obj

    def object_count(self, total_size: int) -> int:
        """How many objects a byte space of total_size can touch.

        With stripe_count > 1 the last *byte* does not land in the last
        *object* (units go round-robin), so this counts analytically:
        full object sets contribute stripe_count objects each; a partial
        set touches one object per leading unit, capped at stripe_count.
        """
        if total_size == 0:
            return 0
        lo = self.layout
        units = (total_size + lo.stripe_unit - 1) // lo.stripe_unit
        units_per_set = lo.stripe_count * (lo.object_size // lo.stripe_unit)
        full_sets, rem = divmod(units, units_per_set)
        return full_sets * lo.stripe_count + min(rem, lo.stripe_count)
