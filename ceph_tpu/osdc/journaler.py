"""Journaler: the append-journal client library.

Reference: src/osdc/Journaler.{h,cc} (CephFS MDLog's transport) and
src/journal (librbd journaling) -- a logical byte/entry stream striped
over numbered RADOS objects with four persisted pointers kept in a
header object: write_pos, expire_pos (trim), and the reader's committed
position.  Writers append framed entries; readers replay from the
committed position; trim drops whole journal objects behind expire_pos.

Layout: header omap on ``<name>.journal`` {write_pos, expire_pos,
commit_pos}; entry data appended to ``<name>.journal.<objno>`` objects
of ``object_size`` bytes.  Entries are crc-framed with the shared
encoding framework, so a torn tail (partial append at crash) is
detected and replay stops cleanly at it -- the same guarantee the
reference gets from its entry headers.

Named clients: src/journal's JournalMetadata keeps a registry of
clients (the image itself plus mirror peers), each with its own commit
position; trim may only advance past what EVERY client has consumed
(src/journal/JournalMetadata.cc client_s / committed()).  Here clients
live in the same header omap under ``client.<id>`` keys and
``trim()`` takes the minimum over the master commit position and all
registered clients.
"""

from __future__ import annotations

import asyncio
from typing import List, Optional, Tuple

from ceph_tpu.utils.encoding import Decoder, Encoder, frame, unframe


def _enc(v) -> bytes:
    return Encoder().value(v).bytes()


def _dec(b):
    return Decoder(b).value() if b else None


class Journaler:
    def __init__(self, backend, name: str, object_size: int = 1 << 22):
        self.backend = backend
        self.name = name
        self.object_size = object_size
        self.write_pos = 0
        self.expire_pos = 0
        self.commit_pos = 0
        #: serializes append(): two concurrent appenders would read the
        #: same write_pos, stripe both records over the same extent and
        #: lose one (asyncsan rmw-across-await; the reference Journaler
        #: serializes appends on its lock too)
        self._append_lock = asyncio.Lock()

    @property
    def _header(self) -> str:
        return f"{self.name}.journal"

    def _data(self, objno: int) -> str:
        return f"{self.name}.journal.{objno:08x}"

    # -- header ------------------------------------------------------------

    async def create(self) -> None:
        await self.backend.omap_set(self._header, {
            "write_pos": _enc(0), "expire_pos": _enc(0),
            "commit_pos": _enc(0),
        })

    async def open(self) -> None:
        omap = await self.backend.omap_get(self._header)
        if "write_pos" not in omap:
            await self.create()
            return
        self.write_pos = _dec(omap["write_pos"])
        self.expire_pos = _dec(omap["expire_pos"])
        self.commit_pos = _dec(omap["commit_pos"])

    # -- append (Journaler::append_entry + flush) --------------------------

    async def append(self, entry) -> int:
        """Append one entry (any encodable value); returns its start
        position.  The entry never splits an object boundary mid-frame
        the hard way: a frame that would cross pads to the next object
        (the reference pads with a skip entry at object boundaries)."""
        rec = frame(_enc(entry))
        osz = self.object_size
        async with self._append_lock:
            start = self.write_pos
            if start // osz != (start + len(rec) - 1) // osz:
                start = ((start // osz) + 1) * osz  # next object
            objno, off = divmod(start, osz)
            await self.backend.write_range(self._data(objno), off, rec)
            self.write_pos = start + len(rec)
            # persist only the field this writer owns: the header is
            # shared with committers and trimmers (e.g. a mirror
            # daemon) whose in-memory copies of the OTHER pointers may
            # be stale
            await self.backend.omap_set(
                self._header, {"write_pos": _enc(self.write_pos)})
        return start

    # -- replay (Journaler::read_entry loop) -------------------------------

    async def replay(self, from_pos: Optional[int] = None
                     ) -> List[Tuple[int, object]]:
        """Entries from ``from_pos`` (default: commit_pos) to the write
        head; a torn tail (crashed writer) ends replay cleanly."""
        return [(start, entry) for start, _end, entry in
                await self.replay_entries(from_pos)]

    async def replay_entries(self, from_pos: Optional[int] = None
                             ) -> List[Tuple[int, int, object]]:
        """Like replay but yields (start, end, entry) -- consumers that
        track their own commit position (mirror peers) need the end
        offset of each entry to advance past it."""
        pos = self.commit_pos if from_pos is None else from_pos
        pos = max(pos, self.expire_pos)
        out: List[Tuple[int, int, object]] = []
        osz = self.object_size
        cached_objno, blob = None, b""
        while pos < self.write_pos:
            objno, off = divmod(pos, osz)
            if objno != cached_objno:
                try:
                    blob = await self.backend.read(self._data(objno))
                except IOError:
                    break  # trimmed/missing object
                cached_objno = objno
            rec, newoff = unframe(bytes(blob), off)
            if rec is None:
                # torn or padded tail: skip to the next object if the
                # writer did, else stop (crash tail)
                next_obj = (objno + 1) * osz
                if next_obj < self.write_pos:
                    pos = next_obj
                    continue
                break
            end = objno * osz + newoff
            out.append((pos, end, _dec(rec)))
            pos = end
        return out

    # -- client registry (src/journal JournalMetadata clients) -------------

    async def register_client(self, client_id: str,
                              pos: Optional[int] = None) -> int:
        """Register a named consumer (e.g. a mirror peer) at ``pos``
        (default: the current write head).  Idempotent: re-registering
        returns the existing position."""
        key = f"client.{client_id}"
        omap = await self.backend.omap_get(self._header)
        if key in omap:
            return _dec(omap[key])
        start = self.write_pos if pos is None else pos
        await self.backend.omap_set(self._header, {key: _enc(start)})
        return start

    async def unregister_client(self, client_id: str) -> None:
        await self.backend.omap_rm(self._header, [f"client.{client_id}"])

    async def client_pos(self, client_id: str) -> Optional[int]:
        omap = await self.backend.omap_get(self._header)
        raw = omap.get(f"client.{client_id}")
        return None if raw is None else _dec(raw)

    async def clients(self) -> dict:
        omap = await self.backend.omap_get(self._header)
        return {k[len("client."):]: _dec(v) for k, v in omap.items()
                if k.startswith("client.")}

    # -- commit / trim (Journaler::set_expire_pos + trim) ------------------

    async def committed(self, pos: int,
                        client: Optional[str] = None) -> None:
        """The reader durably applied everything below ``pos``.  With
        ``client`` set, advances that registered client's position
        instead of the master commit pointer."""
        if client is not None:
            cur = await self.client_pos(client)
            if cur is None or pos > cur:
                await self.backend.omap_set(
                    self._header, {f"client.{client}": _enc(pos)})
            return
        self.commit_pos = max(self.commit_pos, pos)
        await self.backend.omap_set(
            self._header, {"commit_pos": _enc(self.commit_pos)})

    async def trim(self) -> int:
        """Drop whole journal objects below the commit position
        (expire); returns objects removed.  A lagging registered client
        pins the journal: trim never passes the slowest consumer.

        Re-reads the header first and writes back only expire_pos:
        trimmers (a mirror daemon tick) share the header with the live
        appender, and persisting stale write/commit pointers here would
        roll back committed appends."""
        await self.open()
        osz = self.object_size
        floor = min([self.commit_pos]
                    + list((await self.clients()).values()))
        target = (floor // osz) * osz
        removed = 0
        for objno in range(self.expire_pos // osz, target // osz):
            try:
                await self.backend.remove_object(self._data(objno))
                removed += 1
            except IOError:
                pass
        if target > self.expire_pos:
            self.expire_pos = target
            await self.backend.omap_set(
                self._header, {"expire_pos": _enc(target)})
        return removed
