"""ObjectCacher: client-side object data cache.

Reference: src/osdc/ObjectCacher.{h,cc} -- the buffer cache librbd and
the CephFS client put in front of the Objecter: reads fill
BufferHead-style extents, repeated reads hit memory, writes either
write-through (update cache + RADOS synchronously) or write-back (dirty
extents flushed later); total size is bounded with LRU eviction and
``flush``/``invalidate`` give the consistency hooks (librbd invalidates
on image refresh, the fs client on cap revoke).

The cache is per-object at extent granularity: each object holds a
sorted list of clean/dirty byte extents; reads coalesce hits and fetch
only the holes.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Dict, List, Optional, Tuple


class _Extent:
    __slots__ = ("off", "data", "dirty")

    def __init__(self, off: int, data: bytearray, dirty: bool):
        self.off = off
        self.data = data
        self.dirty = dirty

    @property
    def end(self) -> int:
        return self.off + len(self.data)


class ObjectCacher:
    def __init__(self, backend, max_bytes: int = 32 << 20,
                 write_back: bool = False):
        self.backend = backend
        self.max_bytes = max_bytes
        self.write_back = write_back
        #: oid -> sorted extents; OrderedDict is the LRU (move_to_end on
        #: touch, evict from the front)
        self._objects: "OrderedDict[str, List[_Extent]]" = OrderedDict()
        self._bytes = 0
        self.hits = 0
        self.misses = 0

    # -- internals ---------------------------------------------------------

    def _touch(self, oid: str) -> List[_Extent]:
        exts = self._objects.setdefault(oid, [])
        self._objects.move_to_end(oid)
        return exts

    def _account(self, delta: int) -> None:
        self._bytes += delta

    async def _evict_to_fit(self) -> None:
        while self._bytes > self.max_bytes and self._objects:
            oid, exts = next(iter(self._objects.items()))
            if any(e.dirty for e in exts):
                await self._flush_object(oid, exts)
            self._account(-sum(len(e.data) for e in exts))
            del self._objects[oid]

    def _insert(self, exts: List[_Extent], off: int, data: bytes,
                dirty: bool) -> None:
        """Merge [off, off+len) into the extent list (new data wins).

        Clean and dirty extents never merge with each other (the
        reference keeps separate clean/dirty BufferHeads): folding a
        clean neighbour into a dirty write would make flush write back
        bytes the client never modified -- write amplification, and a
        lost-update hazard for a shared image."""
        new = _Extent(off, bytearray(data), dirty)
        out: List[_Extent] = []
        self._account(len(data))
        for e in exts:
            if e.dirty == new.dirty:
                if e.end < new.off or e.off > new.end:
                    out.append(e)
                    continue
                # same state, overlap/adjacent: merge (new bytes win)
                if e.off < new.off:
                    head = e.data[: new.off - e.off]
                    merged = _Extent(e.off, bytearray(head) + new.data,
                                     new.dirty)
                    self._account(len(merged.data) - len(new.data))
                    new = merged
                if e.end > new.end:
                    tail = e.data[new.end - e.off:]
                    self._account(len(tail))
                    new.data.extend(tail)
                self._account(-len(e.data))
                continue
            # different clean/dirty state: never merge; trim the old
            # extent around the new bytes (new data wins the overlap)
            if e.end <= new.off or e.off >= new.end:
                out.append(e)
                continue
            if e.off < new.off:
                head = e.data[: new.off - e.off]
                out.append(_Extent(e.off, bytearray(head), e.dirty))
                self._account(len(head))
            if e.end > new.end:
                tail = e.data[new.end - e.off:]
                out.append(_Extent(new.end, bytearray(tail), e.dirty))
                self._account(len(tail))
            self._account(-len(e.data))
        out.append(new)
        out.sort(key=lambda e: e.off)
        exts[:] = out

    # -- read path (ObjectCacher::readx) -----------------------------------

    async def read(self, oid: str, off: int, length: int) -> bytes:
        exts = self._touch(oid)
        out = bytearray(length)
        pos = off
        end = off + length
        holes: List[Tuple[int, int]] = []
        for e in sorted(exts, key=lambda e: e.off):
            if e.end <= pos or e.off >= end:
                continue
            if e.off > pos:
                holes.append((pos, e.off - pos))
            lo, hi = max(pos, e.off), min(end, e.end)
            out[lo - off:hi - off] = e.data[lo - e.off:hi - e.off]
            self.hits += 1
            pos = hi
        if pos < end:
            holes.append((pos, end - pos))
        for h_off, h_len in holes:
            self.misses += 1
            data = await self.backend.read_range(oid, h_off, h_len)
            data = data.ljust(h_len, b"\0")  # short read: zeros
            out[h_off - off:h_off - off + h_len] = data
            self._insert(exts, h_off, data, dirty=False)
        await self._evict_to_fit()
        return bytes(out)

    # -- write path (writex: write-through or write-back) ------------------

    async def write(self, oid: str, off: int, data: bytes) -> None:
        exts = self._touch(oid)
        self._insert(exts, off, data, dirty=self.write_back)
        if not self.write_back:
            await self.backend.write_range(oid, off, data)
        await self._evict_to_fit()

    # -- consistency hooks -------------------------------------------------

    async def _flush_object(self, oid: str, exts: List[_Extent]) -> None:
        for e in exts:
            if e.dirty:
                await self.backend.write_range(oid, e.off, bytes(e.data))
                e.dirty = False

    async def flush(self, oid: Optional[str] = None) -> None:
        """Write every dirty extent back (ObjectCacher::flush_set)."""
        targets = [oid] if oid is not None else list(self._objects)
        for o in targets:
            exts = self._objects.get(o)
            if exts:
                await self._flush_object(o, exts)

    async def invalidate(self, oid: Optional[str] = None) -> None:
        """Drop cached extents (dirty ones are flushed first -- the
        librbd invalidate-on-refresh contract)."""
        await self.flush(oid)
        targets = [oid] if oid is not None else list(self._objects)
        for o in targets:
            exts = self._objects.pop(o, None)
            if exts:
                self._account(-sum(len(e.data) for e in exts))

    @property
    def cached_bytes(self) -> int:
        return self._bytes
