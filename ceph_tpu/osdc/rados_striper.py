"""libradosstriper: striped "files" over plain RADOS objects.

Reference: src/libradosstriper (2.8k LoC) -- a thin client library that
presents one logical byte range striped over ``<soid>.%016x`` objects.
The first object carries the authoritative metadata as xattrs
(striper.layout / striper.size in the reference; omap keys here, the
framework's xattr plane), guarded by a shared lock so concurrent
writers agree on the layout (RadosStriperImpl::createAndSetXattrs).

Surface mirrors the reference's C/C++ API: write (positional),
write_full, read, stat, truncate, remove, get/set xattr passthrough.
A writer extending the file updates the size metadata with CAS
semantics via omap so racing appends keep the max.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from ceph_tpu.osdc.striper import FileLayout, Striper
from ceph_tpu.utils.encoding import Decoder, Encoder


def _enc(v) -> bytes:
    return Encoder().value(v).bytes()


def _dec(b):
    return Decoder(b).value() if b else None


class RadosStriper:
    """One striper handle per pool backend (RadosStriperImpl)."""

    def __init__(self, backend,
                 object_size: int = 1 << 22,
                 stripe_unit: int = 1 << 19,
                 stripe_count: int = 4):
        if object_size % stripe_unit:
            raise ValueError("object_size must be a stripe_unit multiple")
        self.backend = backend
        self.default_layout = FileLayout(
            object_size=object_size, stripe_unit=stripe_unit,
            stripe_count=stripe_count)

    @staticmethod
    def _obj(soid: str, object_no: int) -> str:
        # the reference names stripe objects "<soid>.%016x"
        return f"{soid}.{object_no:016x}"

    def _meta_oid(self, soid: str) -> str:
        return self._obj(soid, 0)

    # -- metadata ----------------------------------------------------------

    async def _load_meta(self, soid: str
                         ) -> Optional[Tuple[Striper, int]]:
        omap = await self.backend.omap_get(self._meta_oid(soid))
        raw = omap.get("striper.layout")
        if raw is None:
            return None
        lo = _dec(raw)
        layout = FileLayout(object_size=lo["object_size"],
                            stripe_unit=lo["stripe_unit"],
                            stripe_count=lo["stripe_count"])
        size = _dec(omap.get("striper.size")) or 0
        return Striper(layout), size

    _DIR_OID = "striper_directory"

    async def _ensure_meta(self, soid: str) -> Tuple[Striper, int]:
        meta = await self._load_meta(soid)
        if meta is not None:
            return meta
        lo = self.default_layout
        # create-exclusive CAS: racing first writers with different
        # default layouts must all end up striping under ONE layout
        # (the reference guards layout creation with its shared lock)
        ok, _cur = await self.backend.omap_cas(
            self._meta_oid(soid), "striper.layout", None,
            _enc({
                "object_size": lo.object_size,
                "stripe_unit": lo.stripe_unit,
                "stripe_count": lo.stripe_count,
            }))
        if not ok:
            meta = await self._load_meta(soid)
            if meta is not None:
                return meta  # the winner's layout governs
        # CAS size init too: a racing writer may already have grown it
        await self.backend.omap_cas(
            self._meta_oid(soid), "striper.size", None, _enc(0))
        await self.backend.omap_set(self._DIR_OID, {f"soid_{soid}": b"1"})
        return Striper(lo), 0

    async def _cas_max(self, soid: str, key: str, new_val: int) -> None:
        """CAS-retry a monotonically-growing integer omap field (the
        reference updates these xattrs under its shared lock; a plain
        read-check-write would let a smaller racing write persist a
        smaller value and logically truncate the file)."""
        for _ in range(16):
            raw = (await self.backend.omap_get(
                self._meta_oid(soid))).get(key)
            if (_dec(raw) or 0) >= new_val:
                return
            ok, _cur = await self.backend.omap_cas(
                self._meta_oid(soid), key, raw, _enc(new_val))
            if ok:
                return
        raise IOError(f"{key} update contended on {soid}")

    async def _grow_size(self, soid: str, new_size: int) -> None:
        await self._cas_max(soid, "striper.size", new_size)
        # maxsize never shrinks (truncate only zeroes): remove() uses it
        # to find every stripe object ever written
        await self._cas_max(soid, "striper.maxsize", new_size)

    # -- I/O ---------------------------------------------------------------

    async def write(self, soid: str, data: bytes, offset: int = 0) -> None:
        striper, _size = await self._ensure_meta(soid)
        pos = 0
        for object_no, obj_off, length in striper.map_extent(
                offset, len(data)):
            await self.backend.write_range(
                self._obj(soid, object_no), obj_off,
                data[pos:pos + length])
            pos += length
        await self._grow_size(soid, offset + len(data))

    async def write_full(self, soid: str, data: bytes) -> None:
        await self.remove(soid, missing_ok=True)
        await self.write(soid, data, 0)

    async def append(self, soid: str, data: bytes) -> None:
        _striper, size = await self._ensure_meta(soid)
        await self.write(soid, data, size)

    async def read(self, soid: str, length: Optional[int] = None,
                   offset: int = 0) -> bytes:
        meta = await self._load_meta(soid)
        if meta is None:
            raise FileNotFoundError(soid)
        striper, size = meta
        length = size - offset if length is None else \
            min(length, size - offset)
        if length <= 0:
            return b""
        out = bytearray(length)
        pos = 0
        for object_no, obj_off, take in striper.map_extent(offset, length):
            try:
                piece = await self.backend.read_range(
                    self._obj(soid, object_no), obj_off, take)
            except FileNotFoundError:
                piece = b""  # sparse stripe object reads as zeros
            # other IOErrors (e.g. degraded below k shards) propagate:
            # returning zeros there would hand the caller silent
            # corruption instead of an EIO
            out[pos:pos + len(piece)] = piece
            pos += take
        return bytes(out)

    async def stat(self, soid: str) -> int:
        meta = await self._load_meta(soid)
        if meta is None:
            raise FileNotFoundError(soid)
        return meta[1]

    async def truncate(self, soid: str, new_size: int) -> None:
        """Shrink (or sparse-extend) the logical file; whole stripe
        objects past the end are removed and the boundary object's tail
        zeroed, the reference's truncate behavior."""
        meta = await self._load_meta(soid)
        if meta is None:
            raise FileNotFoundError(soid)
        striper, size = meta
        if new_size < size:
            # zero the [new_size, size) range so a later regrow reads
            # zeros; removing whole objects needs per-object span math
            # (round-robin striping puts later bytes in EVERY object),
            # so zeroing is the simple correct form
            span = size - new_size
            zero = bytes(min(span, 1 << 20))
            off = new_size
            while off < size:
                chunk = min(len(zero), size - off)
                pos = 0
                for object_no, obj_off, length in striper.map_extent(
                        off, chunk):
                    await self.backend.write_range(
                        self._obj(soid, object_no), obj_off,
                        zero[pos:pos + length])
                    pos += length
                off += chunk
        await self.backend.omap_set(
            self._meta_oid(soid), {"striper.size": _enc(new_size)})

    async def remove(self, soid: str, missing_ok: bool = False) -> None:
        meta = await self._load_meta(soid)
        if meta is None:
            if missing_ok:
                return
            raise FileNotFoundError(soid)
        striper, size = meta
        # delete by the historical high-water size: a truncate-shrink
        # leaves whole stripe objects in place (it only zeroes), and
        # sizing by the current length would leak them forever
        maxsize = _dec((await self.backend.omap_get(
            self._meta_oid(soid))).get("striper.maxsize")) or size
        n_objects = max(1, striper.object_count(max(size, maxsize)))
        for object_no in range(n_objects):
            try:
                await self.backend.remove_object(self._obj(soid, object_no))
            except (FileNotFoundError, IOError):
                pass
        await self.backend.omap_rm(
            self._meta_oid(soid),
            ["striper.layout", "striper.size", "striper.maxsize"])
        await self.backend.omap_rm(self._DIR_OID, [f"soid_{soid}"])

    async def list_striped(self) -> List[str]:
        """Logical names present (directory-object index)."""
        try:
            omap = await self.backend.omap_get(self._DIR_OID)
        except (FileNotFoundError, IOError):
            return []
        return sorted(k[len("soid_"):] for k in omap
                      if k.startswith("soid_"))
