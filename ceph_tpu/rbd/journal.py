"""RBD image journaling (reference: src/librbd/Journal.cc over
src/journal).

With the ``journaling`` feature enabled, every mutating image op is
recorded as a typed event in a per-image journal (``rbd_journal.<name>``
striped over RADOS objects via the shared Journaler) BEFORE it is
applied to the image, and the master commit position advances only
after the data path accepted it.  Two consumers read this stream:

- crash replay: ``Image.open`` re-applies any events between the commit
  position and the write head (the reference's librbd::Journal replay
  on open when the journal is not clean);
- rbd-mirror: a peer registered as a named journal client tails the
  stream into a remote image (``ceph_tpu.rbd.mirror``) and its commit
  position pins trim, exactly like the reference's mirror-peer client
  in src/journal/JournalMetadata.

Events mirror librbd::journal::EventType (AioWriteEvent, ResizeEvent,
SnapCreateEvent, SnapRemoveEvent, SnapRollbackEvent, FlattenEvent).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from ceph_tpu.osdc.journaler import Journaler

FEATURE_JOURNALING = "journaling"
MASTER_CLIENT = ""  # the image's own replay uses the master commit_pos

# pool-level mirroring directory (lives here, not in mirror.py, so the
# image layer can refuse feature changes that would break mirroring
# without a circular import)
MIRROR_DIR_OID = "rbd_mirroring"


def journal_name(image: str) -> str:
    return f"rbd_journal.{image}"


async def destroy_journal(backend, image: str) -> None:
    """Remove an image's journal: every data object plus the header
    (reference: librbd::Journal::remove on feature disable / image
    removal)."""
    j = Journaler(backend, journal_name(image))
    await j.open()
    osz = j.object_size
    for objno in range(j.expire_pos // osz, j.write_pos // osz + 1):
        try:
            await backend.remove_object(j._data(objno))
        except (FileNotFoundError, IOError):
            pass
    try:
        await backend.omap_clear(j._header)  # pointers live in omap
        await backend.remove_object(j._header)
    except (FileNotFoundError, IOError):
        pass


class ImageJournal:
    """Typed-event wrapper over a Journaler for one image."""

    def __init__(self, backend, image: str, object_size: int = 1 << 20):
        self.j = Journaler(backend, journal_name(image),
                           object_size=object_size)

    async def open(self) -> None:
        await self.j.open()

    # -- append (librbd::Journal::append_io_event / append_op_event) ------

    async def append(self, event: dict) -> Tuple[int, int]:
        """Append one event; returns (start, end) stream positions."""
        start = await self.j.append(event)
        return start, self.j.write_pos

    async def commit(self, end_pos: int) -> None:
        await self.j.committed(end_pos)

    # -- replay -----------------------------------------------------------

    async def uncommitted(self) -> List[Tuple[int, int, dict]]:
        """Events appended but not yet committed (crash tail)."""
        return await self.j.replay_entries()

    # -- mirror-peer client registry --------------------------------------

    async def register_peer(self, peer_id: str,
                            pos: Optional[int] = None) -> int:
        return await self.j.register_client(peer_id, pos)

    async def unregister_peer(self, peer_id: str) -> None:
        await self.j.unregister_client(peer_id)

    async def peer_entries(self, peer_id: str
                           ) -> List[Tuple[int, int, dict]]:
        """Pending entries for a REGISTERED peer; an unknown peer gets
        nothing (registration is bootstrap's job -- auto-registering
        here would both skip bootstrap and pin trim at 0)."""
        pos = await self.j.client_pos(peer_id)
        if pos is None or pos >= self.j.write_pos:
            return []
        return await self.j.replay_entries(pos)

    async def peer_committed(self, peer_id: str, end_pos: int) -> None:
        await self.j.committed(end_pos, client=peer_id)

    async def trim(self) -> int:
        return await self.j.trim()


async def apply_event(image, event: dict) -> None:
    """Apply one journal event to an image through the plain data path
    (journaling suppressed by the caller).  Snapshot events tolerate
    already-applied states so replay after a crash between apply and
    commit is idempotent (the reference checks applied op return codes
    the same way, librbd::journal::Replay)."""
    op = event["op"]
    if op == "write":
        await image.write(event["off"], event["data"])
    elif op == "discard":
        await image.discard(event["off"], event["len"])
    elif op == "resize":
        await image.resize(event["size"])
    elif op == "snap_create":
        try:
            await image.snap_create(event["name"])
        except IOError:
            pass  # -EEXIST: applied before the crash
    elif op == "snap_remove":
        try:
            await image.snap_remove(event["name"])
        except PermissionError:
            raise  # protected snap: real divergence, never swallow
        except (IOError, FileNotFoundError):
            pass  # -ENOENT: applied before the crash
    elif op == "snap_protect":
        await image.snap_protect(event["name"])  # idempotent in cls_rbd
    elif op == "snap_unprotect":
        await image.snap_unprotect(event["name"])
    elif op == "snap_rollback":
        await image.snap_rollback(event["name"])
    elif op == "flatten":
        await image.flatten()
    else:
        raise ValueError(f"unknown journal event {op!r}")
