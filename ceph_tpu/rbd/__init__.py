"""RBD: block images striped over RADOS objects.

Reference: src/librbd (58.7k LoC) reduced to the core image model:

* header object ``rbd_header.<name>`` -- size/order/snaps/features in
  omap, managed by the ``rbd`` object class (ceph_tpu/cls/cls_rbd.py,
  reference src/cls/rbd);
* data objects ``rbd_data.<name>.<object_no:016x>`` -- image extents
  mapped by the Striper (object_size = 2^order);
* exclusive-lock via cls_lock, header-change notification via
  watch/notify (the reference's ExclusiveLock + ImageWatcher roles);
* the image directory object ``rbd_directory`` lists images (cls_rbd
  dir methods' role);
* REAL data snapshots via the RADOS self-managed SnapContext, COW
  clone layering with copy-up, flatten (src/librbd/io + Operations);
* image journaling (feature ``journaling``): mutations recorded as
  typed events in a per-image journal before application, crash replay
  on open (src/librbd/Journal.cc over src/journal);
* rbd-mirror: journal replay into a peer cluster with a registered
  journal client pinning trim (src/tools/rbd_mirror).

Reductions vs the reference (documented, not hidden): no object-map
feature, no promotion/demotion tags in mirroring (source is always
primary).
"""

from ceph_tpu.rbd.image import RBD, Image
from ceph_tpu.rbd.journal import FEATURE_JOURNALING, ImageJournal
from ceph_tpu.rbd.mirror import (ImageReplayer, MirrorDaemon,
                                 mirror_disable, mirror_enable, mirror_list)

__all__ = ["RBD", "Image", "FEATURE_JOURNALING", "ImageJournal",
           "ImageReplayer", "MirrorDaemon", "mirror_disable",
           "mirror_enable", "mirror_list"]
