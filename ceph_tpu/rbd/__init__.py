"""RBD: block images striped over RADOS objects.

Reference: src/librbd (58.7k LoC) reduced to the core image model:

* header object ``rbd_header.<name>`` -- size/order/snaps/metadata in
  omap, managed by the ``rbd`` object class (ceph_tpu/cls/cls_rbd.py,
  reference src/cls/rbd);
* data objects ``rbd_data.<name>.<object_no:016x>`` -- image extents
  mapped by the Striper (object_size = 2^order);
* exclusive-lock via cls_lock, header-change notification via
  watch/notify (the reference's ExclusiveLock + ImageWatcher roles);
* the image directory object ``rbd_directory`` lists images (cls_rbd
  dir methods' role).

Reductions vs the reference (documented, not hidden): snapshots are
header metadata only (no OSD-level COW clones), no journaling/mirroring,
no parent/child layering.
"""

from ceph_tpu.rbd.image import RBD, Image

__all__ = ["RBD", "Image"]
