"""RBD: block images striped over RADOS objects.

Reference: src/librbd (58.7k LoC) reduced to the core image model:

* header object ``rbd_header.<name>`` -- size/order/snaps/features in
  omap, managed by the ``rbd`` object class (ceph_tpu/cls/cls_rbd.py,
  reference src/cls/rbd);
* data objects ``rbd_data.<name>.<object_no:016x>`` -- image extents
  mapped by the Striper (object_size = 2^order);
* exclusive-lock via cls_lock, header-change notification via
  watch/notify (the reference's ExclusiveLock + ImageWatcher roles);
* the image directory object ``rbd_directory`` lists images (cls_rbd
  dir methods' role);
* REAL data snapshots via the RADOS self-managed SnapContext, COW
  clone layering with copy-up, flatten (src/librbd/io + Operations);
* image journaling (feature ``journaling``): mutations recorded as
  typed events in a per-image journal before application, crash replay
  on open (src/librbd/Journal.cc over src/journal);
* rbd-mirror: journal replay into a peer cluster with a registered
  journal client pinning trim (src/tools/rbd_mirror).

Round 5 adds the object-map + fast-diff features (src/librbd/
ObjectMap.cc): per-object state maps maintained by the write path,
frozen per snapshot, powering stat-free existence checks and
map-only diffs.
"""

from ceph_tpu.rbd.image import RBD, Image
from ceph_tpu.rbd.journal import FEATURE_JOURNALING, ImageJournal
from ceph_tpu.rbd.mirror import (ImageReplayer, MirrorDaemon,
                                 mirror_demote, mirror_disable,
                                 mirror_enable, mirror_is_primary,
                                 mirror_list, mirror_promote)
from ceph_tpu.rbd.objectmap import (FEATURE_FAST_DIFF, FEATURE_OBJECT_MAP,
                                    ObjectMap)

__all__ = ["RBD", "Image", "FEATURE_JOURNALING", "FEATURE_OBJECT_MAP",
           "FEATURE_FAST_DIFF", "ImageJournal", "ImageReplayer",
           "MirrorDaemon", "ObjectMap", "mirror_demote",
           "mirror_disable", "mirror_enable", "mirror_is_primary",
           "mirror_list", "mirror_promote"]
