"""rbd-mirror: journal-based image replication between clusters.

Reference: src/tools/rbd_mirror -- a daemon that, for every
mirror-enabled image in a peer cluster, registers itself as a client on
the image's journal (src/journal JournalMetadata client registry),
bootstraps a local copy, then tails the journal and re-applies each
event locally (ImageReplayer), advancing its commit position on the
remote journal so trim cannot outrun it.

Reductions vs the reference (documented): pool-level peer config is a
constructor argument instead of mon-stored peer records; no
promotion/demotion tags (the source is always primary).  Bootstrap
deep-copies the snapshot history oldest-first and then the head (the
reference's image-sync snapshot walk); later events flow through the
journal.  The replay core -- client registry, positional restart,
idempotent event application, trim pinning -- matches the reference's
semantics and is what the tests exercise.
"""

from __future__ import annotations

from typing import Dict, List

from ceph_tpu.rbd.image import RBD, Image, _data_oid
from ceph_tpu.rbd.journal import (FEATURE_JOURNALING, MIRROR_DIR_OID,
                                  ImageJournal, apply_event)


# -- pool-level mirroring directory (cls_rbd mirror_image_* analogue) -------


async def mirror_enable(backend, image: str, primary: bool = True) -> None:
    """Mark an image for mirroring.  Requires the journaling feature
    (the reference refuses too: no journal, nothing to replay).  The
    enabling side starts PRIMARY (it owns the write role -- the
    reference's journal tag holds the owning mirror_uuid,
    src/librbd/Journal.cc allocate_tag); a replayer's destination copy
    is enabled non-primary."""
    img = await Image.open(backend, image)
    if FEATURE_JOURNALING not in img.features:
        raise IOError(f"image {image} does not have journaling enabled")
    state = b"enabled:primary" if primary else b"enabled:non-primary"
    await backend.omap_set(MIRROR_DIR_OID, {f"image_{image}": state})


async def mirror_is_primary(backend, image: str) -> bool:
    """Does the local copy own the write role?  Unmirrored images are
    always writable (the gate only exists for mirrored pairs)."""
    try:
        got = await backend.omap_get(MIRROR_DIR_OID, [f"image_{image}"])
    except FileNotFoundError:
        return True
    val = got.get(f"image_{image}")
    return val is None or b"non-primary" not in val


async def mirror_promote(backend, image: str, force: bool = False) -> None:
    """Take the write role for the local copy (`rbd mirror image
    promote`, reference src/tools/rbd_mirror + librbd Journal tag
    ownership): the normal failover is demote-old-primary first; with
    the old primary unreachable ``force=True`` promotes anyway
    (split-brain is then the operator's to resolve, as in the
    reference)."""
    key = f"image_{image}"
    try:
        got = await backend.omap_get(MIRROR_DIR_OID, [key])
    except FileNotFoundError:
        got = {}
    if key not in got:
        raise IOError(f"image {image} is not mirror-enabled")
    if b"non-primary" not in got[key] and not force:
        raise IOError(f"image {image} is already primary")
    await backend.omap_set(MIRROR_DIR_OID, {key: b"enabled:primary"})


async def mirror_demote(backend, image: str) -> None:
    """Release the write role (`rbd mirror image demote`): client
    writes refuse until a later promote, while a peer replayer keeps
    applying events."""
    key = f"image_{image}"
    try:
        got = await backend.omap_get(MIRROR_DIR_OID, [key])
    except FileNotFoundError:
        got = {}
    if key not in got:
        raise IOError(f"image {image} is not mirror-enabled")
    await backend.omap_set(MIRROR_DIR_OID, {key: b"enabled:non-primary"})


async def mirror_disable(backend, image: str,
                         peer_id: str = "mirror-peer") -> None:
    """Stop mirroring an image AND deregister the peer's journal
    client -- a stale client position would pin journal trim forever
    (the reference removes the peer client on disable too)."""
    await backend.omap_rm(MIRROR_DIR_OID, [f"image_{image}"])
    jr = ImageJournal(backend, image)
    await jr.open()
    await jr.unregister_peer(peer_id)


async def mirror_list(backend) -> List[str]:
    try:
        omap = await backend.omap_get(MIRROR_DIR_OID)
    except FileNotFoundError:
        return []
    return sorted(k[len("image_"):] for k in omap
                  if k.startswith("image_"))


# -- per-image replayer ------------------------------------------------------


class ImageReplayer:
    """Tail one image's journal from the source pool into the
    destination pool (rbd_mirror::ImageReplayer)."""

    def __init__(self, src_backend, dst_backend, image: str,
                 peer_id: str = "mirror-peer"):
        self.src = src_backend
        self.dst = dst_backend
        self.image = image
        self.peer_id = peer_id
        self._bootstrapped = False
        self.last_error: str = ""

    async def bootstrap(self) -> None:
        """Create the local image, deep-copy the snapshot history
        (oldest first, snapping the copy after each state -- the
        reference's image-sync snapshot walk), then copy the head.

        The journal position is captured BEFORE the copy starts but the
        peer client registers only AFTER the copy completes: the
        registration is the durable bootstrapped marker (a crashed
        half-bootstrap redoes the copy; a finished one is never
        repeated), and replay starts from the captured position so
        events racing the copy are still applied -- positional writes
        make double-application idempotent (the reference gets the same
        guarantee from its sync-point snapshot)."""
        # capture the replay start BEFORE reading the source metadata:
        # an event landing between the two is then merely replayed onto
        # state that may already include it (idempotent), never lost
        jr = ImageJournal(self.src, self.image)
        await jr.open()
        start_pos = jr.j.write_pos
        src_img = await Image.open(self.src, self.image)
        dst_rbd = RBD(self.dst)
        fresh = True
        try:
            await dst_rbd.create(self.image, src_img.size,
                                 order=src_img.order)
        except FileExistsError:
            # a prior partial bootstrap may have left data: every block
            # must be rewritten, including zeros over stale bytes
            fresh = False
        dst_img = await Image.open(self.dst, self.image)
        dst_dir = await self._dst_mirror_dir()
        ent = dst_dir.get(f"image_{self.image}")
        if ent is not None and b"non-primary" not in ent:
            # the destination copy owns the write role (it was promoted):
            # replaying onto it would silently destroy its writes --
            # the reference's split-brain detection refuses the same way
            raise IOError(
                f"destination image {self.image} is primary; refusing "
                "to replay onto it (demote it or force-resync)")
        dst_img._mirror_bypass = True
        for name, ent in sorted(src_img.snaps.items(),
                                key=lambda kv: kv[1]["id"]):
            view = await Image.open(self.src, self.image, snap=name)
            await self._copy_content(view, dst_img, fresh)
            fresh = False
            try:
                await dst_img.snap_create(name)
            except IOError:
                pass  # re-bootstrap after a partial earlier run
            if ent.get("protected"):
                await dst_img.snap_protect(name)
        await self._copy_content(src_img, dst_img, fresh)
        await jr.register_peer(self.peer_id, start_pos)
        # the destination copy is mirror-tracked NON-PRIMARY: client
        # writes there refuse until an operator promotes it (failover)
        await self.dst.omap_set(
            MIRROR_DIR_OID, {f"image_{self.image}": b"enabled:non-primary"})
        self._bootstrapped = True

    async def _dst_mirror_dir(self) -> dict:
        try:
            return await self.dst.omap_get(MIRROR_DIR_OID)
        except FileNotFoundError:
            return {}

    async def _copy_content(self, view: Image, dst_img: Image,
                            fresh: bool) -> None:
        """Copy one image state into dst.  On a fresh (never-written)
        destination all-zero blocks are skipped; on later passes every
        block is written so data deleted between snapshots does not
        survive as stale bytes."""
        if dst_img.size != view.size:
            await dst_img.resize(view.size)
        osz = 1 << view.order
        for object_no in range(view.striper.object_count(view.size)):
            # head-object stat is only a safe absence proxy when reading
            # the head itself (a snap view may be served by COW clones)
            if fresh and view.parent is None and view.read_snap_id is None:
                try:
                    sz, hinfo = await self.src.stat(
                        _data_oid(self.image, object_no))
                except (FileNotFoundError, IOError):
                    continue
                if sz == 0 and hinfo is None:
                    continue  # never written, nothing to copy
            base = object_no * osz
            span = min(osz, view.size - base)
            if span <= 0:
                continue
            block = await view.read(base, span)
            if block.strip(b"\0") or not fresh:
                await dst_img.write(base, block)

    async def replay_once(self) -> int:
        """Apply every pending journal event; returns how many."""
        jr = ImageJournal(self.src, self.image)
        await jr.open()
        if not self._bootstrapped:
            # a registered peer client IS the durable bootstrap marker:
            # a restarted daemon resumes from the persisted position
            # instead of re-copying the whole image
            if await jr.j.client_pos(self.peer_id) is not None:
                # one-way latch: every writer stores True, so two
                # replay_once calls racing this window agree on the
                # value -- nothing to clobber
                self._bootstrapped = True  # cephlint: disable=async-rmw-across-await
            else:
                await self.bootstrap()
        entries = await jr.peer_entries(self.peer_id)
        if entries:
            dst_img = await Image.open(self.dst, self.image)
            if dst_img._primary is not False:
                # split-brain guard (see bootstrap): never replay onto a
                # copy that owns the write role
                raise IOError(
                    f"destination image {self.image} is primary; "
                    "refusing to replay onto it")
            dst_img._mirror_bypass = True
            for _start, end, ev in entries:
                await apply_event(dst_img, ev)
                await jr.peer_committed(self.peer_id, end)
        await jr.trim()  # reuse this handle; consumed objects can go
        return len(entries)

    async def entries_behind(self) -> int:
        """Pending-event count.  peer_entries short-circuits the caught-
        up case on positions alone; a genuinely lagging peer pays one
        decode pass (the same I/O the next replay_once needs anyway)."""
        jr = ImageJournal(self.src, self.image)
        await jr.open()
        return len(await jr.peer_entries(self.peer_id))


# -- the daemon --------------------------------------------------------------


class MirrorDaemon:
    """One direction of an rbd-mirror daemon: replays every
    mirror-enabled image of ``src_backend`` into ``dst_backend``."""

    def __init__(self, src_backend, dst_backend,
                 peer_id: str = "mirror-peer"):
        self.src = src_backend
        self.dst = dst_backend
        self.peer_id = peer_id
        self.replayers: Dict[str, ImageReplayer] = {}

    async def run_once(self) -> Dict[str, int]:
        """One tick: pick up newly-enabled images, replay all pending
        events, trim consumed journal objects.  Returns events applied
        per image."""
        applied: Dict[str, int] = {}
        for image in await mirror_list(self.src):
            if not await mirror_is_primary(self.src, image):
                # this side's copy is demoted: the replication direction
                # reversed (failover) -- stop pulling from it
                applied[image] = 0
                continue
            rep = self.replayers.get(image)
            if rep is None:
                rep = self.replayers[image] = ImageReplayer(
                    self.src, self.dst, image, self.peer_id)
            try:
                applied[image] = await rep.replay_once()
                rep.last_error = ""
            except (FileNotFoundError, IOError) as e:
                # one broken image (deleted source, unreachable pool)
                # must not abort replay of every other image this tick
                rep.last_error = str(e) or type(e).__name__
                applied[image] = 0
        return applied

    async def status(self) -> Dict[str, dict]:
        out: Dict[str, dict] = {}
        for image in await mirror_list(self.src):
            if not await mirror_is_primary(self.src, image):
                out[image] = {"state": "stopped", "reason": "non-primary"}
                continue
            rep = self.replayers.get(image)
            if rep is not None and rep.last_error:
                out[image] = {"state": "error", "error": rep.last_error}
            elif rep is None or not rep._bootstrapped:
                out[image] = {"state": "starting_replay"}
            else:
                behind = await rep.entries_behind()
                out[image] = {
                    "state": "replaying" if behind else "up+replaying",
                    "entries_behind": behind,
                }
        return out
