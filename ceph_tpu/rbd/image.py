"""RBD image management + I/O (librbd core surface).

Round-4 upgrade: snapshots are REAL data snapshots (every data-object
write carries the image's self-managed SnapContext, so the RADOS layer
COW-clones pre-snap blocks -- the librbd snapshot model), and clones are
REAL COW clones: a child image references ``parent@snap``; reads of
never-written child blocks fall through to the parent at that snap and
partial child writes copy the parent block up first (librbd layering +
copy-up, src/librbd/io/CopyupRequest.cc).  ``flatten`` severs the
dependency by copying every still-inherited block.
"""

from __future__ import annotations

import contextvars

from typing import Dict, List, Optional

from ceph_tpu.osdc.striper import FileLayout, Striper
from ceph_tpu.rbd.objectmap import (FEATURE_FAST_DIFF, FEATURE_OBJECT_MAP,
                                    OBJECT_EXISTS, ObjectMap, fast_diff,
                                    map_oid, rebuild)
from ceph_tpu.rbd.journal import (FEATURE_JOURNALING, MIRROR_DIR_OID,
                                  ImageJournal, apply_event,
                                  destroy_journal)
from ceph_tpu.utils.encoding import Decoder, Encoder

#: images (by instance id) whose journal events are being re-applied in
#: the CURRENT task -- see Image._replay_mode for why this is a
#: contextvar rather than an instance flag
_REPLAYING: contextvars.ContextVar = contextvars.ContextVar(
    "rbd_replaying", default=frozenset())

_DIR_OID = "rbd_directory"


def _enc(v) -> bytes:
    return Encoder().value(v).bytes()


def _dec(b):
    return Decoder(b).value() if b else None


def _header_oid(name: str) -> str:
    return f"rbd_header.{name}"


def _data_oid(name: str, object_no: int) -> str:
    return f"rbd_data.{name}.{object_no:016x}"


class RBD:
    """Image management (librbd::RBD): create/list/remove/clone."""

    def __init__(self, backend):
        self.backend = backend  # the pool's primary EC engine

    async def create(self, name: str, size: int, order: int = 22,
                     features: Optional[List[str]] = None) -> None:
        ret, _ = await self.backend.exec(
            _header_oid(name), "rbd", "create",
            _enc({"size": size, "order": order,
                  "features": features or []}),
        )
        if ret == -17:
            raise FileExistsError(name)
        if ret != 0:
            raise IOError(f"rbd create {name}: rc={ret}")
        await self.backend.omap_set(_DIR_OID, {f"name_{name}": b"1"})

    async def clone(self, parent: str, snap: str, child: str) -> None:
        """COW clone of parent@snap (librbd::RBD::clone).  The snap must
        be protected first (the reference's guard against trimming a
        snap that children still read through)."""
        pimg = await Image.open(self.backend, parent)
        ent = pimg.snaps.get(snap)
        if ent is None:
            raise FileNotFoundError(f"{parent}@{snap}")
        if not ent.get("protected"):
            raise PermissionError(
                f"snap {parent}@{snap} is not protected"
            )
        await self.create(child, ent["size"], order=pimg.order)
        ret, _ = await self.backend.exec(
            _header_oid(child), "rbd", "set_parent",
            _enc({"image": parent, "snap_id": ent["id"],
                  "snap_name": snap, "overlap": ent["size"]}),
        )
        if ret != 0:
            raise IOError(f"set_parent rc={ret}")
        await self.backend.exec(
            _header_oid(parent), "rbd", "add_child",
            _enc({"snap_id": ent["id"], "child": child}),
        )

    async def list(self) -> List[str]:
        try:
            omap = await self.backend.omap_get(_DIR_OID)
        except FileNotFoundError:
            return []
        return sorted(
            k[len("name_"):] for k in omap if k.startswith("name_")
        )

    async def remove(self, name: str) -> None:
        img = await Image.open(self.backend, name)
        # refuse while mirroring (or any journal consumer) depends on
        # the image -- destroying the journal under a registered peer
        # would leave a dangling enrollment that breaks every daemon
        # tick (same guard as update_features)
        try:
            mdir = await self.backend.omap_get(MIRROR_DIR_OID)
        except FileNotFoundError:
            mdir = {}
        if f"image_{name}" in mdir:
            raise BlockingIOError(
                f"image {name} is mirror-enabled; disable mirroring first")
        if img._journal is not None:
            clients = await img._journal.j.clients()
            if clients:
                raise BlockingIOError(
                    f"journal has registered clients: {sorted(clients)}")
        if img.snaps:
            # the reference refuses too: deleting the head would orphan
            # the snap clone objects with no way to ever trim them --
            # and since clone children attach to snaps, a snapless image
            # cannot have children either
            raise IOError(f"image {name} has snapshots; remove them first")
        if img.parent is not None:
            await self.backend.exec(
                _header_oid(img.parent["image"]), "rbd", "remove_child",
                _enc({"snap_id": img.parent["snap_id"], "child": name}),
            )
        n_objects = img.striper.object_count(img.size)
        for object_no in range(n_objects):
            try:
                await self.backend.remove_object(_data_oid(name, object_no))
            except (FileNotFoundError, IOError):
                pass  # never-written object
        if img._journal is not None:
            # drop the journal too, or a recreated same-name image would
            # attach to the dead image's stream and replay its tail
            await destroy_journal(self.backend, name)
        await self.backend.omap_clear(_header_oid(name))
        await self.backend.omap_rm(_DIR_OID, [f"name_{name}"])


class Image:
    """An open image (librbd::Image): read/write/resize/snap/clone/lock."""

    def __init__(self, backend, name: str, size: int, order: int,
                 snaps: Dict[str, dict], snap_seq: int = 0,
                 parent: Optional[dict] = None,
                 read_snap: Optional[str] = None,
                 features: Optional[List[str]] = None):
        self.backend = backend
        self.name = name
        self.size = size
        self.order = order
        self.snaps = snaps
        self.snap_seq = snap_seq
        self.parent = parent
        self.features: List[str] = features or []
        self._journal: Optional[ImageJournal] = None
        self.read_snap_id: Optional[int] = None
        if read_snap is not None:
            ent = snaps.get(read_snap)
            if ent is None:
                raise FileNotFoundError(f"{name}@{read_snap}")
            self.read_snap_id = ent["id"]
            self.size = ent["size"]
        self.striper = Striper(FileLayout(
            object_size=1 << order, stripe_unit=1 << order, stripe_count=1,
        ))
        #: head object map when FEATURE_OBJECT_MAP is on (reference
        #: src/librbd/ObjectMap.cc); attached by open()/refresh()
        self._omap: Optional[ObjectMap] = None
        #: mirror write-role: False = this copy is demoted (non-primary)
        #: and client mutation refuses (the reference's journal-tag
        #: ownership check at exclusive-lock acquisition,
        #: src/librbd/Journal.cc is_tag_owner); None/True = writable.
        #: A replayer sets _mirror_bypass to apply peer events.
        self._primary: Optional[bool] = None
        self._mirror_bypass = False

    @classmethod
    async def open(cls, backend, name: str,
                   snap: Optional[str] = None) -> "Image":
        ret, out = await backend.exec(_header_oid(name), "rbd",
                                      "get_metadata")
        if ret == -2:
            raise FileNotFoundError(name)
        md = _dec(out)
        img = cls(backend, name, md["size"], md["order"], md["snaps"],
                  snap_seq=md.get("snap_seq", 0),
                  parent=md.get("parent"), read_snap=snap,
                  features=md.get("features", []))
        if FEATURE_OBJECT_MAP in img.features and snap is None:
            img._omap = ObjectMap(backend, name)
            await img._omap.load(img.striper.object_count(img.size))
        if snap is None:
            # the mirror write-role applies to journaled AND bootstrapped
            # (journal-less destination) copies alike
            await img._load_primary()
        if FEATURE_JOURNALING in img.features and snap is None:
            img._journal = ImageJournal(backend, name)
            await img._journal.open()
            await img._crash_replay()
        return img

    async def _load_primary(self) -> None:
        """Learn the mirror write-role from the pool's mirroring
        directory (inline rather than via ceph_tpu.rbd.mirror to avoid
        the import cycle; the value format is mirror.py's)."""
        try:
            got = await self.backend.omap_get(
                MIRROR_DIR_OID, [f"image_{self.name}"])
        except FileNotFoundError:
            got = {}
        val = got.get(f"image_{self.name}")
        self._primary = val is None or b"non-primary" not in val

    def _check_writable(self) -> None:
        if (self._primary is False and not self._replay_mode
                and not self._mirror_bypass):
            raise PermissionError(
                f"image {self.name} is non-primary (demoted); promote "
                "it or write on the primary peer")

    @property
    def _replay_mode(self) -> bool:
        """True while THIS task re-applies journal events to this image
        (librbd Replay's re-entrancy marker): the mutators run their
        plain data path instead of re-journaling.  Task-local by
        construction (a contextvar, not an instance flag): two client
        ops journaling concurrently must not see each other's replay
        state -- an instance bool cleared by whichever op finished
        first would let the other's nested mutators re-journal
        mid-apply (the asyncsan rmw-across-await class)."""
        return id(self) in _REPLAYING.get()

    def _enter_replay(self):
        return _REPLAYING.set(_REPLAYING.get() | {id(self)})

    async def _crash_replay(self) -> None:
        """Re-apply journal events past the commit position (a writer
        crashed between append and commit -- librbd Journal replay on
        dirty open)."""
        entries = await self._journal.uncommitted()
        token = self._enter_replay()
        try:
            for _start, end, ev in entries:
                await apply_event(self, ev)
                await self._journal.commit(end)
        finally:
            _REPLAYING.reset(token)

    async def _journaled(self, event: dict) -> bool:
        """Record ``event`` in the image journal, apply it through the
        plain data path, then advance the commit pointer.  Returns False
        when journaling is off (caller runs its plain path)."""
        if self._journal is None or self._replay_mode:
            return False
        _start, end = await self._journal.append(event)
        token = self._enter_replay()
        try:
            await apply_event(self, event)
        finally:
            _REPLAYING.reset(token)
        await self._journal.commit(end)
        return True

    async def update_features(self, enable: Optional[List[str]] = None,
                              disable: Optional[List[str]] = None) -> None:
        """Dynamic feature toggle (librbd::Image::update_features)."""
        # fast-diff rides the object map (reference feature dependency,
        # src/librbd/Operations.cc update_features checks)
        after = (set(self.features) | set(enable or [])) - set(disable or [])
        if FEATURE_FAST_DIFF in after and FEATURE_OBJECT_MAP not in after:
            raise ValueError("fast-diff requires object-map")
        dropping_journal = (FEATURE_JOURNALING in (disable or [])
                            and FEATURE_JOURNALING in self.features)
        if dropping_journal:
            # the reference refuses to disable journaling while
            # mirroring depends on it; same for any registered journal
            # consumer (a mirror peer's position would dangle).  Checks
            # go through a fresh journal handle: this Image handle may
            # predate the feature and have no journal attached.
            try:
                mdir = await self.backend.omap_get(MIRROR_DIR_OID)
            except FileNotFoundError:
                mdir = {}
            if f"image_{self.name}" in mdir:
                raise BlockingIOError(
                    f"image {self.name} is mirror-enabled; disable "
                    "mirroring first")
            jr = ImageJournal(self.backend, self.name)
            await jr.open()
            clients = await jr.j.clients()
            if clients:
                raise BlockingIOError(
                    f"journal has registered clients: {sorted(clients)}")
        ret, _ = await self.backend.exec(
            _header_oid(self.name), "rbd", "set_features",
            _enc({"enable": enable or [], "disable": disable or []}))
        if ret != 0:
            raise IOError(f"set_features rc={ret}")
        if dropping_journal:
            await destroy_journal(self.backend, self.name)
            self._journal = None
        if FEATURE_OBJECT_MAP in (disable or []):
            # drop the head map and every snapshot's frozen map
            await ObjectMap(self.backend, self.name).remove()
            for ent in self.snaps.values():
                await ObjectMap(self.backend, self.name,
                                ent["id"]).remove()
            self._omap = None
        await self.refresh()  # attaches/detaches the journal as needed
        if FEATURE_OBJECT_MAP in (enable or []) and self._omap is not None:
            # a just-enabled map knows nothing about existing objects:
            # build it from the store (RebuildRequest role)
            self._omap = await rebuild(
                self.backend, self.name,
                self.striper.object_count(self.size),
                lambda o: _data_oid(self.name, o),
            )

    async def refresh(self) -> None:
        md = _dec((await self.backend.exec(
            _header_oid(self.name), "rbd", "get_metadata"))[1])
        if self.read_snap_id is None:
            self.size = md["size"]
        self.order = md["order"]
        self.snaps = md["snaps"]
        self.snap_seq = md.get("snap_seq", 0)
        self.parent = md.get("parent")
        self.features = md.get("features", [])
        # track feature changes made through OTHER handles: a handle
        # that kept writing through the plain path after journaling was
        # enabled elsewhere would silently starve mirror peers
        journaled = (FEATURE_JOURNALING in self.features
                     and self.read_snap_id is None)
        if journaled and self._journal is None:
            self._journal = ImageJournal(self.backend, self.name)
            await self._journal.open()
        elif not journaled and self._journal is not None:
            self._journal = None
        if self.read_snap_id is None:
            await self._load_primary()  # promote/demote by another handle
        mapped = (FEATURE_OBJECT_MAP in self.features
                  and self.read_snap_id is None)
        if mapped and self._omap is None:
            self._omap = ObjectMap(self.backend, self.name)
            await self._omap.load(self.striper.object_count(self.size))
        elif not mapped and self._omap is not None:
            self._omap = None

    # -- snap context (the librados self-managed SnapContext) --------------

    def _snapc(self) -> Optional[dict]:
        ids = sorted((e["id"] for e in self.snaps.values()), reverse=True)
        if not ids:
            return None
        return {"seq": self.snap_seq, "snaps": ids}

    # -- layering helpers (librbd io layer) --------------------------------

    async def _object_absent(self, oid: str) -> bool:
        if self._omap is not None:
            # object map answers without a stat round trip (the whole
            # point of the feature, reference ObjectMap::object_may_exist)
            object_no = int(oid.rsplit(".", 1)[1], 16)
            return not self._omap.exists(object_no)
        size, hinfo = await self.backend.stat(oid)
        return size == 0 and hinfo is None

    async def _omap_mark(self, object_no: int) -> None:
        """Pre-write map update (ObjectMap::aio_update EXISTS)."""
        if self._omap is not None:
            await self._omap.update(object_no, OBJECT_EXISTS)

    async def _object_absent_at(self, oid: str,
                                snap: Optional[int]) -> bool:
        """Did the object exist at ``snap``?  A clone with id >= snap
        serves that state; a head whose SnapSet seq predates the snap is
        unchanged since then; a head first written AT/AFTER the snap
        (seq >= snap, no covering clone) did not exist yet -- reading a
        child snapshot must then fall through to the parent even though
        a later copy-up created the head (librbd head-vs-snap split)."""
        if snap is None:
            return await self._object_absent(oid)
        try:
            ss = await self.backend.list_snaps(oid)
        except IOError:
            return True
        if any(c["id"] >= snap for c in ss["clones"]):
            return False
        return not ss["head_exists"] or ss["seq"] >= snap

    async def _parent_image(self) -> "Image":
        p = self.parent
        img = await Image.open(self.backend, p["image"])
        # read strictly at the cloned snap id, clipped to the overlap
        img.read_snap_id = p["snap_id"]
        img.size = p["overlap"]
        return img

    async def _read_parent(self, offset: int, length: int) -> bytes:
        """Read [offset, offset+length) from parent@snap, zero-padded
        past the overlap (librbd reads clip to the parent overlap)."""
        p = self.parent
        end = min(offset + length, p["overlap"])
        if end <= offset:
            return bytes(length)
        parent = await self._parent_image()
        data = await parent.read(offset, end - offset)
        return data.ljust(length, b"\0")

    async def _copy_up(self, object_no: int) -> None:
        """Materialize a child object from the parent before a partial
        write (librbd CopyupRequest): the whole parent block lands in
        the child object so the rest of the block is never lost."""
        osz = 1 << self.order
        base = object_no * osz
        span = min(osz, max(0, self.parent["overlap"] - base))
        if span <= 0:
            return
        block = await self._read_parent(base, span)
        await self._omap_mark(object_no)
        await self.backend.write_range(
            _data_oid(self.name, object_no), 0, block,
            snapc=self._snapc(),
        )

    # -- I/O ---------------------------------------------------------------

    async def write(self, offset: int, data: bytes) -> None:
        if self.read_snap_id is not None:
            raise IOError("image opened read-only at a snapshot")
        self._check_writable()
        if offset + len(data) > self.size:
            raise IOError("write past end of image")
        if self._journal is not None and not self._replay_mode:
            # bound each journal entry (librbd splits large AIOs into
            # multiple AioWriteEvents so no event outgrows a journal
            # object); positional writes keep the split replay-safe
            step = 256 << 10
            for i in range(0, len(data), step):
                await self._journaled({"op": "write", "off": offset + i,
                                       "data": data[i:i + step]})
            return
        pos = 0
        osz = 1 << self.order
        for object_no, obj_off, length in self.striper.map_extent(
            offset, len(data)
        ):
            oid = _data_oid(self.name, object_no)
            if (
                self.parent is not None
                and length < osz
                and object_no * osz < self.parent["overlap"]
                and await self._object_absent(oid)
            ):
                await self._copy_up(object_no)
            await self._omap_mark(object_no)  # pre-write map update
            await self.backend.write_range(
                oid, obj_off, data[pos : pos + length],
                snapc=self._snapc(),
            )
            pos += length

    async def read(self, offset: int, length: int) -> bytes:
        length = max(0, min(length, self.size - offset))
        out = bytearray(length)
        pos = 0
        for object_no, obj_off, take in self.striper.map_extent(
            offset, length
        ):
            oid = _data_oid(self.name, object_no)
            piece = b""
            absent = False
            try:
                piece = await self.backend.read_range(
                    oid, obj_off, take, snap=self.read_snap_id,
                )
            except (FileNotFoundError, IOError):
                absent = True
            if (absent or not piece) and self.parent is not None:
                # block absent at the version being read: fall through
                if await self._object_absent_at(oid, self.read_snap_id):
                    piece = await self._read_parent(
                        object_no * (1 << self.order) + obj_off, take
                    )
            out[pos : pos + len(piece)] = piece
            pos += take
        return bytes(out)

    async def discard(self, offset: int, length: int) -> None:
        """Zero a range (librbd::Image::discard).  Runs through the
        write path so SnapContext COW and clone copy-up semantics hold;
        trimming whole objects is an optimization the reference applies
        only when the object has no snap/parent dependency."""
        if self.read_snap_id is not None:
            raise IOError("image opened read-only at a snapshot")
        self._check_writable()
        length = max(0, min(length, self.size - offset))
        if length == 0:
            return
        if await self._journaled({"op": "discard", "off": offset,
                                  "len": length}):
            return
        await self.write(offset, bytes(length))

    async def flatten(self) -> None:
        """Copy every still-inherited block from the parent and sever
        the dependency (librbd::Image::flatten)."""
        # snapshot the link once: a concurrent flatten nulling
        # self.parent between the copy-up awaits would crash the
        # dereferences below (asyncsan rmw-across-await window)
        parent = self.parent
        if parent is None:
            return
        if await self._journaled({"op": "flatten"}):
            return
        osz = 1 << self.order
        overlap = parent["overlap"]
        for object_no in range((overlap + osz - 1) // osz):
            if await self._object_absent(_data_oid(self.name, object_no)):
                await self._copy_up(object_no)
        await self.backend.exec(
            _header_oid(parent["image"]), "rbd", "remove_child",
            _enc({"snap_id": parent["snap_id"], "child": self.name}),
        )
        await self.backend.exec(
            _header_oid(self.name), "rbd", "remove_parent", b"")
        self.parent = None

    async def resize(self, new_size: int) -> None:
        self._check_writable()
        if await self._journaled({"op": "resize", "size": new_size}):
            return
        old_size = self.size
        ret, _ = await self.backend.exec(
            _header_oid(self.name), "rbd", "set_size",
            _enc({"size": new_size}),
        )
        if ret != 0:
            raise IOError(f"resize rc={ret}")
        self.size = new_size
        if (
            new_size < old_size
            and self.parent is not None
            and self.parent["overlap"] > new_size
        ):
            # librbd shrinks the parent overlap on resize: a later regrow
            # must read zeros, never resurface parent bytes
            self.parent = dict(self.parent, overlap=new_size)
            await self.backend.exec(
                _header_oid(self.name), "rbd", "set_parent",
                _enc({"image": self.parent["image"],
                      "snap_id": self.parent["snap_id"],
                      "snap_name": self.parent.get("snap_name", ""),
                      "overlap": new_size}),
            )
        if new_size < old_size:
            # trim (librbd shrink semantics): whole objects past the new
            # end are deleted and the boundary object's tail is zeroed --
            # otherwise a later regrow would resurface the old bytes
            osz = 1 << self.order
            first_dead = (new_size + osz - 1) // osz
            for object_no in range(first_dead,
                                   self.striper.object_count(old_size)):
                try:
                    await self.backend.remove_object(
                        _data_oid(self.name, object_no),
                        snapc=self._snapc(),
                    )
                except (FileNotFoundError, IOError):
                    pass
            boundary = new_size % osz
            if boundary:
                oid = _data_oid(self.name, new_size // osz)
                obj_size, _ = await self.backend.stat(oid)
                if obj_size > boundary:
                    await self.backend.write_range(
                        oid, boundary, b"\0" * (obj_size - boundary),
                        snapc=self._snapc(),
                    )
        if self._omap is not None:
            # truncate/extend the map with the image (shrink drops the
            # trimmed objects' entries; grow pads NONEXISTENT)
            await self._omap.resize(self.striper.object_count(new_size))
        # header watchers (other clients with the image open) refresh
        await self.backend.notify(
            _header_oid(self.name), {"event": "resize", "size": new_size},
            timeout=1.0,
        )

    # -- snapshots (REAL data snapshots via the RADOS snap layer) ----------

    async def snap_create(self, snap: str) -> int:
        self._check_writable()
        if self._journal is not None and not self._replay_mode:
            # validate BEFORE journaling: apply_event tolerates -EEXIST
            # for crash-replay idempotency, so the live path must raise
            # it itself (and keep garbage events out of the journal)
            await self.refresh()
            if snap in self.snaps:
                raise IOError("snap_create rc=-17")
        if await self._journaled({"op": "snap_create", "name": snap}):
            return self.snaps[snap]["id"]
        ret, out = await self.backend.exec(
            _header_oid(self.name), "rbd", "snap_add", _enc({"name": snap}))
        if ret != 0:
            raise IOError(f"snap_create rc={ret}")
        if self._omap is not None:
            # freeze the snapshot's map, sweep the head dirty->clean
            # (fast-diff interval bookkeeping; ObjectMap snap create)
            await self._omap.snapshot_to(_dec(out))
        await self.refresh()
        return _dec(out)

    async def snap_remove(self, snap: str) -> None:
        if self._journal is not None and not self._replay_mode:
            await self.refresh()
            if snap not in self.snaps:
                raise IOError("snap_remove rc=-2")
        ent = self.snaps.get(snap)
        if ent is not None and ent.get("protected"):
            raise PermissionError(f"snap {snap} is protected")
        if await self._journaled({"op": "snap_remove", "name": snap}):
            return
        ret, _ = await self.backend.exec(
            _header_oid(self.name), "rbd", "snap_remove",
            _enc({"name": snap}))
        if ret != 0:
            raise IOError(f"snap_remove rc={ret}")
        await self.refresh()
        # trim RADOS-level clones the dropped snap alone kept alive
        live = [e["id"] for e in self.snaps.values()]
        max_objs = self.striper.object_count(
            max([self.size] + [e["size"] for e in self.snaps.values()]
                + ([ent["size"]] if ent else []))
        )
        for object_no in range(max_objs):
            try:
                await self.backend.snap_trim(
                    _data_oid(self.name, object_no), live
                )
            except IOError:
                pass
        if self._omap is not None and ent is not None:
            # drop the snapshot's frozen map with the snapshot
            await ObjectMap(self.backend, self.name, ent["id"]).remove()

    async def snap_rollback(self, snap: str) -> None:
        """Restore the image data+size to the snapshot
        (librbd::Image::snap_rollback)."""
        if self._journal is not None and not self._replay_mode:
            await self.refresh()  # stale snaps dict must not journal a
            # rollback against a dead snap id (same rule as siblings)
        ent = self.snaps.get(snap)
        if ent is None:
            raise FileNotFoundError(f"{self.name}@{snap}")
        if await self._journaled({"op": "snap_rollback", "name": snap}):
            return
        max_objs = self.striper.object_count(max(self.size, ent["size"]))
        for object_no in range(max_objs):
            try:
                await self.backend.snap_rollback(
                    _data_oid(self.name, object_no), ent["id"],
                    snapc=self._snapc(),
                )
            except IOError:
                pass  # object absent in both states
        await self.backend.exec(
            _header_oid(self.name), "rbd", "set_size",
            _enc({"size": ent["size"]}),
        )
        self.size = ent["size"]
        if self._omap is not None:
            # object existence changed wholesale: rebuild from the store
            # (the reference invalidates + rebuilds the map on rollback)
            self._omap = await rebuild(
                self.backend, self.name,
                self.striper.object_count(self.size),
                lambda o: _data_oid(self.name, o),
            )

    async def snap_protect(self, snap: str) -> None:
        if self._journal is not None and not self._replay_mode:
            await self.refresh()
            if snap not in self.snaps:
                raise IOError("snap_protect rc=-2")
        if await self._journaled({"op": "snap_protect", "name": snap}):
            return
        ret, _ = await self.backend.exec(
            _header_oid(self.name), "rbd", "snap_protect",
            _enc({"name": snap}))
        if ret != 0:
            raise IOError(f"snap_protect rc={ret}")
        await self.refresh()

    async def snap_unprotect(self, snap: str) -> None:
        if self._journal is not None and not self._replay_mode:
            # pre-validate so a doomed op never lands in the journal
            # (a journaled event that fails to apply would poison every
            # later replay); the reference records op-finish results
            await self.refresh()
            ent = self.snaps.get(snap)
            if ent is None:
                raise IOError("snap_unprotect rc=-2")
            _, out = await self.backend.exec(
                _header_oid(self.name), "rbd", "get_children",
                _enc({"snap_id": ent["id"]}))
            if _dec(out):
                raise BlockingIOError(f"snap {snap} has clone children")
        if await self._journaled({"op": "snap_unprotect", "name": snap}):
            return
        ret, _ = await self.backend.exec(
            _header_oid(self.name), "rbd", "snap_unprotect",
            _enc({"name": snap}))
        if ret == -16:
            raise BlockingIOError(f"snap {snap} has clone children")
        if ret != 0:
            raise IOError(f"snap_unprotect rc={ret}")
        await self.refresh()

    def snap_list(self) -> List[str]:
        return sorted(self.snaps)

    # -- object map / fast-diff public surface -----------------------------

    async def diff(self, from_snap: Optional[str] = None):
        """Changed extents since ``from_snap`` (None = since creation)
        computed from the OBJECT MAPS ALONE -- no per-object stats or
        data reads (librbd diff_iterate whole_object fast-diff path).
        Returns [(offset, length, exists), ...]."""
        if self._omap is None:
            raise ValueError("fast-diff needs the object-map feature")
        return await fast_diff(
            self.backend, self.name, self.snaps, self._omap,
            1 << self.order, self.size, from_snap=from_snap,
        )

    async def object_map_rebuild(self) -> None:
        """Re-derive the head map from the store (rbd object-map rebuild
        CLI role: repair after out-of-band writes or invalidation)."""
        if self._omap is None:
            raise ValueError("object-map feature is off")
        self._omap = await rebuild(
            self.backend, self.name,
            self.striper.object_count(self.size),
            lambda o: _data_oid(self.name, o),
        )

    def object_map_states(self) -> bytes:
        """Raw head-map states (introspection/test hook)."""
        if self._omap is None:
            raise ValueError("object-map feature is off")
        return bytes(self._omap.states)

    # -- exclusive lock (cls_lock-backed, ExclusiveLock role) --------------

    async def lock_acquire(self, cookie: str) -> None:
        ret, _ = await self.backend.exec(
            _header_oid(self.name), "lock", "lock",
            _enc({"name": "rbd_lock", "locker": cookie,
                  "type": "exclusive"}),
        )
        if ret == -16:
            raise BlockingIOError(f"image {self.name} is locked")
        if ret != 0:
            raise IOError(f"lock rc={ret}")

    async def lock_release(self, cookie: str) -> None:
        await self.backend.exec(
            _header_oid(self.name), "lock", "unlock",
            _enc({"name": "rbd_lock", "locker": cookie}),
        )

    async def watch_header(self, callback) -> None:
        """ImageWatcher role: get notified of header changes."""
        await self.backend.watch(_header_oid(self.name), callback)

    async def unwatch_header(self) -> None:
        await self.backend.unwatch(_header_oid(self.name))
