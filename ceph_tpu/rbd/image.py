"""RBD image management + I/O (librbd core surface)."""

from __future__ import annotations

from typing import Dict, List, Optional

from ceph_tpu.osdc.striper import FileLayout, Striper
from ceph_tpu.utils.encoding import Decoder, Encoder

_DIR_OID = "rbd_directory"


def _enc(v) -> bytes:
    return Encoder().value(v).bytes()


def _dec(b):
    return Decoder(b).value() if b else None


def _header_oid(name: str) -> str:
    return f"rbd_header.{name}"


def _data_oid(name: str, object_no: int) -> str:
    return f"rbd_data.{name}.{object_no:016x}"


class RBD:
    """Image management (librbd::RBD): create/list/remove/resize."""

    def __init__(self, backend):
        self.backend = backend  # the pool's primary EC engine

    async def create(self, name: str, size: int, order: int = 22) -> None:
        ret, _ = await self.backend.exec(
            _header_oid(name), "rbd", "create",
            _enc({"size": size, "order": order}),
        )
        if ret == -17:
            raise FileExistsError(name)
        if ret != 0:
            raise IOError(f"rbd create {name}: rc={ret}")
        await self.backend.omap_set(_DIR_OID, {f"name_{name}": b"1"})

    async def list(self) -> List[str]:
        try:
            omap = await self.backend.omap_get(_DIR_OID)
        except FileNotFoundError:
            return []
        return sorted(
            k[len("name_"):] for k in omap if k.startswith("name_")
        )

    async def remove(self, name: str) -> None:
        img = await Image.open(self.backend, name)
        n_objects = img.striper.object_count(img.size)
        for object_no in range(n_objects):
            try:
                await self.backend.remove_object(_data_oid(name, object_no))
            except (FileNotFoundError, IOError):
                pass  # never-written object
        await self.backend.omap_clear(_header_oid(name))
        await self.backend.omap_rm(_DIR_OID, [f"name_{name}"])


class Image:
    """An open image (librbd::Image): read/write/resize/snap/lock."""

    def __init__(self, backend, name: str, size: int, order: int,
                 snaps: Dict[str, dict]):
        self.backend = backend
        self.name = name
        self.size = size
        self.order = order
        self.snaps = snaps
        self.striper = Striper(FileLayout(
            object_size=1 << order, stripe_unit=1 << order, stripe_count=1,
        ))

    @classmethod
    async def open(cls, backend, name: str) -> "Image":
        ret, out = await backend.exec(_header_oid(name), "rbd",
                                      "get_metadata")
        if ret == -2:
            raise FileNotFoundError(name)
        md = _dec(out)
        return cls(backend, name, md["size"], md["order"], md["snaps"])

    async def refresh(self) -> None:
        md = _dec((await self.backend.exec(
            _header_oid(self.name), "rbd", "get_metadata"))[1])
        self.size, self.order = md["size"], md["order"]
        self.snaps = md["snaps"]

    # -- I/O ---------------------------------------------------------------

    async def write(self, offset: int, data: bytes) -> None:
        if offset + len(data) > self.size:
            raise IOError("write past end of image")
        pos = 0
        for object_no, obj_off, length in self.striper.map_extent(
            offset, len(data)
        ):
            oid = _data_oid(self.name, object_no)
            await self.backend.write_range(
                oid, obj_off, data[pos : pos + length]
            )
            pos += length

    async def read(self, offset: int, length: int) -> bytes:
        length = max(0, min(length, self.size - offset))
        out = bytearray(length)
        pos = 0
        for object_no, obj_off, take in self.striper.map_extent(
            offset, length
        ):
            oid = _data_oid(self.name, object_no)
            try:
                piece = await self.backend.read_range(oid, obj_off, take)
            except (FileNotFoundError, IOError):
                piece = b""  # never-written object reads as zeros
            out[pos : pos + len(piece)] = piece
            pos += take
        return bytes(out)

    async def resize(self, new_size: int) -> None:
        old_size = self.size
        ret, _ = await self.backend.exec(
            _header_oid(self.name), "rbd", "set_size",
            _enc({"size": new_size}),
        )
        if ret != 0:
            raise IOError(f"resize rc={ret}")
        self.size = new_size
        if new_size < old_size:
            # trim (librbd shrink semantics): whole objects past the new
            # end are deleted and the boundary object's tail is zeroed --
            # otherwise a later regrow would resurface the old bytes
            osz = 1 << self.order
            first_dead = (new_size + osz - 1) // osz
            for object_no in range(first_dead,
                                   self.striper.object_count(old_size)):
                try:
                    await self.backend.remove_object(
                        _data_oid(self.name, object_no)
                    )
                except (FileNotFoundError, IOError):
                    pass
            boundary = new_size % osz
            if boundary:
                oid = _data_oid(self.name, new_size // osz)
                obj_size, _ = await self.backend.stat(oid)
                if obj_size > boundary:
                    await self.backend.write_range(
                        oid, boundary, b"\0" * (obj_size - boundary)
                    )
        # header watchers (other clients with the image open) refresh
        await self.backend.notify(
            _header_oid(self.name), {"event": "resize", "size": new_size},
            timeout=1.0,
        )

    # -- snapshots (metadata-level; see package docstring) ----------------

    async def snap_create(self, snap: str) -> int:
        ret, out = await self.backend.exec(
            _header_oid(self.name), "rbd", "snap_add", _enc({"name": snap}))
        if ret != 0:
            raise IOError(f"snap_create rc={ret}")
        await self.refresh()
        return _dec(out)

    async def snap_remove(self, snap: str) -> None:
        ret, _ = await self.backend.exec(
            _header_oid(self.name), "rbd", "snap_remove",
            _enc({"name": snap}))
        if ret != 0:
            raise IOError(f"snap_remove rc={ret}")
        await self.refresh()

    def snap_list(self) -> List[str]:
        return sorted(self.snaps)

    # -- exclusive lock (cls_lock-backed, ExclusiveLock role) --------------

    async def lock_acquire(self, cookie: str) -> None:
        ret, _ = await self.backend.exec(
            _header_oid(self.name), "lock", "lock",
            _enc({"name": "rbd_lock", "locker": cookie,
                  "type": "exclusive"}),
        )
        if ret == -16:
            raise BlockingIOError(f"image {self.name} is locked")
        if ret != 0:
            raise IOError(f"lock rc={ret}")

    async def lock_release(self, cookie: str) -> None:
        await self.backend.exec(
            _header_oid(self.name), "lock", "unlock",
            _enc({"name": "rbd_lock", "locker": cookie}),
        )

    async def watch_header(self, callback) -> None:
        """ImageWatcher role: get notified of header changes."""
        await self.backend.watch(_header_oid(self.name), callback)

    async def unwatch_header(self) -> None:
        await self.backend.unwatch(_header_oid(self.name))
