"""RBD object map + fast-diff (reference src/librbd/ObjectMap.cc,
src/cls/rbd/cls_rbd.cc object_map_* ops).

The object map tracks one state per data object of an image so the I/O
and diff paths can answer "does block N exist / did it change?" without
a round trip per object -- the feature that makes snapshots, clones and
mirroring cheap at scale.  States follow the reference's constants:

* ``OBJECT_NONEXISTENT`` (0) -- no data object;
* ``OBJECT_EXISTS`` (1) -- exists and was modified since the last
  snapshot (the fast-diff "dirty" state);
* ``OBJECT_PENDING`` (2) -- reserved (in-flight delete in the
  reference; unused here);
* ``OBJECT_EXISTS_CLEAN`` (3) -- exists, unmodified since the last
  snapshot (fast-diff).

Storage reduction (documented): one byte per object in a plain RADOS
object ``rbd_object_map.<image>[.<snap_id>]`` instead of the reference's
2-bit packing + cls-side update ops.  Semantics -- head map maintained
by the write path, a frozen per-snapshot copy taken at snap_create
BEFORE the dirty->clean sweep (so each snapshot map's EXISTS set is
exactly "modified since the previous snapshot", which is what fast-diff
unions) -- match the reference.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

OBJECT_NONEXISTENT = 0
OBJECT_EXISTS = 1
OBJECT_PENDING = 2
OBJECT_EXISTS_CLEAN = 3

FEATURE_OBJECT_MAP = "object-map"
FEATURE_FAST_DIFF = "fast-diff"


def map_oid(name: str, snap_id: Optional[int] = None) -> str:
    base = f"rbd_object_map.{name}"
    return f"{base}.{snap_id}" if snap_id is not None else base


class ObjectMap:
    """One image's (or one snapshot's) object-state map."""

    def __init__(self, backend, name: str,
                 snap_id: Optional[int] = None):
        self.backend = backend
        self.oid = map_oid(name, snap_id)
        self.states = bytearray()

    async def load(self, n_objects: int) -> None:
        try:
            raw = await self.backend.read(self.oid)
        except (FileNotFoundError, IOError):
            raw = b""
        self.states = bytearray(raw[:n_objects])
        if len(self.states) < n_objects:
            self.states += bytes(n_objects - len(self.states))

    async def save(self) -> None:
        """Full rewrite (resize / rebuild / snapshot sweep)."""
        await self.backend.write(self.oid, bytes(self.states))

    async def remove(self) -> None:
        try:
            await self.backend.remove_object(self.oid)
        except (FileNotFoundError, IOError):
            pass

    def state(self, object_no: int) -> int:
        if object_no >= len(self.states):
            return OBJECT_NONEXISTENT
        return self.states[object_no]

    def exists(self, object_no: int) -> bool:
        return self.state(object_no) in (OBJECT_EXISTS, OBJECT_EXISTS_CLEAN)

    async def update(self, object_no: int, state: int) -> None:
        """Point update, persisted only on a real transition (the steady
        state -- rewriting an already-EXISTS object -- costs nothing,
        the reference's ObjectMap::aio_update fast path)."""
        if object_no >= len(self.states):
            self.states += bytes(object_no + 1 - len(self.states))
        if self.states[object_no] == state:
            return
        self.states[object_no] = state
        await self.backend.write_range(
            self.oid, object_no, bytes([state]))

    def dirty_objects(self) -> List[int]:
        """Objects modified since the last snapshot (fast-diff)."""
        return [o for o, s in enumerate(self.states) if s == OBJECT_EXISTS]

    async def snapshot_to(self, snap_id: int) -> "ObjectMap":
        """Freeze the current state as the snapshot's map, then sweep
        EXISTS -> EXISTS_CLEAN in this (head) map -- the reference's
        object_map_snap_add + rbd::object_map::SnapshotCreateRequest."""
        name = self.oid[len("rbd_object_map."):]
        snap_map = ObjectMap(self.backend, name, snap_id)
        snap_map.states = bytearray(self.states)
        await snap_map.save()
        changed = False
        for o, s in enumerate(self.states):
            if s == OBJECT_EXISTS:
                self.states[o] = OBJECT_EXISTS_CLEAN
                changed = True
        if changed:
            await self.save()
        return snap_map

    async def resize(self, n_objects: int) -> None:
        if n_objects < len(self.states):
            self.states = self.states[:n_objects]
            await self.save()
        elif n_objects > len(self.states):
            self.states += bytes(n_objects - len(self.states))
            await self.save()


async def rebuild(backend, name: str, n_objects: int,
                  data_oid_fn) -> ObjectMap:
    """Reconstruct the head map by statting every data object (feature
    enable on an existing image / repair after out-of-band writes --
    the rbd_object_map_rebuild role, reference
    src/librbd/object_map/RebuildRequest.cc)."""
    m = ObjectMap(backend, name)
    m.states = bytearray(n_objects)
    for object_no in range(n_objects):
        try:
            size, hinfo = await backend.stat(data_oid_fn(object_no))
            present = not (size == 0 and hinfo is None)
        except (FileNotFoundError, IOError):
            present = False
        m.states[object_no] = OBJECT_EXISTS if present else OBJECT_NONEXISTENT
    await m.save()
    return m


async def fast_diff(backend, name: str, snaps: dict, head_map: ObjectMap,
                    object_size: int, image_size: int,
                    from_snap: Optional[str] = None,
                    ) -> List[Tuple[int, int, bool]]:
    """Changed extents since ``from_snap`` (None = since creation) from
    the object maps alone -- no data reads (the fast-diff promise;
    reference diff_iterate whole_object path over object map states).

    Returns [(offset, length, exists), ...] per changed object, where
    ``exists`` False marks an object deleted since the snapshot."""
    if from_snap is not None and from_snap not in snaps:
        raise FileNotFoundError(from_snap)
    from_id = snaps[from_snap]["id"] if from_snap is not None else 0

    async def read_map(snap_id):
        try:
            return await backend.read(map_oid(name, snap_id))
        except (FileNotFoundError, IOError):
            return b""

    changed = set()
    # each later snapshot map's EXISTS set = modified in its interval
    for ent in snaps.values():
        if ent["id"] <= from_id:
            continue
        for o, s in enumerate(await read_map(ent["id"])):
            if s == OBJECT_EXISTS:
                changed.add(o)
    changed.update(head_map.dirty_objects())
    if from_snap is None:
        # diff from empty: every currently-existing object counts
        for o in range(len(head_map.states)):
            if head_map.exists(o):
                changed.add(o)
        from_exists = {}
    else:
        raw = await read_map(from_id)
        from_exists = {
            o: s in (OBJECT_EXISTS, OBJECT_EXISTS_CLEAN)
            for o, s in enumerate(raw)
        }
        # existence flips (created/deleted across the span)
        for o in range(max(len(raw), len(head_map.states))):
            if head_map.exists(o) != from_exists.get(o, False):
                changed.add(o)
    out = []
    for o in sorted(changed):
        off = o * object_size
        exists = head_map.exists(o)
        if exists:
            if off >= image_size:
                continue  # map tail beyond the shrunk image
            length = min(object_size, image_size - off)
        else:
            length = object_size  # deleted block: its former span
        out.append((off, length, exists))
    return out
