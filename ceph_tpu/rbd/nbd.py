"""rbd-nbd: expose an RBD image as an NBD block export.

Reference: src/tools/rbd_nbd/rbd-nbd.cc -- the reference maps an image
into the kernel's nbd driver; here the same role is a standalone NBD
SERVER speaking the standard fixed-newstyle protocol, so any NBD client
(kernel nbd-client, qemu-nbd, nbdfuse) can attach an image as a block
device.  This also covers the rbd_fuse role (the other file/block
attachment surface) without requiring a FUSE runtime in the image.

Protocol per the canonical NBD spec (the same wire format
rbd-nbd.cc:307-340 services from the kernel side):

* handshake: ``NBDMAGIC`` + ``IHAVEOPT`` + handshake flags; client
  flags; option haggling (LIST / ABORT / EXPORT_NAME);
* transmission: 28-byte requests (magic 0x25609513) for
  READ/WRITE/DISC/FLUSH/TRIM, 16-byte simple replies (magic
  0x67446698).

WRITE and TRIM run through ``Image.write``/``Image.discard`` (snapshot
COW, object map, journaling all apply); FLUSH is a no-op acknowledgment
because every write is already durable at reply time (RADOS commit
semantics) -- the reference acks flush the same way after rbd_flush.
"""

from __future__ import annotations

import asyncio
import struct
from typing import Dict, Optional

from ceph_tpu.rbd.image import Image

NBDMAGIC = 0x4E42444D41474943        # "NBDMAGIC"
IHAVEOPT = 0x49484156454F5054        # "IHAVEOPT"
REP_MAGIC = 0x3E889045565A9

FLAG_FIXED_NEWSTYLE = 1 << 0
FLAG_NO_ZEROES = 1 << 1

OPT_EXPORT_NAME = 1
OPT_ABORT = 2
OPT_LIST = 3

REP_ACK = 1
REP_SERVER = 2
REP_ERR_UNSUP = (1 << 31) | 1

# transmission flags
FLAG_HAS_FLAGS = 1 << 0
FLAG_SEND_FLUSH = 1 << 2
FLAG_SEND_TRIM = 1 << 5

REQ_MAGIC = 0x25609513
REPLY_MAGIC = 0x67446698

CMD_READ = 0
CMD_WRITE = 1
CMD_DISC = 2
CMD_FLUSH = 3
CMD_TRIM = 4

EIO = 5
EINVAL = 22

#: largest request payload honored (the NBD spec's recommended cap;
#: without it a single 32-bit length field could make the server
#: buffer 4 GiB -- the dispatch-throttle class of problem)
MAX_PAYLOAD = 32 << 20

#: flow-control high-water mark: drain() is awaited only once this many
#: bytes sit unflushed on the transport.  Replies reach the wire
#: asynchronously as soon as they are written; a per-reply drain is one
#: coroutine round of pure overhead per request (the round-8 corked-
#: messenger discipline: drain is backpressure, not delivery)
DRAIN_HIWAT = 1 << 20


class NBDServer:
    """Serve the pool's RBD images over NBD (one export per image)."""

    def __init__(self, backend, host: str = "127.0.0.1", port: int = 0):
        self.backend = backend
        self.host = host
        self.port = port
        self._server: Optional[asyncio.AbstractServer] = None
        self._serve_tasks: set = set()
        #: requests served, by command name (introspection/test hook)
        self.stats: Dict[str, int] = {}

    async def start(self) -> int:
        self._server = await asyncio.start_server(
            self._serve, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        return self.port

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            # 3.12's wait_closed waits on live handlers: cancel attached
            # clients first (kernel nbd-clients hold the device open)
            for task in list(self._serve_tasks):
                task.cancel()
            for task in list(self._serve_tasks):
                try:
                    await task
                except (asyncio.CancelledError, Exception):
                    pass
            await self._server.wait_closed()

    async def _serve(self, reader: asyncio.StreamReader,
                     writer: asyncio.StreamWriter) -> None:
        task = asyncio.current_task()
        self._serve_tasks.add(task)
        try:
            await self._serve_inner(reader, writer)
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        finally:
            self._serve_tasks.discard(task)
            writer.close()

    @staticmethod
    async def _pace(writer) -> None:
        """Backpressure only: a slow or stalled client eventually fills
        the transport buffer and this parks the handler until it
        drains, bounding per-connection memory.  Everything below the
        high-water mark flushes asynchronously without costing a
        coroutine round per reply."""
        if writer.transport.get_write_buffer_size() >= DRAIN_HIWAT:
            await writer.drain()

    async def _serve_inner(self, reader, writer) -> None:
        # -- fixed-newstyle handshake --------------------------------------
        writer.write(struct.pack(
            ">QQH", NBDMAGIC, IHAVEOPT,
            FLAG_FIXED_NEWSTYLE | FLAG_NO_ZEROES))
        await writer.drain()
        (client_flags,) = struct.unpack(
            ">I", await reader.readexactly(4))
        img: Optional[Image] = None
        while img is None:
            magic, opt, datalen = struct.unpack(
                ">QII", await reader.readexactly(16))
            data = await reader.readexactly(datalen) if datalen else b""
            if magic != IHAVEOPT:
                return
            if opt == OPT_EXPORT_NAME:
                name = data.decode()
                try:
                    img = await Image.open(self.backend, name)
                except FileNotFoundError:
                    return  # EXPORT_NAME has no error reply: disconnect
                flags = FLAG_HAS_FLAGS | FLAG_SEND_FLUSH | FLAG_SEND_TRIM
                out = struct.pack(">QH", img.size, flags)
                if not client_flags & FLAG_NO_ZEROES:
                    out += bytes(124)
                writer.write(out)
                await self._pace(writer)
            elif opt == OPT_LIST:
                from ceph_tpu.rbd.image import RBD

                for name in await RBD(self.backend).list():
                    payload = struct.pack(">I", len(name)) + name.encode()
                    writer.write(struct.pack(
                        ">QIII", REP_MAGIC, opt, REP_SERVER, len(payload)
                    ) + payload)
                writer.write(struct.pack(">QIII", REP_MAGIC, opt,
                                         REP_ACK, 0))
                await self._pace(writer)
            elif opt == OPT_ABORT:
                writer.write(struct.pack(">QIII", REP_MAGIC, opt,
                                         REP_ACK, 0))
                return  # close() flushes the ack on the way out
            else:
                writer.write(struct.pack(">QIII", REP_MAGIC, opt,
                                         REP_ERR_UNSUP, 0))
                await self._pace(writer)

        # -- transmission phase --------------------------------------------
        while True:
            hdr = await reader.readexactly(28)
            magic, _flags, cmd, handle, offset, length = struct.unpack(
                ">IHHQQI", hdr)
            if magic != REQ_MAGIC:
                return
            if length > MAX_PAYLOAD:
                if cmd == CMD_WRITE:
                    return  # cannot resync past an absurd payload: drop
                writer.write(struct.pack(
                    ">IIQ", REPLY_MAGIC, EINVAL, handle))
                await self._pace(writer)
                continue
            payload = (await reader.readexactly(length)
                       if cmd == CMD_WRITE else b"")
            if cmd == CMD_DISC:
                self._count("disc")
                return
            err, out = 0, b""
            try:
                if cmd == CMD_READ:
                    self._count("read")
                    if offset + length > img.size:
                        err = EINVAL
                    else:
                        out = await img.read(offset, length)
                elif cmd == CMD_WRITE:
                    self._count("write")
                    if offset + length > img.size:
                        err = EINVAL
                    else:
                        await img.write(offset, payload)
                elif cmd == CMD_FLUSH:
                    self._count("flush")  # writes are already durable
                elif cmd == CMD_TRIM:
                    self._count("trim")
                    await img.discard(offset, length)
                else:
                    err = EINVAL
            except Exception:  # noqa: BLE001 -- a failed op answers EIO,
                # it must not kill the device (rbd-nbd.cc error path)
                err = EIO
            writer.write(struct.pack(">IIQ", REPLY_MAGIC, err, handle))
            if cmd == CMD_READ and not err:
                writer.write(out)
            await self._pace(writer)

    def _count(self, op: str) -> None:
        self.stats[op] = self.stats.get(op, 0) + 1
