"""rbd-replay: capture and replay RBD I/O workloads.

Reference: src/rbd_replay (~2.7k LoC) -- ``rbd-replay-prep`` turns an
LTTng trace of librbd calls into an action file; ``rbd-replay``
re-issues those actions against an image, preserving think time and
dependencies.  Here the capture side is a recording proxy around
``Image`` (the framework's librbd surface is async Python, so proxying
beats out-of-band tracing), producing a JSONL action file the replayer
re-issues with optional speed scaling.

Actions: {"ts": seconds-from-start, "op": ..., ...op fields...}.
"""

from __future__ import annotations

import asyncio
import base64
import json
import time
from typing import List, Optional

from ceph_tpu.rbd.image import Image


class RecordingImage:
    """Proxy that forwards to a real Image and appends each mutating or
    reading op to an in-memory trace (rbd-replay-prep's action list)."""

    def __init__(self, image: Image):
        self._img = image
        self.actions: List[dict] = []
        self._t0 = time.perf_counter()

    def _log(self, op: str, **fields) -> None:
        self.actions.append(
            dict({"ts": round(time.perf_counter() - self._t0, 6),
                  "op": op}, **fields))

    async def write(self, offset: int, data: bytes) -> None:
        self._log("write", off=offset,
                  data=base64.b64encode(bytes(data)).decode())
        await self._img.write(offset, data)

    async def read(self, offset: int, length: int) -> bytes:
        self._log("read", off=offset, len=length)
        return await self._img.read(offset, length)

    async def discard(self, offset: int, length: int) -> None:
        self._log("discard", off=offset, len=length)
        await self._img.discard(offset, length)

    async def resize(self, size: int) -> None:
        self._log("resize", size=size)
        await self._img.resize(size)

    async def snap_create(self, snap: str) -> int:
        self._log("snap_create", name=snap)
        return await self._img.snap_create(snap)

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            for a in self.actions:
                f.write(json.dumps(a) + "\n")


def load_trace(path: str) -> List[dict]:
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


async def replay(image: Image, actions: List[dict],
                 speed: float = 0.0) -> dict:
    """Re-issue a trace against ``image``.  ``speed`` > 0 preserves
    inter-op think time scaled by 1/speed (rbd-replay --pacing role);
    0 replays as fast as possible.  Returns op counts + elapsed."""
    counts: dict = {}
    t0 = time.perf_counter()
    prev_ts: Optional[float] = None
    for a in actions:
        if speed > 0 and prev_ts is not None:
            gap = (a["ts"] - prev_ts) / speed
            if gap > 0:
                await asyncio.sleep(gap)
        prev_ts = a["ts"]
        op = a["op"]
        counts[op] = counts.get(op, 0) + 1
        if op == "write":
            await image.write(a["off"], base64.b64decode(a["data"]))
        elif op == "read":
            await image.read(a["off"], a["len"])
        elif op == "discard":
            await image.discard(a["off"], a["len"])
        elif op == "resize":
            await image.resize(a["size"])
        elif op == "snap_create":
            try:
                await image.snap_create(a["name"])
            except IOError as e:
                # tolerate ONLY already-exists (-17): swallowing a real
                # failure would skip the COW point and diverge silently
                if "rc=-17" not in str(e):
                    raise
        else:
            raise ValueError(f"unknown trace op {op!r}")
    return {"ops": counts, "elapsed": time.perf_counter() - t0}
