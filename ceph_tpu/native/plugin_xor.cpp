// Native example plugin: libec_xor_native.so (k configurable, m=1 XOR).
//
// The native twin of the Python example plugin (reference fixture shape:
// src/test/erasure-code/ErasureCodePluginExample.cc); also the template for
// future native codec plugins.

#include "ec_plugin.h"

#include <cstdlib>
#include <cstring>
#include <new>

extern "C" {
void ec_region_xor(const uint8_t *const *srcs, int k, uint8_t *out, size_t n);
}

namespace {

int xor_encode(ec_codec *self, const uint8_t *const *data,
               uint8_t *const *coding, size_t chunk_len) {
  ec_region_xor(data, self->k, coding[0], chunk_len);
  return 0;
}

int xor_decode(ec_codec *self, uint8_t *const *chunks, const int *erased,
               size_t chunk_len) {
  int nerased = 0;
  int eid = -1;
  for (int i = 0; erased[i] != -1; ++i) {
    eid = erased[i];
    ++nerased;
  }
  if (nerased == 0) return 0;
  if (nerased > 1) return -1;  // m=1
  const uint8_t *srcs[256];
  int cnt = 0;
  for (int i = 0; i < self->k + self->m; ++i)
    if (i != eid) srcs[cnt++] = chunks[i];
  ec_region_xor(srcs, cnt, chunks[eid], chunk_len);
  return 0;
}

void xor_destroy(ec_codec *self) { delete self; }

ec_codec *xor_factory(const char *const *profile) {
  int k = 2;
  for (int i = 0; profile && profile[i]; ++i) {
    if (std::strncmp(profile[i], "k=", 2) == 0)
      k = std::atoi(profile[i] + 2);
  }
  if (k < 2) return nullptr;
  ec_codec *c = new (std::nothrow) ec_codec();
  if (!c) return nullptr;
  c->k = k;
  c->m = 1;
  c->priv = nullptr;
  c->encode = xor_encode;
  c->decode = xor_decode;
  c->destroy = xor_destroy;
  return c;
}

ec_plugin g_plugin = {"xor_native", xor_factory};

}  // namespace

extern "C" {

const char *__erasure_code_version() { return CEPH_TPU_EC_VERSION; }

int __erasure_code_init(const char *name, const char *dir) {
  (void)dir;
  return ec_registry_add(name, &g_plugin);
}

}  // extern "C"
