// Native regenerating-code plugin: libec_regen_native.so.
//
// C++ twin of the Python product-matrix MSR plugin (plugins/regen.py over
// matrices/product_matrix.py): d = 2k-2, alpha = k-1, every node stores
// alpha sub-chunks and the whole code linearizes to one systematic
// GF(2^8) generator over virtual rows (node i sub-chunk j = virtual row
// i*alpha+j).  Same field polynomial (0x11D), same evaluation-point
// selection and the same generator algebra as the Python construction,
// so chunks encoded here are bit-identical to the Python plugin's.

#include "ec_plugin.h"

#include <cstdlib>
#include <cstring>
#include <new>
#include <vector>

namespace {

// -- GF(2^8), poly x^8+x^4+x^3+x^2+1 (0x11D), generator x=2 ------------

struct GF8 {
  uint8_t exp[512];
  uint8_t log[256];
  GF8() {
    unsigned v = 1;
    for (int i = 0; i < 255; ++i) {
      exp[i] = static_cast<uint8_t>(v);
      log[v] = static_cast<uint8_t>(i);
      v <<= 1;
      if (v & 0x100) v ^= 0x11D;
    }
    for (int i = 255; i < 512; ++i) exp[i] = exp[i - 255];
    log[0] = 0;
  }
  uint8_t mul(uint8_t a, uint8_t b) const {
    if (!a || !b) return 0;
    return exp[log[a] + log[b]];
  }
  uint8_t inv(uint8_t a) const { return exp[255 - log[a]]; }
  uint8_t pow(uint8_t x, unsigned e) const {
    if (e == 0) return 1;
    if (x == 0) return 0;
    return exp[(log[x] * (e % 255)) % 255];
  }
};

const GF8 &gf() {
  static const GF8 field;
  return field;
}

using Mat = std::vector<std::vector<uint8_t>>;

Mat mat_mul(const Mat &a, const Mat &b) {
  const GF8 &f = gf();
  size_t n = a.size(), p = b.size(), m = b[0].size();
  Mat out(n, std::vector<uint8_t>(m, 0));
  for (size_t i = 0; i < n; ++i)
    for (size_t t = 0; t < p; ++t) {
      uint8_t c = a[i][t];
      if (!c) continue;
      for (size_t j = 0; j < m; ++j) out[i][j] ^= f.mul(c, b[t][j]);
    }
  return out;
}

// Gauss-Jordan inverse; false when singular
bool mat_invert(Mat m, Mat &out) {
  const GF8 &f = gf();
  size_t n = m.size();
  out.assign(n, std::vector<uint8_t>(n, 0));
  for (size_t i = 0; i < n; ++i) out[i][i] = 1;
  for (size_t col = 0; col < n; ++col) {
    size_t piv = col;
    while (piv < n && m[piv][col] == 0) ++piv;
    if (piv == n) return false;
    std::swap(m[piv], m[col]);
    std::swap(out[piv], out[col]);
    uint8_t d = f.inv(m[col][col]);
    for (size_t j = 0; j < n; ++j) {
      m[col][j] = f.mul(m[col][j], d);
      out[col][j] = f.mul(out[col][j], d);
    }
    for (size_t r = 0; r < n; ++r) {
      if (r == col || m[r][col] == 0) continue;
      uint8_t c = m[r][col];
      for (size_t j = 0; j < n; ++j) {
        m[r][j] ^= f.mul(c, m[col][j]);
        out[r][j] ^= f.mul(c, out[col][j]);
      }
    }
  }
  return true;
}

// -- product-matrix construction (mirrors matrices/product_matrix.py) --

struct Regen {
  int k, m, n, alpha;
  Mat generator;  // (m*alpha, k*alpha)
};

// n evaluation points with pairwise-distinct alpha-th powers, in the
// same iteration order as the Python _select_points
bool select_points(int n, int alpha, std::vector<uint8_t> &lam) {
  const GF8 &f = gf();
  bool seen[256] = {false};
  for (int x = 0; x < 256 && static_cast<int>(lam.size()) < n; ++x) {
    uint8_t p = f.pow(static_cast<uint8_t>(x), alpha);
    if (seen[p]) continue;
    seen[p] = true;
    lam.push_back(static_cast<uint8_t>(x));
  }
  return static_cast<int>(lam.size()) == n;
}

bool build_generator(Regen &rg) {
  const GF8 &f = gf();
  const int k = rg.k, n = rg.n, alpha = rg.alpha, B = k * alpha;
  std::vector<uint8_t> lam;
  if (!select_points(n, alpha, lam)) return false;
  // free-symbol slots: S1 then S2 upper triangles, symmetry folded
  std::vector<std::vector<int>> idx(2 * alpha, std::vector<int>(alpha));
  int slot = 0;
  for (int which = 0; which < 2; ++which)
    for (int i = 0; i < alpha; ++i)
      for (int j = i; j < alpha; ++j) {
        idx[which * alpha + i][j] = slot;
        idx[which * alpha + j][i] = slot;
        ++slot;
      }
  // A_i per node: alpha linear forms over the B free symbols
  Mat a_data(B, std::vector<uint8_t>(B, 0));
  Mat a_parity(rg.m * alpha, std::vector<uint8_t>(B, 0));
  for (int node = 0; node < n; ++node) {
    uint8_t la = f.pow(lam[node], alpha);
    for (int j = 0; j < alpha; ++j) {
      std::vector<uint8_t> &row = node < k ? a_data[node * alpha + j]
                                           : a_parity[(node - k) * alpha + j];
      for (int t = 0; t < alpha; ++t) {
        uint8_t c = f.pow(lam[node], t);  // phi[node][t]
        row[idx[t][j]] ^= c;
        row[idx[alpha + t][j]] ^= f.mul(la, c);
      }
    }
  }
  Mat inv;
  if (!mat_invert(a_data, inv)) return false;
  rg.generator = mat_mul(a_parity, inv);
  return true;
}

// -- vtable ------------------------------------------------------------

int regen_encode(ec_codec *self, const uint8_t *const *data,
                 uint8_t *const *coding, size_t chunk_len) {
  const GF8 &f = gf();
  const Regen *rg = static_cast<const Regen *>(self->priv);
  const int alpha = rg->alpha;
  if (chunk_len % alpha) return -1;  // need whole sub-chunks
  const size_t beta = chunk_len / alpha;
  for (int node = 0; node < rg->m; ++node)
    for (int j = 0; j < alpha; ++j) {
      uint8_t *out = coding[node] + j * beta;
      std::memset(out, 0, beta);
      const std::vector<uint8_t> &grow = rg->generator[node * alpha + j];
      for (int c = 0; c < rg->k * alpha; ++c) {
        uint8_t g = grow[c];
        if (!g) continue;
        const uint8_t *src = data[c / alpha] + (c % alpha) * beta;
        for (size_t b = 0; b < beta; ++b) out[b] ^= f.mul(g, src[b]);
      }
    }
  return 0;
}

int regen_decode(ec_codec *self, uint8_t *const *chunks, const int *erased,
                 size_t chunk_len) {
  const GF8 &f = gf();
  const Regen *rg = static_cast<const Regen *>(self->priv);
  const int k = rg->k, alpha = rg->alpha, kv = k * alpha;
  if (chunk_len % alpha) return -1;
  const size_t beta = chunk_len / alpha;
  bool gone[256] = {false};
  int nerased = 0;
  for (int i = 0; erased[i] != -1; ++i) {
    gone[erased[i]] = true;
    ++nerased;
  }
  if (nerased == 0) return 0;
  // first k whole surviving nodes; their stacked virtual rows are
  // invertible by the MDS property of the linearized code
  std::vector<int> src_nodes;
  for (int i = 0; i < rg->n && static_cast<int>(src_nodes.size()) < k; ++i)
    if (!gone[i]) src_nodes.push_back(i);
  if (static_cast<int>(src_nodes.size()) < k) return -1;
  Mat sel(kv, std::vector<uint8_t>(kv, 0));
  for (int r = 0; r < k; ++r) {
    int node = src_nodes[r];
    for (int j = 0; j < alpha; ++j) {
      if (node < k)
        sel[r * alpha + j][node * alpha + j] = 1;
      else
        sel[r * alpha + j] = rg->generator[(node - k) * alpha + j];
    }
  }
  Mat inv;
  if (!mat_invert(sel, inv)) return -1;
  // data virtual rows = inv @ stacked survivor rows
  std::vector<std::vector<uint8_t>> dvr(
      kv, std::vector<uint8_t>(beta, 0));
  for (int r = 0; r < kv; ++r)
    for (int c = 0; c < kv; ++c) {
      uint8_t g = inv[r][c];
      if (!g) continue;
      const uint8_t *src =
          chunks[src_nodes[c / alpha]] + (c % alpha) * beta;
      for (size_t b = 0; b < beta; ++b) dvr[r][b] ^= f.mul(g, src[b]);
    }
  for (int i = 0; erased[i] != -1; ++i) {
    int node = erased[i];
    for (int j = 0; j < alpha; ++j) {
      uint8_t *out = chunks[node] + j * beta;
      if (node < k) {
        std::memcpy(out, dvr[node * alpha + j].data(), beta);
      } else {
        std::memset(out, 0, beta);
        const std::vector<uint8_t> &grow =
            rg->generator[(node - k) * alpha + j];
        for (int c = 0; c < kv; ++c) {
          uint8_t g = grow[c];
          if (!g) continue;
          for (size_t b = 0; b < beta; ++b)
            out[b] ^= f.mul(g, dvr[c][b]);
        }
      }
    }
  }
  return 0;
}

void regen_destroy(ec_codec *self) {
  delete static_cast<Regen *>(self->priv);
  delete self;
}

ec_codec *regen_factory(const char *const *profile) {
  int k = 4, m = 3, w = 8, d = -1;
  const char *technique = nullptr;
  for (int i = 0; profile && profile[i]; ++i) {
    if (std::strncmp(profile[i], "k=", 2) == 0)
      k = std::atoi(profile[i] + 2);
    else if (std::strncmp(profile[i], "m=", 2) == 0)
      m = std::atoi(profile[i] + 2);
    else if (std::strncmp(profile[i], "w=", 2) == 0)
      w = std::atoi(profile[i] + 2);
    else if (std::strncmp(profile[i], "d=", 2) == 0)
      d = std::atoi(profile[i] + 2);
    else if (std::strncmp(profile[i], "technique=", 10) == 0)
      technique = profile[i] + 10;
  }
  // same validation surface as the Python plugin's -EINVAL parse
  if (w != 8) return nullptr;
  if (k < 2 || m < k - 1) return nullptr;
  if (d != -1 && d != 2 * k - 2) return nullptr;
  if (technique && std::strcmp(technique, "product_matrix") != 0)
    return nullptr;
  Regen *rg = new (std::nothrow) Regen();
  if (!rg) return nullptr;
  rg->k = k;
  rg->m = m;
  rg->n = k + m;
  rg->alpha = k - 1;
  if (!build_generator(*rg)) {
    delete rg;
    return nullptr;
  }
  ec_codec *c = new (std::nothrow) ec_codec();
  if (!c) {
    delete rg;
    return nullptr;
  }
  c->k = k;
  c->m = m;
  c->priv = rg;
  c->encode = regen_encode;
  c->decode = regen_decode;
  c->destroy = regen_destroy;
  return c;
}

ec_plugin g_plugin = {"regen_native", regen_factory};

}  // namespace

extern "C" {

const char *__erasure_code_version() { return CEPH_TPU_EC_VERSION; }

int __erasure_code_init(const char *name, const char *dir) {
  (void)dir;
  return ec_registry_add(name, &g_plugin);
}

}  // extern "C"
