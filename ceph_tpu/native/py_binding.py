"""Loader for the CPython C-API extension (the src/pybind role).

Builds ``_ec_native`` on demand (Makefile py_ext target) and imports it
from the build directory; the module binds the native kernels through
the C API proper -- PyArg_Parse / buffer protocol / GIL release --
rather than ctypes marshalling.
"""

from __future__ import annotations

import importlib.util
import os
import subprocess
import sysconfig

_DIR = os.path.dirname(os.path.abspath(__file__))


def load():
    suffix = sysconfig.get_config_var("EXT_SUFFIX")
    so = os.path.join(_DIR, f"_ec_native{suffix}")
    if not os.path.exists(so):
        subprocess.run(
            ["make", "-C", _DIR, "py_ext"], check=True, capture_output=True
        )
    spec = importlib.util.spec_from_file_location("_ec_native", so)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod
