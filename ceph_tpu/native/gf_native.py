"""ctypes bindings for the native CPU codec kernels (libec_kernels.so).

The native library plays the role of jerasure/gf-complete/isa-l in the
reference: the fast host-CPU path and the realistic CPU baseline that
bench.py compares the TPU engine against.  Builds lazily via make on first
import if the shared object is missing; API mirrors
ceph_tpu/ops/cpu_engine.py (bit-exact, enforced by tests).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import List

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "libec_kernels.so")


def _rebuild_and_load() -> ctypes.CDLL:
    """Rebuild, then dlopen through a UNIQUE path: dlopen caches by
    path within a process, so reloading the same filename after a
    rebuild would silently return the stale handle."""
    import shutil
    import tempfile

    subprocess.run(
        ["make", "-B", "-C", _DIR, "libec_kernels.so"],
        check=True, capture_output=True,
    )
    tmp = tempfile.NamedTemporaryFile(
        suffix=".so", prefix="libec_kernels-", delete=False
    )
    tmp.close()
    shutil.copyfile(_SO, tmp.name)
    return ctypes.CDLL(tmp.name)


def _load() -> ctypes.CDLL:
    # a present, current prebuilt library loads directly -- no toolchain
    # needed on deploy hosts; missing or stale (pre-arch-probe) builds
    # rebuild via make (dependency-tracked)
    if not os.path.exists(_SO):
        subprocess.run(
            ["make", "-C", _DIR, "libec_kernels.so"],
            check=True, capture_output=True,
        )
    lib = ctypes.CDLL(_SO)
    if not hasattr(lib, "ec_arch_probe"):
        lib = _rebuild_and_load()
    lib.ec_arch_probe.restype = ctypes.c_int
    lib.ec_arch_built.restype = ctypes.c_int
    # runtime feature gate (reference ceph_arch_probe): refuse a library
    # whose compile-time ISA the running CPU lacks -- e.g. an AVX2 build
    # copied to a pre-Haswell machine -- instead of SIGILL'ing later
    built, have = lib.ec_arch_built(), lib.ec_arch_probe()
    if built & ~have:
        raise OSError(
            f"native EC library needs CPU features 0x{built:x}, "
            f"CPU has 0x{have:x} (rebuild with 'make -C {_DIR}')"
        )
    lib.ec_gf8_mul_region.argtypes = [
        ctypes.c_uint8,
        ctypes.c_void_p,
        ctypes.c_void_p,
        ctypes.c_size_t,
        ctypes.c_int,
    ]
    lib.ec_region_xor.argtypes = [
        ctypes.POINTER(ctypes.c_void_p),
        ctypes.c_int,
        ctypes.c_void_p,
        ctypes.c_size_t,
    ]
    lib.ec_gf8_matrix_encode.argtypes = [
        ctypes.c_void_p,
        ctypes.c_int,
        ctypes.c_int,
        ctypes.POINTER(ctypes.c_void_p),
        ctypes.POINTER(ctypes.c_void_p),
        ctypes.c_size_t,
    ]
    lib.ec_bitmatrix_packet_encode.argtypes = [
        ctypes.c_void_p,
        ctypes.c_int,
        ctypes.c_int,
        ctypes.POINTER(ctypes.c_void_p),
        ctypes.POINTER(ctypes.c_void_p),
        ctypes.c_size_t,
    ]
    lib.ec_crc32c.restype = ctypes.c_uint32
    lib.ec_crc32c.argtypes = [
        ctypes.c_uint32,
        ctypes.c_void_p,
        ctypes.c_size_t,
    ]
    return lib


_lib = _load()


def cpu_features() -> dict:
    """Decoded runtime/build ISA flags (the src/arch introspection)."""
    have, built = _lib.ec_arch_probe(), _lib.ec_arch_built()
    names = {1: "sse4.2", 2: "avx", 4: "avx2", 8: "avx512f"}
    return {
        "cpu": [n for b, n in names.items() if have & b],
        "build": [n for b, n in names.items() if built & b],
    }


def _ptr_array(arrays) -> "ctypes.Array":
    ptrs = (ctypes.c_void_p * len(arrays))()
    for i, a in enumerate(arrays):
        ptrs[i] = a.ctypes.data_as(ctypes.c_void_p)
    return ptrs


def mul_region(c: int, region: np.ndarray, accum: np.ndarray | None = None) -> np.ndarray:
    region = np.ascontiguousarray(region, dtype=np.uint8)
    out = accum if accum is not None else np.zeros_like(region)
    _lib.ec_gf8_mul_region(
        c,
        region.ctypes.data_as(ctypes.c_void_p),
        out.ctypes.data_as(ctypes.c_void_p),
        region.size,
        1 if accum is not None else 0,
    )
    return out


def region_xor(srcs: list[np.ndarray]) -> np.ndarray:
    n = srcs[0].size
    out = np.empty(n, dtype=np.uint8)
    _lib.ec_region_xor(
        _ptr_array(srcs), len(srcs), out.ctypes.data_as(ctypes.c_void_p), n
    )
    return out


def matrix_encode(matrix: np.ndarray, data: np.ndarray, w: int = 8) -> np.ndarray:
    """GF(2^8) only; mirrors cpu_engine.matrix_encode for w=8."""
    if w != 8:
        raise NotImplementedError("native path supports w=8")
    matrix = np.ascontiguousarray(matrix, dtype=np.uint8)
    data = np.ascontiguousarray(data, dtype=np.uint8)
    m, k = matrix.shape
    n = data.shape[1]
    coding = np.zeros((m, n), dtype=np.uint8)
    _lib.ec_gf8_matrix_encode(
        matrix.ctypes.data_as(ctypes.c_void_p),
        k,
        m,
        _ptr_array([data[j] for j in range(k)]),
        _ptr_array([coding[i] for i in range(m)]),
        n,
    )
    return coding


def bitmatrix_packet_encode(
    bitmatrix: np.ndarray, rows: np.ndarray
) -> np.ndarray:
    bitmatrix = np.ascontiguousarray(bitmatrix, dtype=np.uint8)
    rows = np.ascontiguousarray(rows, dtype=np.uint8)
    r, c = bitmatrix.shape
    n = rows.shape[1]
    out = np.zeros((r, n), dtype=np.uint8)
    _lib.ec_bitmatrix_packet_encode(
        bitmatrix.ctypes.data_as(ctypes.c_void_p),
        r,
        c,
        _ptr_array([rows[j] for j in range(c)]),
        _ptr_array([out[i] for i in range(r)]),
        n,
    )
    return out


def crc32c(data: bytes | np.ndarray, crc: int = 0xFFFFFFFF) -> int:
    """crc32c-castagnoli with ceph's -1 initial value convention.

    Chains without a final xor-out (the ceph_crc32c convention), so
    ``crc32c(b, crc32c(a)) == crc32c(a + b)`` -- the messenger's
    scatter-gather framing folds a frame's crc over its part list with
    this identity instead of concatenating.
    """
    if type(data) is bytes:
        # ctypes passes an immutable bytes buffer directly (zero copy,
        # no numpy wrapper) -- the messenger crc's every frame, and the
        # wrapper overhead was 4x the call itself at 2 KiB
        return int(_lib.ec_crc32c(ctypes.c_uint32(crc), data, len(data)))
    # np.frombuffer wraps bytearray/contiguous memoryview without
    # copying (the old bytes(data) round-trip copied every buffer-protocol
    # input -- a full extra pass per framed payload)
    arr = np.frombuffer(data, dtype=np.uint8) if isinstance(
        data, (bytearray, memoryview)
    ) else np.ascontiguousarray(data, dtype=np.uint8)
    return int(
        _lib.ec_crc32c(
            ctypes.c_uint32(crc),
            arr.ctypes.data_as(ctypes.c_void_p),
            arr.size,
        )
    )


def crc32c_rows(chunks, crcs) -> List[int]:
    """Cumulative crc32c over many buffers in one tight FFI loop.

    Same semantics as ``[crc32c(c, v) for c, v in zip(chunks, crcs)]``
    but the per-call wrapper work (type dispatch, contiguity copy,
    ``c_void_p`` boxing) is hoisted out of the loop: the OSD commit path
    crc's k+m shard chunks per object, and at 2 KiB chunks the wrapper
    cost ~4x the crc itself (argtypes are declared, so the raw data
    address passes as ``c_void_p`` with no per-call boxing).
    """
    fn = _lib.ec_crc32c
    out = []
    for chunk, crc in zip(chunks, crcs):
        arr = chunk if isinstance(chunk, np.ndarray) else \
            np.frombuffer(chunk, dtype=np.uint8)
        if not arr.flags.c_contiguous:
            arr = np.ascontiguousarray(arr)
        out.append(int(fn(crc, arr.ctypes.data, arr.nbytes)))
    return out
