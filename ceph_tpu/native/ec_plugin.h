// Native erasure-code plugin ABI + registry (C++ twin of the Python
// registry in ceph_tpu/plugins/registry.py).
//
// Mirrors the reference's dlopen plugin protocol (reference:
// src/erasure-code/ErasureCodePlugin.h:24-27 C entry points,
// ErasureCodePlugin.cc:126-184 load/version handshake): a plugin is a
// shared object named libec_<name>.so exposing
//
//   const char *__erasure_code_version();       // must equal ours (-EXDEV)
//   int __erasure_code_init(const char *name, const char *dir);
//                                               // must register (-EBADF)
//
// The registry is a process singleton guarded by a mutex; codecs are
// exposed through a plain C vtable so both C++ callers and Python (ctypes)
// can drive them.

#ifndef CEPH_TPU_EC_PLUGIN_H
#define CEPH_TPU_EC_PLUGIN_H

#include <cstddef>
#include <cstdint>

#define CEPH_TPU_EC_VERSION "0.1.0"

extern "C" {

// codec vtable: a plugin's factory fills this in
struct ec_codec {
  int k;
  int m;
  void *priv;
  // encode: data[k] chunk pointers, coding[m] outputs, chunk_len bytes each
  int (*encode)(struct ec_codec *self, const uint8_t *const *data,
                uint8_t *const *coding, size_t chunk_len);
  // decode: chunks[k+m] pointers (erased ones writable, present read-only),
  // erased[] = ids terminated by -1
  int (*decode)(struct ec_codec *self, uint8_t *const *chunks,
                const int *erased, size_t chunk_len);
  void (*destroy)(struct ec_codec *self);
};

struct ec_plugin {
  const char *name;
  // factory: profile as NULL-terminated array of "key=value" strings
  struct ec_codec *(*factory)(const char *const *profile);
};

// registry API (exported by libec_registry.so)
int ec_registry_add(const char *name, struct ec_plugin *plugin);
struct ec_plugin *ec_registry_get(const char *name);
// load resolves <dir>/libec_<name>.so; returns 0 or -errno
// (-EXDEV version mismatch, -ENOENT missing entry point/file,
//  -EBADF loaded but did not register)
int ec_registry_load(const char *name, const char *dir);

// watchdog load: -ETIMEDOUT when the plugin hangs in dlopen/init
// (the ErasureCodePluginHangs failure mode; the stuck worker thread is
// detached -- it cannot be cancelled safely)
int ec_registry_load_timeout(const char *name, const char *dir,
                             int timeout_ms);
struct ec_codec *ec_registry_factory(const char *name, const char *dir,
                                     const char *const *profile);
const char *ec_registry_last_error(void);

}  // extern "C"

#endif  // CEPH_TPU_EC_PLUGIN_H
