/* _ec_native: CPython C-API binding to the native EC kernels.
 *
 * Reference role: src/pybind -- the reference ships real C-extension
 * bindings (Cython -> C API) over its native libraries rather than
 * ffi-style wrappers.  This module binds the hot native entry points
 * (crc32c, GF(2^8) region multiply-accumulate, region XOR) through
 * PyMethodDef/PyArg_Parse, releasing the GIL around the kernels.
 * Built by the native Makefile (py_ext target) against gf_kernels.cpp.
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stdint.h>
#include <string.h>

/* native kernels (gf_kernels.cpp, extern "C"); the Makefile compiles
 * this file with g++, so the declarations need the C linkage guard */
#ifdef __cplusplus
extern "C" {
#endif
extern uint32_t ec_crc32c(uint32_t crc, const uint8_t *data, size_t n);
extern void ec_gf8_mul_region(uint8_t c, const uint8_t *in, uint8_t *out,
                              size_t n, int accum);
extern void ec_region_xor(const uint8_t *const *srcs, int k, uint8_t *out,
                          size_t n);
extern int ec_arch_probe(void);
#ifdef __cplusplus
}
#endif

static PyObject *py_crc32c(PyObject *self, PyObject *args) {
  Py_buffer buf;
  unsigned int seed = 0xFFFFFFFFu;
  if (!PyArg_ParseTuple(args, "y*|I", &buf, &seed)) return NULL;
  uint32_t out;
  Py_BEGIN_ALLOW_THREADS
  out = ec_crc32c(seed, (const uint8_t *)buf.buf, (size_t)buf.len);
  Py_END_ALLOW_THREADS
  PyBuffer_Release(&buf);
  return PyLong_FromUnsignedLong(out);
}

static PyObject *py_gf8_mul_region(PyObject *self, PyObject *args) {
  unsigned char c;
  Py_buffer in;
  PyObject *accum_obj = Py_None;
  if (!PyArg_ParseTuple(args, "by*|O", &c, &in, &accum_obj)) return NULL;
  PyObject *out_bytes = PyBytes_FromStringAndSize(NULL, in.len);
  if (out_bytes == NULL) {
    PyBuffer_Release(&in);
    return NULL;
  }
  uint8_t *out = (uint8_t *)PyBytes_AS_STRING(out_bytes);
  int accum = 0;
  if (accum_obj != Py_None) {
    Py_buffer acc;
    if (PyObject_GetBuffer(accum_obj, &acc, PyBUF_SIMPLE) < 0) {
      /* acc is NOT initialized on failure: do not touch it */
      Py_DECREF(out_bytes);
      PyBuffer_Release(&in);
      return NULL; /* propagate the TypeError from GetBuffer */
    }
    if (acc.len != in.len) {
      PyBuffer_Release(&acc);
      Py_DECREF(out_bytes);
      PyBuffer_Release(&in);
      PyErr_SetString(PyExc_ValueError, "accum length mismatch");
      return NULL;
    }
    memcpy(out, acc.buf, (size_t)in.len);
    PyBuffer_Release(&acc);
    accum = 1;
  }
  Py_BEGIN_ALLOW_THREADS
  ec_gf8_mul_region(c, (const uint8_t *)in.buf, out, (size_t)in.len, accum);
  Py_END_ALLOW_THREADS
  PyBuffer_Release(&in);
  return out_bytes;
}

static PyObject *py_region_xor(PyObject *self, PyObject *args) {
  PyObject *seq;
  if (!PyArg_ParseTuple(args, "O", &seq)) return NULL;
  PyObject *fast = PySequence_Fast(seq, "expected a sequence of buffers");
  if (fast == NULL) return NULL;
  Py_ssize_t k = PySequence_Fast_GET_SIZE(fast);
  if (k < 1) {
    Py_DECREF(fast);
    PyErr_SetString(PyExc_ValueError, "need at least one source");
    return NULL;
  }
  Py_buffer *bufs = (Py_buffer *)PyMem_Malloc(sizeof(Py_buffer) * k);
  const uint8_t **ptrs =
      (const uint8_t **)PyMem_Malloc(sizeof(uint8_t *) * k);
  if (bufs == NULL || ptrs == NULL) {
    PyMem_Free(bufs);
    PyMem_Free(ptrs);
    Py_DECREF(fast);
    return PyErr_NoMemory();
  }
  PyObject *out_bytes = NULL;
  Py_ssize_t n = -1, got = 0;
  for (Py_ssize_t i = 0; i < k; ++i, ++got) {
    if (PyObject_GetBuffer(PySequence_Fast_GET_ITEM(fast, i), &bufs[i],
                           PyBUF_SIMPLE) < 0)
      goto fail;
    if (n < 0) n = bufs[i].len;
    if (bufs[i].len != n) {
      got++;
      PyErr_SetString(PyExc_ValueError, "source length mismatch");
      goto fail;
    }
    ptrs[i] = (const uint8_t *)bufs[i].buf;
  }
  out_bytes = PyBytes_FromStringAndSize(NULL, n);
  if (out_bytes == NULL) goto fail;
  Py_BEGIN_ALLOW_THREADS
  ec_region_xor(ptrs, (int)k, (uint8_t *)PyBytes_AS_STRING(out_bytes),
                (size_t)n);
  Py_END_ALLOW_THREADS
  for (Py_ssize_t i = 0; i < k; ++i) PyBuffer_Release(&bufs[i]);
  PyMem_Free(bufs);
  PyMem_Free(ptrs);
  Py_DECREF(fast);
  return out_bytes;
fail:
  for (Py_ssize_t i = 0; i < got; ++i) PyBuffer_Release(&bufs[i]);
  PyMem_Free(bufs);
  PyMem_Free(ptrs);
  Py_XDECREF(out_bytes);
  Py_DECREF(fast);
  return NULL;
}

static PyObject *py_arch_probe(PyObject *self, PyObject *args) {
  return PyLong_FromLong(ec_arch_probe());
}

static PyMethodDef Methods[] = {
    {"crc32c", py_crc32c, METH_VARARGS,
     "crc32c(data, seed=0xFFFFFFFF) -> int"},
    {"gf8_mul_region", py_gf8_mul_region, METH_VARARGS,
     "gf8_mul_region(c, data, accum=None) -> bytes (out (^)= c*data)"},
    {"region_xor", py_region_xor, METH_VARARGS,
     "region_xor([buf, ...]) -> bytes"},
    {"arch_probe", py_arch_probe, METH_NOARGS,
     "arch_probe() -> ISA feature bitmask"},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_ec_native",
    "C-API bindings to the native EC kernels", -1, Methods,
};

PyMODINIT_FUNC PyInit__ec_native(void) {
  return PyModule_Create(&moduledef);
}
