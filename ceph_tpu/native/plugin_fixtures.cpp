// Broken-plugin fixtures for registry failure-path tests (reference:
// src/test/erasure-code/ErasureCodePluginMissingEntryPoint.cc etc.).
// Compiled into several .so's selected by -D flags.

#include "ec_plugin.h"

#if defined(FIXTURE_MISSING_VERSION)
// no version symbol at all
extern "C" int __erasure_code_init(const char *, const char *) { return 0; }

#elif defined(FIXTURE_WRONG_VERSION)
extern "C" const char *__erasure_code_version() { return "an older version"; }
extern "C" int __erasure_code_init(const char *, const char *) { return 0; }

#elif defined(FIXTURE_MISSING_ENTRY_POINT)
extern "C" const char *__erasure_code_version() { return CEPH_TPU_EC_VERSION; }
// no init symbol

#elif defined(FIXTURE_FAIL_TO_INITIALIZE)
extern "C" const char *__erasure_code_version() { return CEPH_TPU_EC_VERSION; }
extern "C" int __erasure_code_init(const char *, const char *) { return -3; }

#elif defined(FIXTURE_FAIL_TO_REGISTER)
extern "C" const char *__erasure_code_version() { return CEPH_TPU_EC_VERSION; }
extern "C" int __erasure_code_init(const char *, const char *) { return 0; }

#elif defined(FIXTURE_HANGS)
// hangs inside the load path forever (the ErasureCodePluginHangs role:
// the reference's fixture sleeps in dlopen; hanging in init exercises
// the same watchdog contract)
#include <unistd.h>
extern "C" const char *__erasure_code_version() { return CEPH_TPU_EC_VERSION; }
extern "C" int __erasure_code_init(const char *, const char *) {
  for (;;) sleep(3600);
  return 0;
}
#endif
