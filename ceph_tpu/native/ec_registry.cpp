// Native plugin registry implementation (see ec_plugin.h).
//
// Reference behavior being mirrored: ErasureCodePluginRegistry::load
// (src/erasure-code/ErasureCodePlugin.cc:126-184): dlopen, version symbol
// check (mismatch -> -EXDEV), init entry point (missing -> -ENOENT, error
// propagates), registered-check (-EBADF), mutex-guarded singleton state.

#include "ec_plugin.h"

#include <dlfcn.h>
#include <cerrno>
#include <chrono>
#include <condition_variable>
#include <cstdio>
#include <cstring>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <thread>

namespace {

std::mutex g_lock;
std::map<std::string, ec_plugin *> g_plugins;
thread_local std::string g_last_error;

void set_error(const std::string &msg) { g_last_error = msg; }

}  // namespace

extern "C" {

const char *ec_registry_last_error(void) { return g_last_error.c_str(); }

int ec_registry_add(const char *name, struct ec_plugin *plugin) {
  std::lock_guard<std::mutex> l(g_lock);
  if (g_plugins.count(name)) {
    set_error(std::string(name) + " already registered");
    return -EEXIST;
  }
  g_plugins[name] = plugin;
  return 0;
}

struct ec_plugin *ec_registry_get(const char *name) {
  std::lock_guard<std::mutex> l(g_lock);
  auto it = g_plugins.find(name);
  return it == g_plugins.end() ? nullptr : it->second;
}

int ec_registry_load(const char *name, const char *dir) {
  {
    std::lock_guard<std::mutex> l(g_lock);
    if (g_plugins.count(name)) return 0;
  }
  std::string path = std::string(dir) + "/libec_" + name + ".so";
  void *handle = dlopen(path.c_str(), RTLD_NOW);
  if (!handle) {
    set_error(std::string("dlopen(") + path + "): " + dlerror());
    return -ENOENT;
  }
  using version_fn = const char *(*)();
  auto version =
      reinterpret_cast<version_fn>(dlsym(handle, "__erasure_code_version"));
  if (!version) {
    set_error(std::string(name) +
              " plugin has no version (loaded from an older version?)");
    dlclose(handle);
    return -EXDEV;
  }
  if (std::strcmp(version(), CEPH_TPU_EC_VERSION) != 0) {
    set_error(std::string(name) + " version " + version() +
              " != expected " CEPH_TPU_EC_VERSION);
    dlclose(handle);
    return -EXDEV;
  }
  using init_fn = int (*)(const char *, const char *);
  auto init =
      reinterpret_cast<init_fn>(dlsym(handle, "__erasure_code_init"));
  if (!init) {
    set_error(std::string(name) + " plugin is missing the entry point");
    dlclose(handle);
    return -ENOENT;
  }
  int r = init(name, dir);
  if (r < 0) {
    set_error(std::string(name) + " init failed");
    dlclose(handle);
    return r;
  }
  {
    std::lock_guard<std::mutex> l(g_lock);
    if (!g_plugins.count(name)) {
      set_error(std::string(name) +
                " initialized but did not register itself");
      dlclose(handle);
      return -EBADF;
    }
  }
  // handle intentionally kept open (disable_dlclose semantics: plugins
  // stay mapped for the process lifetime, reference ErasureCodePlugin.h:49)
  return 0;
}

int ec_registry_load_timeout(const char *name, const char *dir,
                             int timeout_ms) {
  // The reference's "plugin hangs in dlopen" failure mode
  // (src/test/erasure-code/ErasureCodePluginHangs.cc): a load that
  // never returns must not wedge the daemon.  Run the load on a worker
  // thread and give up at the deadline; the worker stays detached (a
  // thread stuck inside dlopen/init cannot be cancelled safely), the
  // caller treats the plugin as failed and carries on.
  struct State {
    std::mutex m;
    std::condition_variable cv;
    bool done = false;
    int rc = 0;
    std::string error;  // g_last_error is thread_local: the worker's
                        // message must travel back explicitly
  };
  auto st = std::make_shared<State>();
  std::string n = name, d = dir;
  std::thread([st, n, d]() {
    int r = ec_registry_load(n.c_str(), d.c_str());
    std::lock_guard<std::mutex> l(st->m);
    st->rc = r;
    st->error = g_last_error;
    st->done = true;
    st->cv.notify_all();
  }).detach();
  std::unique_lock<std::mutex> l(st->m);
  if (!st->cv.wait_for(l, std::chrono::milliseconds(timeout_ms),
                       [&] { return st->done; })) {
    set_error(std::string(name) + " load timed out (hung in dlopen/init)");
    return -ETIMEDOUT;
  }
  if (st->rc < 0) set_error(st->error);
  return st->rc;
}

struct ec_codec *ec_registry_factory(const char *name, const char *dir,
                                     const char *const *profile) {
  if (!ec_registry_get(name)) {
    int r = ec_registry_load(name, dir);
    if (r < 0) return nullptr;
  }
  ec_plugin *plugin = ec_registry_get(name);
  if (!plugin) return nullptr;
  return plugin->factory(profile);
}

}  // extern "C"
