/* _wire_native: CPython C-API batched wire codec (the round-20 native
 * framing core, ROADMAP item 2).
 *
 * Reference role: src/msg/async frame assembly + src/messages codecs --
 * the reference serializes every message through compiled C++; here the
 * measured Python wire tax (encode 14-15% + decode_body 16-17% +
 * envelope 4% of the saturated cluster-path wall, PERF_NOTES r19) moves
 * into one C pass per direction:
 *
 *   encode_entry(head, seq, msg) composes a whole MSG payload -- the
 *     kind|src|dst head, seq/length varints and the typed body -- as a
 *     scatter-gather part list with the frame crc folded in the same
 *     pass (large payload blobs are REFERENCED, never copied; small
 *     runs join into single buffers);
 *   seal_frames(entries, ack) seals a whole cork-queue batch: frame
 *     headers + piggyback-ack tail composed natively, cached payload
 *     crcs extended (never recomputed) over the tail;
 *   parse_burst(buf, pos) scans every complete frame in a received
 *     burst -- magic/length/crc validated in ONE GIL-released pass;
 *   decode_msg(rec, off) / decode_body(body) parse the envelope tail
 *     and the typed body straight from the record buffer.
 *
 * Bit-exactness contract: the byte stream is identical to the pure
 * Python codec in ceph_tpu/msg/wire.py + utils/encoding.py (property-
 * tested both directions in tests/test_wire_native.py).  Any value
 * outside the implemented model raises FallbackError and the caller
 * re-encodes that message through the Python codec -- graceful
 * degradation at message granularity, never a wire difference.
 *
 * Message types are Python dataclasses: the loader registers them via
 * register() (no imports here -- the module stays cycle-free), and
 * decode constructs instances through the same constructors the Python
 * codec calls.  Built by the native Makefile (wire_ext target) against
 * gf_kernels.cpp for crc32c.
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stdint.h>
#include <string.h>
#include <structmember.h>
#include <time.h>

#ifdef __cplusplus
extern "C" {
#endif
extern uint32_t ec_crc32c(uint32_t crc, const uint8_t *data, size_t n);
#ifdef __cplusplus
}
#endif

#define MAGIC 0xCE9B10C5u
#define CRC_SEED 0xFFFFFFFFu
/* payload blobs at or above this stay scatter-gather (referenced); the
 * utils/encoding.Encoder.parts "small" threshold */
#define SCATTER 4096
/* whole payloads at or below this join into one buffer (msg/tcp.py
 * _JOIN_BELOW: a short memcpy beats per-part bookkeeping) */
#define JOIN_BELOW 4096

/* value tags (utils/encoding.py) */
enum {
  WT_NONE = 0, WT_FALSE = 1, WT_TRUE = 2, WT_INT = 3, WT_NEGINT = 4,
  WT_BYTES = 5, WT_STR = 6, WT_LIST = 7, WT_DICT = 8, WT_TUPLE = 9,
  WT_FLOAT = 10,
};

/* message kind bytes (msg/wire.py) */
enum {
  MSG_VALUE = 0, MSG_EC_SUB_WRITE = 1, MSG_EC_SUB_WRITE_REPLY = 2,
  MSG_EC_SUB_READ = 3, MSG_EC_SUB_READ_REPLY = 4, MSG_MGR_BEACON = 5,
  MSG_MGR_REPORT = 6,
};

/* -- module state ---------------------------------------------------------- */

static PyObject *FallbackError;   /* "re-encode via the Python codec" */
static PyObject *Unknown;         /* sentinel: unknown inbound frame kind */

/* registered dataclass types (borrowed semantics: we own one ref each) */
static PyObject *cls_sub_write, *cls_sub_write_reply, *cls_sub_read,
    *cls_sub_read_reply, *cls_transaction, *cls_txn_op, *cls_log_entry,
    *cls_mgr_beacon, *cls_mgr_report, *cls_np_integer;

/* interned attribute / kwarg names */
static PyObject *s_from_shard, *s_tid, *s_oid, *s_transaction,
    *s_at_version, *s_log_entries, *s_op_class, *s_rollback,
    *s_prev_version, *s_reqid, *s_trace, *s_qos_class, *s_committed,
    *s_applied, *s_current_version, *s_missed, *s_to_read,
    *s_attrs_to_read, *s_subchunks, *s_buffers_read, *s_attrs_read,
    *s_errors, *s_name, *s_seq, *s_interval, *s_stats, *s_lag_ms,
    *s_ops, *s_op, *s_offset, *s_data, *s_attr_name, *s_attr_value,
    *s_version, *s_prior_size, *s_parts, *s_crc, *s_regen;
static PyObject *empty_tuple;

/* -- output emitter -------------------------------------------------------- */

typedef struct {
  PyObject *parts;   /* list of finished output buffers */
  uint8_t *buf;      /* accumulating small-run buffer */
  size_t len, cap;
  size_t total;      /* bytes emitted so far (runs + refs) */
} Emit;

static int emit_init(Emit *e) {
  e->parts = PyList_New(0);
  if (e->parts == NULL) return -1;
  e->cap = 512;
  e->buf = (uint8_t *)PyMem_Malloc(e->cap);
  if (e->buf == NULL) {
    Py_CLEAR(e->parts);
    PyErr_NoMemory();
    return -1;
  }
  e->len = 0;
  e->total = 0;
  return 0;
}

static void emit_free(Emit *e) {
  PyMem_Free(e->buf);
  e->buf = NULL;
  Py_CLEAR(e->parts);
}

static int emit_flush_run(Emit *e) {
  PyObject *run;
  if (e->len == 0) return 0;
  run = PyBytes_FromStringAndSize((const char *)e->buf, (Py_ssize_t)e->len);
  if (run == NULL) return -1;
  if (PyList_Append(e->parts, run) < 0) {
    Py_DECREF(run);
    return -1;
  }
  Py_DECREF(run);
  e->len = 0;
  return 0;
}

static int emit_raw(Emit *e, const void *data, size_t n) {
  if (e->len + n > e->cap) {
    size_t cap = e->cap;
    uint8_t *nbuf;
    while (e->len + n > cap) cap *= 2;
    nbuf = (uint8_t *)PyMem_Realloc(e->buf, cap);
    if (nbuf == NULL) {
      PyErr_NoMemory();
      return -1;
    }
    e->buf = nbuf;
    e->cap = cap;
  }
  memcpy(e->buf + e->len, data, n);
  e->len += n;
  e->total += n;
  return 0;
}

static int emit_u8(Emit *e, uint8_t b) { return emit_raw(e, &b, 1); }

static int emit_varint(Emit *e, uint64_t v) {
  uint8_t out[10];
  int n = 0;
  for (;;) {
    uint8_t b = (uint8_t)(v & 0x7F);
    v >>= 7;
    if (v) {
      out[n++] = b | 0x80;
    } else {
      out[n++] = b;
      break;
    }
  }
  return emit_raw(e, out, (size_t)n);
}

/* reference a bytes object as its own scatter part (zero copy) */
static int emit_ref(Emit *e, PyObject *bytes_obj) {
  if (emit_flush_run(e) < 0) return -1;
  if (PyList_Append(e->parts, bytes_obj) < 0) return -1;
  e->total += (size_t)PyBytes_GET_SIZE(bytes_obj);
  return 0;
}

/* length-prefixed blob: big immutable bytes are referenced, everything
 * else (and small bytes) copies into the run -- Encoder.blob + parts() */
static int emit_blob(Emit *e, PyObject *obj) {
  if (PyBytes_Check(obj)) {
    Py_ssize_t n = PyBytes_GET_SIZE(obj);
    if (emit_varint(e, (uint64_t)n) < 0) return -1;
    if (n >= SCATTER) return emit_ref(e, obj);
    return emit_raw(e, PyBytes_AS_STRING(obj), (size_t)n);
  }
  if (PyByteArray_Check(obj)) {
    Py_ssize_t n = PyByteArray_GET_SIZE(obj);
    if (emit_varint(e, (uint64_t)n) < 0) return -1;
    return emit_raw(e, PyByteArray_AS_STRING(obj), (size_t)n);
  }
  if (PyObject_CheckBuffer(obj)) {
    Py_buffer view;
    int rc;
    if (PyObject_GetBuffer(obj, &view, PyBUF_SIMPLE) < 0) return -1;
    rc = emit_varint(e, (uint64_t)view.len);
    if (rc == 0) rc = emit_raw(e, view.buf, (size_t)view.len);
    PyBuffer_Release(&view);
    return rc;
  }
  PyErr_SetString(FallbackError, "unbloblable object");
  return -1;
}

static int emit_string(Emit *e, PyObject *str) {
  Py_ssize_t n;
  const char *utf8;
  if (!PyUnicode_Check(str)) {
    PyErr_SetString(FallbackError, "expected str");
    return -1;
  }
  utf8 = PyUnicode_AsUTF8AndSize(str, &n);
  if (utf8 == NULL) return -1;
  if (emit_varint(e, (uint64_t)n) < 0) return -1;
  return emit_raw(e, utf8, (size_t)n);
}

/* -- value encoder (Encoder.value, exact tag/order semantics) -------------- */

static int emit_value(Emit *e, PyObject *v);

static int emit_long(Emit *e, PyObject *v) {
  int overflow = 0;
  long long sv = PyLong_AsLongLongAndOverflow(v, &overflow);
  if (sv == -1 && PyErr_Occurred()) return -1;
  if (overflow > 0) {
    /* positive past 63 bits: still fits the unsigned varint */
    uint64_t uv = PyLong_AsUnsignedLongLong(v);
    if (uv == (uint64_t)-1 && PyErr_Occurred()) {
      /* arbitrary precision: the Python encoder handles it */
      PyErr_Clear();
      PyErr_SetString(FallbackError, "int wider than 64 bits");
      return -1;
    }
    if (emit_u8(e, WT_INT) < 0) return -1;
    return emit_varint(e, uv);
  }
  if (overflow < 0) {
    PyErr_SetString(FallbackError, "int wider than 64 bits");
    return -1;
  }
  if (sv >= 0) {
    if (emit_u8(e, WT_INT) < 0) return -1;
    return emit_varint(e, (uint64_t)sv);
  }
  if (emit_u8(e, WT_NEGINT) < 0) return -1;
  return emit_varint(e, (uint64_t)(-(sv + 1)) + 1);
}

static int emit_seq_items(Emit *e, PyObject *seq, uint8_t tag) {
  PyObject *fast = PySequence_Fast(seq, "expected a sequence");
  Py_ssize_t i, n;
  if (fast == NULL) return -1;
  n = PySequence_Fast_GET_SIZE(fast);
  if (emit_u8(e, tag) < 0 || emit_varint(e, (uint64_t)n) < 0) {
    Py_DECREF(fast);
    return -1;
  }
  for (i = 0; i < n; ++i) {
    if (emit_value(e, PySequence_Fast_GET_ITEM(fast, i)) < 0) {
      Py_DECREF(fast);
      return -1;
    }
  }
  Py_DECREF(fast);
  return 0;
}

static int emit_dict(Emit *e, PyObject *d) {
  PyObject *key, *val;
  Py_ssize_t pos = 0;
  if (emit_u8(e, WT_DICT) < 0) return -1;
  if (emit_varint(e, (uint64_t)PyDict_GET_SIZE(d)) < 0) return -1;
  while (PyDict_Next(d, &pos, &key, &val)) {
    if (!PyUnicode_Check(key)) {
      /* the Python encoder raises TypeError here -- same contract */
      /* cephlint: disable-next-line=native-missing-fallback */
      PyErr_Format(PyExc_TypeError, "dict keys must be str, got %R",
                   (PyObject *)Py_TYPE(key));
      return -1;
    }
    if (emit_string(e, key) < 0) return -1;
    if (emit_value(e, val) < 0) return -1;
  }
  return 0;
}

static int emit_value(Emit *e, PyObject *v) {
  int rc;
  if (v == Py_None) return emit_u8(e, WT_NONE);
  if (v == Py_True) return emit_u8(e, WT_TRUE);
  if (v == Py_False) return emit_u8(e, WT_FALSE);
  if (PyLong_Check(v)) return emit_long(e, v);
  if (PyBytes_Check(v)) {
    if (emit_u8(e, WT_BYTES) < 0) return -1;
    return emit_blob(e, v);
  }
  if (PyUnicode_Check(v)) {
    if (emit_u8(e, WT_STR) < 0) return -1;
    return emit_string(e, v);
  }
  if (PyFloat_Check(v)) {
    double d = PyFloat_AS_DOUBLE(v);
    uint8_t le[8];
#if defined(__BYTE_ORDER__) && __BYTE_ORDER__ == __ORDER_BIG_ENDIAN__
    {
      const uint8_t *p = (const uint8_t *)&d;
      int i;
      for (i = 0; i < 8; ++i) le[i] = p[7 - i];
    }
#else
    memcpy(le, &d, 8);
#endif
    if (emit_u8(e, WT_FLOAT) < 0) return -1;
    return emit_raw(e, le, 8);
  }
  if (PyTuple_Check(v)) return emit_seq_items(e, v, WT_TUPLE);
  if (PyList_Check(v)) return emit_seq_items(e, v, WT_LIST);
  if (PyDict_Check(v)) return emit_dict(e, v);
  if (PyByteArray_Check(v) || PyMemoryView_Check(v)) {
    if (emit_u8(e, WT_BYTES) < 0) return -1;
    return emit_blob(e, v);
  }
  if (cls_np_integer != NULL &&
      (rc = PyObject_IsInstance(v, cls_np_integer)) != 0) {
    PyObject *as_int;
    if (rc < 0) return -1;
    as_int = PyNumber_Index(v);
    if (as_int == NULL) return -1;
    rc = emit_long(e, as_int);
    Py_DECREF(as_int);
    return rc;
  }
  PyErr_Format(FallbackError, "unencodable type %R", (PyObject *)Py_TYPE(v));
  return -1;
}

/* ``tuple(x) if isinstance(x, (tuple, list)) else x`` / the list twin:
 * the wire.py normalizations for version/reqid/trace fields */
static int emit_value_seq_normalized(Emit *e, PyObject *v, uint8_t tag) {
  if (PyTuple_Check(v) || PyList_Check(v)) return emit_seq_items(e, v, tag);
  return emit_value(e, v);
}

/* ``{k: [tuple(x) for x in v] for k, v in d.items()}`` -- the extent-map
 * normalization (ECSubRead to_read/subchunks) without building temps */
static int emit_extent_map(Emit *e, PyObject *d) {
  PyObject *key, *val;
  Py_ssize_t pos = 0;
  if (!PyDict_Check(d)) {
    PyErr_SetString(FallbackError, "extent map is not a dict");
    return -1;
  }
  if (emit_u8(e, WT_DICT) < 0) return -1;
  if (emit_varint(e, (uint64_t)PyDict_GET_SIZE(d)) < 0) return -1;
  while (PyDict_Next(d, &pos, &key, &val)) {
    PyObject *fast;
    Py_ssize_t i, n;
    if (!PyUnicode_Check(key)) {
      /* the Python encoder raises TypeError here -- same contract */
      /* cephlint: disable-next-line=native-missing-fallback */
      PyErr_Format(PyExc_TypeError, "dict keys must be str, got %R",
                   (PyObject *)Py_TYPE(key));
      return -1;
    }
    if (emit_string(e, key) < 0) return -1;
    fast = PySequence_Fast(val, "extent list expected");
    if (fast == NULL) return -1;
    n = PySequence_Fast_GET_SIZE(fast);
    if (emit_u8(e, WT_LIST) < 0 || emit_varint(e, (uint64_t)n) < 0) {
      Py_DECREF(fast);
      return -1;
    }
    for (i = 0; i < n; ++i) {
      if (emit_value_seq_normalized(
              e, PySequence_Fast_GET_ITEM(fast, i), WT_TUPLE) < 0) {
        Py_DECREF(fast);
        return -1;
      }
    }
    Py_DECREF(fast);
  }
  return 0;
}

/* ``{k: [(off, bytes(b)) for off, b in v] ...}`` -- ECSubReadReply
 * buffers_read normalization */
static int emit_buffers_read(Emit *e, PyObject *d) {
  PyObject *key, *val;
  Py_ssize_t pos = 0;
  if (!PyDict_Check(d)) {
    PyErr_SetString(FallbackError, "buffers_read is not a dict");
    return -1;
  }
  if (emit_u8(e, WT_DICT) < 0) return -1;
  if (emit_varint(e, (uint64_t)PyDict_GET_SIZE(d)) < 0) return -1;
  while (PyDict_Next(d, &pos, &key, &val)) {
    PyObject *fast;
    Py_ssize_t i, n;
    if (!PyUnicode_Check(key)) {
      /* the Python encoder raises TypeError here -- same contract */
      /* cephlint: disable-next-line=native-missing-fallback */
      PyErr_Format(PyExc_TypeError, "dict keys must be str, got %R",
                   (PyObject *)Py_TYPE(key));
      return -1;
    }
    if (emit_string(e, key) < 0) return -1;
    fast = PySequence_Fast(val, "buffer list expected");
    if (fast == NULL) return -1;
    n = PySequence_Fast_GET_SIZE(fast);
    if (emit_u8(e, WT_LIST) < 0 || emit_varint(e, (uint64_t)n) < 0) {
      Py_DECREF(fast);
      return -1;
    }
    for (i = 0; i < n; ++i) {
      PyObject *pair = PySequence_Fast_GET_ITEM(fast, i);
      PyObject *off, *b;
      if (!PyTuple_Check(pair) && !PyList_Check(pair)) {
        Py_DECREF(fast);
        PyErr_SetString(FallbackError, "buffer pair shape");
        return -1;
      }
      if (PySequence_Size(pair) != 2) {
        Py_DECREF(fast);
        PyErr_SetString(FallbackError, "buffer pair shape");
        return -1;
      }
      off = PySequence_GetItem(pair, 0);
      b = PySequence_GetItem(pair, 1);
      if (off == NULL || b == NULL ||
          emit_u8(e, WT_TUPLE) < 0 || emit_varint(e, 2) < 0 ||
          emit_value(e, off) < 0 ||
          emit_u8(e, WT_BYTES) < 0 || emit_blob(e, b) < 0) {
        Py_XDECREF(off);
        Py_XDECREF(b);
        Py_DECREF(fast);
        return -1;
      }
      Py_DECREF(off);
      Py_DECREF(b);
    }
    Py_DECREF(fast);
  }
  return 0;
}

/* -- typed body encoders (msg/wire.py message_encoder) --------------------- */

/* fetch msg.<attr>, emit through fn, drop the ref; -1 on error */
#define GET(obj, name, into)                          \
  do {                                                \
    (into) = PyObject_GetAttr((obj), (name));         \
    if ((into) == NULL) return -1;                    \
  } while (0)

static int emit_attr_varint(Emit *e, PyObject *msg, PyObject *name) {
  PyObject *v;
  uint64_t uv;
  GET(msg, name, v);
  uv = PyLong_AsUnsignedLongLong(v);
  if (uv == (uint64_t)-1 && PyErr_Occurred()) {
    Py_DECREF(v);
    /* negative / non-int field: the Python encoder would assert */
    PyErr_Clear();
    PyErr_SetString(FallbackError, "varint field out of range");
    return -1;
  }
  Py_DECREF(v);
  return emit_varint(e, uv);
}

static int emit_attr_string(Emit *e, PyObject *msg, PyObject *name) {
  PyObject *v;
  int rc;
  GET(msg, name, v);
  rc = emit_string(e, v);
  Py_DECREF(v);
  return rc;
}

static int emit_attr_value(Emit *e, PyObject *msg, PyObject *name) {
  PyObject *v;
  int rc;
  GET(msg, name, v);
  rc = emit_value(e, v);
  Py_DECREF(v);
  return rc;
}

static int emit_attr_value_norm(Emit *e, PyObject *msg, PyObject *name,
                                uint8_t tag) {
  PyObject *v;
  int rc;
  GET(msg, name, v);
  rc = emit_value_seq_normalized(e, v, tag);
  Py_DECREF(v);
  return rc;
}

static int emit_transaction(Emit *e, PyObject *txn) {
  PyObject *ops, *fast;
  Py_ssize_t i, n;
  GET(txn, s_ops, ops);
  fast = PySequence_Fast(ops, "transaction ops");
  Py_DECREF(ops);
  if (fast == NULL) return -1;
  n = PySequence_Fast_GET_SIZE(fast);
  if (emit_varint(e, (uint64_t)n) < 0) {
    Py_DECREF(fast);
    return -1;
  }
  for (i = 0; i < n; ++i) {
    PyObject *op = PySequence_Fast_GET_ITEM(fast, i);
    PyObject *data;
    if (emit_attr_string(e, op, s_op) < 0 ||
        emit_attr_string(e, op, s_oid) < 0 ||
        emit_attr_varint(e, op, s_offset) < 0) {
      Py_DECREF(fast);
      return -1;
    }
    data = PyObject_GetAttr(op, s_data);
    if (data == NULL || emit_blob(e, data) < 0) {
      Py_XDECREF(data);
      Py_DECREF(fast);
      return -1;
    }
    Py_DECREF(data);
    if (emit_attr_string(e, op, s_attr_name) < 0 ||
        emit_attr_value(e, op, s_attr_value) < 0) {
      Py_DECREF(fast);
      return -1;
    }
  }
  Py_DECREF(fast);
  return 0;
}

static int emit_log_entries(Emit *e, PyObject *msg) {
  PyObject *entries, *fast;
  Py_ssize_t i, n;
  GET(msg, s_log_entries, entries);
  fast = PySequence_Fast(entries, "log entries");
  Py_DECREF(entries);
  if (fast == NULL) return -1;
  n = PySequence_Fast_GET_SIZE(fast);
  if (emit_varint(e, (uint64_t)n) < 0) {
    Py_DECREF(fast);
    return -1;
  }
  for (i = 0; i < n; ++i) {
    PyObject *le = PySequence_Fast_GET_ITEM(fast, i);
    if (emit_attr_varint(e, le, s_version) < 0 ||
        emit_attr_string(e, le, s_oid) < 0 ||
        emit_attr_string(e, le, s_op) < 0 ||
        emit_attr_varint(e, le, s_prior_size) < 0) {
      Py_DECREF(fast);
      return -1;
    }
  }
  Py_DECREF(fast);
  return 0;
}

static int emit_attr_extent_map(Emit *e, PyObject *msg, PyObject *name) {
  PyObject *v;
  int rc;
  GET(msg, name, v);
  rc = emit_extent_map(e, v);
  Py_DECREF(v);
  return rc;
}

/* ``enc.value(list(x))`` */
static int emit_attr_value_as_list(Emit *e, PyObject *msg, PyObject *name) {
  PyObject *v, *fast;
  Py_ssize_t i, n;
  GET(msg, name, v);
  fast = PySequence_Fast(v, "expected a sequence");
  Py_DECREF(v);
  if (fast == NULL) return -1;
  n = PySequence_Fast_GET_SIZE(fast);
  if (emit_u8(e, WT_LIST) < 0 || emit_varint(e, (uint64_t)n) < 0) {
    Py_DECREF(fast);
    return -1;
  }
  for (i = 0; i < n; ++i) {
    if (emit_value(e, PySequence_Fast_GET_ITEM(fast, i)) < 0) {
      Py_DECREF(fast);
      return -1;
    }
  }
  Py_DECREF(fast);
  return 0;
}

static int emit_body(Emit *e, PyObject *msg) {
  int rc;
  if (cls_sub_write != NULL &&
      (rc = PyObject_IsInstance(msg, cls_sub_write)) != 0) {
    PyObject *txn;
    if (rc < 0) return -1;
    if (emit_u8(e, MSG_EC_SUB_WRITE) < 0 ||
        emit_attr_varint(e, msg, s_from_shard) < 0 ||
        emit_attr_varint(e, msg, s_tid) < 0 ||
        emit_attr_string(e, msg, s_oid) < 0)
      return -1;
    GET(msg, s_transaction, txn);
    rc = emit_transaction(e, txn);
    Py_DECREF(txn);
    if (rc < 0) return -1;
    if (emit_attr_value_norm(e, msg, s_at_version, WT_TUPLE) < 0 ||
        emit_log_entries(e, msg) < 0 ||
        emit_attr_string(e, msg, s_op_class) < 0 ||
        emit_attr_value(e, msg, s_rollback) < 0 ||
        emit_attr_value(e, msg, s_prev_version) < 0 ||
        emit_attr_value_norm(e, msg, s_reqid, WT_TUPLE) < 0 ||
        emit_attr_value_norm(e, msg, s_trace, WT_LIST) < 0 ||
        emit_attr_value(e, msg, s_qos_class) < 0)
      return -1;
    return 0;
  }
  if (cls_sub_write_reply != NULL &&
      (rc = PyObject_IsInstance(msg, cls_sub_write_reply)) != 0) {
    if (rc < 0) return -1;
    if (emit_u8(e, MSG_EC_SUB_WRITE_REPLY) < 0 ||
        emit_attr_varint(e, msg, s_from_shard) < 0 ||
        emit_attr_varint(e, msg, s_tid) < 0 ||
        emit_attr_value(e, msg, s_committed) < 0 ||
        emit_attr_value(e, msg, s_applied) < 0 ||
        emit_attr_value_norm(e, msg, s_current_version, WT_TUPLE) < 0 ||
        emit_attr_value(e, msg, s_missed) < 0)
      return -1;
    return 0;
  }
  if (cls_sub_read != NULL &&
      (rc = PyObject_IsInstance(msg, cls_sub_read)) != 0) {
    if (rc < 0) return -1;
    if (emit_u8(e, MSG_EC_SUB_READ) < 0 ||
        emit_attr_varint(e, msg, s_from_shard) < 0 ||
        emit_attr_varint(e, msg, s_tid) < 0 ||
        emit_attr_extent_map(e, msg, s_to_read) < 0 ||
        emit_attr_value_as_list(e, msg, s_attrs_to_read) < 0 ||
        emit_attr_extent_map(e, msg, s_subchunks) < 0 ||
        emit_attr_string(e, msg, s_op_class) < 0 ||
        emit_attr_value_norm(e, msg, s_trace, WT_LIST) < 0 ||
        emit_attr_value(e, msg, s_qos_class) < 0 ||
        emit_attr_value(e, msg, s_regen) < 0)
      return -1;
    return 0;
  }
  if (cls_sub_read_reply != NULL &&
      (rc = PyObject_IsInstance(msg, cls_sub_read_reply)) != 0) {
    PyObject *br;
    if (rc < 0) return -1;
    if (emit_u8(e, MSG_EC_SUB_READ_REPLY) < 0 ||
        emit_attr_varint(e, msg, s_from_shard) < 0 ||
        emit_attr_varint(e, msg, s_tid) < 0)
      return -1;
    GET(msg, s_buffers_read, br);
    rc = emit_buffers_read(e, br);
    Py_DECREF(br);
    if (rc < 0) return -1;
    if (emit_attr_value(e, msg, s_attrs_read) < 0 ||
        emit_attr_value(e, msg, s_errors) < 0)
      return -1;
    return 0;
  }
  if (cls_mgr_beacon != NULL &&
      (rc = PyObject_IsInstance(msg, cls_mgr_beacon)) != 0) {
    if (rc < 0) return -1;
    if (emit_u8(e, MSG_MGR_BEACON) < 0 ||
        emit_attr_string(e, msg, s_name) < 0 ||
        emit_attr_varint(e, msg, s_seq) < 0 ||
        emit_attr_value(e, msg, s_lag_ms) < 0)
      return -1;
    return 0;
  }
  if (cls_mgr_report != NULL &&
      (rc = PyObject_IsInstance(msg, cls_mgr_report)) != 0) {
    if (rc < 0) return -1;
    if (emit_u8(e, MSG_MGR_REPORT) < 0 ||
        emit_attr_string(e, msg, s_name) < 0 ||
        emit_attr_varint(e, msg, s_seq) < 0 ||
        emit_attr_value(e, msg, s_interval) < 0 ||
        emit_attr_value(e, msg, s_stats) < 0 ||
        emit_attr_value(e, msg, s_lag_ms) < 0)
      return -1;
    return 0;
  }
  if (emit_u8(e, MSG_VALUE) < 0) return -1;
  return emit_value(e, msg);
}

/* fold the frame crc over a finished part list (chained castagnoli) */
static uint32_t crc_parts(PyObject *parts, uint32_t crc, int *err) {
  Py_ssize_t i, n = PyList_GET_SIZE(parts);
  *err = 0;
  for (i = 0; i < n; ++i) {
    PyObject *p = PyList_GET_ITEM(parts, i);
    if (PyBytes_Check(p)) {
      crc = ec_crc32c(crc, (const uint8_t *)PyBytes_AS_STRING(p),
                      (size_t)PyBytes_GET_SIZE(p));
    } else {
      Py_buffer view;
      if (PyObject_GetBuffer(p, &view, PyBUF_SIMPLE) < 0) {
        *err = 1;
        return crc;
      }
      crc = ec_crc32c(crc, (const uint8_t *)view.buf, (size_t)view.len);
      PyBuffer_Release(&view);
    }
  }
  return crc;
}

/* -- encode entry points --------------------------------------------------- */

/* encode_body(msg) -> bytes: the joined typed body (wire.encode_message
 * twin; the interop-test surface) */
static PyObject *py_encode_body(PyObject *self, PyObject *msg) {
  Emit e;
  PyObject *out = NULL, *joined;
  Py_ssize_t i, n;
  char *w;
  if (emit_init(&e) < 0) return NULL;
  if (emit_body(&e, msg) < 0) goto fail;
  if (emit_flush_run(&e) < 0) goto fail;
  n = PyList_GET_SIZE(e.parts);
  if (n == 1) {
    out = PyList_GET_ITEM(e.parts, 0);
    Py_INCREF(out);
    emit_free(&e);
    return out;
  }
  joined = PyBytes_FromStringAndSize(NULL, (Py_ssize_t)e.total);
  if (joined == NULL) goto fail;
  w = PyBytes_AS_STRING(joined);
  for (i = 0; i < n; ++i) {
    PyObject *p = PyList_GET_ITEM(e.parts, i);
    memcpy(w, PyBytes_AS_STRING(p), (size_t)PyBytes_GET_SIZE(p));
    w += PyBytes_GET_SIZE(p);
  }
  emit_free(&e);
  return joined;
fail:
  emit_free(&e);
  return NULL;
}

static int varint_len(uint64_t v) {
  int n = 1;
  while (v >= 0x80) {
    v >>= 7;
    ++n;
  }
  return n;
}

static void write_varint(uint8_t *p, uint64_t v) {
  for (;;) {
    uint8_t b = (uint8_t)(v & 0x7F);
    v >>= 7;
    if (v) {
      *p++ = b | 0x80;
    } else {
      *p++ = b;
      break;
    }
  }
}

/* encode_entry(head: bytes, seq: int, msg) -> (parts, nbytes, crc)
 *
 * One MSG payload composed in a single pass: the cached kind|src|dst
 * head, seq + body-length varints, and the typed body -- returned as a
 * scatter-gather part list (sub-JOIN_BELOW payloads joined into one
 * buffer) with the payload crc32c already folded, so the transmit-time
 * seal only EXTENDS it over the per-transmission tail. */
static PyObject *py_encode_entry(PyObject *self, PyObject *args) {
  PyObject *head, *msg, *parts_out = NULL, *result;
  unsigned long long seq;
  Emit e;
  uint8_t pre_tail[20];
  Py_ssize_t head_len;
  size_t pre_tail_len, total;
  uint32_t crc = CRC_SEED;
  int err = 0;

  if (!PyArg_ParseTuple(args, "SKO", &head, &seq, &msg)) return NULL;
  if (emit_init(&e) < 0) return NULL;
  if (emit_body(&e, msg) < 0 || emit_flush_run(&e) < 0) {
    emit_free(&e);
    return NULL;
  }
  head_len = PyBytes_GET_SIZE(head);
  write_varint(pre_tail, seq);
  pre_tail_len = (size_t)varint_len(seq);
  write_varint(pre_tail + pre_tail_len, (uint64_t)e.total);
  pre_tail_len += (size_t)varint_len((uint64_t)e.total);
  total = (size_t)head_len + pre_tail_len + e.total;

  if (total <= JOIN_BELOW) {
    /* one joined buffer: the hot sub-op-frame shape */
    PyObject *joined = PyBytes_FromStringAndSize(NULL, (Py_ssize_t)total);
    Py_ssize_t i, n;
    char *w;
    if (joined == NULL) {
      emit_free(&e);
      return NULL;
    }
    w = PyBytes_AS_STRING(joined);
    memcpy(w, PyBytes_AS_STRING(head), (size_t)head_len);
    w += head_len;
    memcpy(w, pre_tail, pre_tail_len);
    w += pre_tail_len;
    n = PyList_GET_SIZE(e.parts);
    for (i = 0; i < n; ++i) {
      PyObject *p = PyList_GET_ITEM(e.parts, i);
      memcpy(w, PyBytes_AS_STRING(p), (size_t)PyBytes_GET_SIZE(p));
      w += PyBytes_GET_SIZE(p);
    }
    crc = ec_crc32c(crc, (const uint8_t *)PyBytes_AS_STRING(joined), total);
    parts_out = PyList_New(1);
    if (parts_out == NULL) {
      Py_DECREF(joined);
      emit_free(&e);
      return NULL;
    }
    PyList_SET_ITEM(parts_out, 0, joined);
  } else {
    /* scatter: pre buffer (head + varints) as its own small part +
     * the body part list, big blobs referenced */
    PyObject *pre = PyBytes_FromStringAndSize(
        NULL, head_len + (Py_ssize_t)pre_tail_len);
    char *w;
    if (pre == NULL) {
      emit_free(&e);
      return NULL;
    }
    w = PyBytes_AS_STRING(pre);
    memcpy(w, PyBytes_AS_STRING(head), (size_t)head_len);
    memcpy(w + head_len, pre_tail, pre_tail_len);
    parts_out = PyList_New(0);
    if (parts_out == NULL || PyList_Append(parts_out, pre) < 0) {
      Py_XDECREF(parts_out);
      Py_DECREF(pre);
      emit_free(&e);
      return NULL;
    }
    Py_DECREF(pre);
    {
      Py_ssize_t i, n = PyList_GET_SIZE(e.parts);
      for (i = 0; i < n; ++i) {
        if (PyList_Append(parts_out, PyList_GET_ITEM(e.parts, i)) < 0) {
          Py_DECREF(parts_out);
          emit_free(&e);
          return NULL;
        }
      }
    }
    crc = crc_parts(parts_out, crc, &err);
    if (err) {
      Py_DECREF(parts_out);
      emit_free(&e);
      return NULL;
    }
  }
  emit_free(&e);
  result = Py_BuildValue("(NnI)", parts_out, (Py_ssize_t)total,
                         (unsigned int)crc);
  return result;
}

/* seal_frames(entries, ack) -> (bufs, nbytes)
 *
 * The whole cork-queue batch sealed in one call (unsigned connections):
 * per entry the cached payload crc is EXTENDED over the piggyback-ack
 * tail (which rides the LAST frame only) and one frame header is
 * composed -- the output is the flat writelines buffer list.  Entries
 * whose crc is still None (Python-encoded fallbacks) get it computed
 * and cached here, so retransmits never re-digest. */
static PyObject *py_seal_frames(PyObject *self, PyObject *args) {
  PyObject *entries, *bufs = NULL, *fast = NULL;
  unsigned long long ack;
  Py_ssize_t i, n;
  size_t nbytes = 0;

  if (!PyArg_ParseTuple(args, "OK", &entries, &ack)) return NULL;
  fast = PySequence_Fast(entries, "expected an entry sequence");
  if (fast == NULL) return NULL;
  n = PySequence_Fast_GET_SIZE(fast);
  bufs = PyList_New(0);
  if (bufs == NULL) goto fail;
  for (i = 0; i < n; ++i) {
    PyObject *entry = PySequence_Fast_GET_ITEM(fast, i);
    PyObject *parts, *crc_obj, *header;
    uint32_t crc;
    size_t plen;
    uint8_t tail[10];
    size_t tail_len = 0;
    int err = 0;
    Py_ssize_t j, np;
    uint8_t *hw;

    parts = PyObject_GetAttr(entry, s_parts);
    if (parts == NULL || !PyList_Check(parts)) {
      Py_XDECREF(parts);
      if (!PyErr_Occurred())
        PyErr_SetString(PyExc_TypeError, "entry.parts must be a list");
      goto fail;
    }
    crc_obj = PyObject_GetAttr(entry, s_crc);
    if (crc_obj == NULL) {
      Py_DECREF(parts);
      goto fail;
    }
    if (crc_obj == Py_None) {
      crc = crc_parts(parts, CRC_SEED, &err);
      if (err) {
        Py_DECREF(parts);
        Py_DECREF(crc_obj);
        goto fail;
      }
      Py_DECREF(crc_obj);
      crc_obj = PyLong_FromUnsignedLong(crc);
      if (crc_obj == NULL ||
          PyObject_SetAttr(entry, s_crc, crc_obj) < 0) {
        Py_XDECREF(crc_obj);
        Py_DECREF(parts);
        goto fail;
      }
    } else {
      crc = (uint32_t)PyLong_AsUnsignedLong(crc_obj);
      if (PyErr_Occurred()) {
        Py_DECREF(parts);
        Py_DECREF(crc_obj);
        goto fail;
      }
    }
    Py_DECREF(crc_obj);
    /* payload length */
    plen = 0;
    np = PyList_GET_SIZE(parts);
    for (j = 0; j < np; ++j) {
      PyObject *p = PyList_GET_ITEM(parts, j);
      Py_ssize_t pl = PyBytes_Check(p) ? PyBytes_GET_SIZE(p)
                                       : PyObject_Length(p);
      if (pl < 0) {
        Py_DECREF(parts);
        goto fail;
      }
      plen += (size_t)pl;
    }
    if (ack != 0 && i == n - 1) {
      write_varint(tail, ack);
      tail_len = (size_t)varint_len(ack);
      crc = ec_crc32c(crc, tail, tail_len);
      plen += tail_len;
    }
    /* frame header: <III magic, len, crc */
    header = PyBytes_FromStringAndSize(NULL, 12);
    if (header == NULL) {
      Py_DECREF(parts);
      goto fail;
    }
    hw = (uint8_t *)PyBytes_AS_STRING(header);
    hw[0] = (uint8_t)(MAGIC & 0xFF);
    hw[1] = (uint8_t)((MAGIC >> 8) & 0xFF);
    hw[2] = (uint8_t)((MAGIC >> 16) & 0xFF);
    hw[3] = (uint8_t)((MAGIC >> 24) & 0xFF);
    hw[4] = (uint8_t)(plen & 0xFF);
    hw[5] = (uint8_t)((plen >> 8) & 0xFF);
    hw[6] = (uint8_t)((plen >> 16) & 0xFF);
    hw[7] = (uint8_t)((plen >> 24) & 0xFF);
    hw[8] = (uint8_t)(crc & 0xFF);
    hw[9] = (uint8_t)((crc >> 8) & 0xFF);
    hw[10] = (uint8_t)((crc >> 16) & 0xFF);
    hw[11] = (uint8_t)((crc >> 24) & 0xFF);
    if (PyList_Append(bufs, header) < 0) {
      Py_DECREF(header);
      Py_DECREF(parts);
      goto fail;
    }
    Py_DECREF(header);
    for (j = 0; j < np; ++j) {
      if (PyList_Append(bufs, PyList_GET_ITEM(parts, j)) < 0) {
        Py_DECREF(parts);
        goto fail;
      }
    }
    Py_DECREF(parts);
    if (tail_len) {
      PyObject *t = PyBytes_FromStringAndSize((const char *)tail,
                                              (Py_ssize_t)tail_len);
      if (t == NULL || PyList_Append(bufs, t) < 0) {
        Py_XDECREF(t);
        goto fail;
      }
      Py_DECREF(t);
    }
    nbytes += 12 + plen;
  }
  Py_DECREF(fast);
  return Py_BuildValue("(Nn)", bufs, (Py_ssize_t)nbytes);
fail:
  Py_XDECREF(bufs);
  Py_XDECREF(fast);
  return NULL;
}

/* -- decode ---------------------------------------------------------------- */

typedef struct {
  const uint8_t *data;
  size_t pos, end;
} Dec;

static int dec_varint(Dec *d, uint64_t *out) {
  uint64_t v = 0;
  int shift = 0;
  while (d->pos < d->end) {
    uint8_t b = d->data[d->pos++];
    if (shift > 57 && ((uint64_t)(b & 0x7F) >> (64 - shift))) {
      /* the group carries bits past 2^64: never silently truncate --
       * lengths/counts this wide are forged or corrupt, and VALUE
       * ints take the wide path in dec_varint_obj instead */
      PyErr_SetString(PyExc_ValueError, "varint overflows u64");
      return -1;
    }
    v |= (uint64_t)(b & 0x7F) << shift;
    if (!(b & 0x80)) {
      *out = v;
      return 0;
    }
    shift += 7;
    if (shift > 63) {
      PyErr_SetString(PyExc_ValueError, "varint too long");
      return -1;
    }
  }
  PyErr_SetString(PyExc_ValueError, "decode past end of buffer");
  return -1;
}

/* Full-width varint as a PyLong.  The Python codec round-trips ints of
 * any width the 10-group wire format holds (up to 70 bits), and its
 * fallback encoder emits the 64..70-bit band the C emitter refuses
 * (FallbackError), so the native DECODER must reconstruct that band
 * exactly -- truncating to u64 here silently corrupts a mixed-codec
 * peer pair. */
static PyObject *dec_varint_obj(Dec *d) {
  unsigned __int128 v = 0;
  int shift = 0;
  while (d->pos < d->end) {
    uint8_t b = d->data[d->pos++];
    v |= (unsigned __int128)(b & 0x7F) << shift;
    if (!(b & 0x80)) {
      if (v >> 64) {
        /* cold path: only python-encoded fallback frames land here */
        PyObject *hi = PyLong_FromUnsignedLongLong((uint64_t)(v >> 64));
        PyObject *lo = PyLong_FromUnsignedLongLong((uint64_t)v);
        PyObject *sixty_four = PyLong_FromLong(64);
        PyObject *shifted = NULL, *out = NULL;
        if (hi != NULL && lo != NULL && sixty_four != NULL) {
          shifted = PyNumber_Lshift(hi, sixty_four);
          if (shifted != NULL) out = PyNumber_Or(shifted, lo);
        }
        Py_XDECREF(shifted);
        Py_XDECREF(sixty_four);
        Py_XDECREF(hi);
        Py_XDECREF(lo);
        return out;
      }
      return PyLong_FromUnsignedLongLong((uint64_t)v);
    }
    shift += 7;
    if (shift > 63) {
      PyErr_SetString(PyExc_ValueError, "varint too long");
      return NULL;
    }
  }
  PyErr_SetString(PyExc_ValueError, "decode past end of buffer");
  return NULL;
}

static int dec_take(Dec *d, size_t n, const uint8_t **out) {
  if (d->pos + n > d->end) {
    PyErr_SetString(PyExc_ValueError, "decode past end of buffer");
    return -1;
  }
  *out = d->data + d->pos;
  d->pos += n;
  return 0;
}

static PyObject *dec_blob(Dec *d) {
  uint64_t n;
  const uint8_t *p;
  if (dec_varint(d, &n) < 0) return NULL;
  if (dec_take(d, (size_t)n, &p) < 0) return NULL;
  return PyBytes_FromStringAndSize((const char *)p, (Py_ssize_t)n);
}

static PyObject *dec_string(Dec *d) {
  uint64_t n;
  const uint8_t *p;
  if (dec_varint(d, &n) < 0) return NULL;
  if (dec_take(d, (size_t)n, &p) < 0) return NULL;
  return PyUnicode_DecodeUTF8((const char *)p, (Py_ssize_t)n, NULL);
}

static PyObject *dec_value(Dec *d) {
  const uint8_t *p;
  uint64_t n;
  PyObject *out;
  uint8_t tag;
  if (d->pos >= d->end) {
    PyErr_SetString(PyExc_ValueError, "decode past end of buffer");
    return NULL;
  }
  tag = d->data[d->pos++];
  switch (tag) {
    case WT_INT:
      return dec_varint_obj(d);
    case WT_BYTES:
      return dec_blob(d);
    case WT_STR:
      return dec_string(d);
    case WT_NONE:
      Py_RETURN_NONE;
    case WT_TRUE:
      Py_RETURN_TRUE;
    case WT_FALSE:
      Py_RETURN_FALSE;
    case WT_NEGINT: {
      PyObject *mag, *neg;
      mag = dec_varint_obj(d);
      if (mag == NULL) return NULL;
      neg = PyNumber_Negative(mag);
      Py_DECREF(mag);
      return neg;
    }
    case WT_LIST:
    case WT_TUPLE: {
      uint64_t i;
      if (dec_varint(d, &n) < 0) return NULL;
      if (n > (uint64_t)(d->end - d->pos)) {
        /* each element needs >= 1 byte: cheap forged-length guard */
        PyErr_SetString(PyExc_ValueError, "sequence length past buffer");
        return NULL;
      }
      if (Py_EnterRecursiveCall(" decoding wire value")) return NULL;
      out = (tag == WT_LIST) ? PyList_New((Py_ssize_t)n)
                            : PyTuple_New((Py_ssize_t)n);
      if (out == NULL) {
        Py_LeaveRecursiveCall();
        return NULL;
      }
      for (i = 0; i < n; ++i) {
        PyObject *item = dec_value(d);
        if (item == NULL) {
          Py_DECREF(out);
          Py_LeaveRecursiveCall();
          return NULL;
        }
        if (tag == WT_LIST)
          PyList_SET_ITEM(out, (Py_ssize_t)i, item);
        else
          PyTuple_SET_ITEM(out, (Py_ssize_t)i, item);
      }
      Py_LeaveRecursiveCall();
      return out;
    }
    case WT_DICT: {
      uint64_t i;
      if (dec_varint(d, &n) < 0) return NULL;
      if (n > (uint64_t)(d->end - d->pos)) {
        PyErr_SetString(PyExc_ValueError, "dict length past buffer");
        return NULL;
      }
      if (Py_EnterRecursiveCall(" decoding wire value")) return NULL;
      out = PyDict_New();
      if (out == NULL) {
        Py_LeaveRecursiveCall();
        return NULL;
      }
      for (i = 0; i < n; ++i) {
        PyObject *key = dec_string(d);
        PyObject *val;
        if (key == NULL) {
          Py_DECREF(out);
          Py_LeaveRecursiveCall();
          return NULL;
        }
        val = dec_value(d);
        if (val == NULL || PyDict_SetItem(out, key, val) < 0) {
          Py_DECREF(key);
          Py_XDECREF(val);
          Py_DECREF(out);
          Py_LeaveRecursiveCall();
          return NULL;
        }
        Py_DECREF(key);
        Py_DECREF(val);
      }
      Py_LeaveRecursiveCall();
      return out;
    }
    case WT_FLOAT: {
      double v;
      if (dec_take(d, 8, &p) < 0) return NULL;
#if defined(__BYTE_ORDER__) && __BYTE_ORDER__ == __ORDER_BIG_ENDIAN__
      {
        uint8_t sw[8];
        int i;
        for (i = 0; i < 8; ++i) sw[i] = p[7 - i];
        memcpy(&v, sw, 8);
      }
#else
      memcpy(&v, p, 8);
#endif
      return PyFloat_FromDouble(v);
    }
    default:
      PyErr_Format(PyExc_ValueError, "bad value tag %d", (int)tag);
      return NULL;
  }
}

/* kwargs-call a registered dataclass constructor; steals nothing */
static PyObject *construct(PyObject *cls, PyObject *kwargs) {
  return PyObject_Call(cls, empty_tuple, kwargs);
}

static int kw_set(PyObject *kw, PyObject *name, PyObject *val_stolen) {
  int rc;
  if (val_stolen == NULL) return -1;
  rc = PyDict_SetItem(kw, name, val_stolen);
  Py_DECREF(val_stolen);
  return rc;
}

/* ``[tuple(x) for x in v]`` in place over a freshly decoded list */
static int listify_tuples(PyObject *lst) {
  Py_ssize_t i, n;
  if (!PyList_Check(lst)) return 0;  /* decoded something else: leave it */
  n = PyList_GET_SIZE(lst);
  for (i = 0; i < n; ++i) {
    PyObject *item = PyList_GET_ITEM(lst, i);
    if (!PyTuple_Check(item)) {
      PyObject *t = PySequence_Tuple(item);
      if (t == NULL) return -1;
      PyList_SetItem(lst, i, t); /* steals t, drops item */
    }
  }
  return 0;
}

/* the extent-map decode transform, a faithful twin of the Python
 * comprehension {k: [tuple(x) for x in v]}: a non-dict input or a
 * non-iterable v RAISES exactly where the comprehension would -- a
 * corrupt frame must fail identically through both codecs, never
 * decode to a struct the Python side refuses (differential-fuzz
 * finding, tools/wire_fuzz.py) */
static int mapify_tuples(PyObject *d) {
  PyObject *key, *val;
  Py_ssize_t pos = 0;
  if (!PyDict_Check(d)) {
    PyErr_SetString(PyExc_ValueError, "extent map is not a dict");
    return -1;
  }
  while (PyDict_Next(d, &pos, &key, &val)) {
    if (PyList_Check(val)) {
      if (listify_tuples(val) < 0) return -1; /* in-place fast path */
    } else {
      /* the comprehension materializes any iterable v as a fresh
       * list (str iterates chars, dict iterates keys) and raises
       * TypeError on the rest; PySequence_List matches that */
      PyObject *lst = PySequence_List(val);
      if (lst == NULL) return -1;
      if (listify_tuples(lst) < 0) {
        Py_DECREF(lst);
        return -1;
      }
      /* value replacement for an existing key: safe under
       * PyDict_Next (the key set does not change) */
      if (PyDict_SetItem(d, key, lst) < 0) {
        Py_DECREF(lst);
        return -1;
      }
      Py_DECREF(lst);
    }
  }
  return 0;
}

static PyObject *decode_transaction(Dec *d) {
  uint64_t n, i;
  PyObject *txn, *ops;
  if (dec_varint(d, &n) < 0) return NULL;
  txn = construct(cls_transaction, NULL);
  if (txn == NULL) return NULL;
  ops = PyObject_GetAttr(txn, s_ops);
  if (ops == NULL) {
    Py_DECREF(txn);
    return NULL;
  }
  for (i = 0; i < n; ++i) {
    PyObject *kw = PyDict_New();
    PyObject *op_obj;
    if (kw == NULL) goto fail;
    if (kw_set(kw, s_op, dec_string(d)) < 0 ||
        kw_set(kw, s_oid, dec_string(d)) < 0 ||
        kw_set(kw, s_offset, dec_varint_obj(d)) < 0 ||
        kw_set(kw, s_data, dec_blob(d)) < 0 ||
        kw_set(kw, s_attr_name, dec_string(d)) < 0 ||
        kw_set(kw, s_attr_value, dec_value(d)) < 0) {
      Py_DECREF(kw);
      goto fail;
    }
    op_obj = construct(cls_txn_op, kw);
    Py_DECREF(kw);
    if (op_obj == NULL) goto fail;
    if (PyList_Append(ops, op_obj) < 0) {
      Py_DECREF(op_obj);
      goto fail;
    }
    Py_DECREF(op_obj);
  }
  Py_DECREF(ops);
  return txn;
fail:
  Py_DECREF(ops);
  Py_DECREF(txn);
  return NULL;
}

static PyObject *decode_body_at(Dec *d) {
  uint8_t kind;
  PyObject *kw = NULL, *out = NULL;
  if (d->pos >= d->end) {
    PyErr_SetString(PyExc_ValueError, "decode past end of buffer");
    return NULL;
  }
  kind = d->data[d->pos++];
  switch (kind) {
    case MSG_VALUE:
      return dec_value(d);
    case MSG_EC_SUB_WRITE: {
      PyObject *txn, *entries;
      uint64_t ne, i;
      kw = PyDict_New();
      if (kw == NULL) return NULL;
      if (kw_set(kw, s_from_shard, dec_varint_obj(d)) < 0 ||
          kw_set(kw, s_tid, dec_varint_obj(d)) < 0 ||
          kw_set(kw, s_oid, dec_string(d)) < 0)
        goto fail;
      txn = decode_transaction(d);
      if (kw_set(kw, s_transaction, txn) < 0) goto fail;
      if (kw_set(kw, s_at_version, dec_value(d)) < 0) goto fail;
      if (dec_varint(d, &ne) < 0) goto fail;
      entries = PyList_New(0);
      if (entries == NULL) goto fail;
      for (i = 0; i < ne; ++i) {
        PyObject *lkw = PyDict_New();
        PyObject *le;
        if (lkw == NULL) {
          Py_DECREF(entries);
          goto fail;
        }
        if (kw_set(lkw, s_version, dec_varint_obj(d)) < 0 ||
            kw_set(lkw, s_oid, dec_string(d)) < 0 ||
            kw_set(lkw, s_op, dec_string(d)) < 0 ||
            kw_set(lkw, s_prior_size, dec_varint_obj(d)) < 0) {
          Py_DECREF(lkw);
          Py_DECREF(entries);
          goto fail;
        }
        le = construct(cls_log_entry, lkw);
        Py_DECREF(lkw);
        if (le == NULL || PyList_Append(entries, le) < 0) {
          Py_XDECREF(le);
          Py_DECREF(entries);
          goto fail;
        }
        Py_DECREF(le);
      }
      if (kw_set(kw, s_log_entries, entries) < 0) goto fail;
      if (kw_set(kw, s_op_class, dec_string(d)) < 0 ||
          kw_set(kw, s_rollback, dec_value(d)) < 0 ||
          kw_set(kw, s_prev_version, dec_value(d)) < 0)
        goto fail;
      /* trailing optionals (wire-optional compat tails): pre-reqid /
       * pre-trace / pre-qos senders end earlier -- mirror the guards */
      if (d->pos < d->end) {
        if (kw_set(kw, s_reqid, dec_value(d)) < 0) goto fail;
      }
      if (d->pos < d->end) {
        if (kw_set(kw, s_trace, dec_value(d)) < 0) goto fail;
      }
      if (d->pos < d->end) {
        if (kw_set(kw, s_qos_class, dec_value(d)) < 0) goto fail;
      }
      out = construct(cls_sub_write, kw);
      Py_DECREF(kw);
      return out;
    }
    case MSG_EC_SUB_WRITE_REPLY:
      kw = PyDict_New();
      if (kw == NULL) return NULL;
      if (kw_set(kw, s_from_shard, dec_varint_obj(d)) < 0 ||
          kw_set(kw, s_tid, dec_varint_obj(d)) < 0 ||
          kw_set(kw, s_committed, dec_value(d)) < 0 ||
          kw_set(kw, s_applied, dec_value(d)) < 0 ||
          kw_set(kw, s_current_version, dec_value(d)) < 0 ||
          kw_set(kw, s_missed, dec_value(d)) < 0)
        goto fail;
      out = construct(cls_sub_write_reply, kw);
      Py_DECREF(kw);
      return out;
    case MSG_EC_SUB_READ: {
      PyObject *m;
      kw = PyDict_New();
      if (kw == NULL) return NULL;
      if (kw_set(kw, s_from_shard, dec_varint_obj(d)) < 0 ||
          kw_set(kw, s_tid, dec_varint_obj(d)) < 0)
        goto fail;
      m = dec_value(d);
      if (m == NULL) goto fail;
      if (mapify_tuples(m) < 0) {
        Py_DECREF(m);
        goto fail;
      }
      if (kw_set(kw, s_to_read, m) < 0) goto fail;
      if (kw_set(kw, s_attrs_to_read, dec_value(d)) < 0) goto fail;
      m = dec_value(d);
      if (m == NULL) goto fail;
      if (mapify_tuples(m) < 0) {
        Py_DECREF(m);
        goto fail;
      }
      if (kw_set(kw, s_subchunks, m) < 0) goto fail;
      if (kw_set(kw, s_op_class, dec_string(d)) < 0) goto fail;
      if (d->pos < d->end) {
        if (kw_set(kw, s_trace, dec_value(d)) < 0) goto fail;
      }
      if (d->pos < d->end) {
        if (kw_set(kw, s_qos_class, dec_value(d)) < 0) goto fail;
      }
      if (d->pos < d->end) {
        if (kw_set(kw, s_regen, dec_value(d)) < 0) goto fail;
      }
      out = construct(cls_sub_read, kw);
      Py_DECREF(kw);
      return out;
    }
    case MSG_EC_SUB_READ_REPLY:
      kw = PyDict_New();
      if (kw == NULL) return NULL;
      if (kw_set(kw, s_from_shard, dec_varint_obj(d)) < 0 ||
          kw_set(kw, s_tid, dec_varint_obj(d)) < 0 ||
          kw_set(kw, s_buffers_read, dec_value(d)) < 0 ||
          kw_set(kw, s_attrs_read, dec_value(d)) < 0 ||
          kw_set(kw, s_errors, dec_value(d)) < 0)
        goto fail;
      out = construct(cls_sub_read_reply, kw);
      Py_DECREF(kw);
      return out;
    case MSG_MGR_BEACON:
      kw = PyDict_New();
      if (kw == NULL) return NULL;
      if (kw_set(kw, s_name, dec_string(d)) < 0 ||
          kw_set(kw, s_seq, dec_varint_obj(d)) < 0)
        goto fail;
      if (d->pos < d->end) {
        if (kw_set(kw, s_lag_ms, dec_value(d)) < 0) goto fail;
      }
      out = construct(cls_mgr_beacon, kw);
      Py_DECREF(kw);
      return out;
    case MSG_MGR_REPORT:
      kw = PyDict_New();
      if (kw == NULL) return NULL;
      if (kw_set(kw, s_name, dec_string(d)) < 0 ||
          kw_set(kw, s_seq, dec_varint_obj(d)) < 0 ||
          kw_set(kw, s_interval, dec_value(d)) < 0 ||
          kw_set(kw, s_stats, dec_value(d)) < 0)
        goto fail;
      if (d->pos < d->end) {
        if (kw_set(kw, s_lag_ms, dec_value(d)) < 0) goto fail;
      }
      out = construct(cls_mgr_report, kw);
      Py_DECREF(kw);
      return out;
    default:
      /* a NEWER peer's frame kind: the transport counts-and-drops */
      Py_INCREF(Unknown);
      return Unknown;
  }
fail:
  Py_XDECREF(kw);
  return NULL;
}

/* decode_body(body: bytes) -> msg (wire.decode_message twin; raises
 * ValueError on an unknown kind, matching the Python codec) */
static PyObject *py_decode_body(PyObject *self, PyObject *arg) {
  Dec d;
  PyObject *out;
  Py_buffer view;
  if (PyObject_GetBuffer(arg, &view, PyBUF_SIMPLE) < 0) return NULL;
  d.data = (const uint8_t *)view.buf;
  d.pos = 0;
  d.end = (size_t)view.len;
  out = decode_body_at(&d);
  PyBuffer_Release(&view);
  if (out == Unknown) {
    Py_DECREF(out);
    PyErr_SetString(PyExc_ValueError, "unknown message type");
    return NULL;
  }
  return out;
}

/* decode_msg(rec: bytes, offset) -> (seq, msg, back_ack)
 *
 * The inbound envelope tail + typed body in one pass: seq varint, the
 * length-prefixed body decoded IN PLACE from the record buffer, and
 * the optional trailing piggyback-ack varint (None when absent -- v3
 * senders end at the body).  ``msg`` is the UNKNOWN sentinel for a
 * newer peer's frame kind (count-and-drop at the transport). */
static PyObject *py_decode_msg(PyObject *self, PyObject *args) {
  PyObject *rec, *msg, *ack_obj, *out;
  Py_ssize_t offset;
  Py_buffer view;
  Dec d, body;
  uint64_t seq, blen, ack;

  if (!PyArg_ParseTuple(args, "On", &rec, &offset)) return NULL;
  if (PyObject_GetBuffer(rec, &view, PyBUF_SIMPLE) < 0) return NULL;
  if (offset < 0 || offset > view.len) {
    PyBuffer_Release(&view);
    PyErr_SetString(PyExc_ValueError, "offset out of range");
    return NULL;
  }
  d.data = (const uint8_t *)view.buf;
  d.pos = (size_t)offset;
  d.end = (size_t)view.len;
  if (dec_varint(&d, &seq) < 0 || dec_varint(&d, &blen) < 0) {
    PyBuffer_Release(&view);
    return NULL;
  }
  if (d.pos + blen > d.end) {
    PyBuffer_Release(&view);
    PyErr_SetString(PyExc_ValueError, "body past end of record");
    return NULL;
  }
  body.data = d.data;
  body.pos = d.pos;
  body.end = d.pos + (size_t)blen;
  msg = decode_body_at(&body);
  if (msg == NULL) {
    PyBuffer_Release(&view);
    return NULL;
  }
  d.pos += (size_t)blen;
  if (d.pos < d.end) {
    if (dec_varint(&d, &ack) < 0) {
      PyBuffer_Release(&view);
      Py_DECREF(msg);
      return NULL;
    }
    ack_obj = PyLong_FromUnsignedLongLong(ack);
  } else {
    ack_obj = Py_None;
    Py_INCREF(Py_None);
  }
  PyBuffer_Release(&view);
  if (ack_obj == NULL) {
    Py_DECREF(msg);
    return NULL;
  }
  out = Py_BuildValue("(KNN)", seq, msg, ack_obj);
  return out;
}

/* parse_burst(buf: bytes, pos) -> (frames, new_pos, ok)
 *
 * Every complete ``MAGIC | len | crc | payload`` frame already buffered
 * is located and crc-validated in ONE GIL-released pass over the raw
 * buffer; the payload slices are materialized afterwards.  ``ok`` is
 * False when the scan hit a corrupt/forged frame (the caller drops the
 * connection, exactly like unframe() returning None). */
static PyObject *py_parse_burst(PyObject *self, PyObject *args) {
  PyObject *buf, *frames;
  Py_ssize_t pos;
  Py_buffer view;
  size_t p, end;
  int ok = 1;
  size_t n_frames = 0, cap_frames = 32;
  size_t *offs;   /* payload offset/length pairs */

  if (!PyArg_ParseTuple(args, "On", &buf, &pos)) return NULL;
  if (PyObject_GetBuffer(buf, &view, PyBUF_SIMPLE) < 0) return NULL;
  if (pos < 0 || pos > view.len) {
    PyBuffer_Release(&view);
    PyErr_SetString(PyExc_ValueError, "pos out of range");
    return NULL;
  }
  offs = (size_t *)PyMem_RawMalloc(sizeof(size_t) * 2 * cap_frames);
  if (offs == NULL) {
    PyBuffer_Release(&view);
    return PyErr_NoMemory();
  }
  p = (size_t)pos;
  end = (size_t)view.len;
  {
    const uint8_t *data = (const uint8_t *)view.buf;
    int mem_fail = 0;
    Py_BEGIN_ALLOW_THREADS
    while (end - p >= 12) {
      uint32_t magic = (uint32_t)data[p] | ((uint32_t)data[p + 1] << 8) |
                       ((uint32_t)data[p + 2] << 16) |
                       ((uint32_t)data[p + 3] << 24);
      uint32_t length = (uint32_t)data[p + 4] |
                        ((uint32_t)data[p + 5] << 8) |
                        ((uint32_t)data[p + 6] << 16) |
                        ((uint32_t)data[p + 7] << 24);
      uint32_t crc = (uint32_t)data[p + 8] | ((uint32_t)data[p + 9] << 8) |
                     ((uint32_t)data[p + 10] << 16) |
                     ((uint32_t)data[p + 11] << 24);
      if (magic != MAGIC) {
        ok = 0;
        break;
      }
      if (end - p - 12 < (size_t)length) break; /* partial tail frame */
      if (ec_crc32c(CRC_SEED, data + p + 12, (size_t)length) != crc) {
        ok = 0;
        break;
      }
      if (n_frames == cap_frames) {
        size_t *grown;
        cap_frames *= 2;
        grown = (size_t *)PyMem_RawRealloc(
            offs, sizeof(size_t) * 2 * cap_frames);
        if (grown == NULL) {
          mem_fail = 1;
          break;
        }
        offs = grown;
      }
      offs[2 * n_frames] = p + 12;
      offs[2 * n_frames + 1] = (size_t)length;
      ++n_frames;
      p += 12 + (size_t)length;
    }
    Py_END_ALLOW_THREADS
    if (mem_fail) {
      PyMem_RawFree(offs);
      PyBuffer_Release(&view);
      return PyErr_NoMemory();
    }
    frames = PyList_New((Py_ssize_t)n_frames);
    if (frames == NULL) {
      PyMem_RawFree(offs);
      PyBuffer_Release(&view);
      return NULL;
    }
    {
      size_t i;
      for (i = 0; i < n_frames; ++i) {
        PyObject *payload = PyBytes_FromStringAndSize(
            (const char *)data + offs[2 * i], (Py_ssize_t)offs[2 * i + 1]);
        if (payload == NULL) {
          Py_DECREF(frames);
          PyMem_RawFree(offs);
          PyBuffer_Release(&view);
          return NULL;
        }
        PyList_SET_ITEM(frames, (Py_ssize_t)i, payload);
      }
    }
  }
  PyMem_RawFree(offs);
  PyBuffer_Release(&view);
  return Py_BuildValue("(NnO)", frames, (Py_ssize_t)p,
                       ok ? Py_True : Py_False);
}


/* -- C stage markers (the profiler's hot path) ------------------------------
 *
 * The ledger's `with stage(name):` markers bracket every wire seam; at
 * r19 their ~0.6us/pair Python cost vanished into a 35%-serialization
 * wall, but against the native codec's halved wall the same pairs
 * became a >3% enabled-profiler overhead -- failing the wire-tax
 * stage's own gate.  This Stage type is the drop-in C twin
 * (ceph_tpu/profiling/ledger.py selects it when the extension loads):
 * identical exclusive-time semantics -- entering banks+pauses the
 * parent's clock, every nanosecond lands in exactly one stage, GC
 * pauses credited out via stage_gc_credit -- at clock_gettime cost.
 * Disabled enter/exit is a flag check returning a borrowed constant:
 * zero allocations, pinned by the bench's off-mode alloc gate. */

typedef struct StageObj {
  PyObject_HEAD
  PyObject *name;
  long long ns, calls, nbytes;
  long long t0;
  struct StageObj *parent;  /* strong ref while on the current chain */
} StageObj;

static int stage_enabled_flag = 0;
static StageObj *stage_current = NULL;  /* strong ref */

static inline long long stage_now_ns(void) {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return (long long)ts.tv_sec * 1000000000LL + (long long)ts.tv_nsec;
}

static PyObject *Stage_new(PyTypeObject *type, PyObject *args,
                           PyObject *kwargs) {
  PyObject *name;
  StageObj *self;
  if (!PyArg_ParseTuple(args, "U", &name)) return NULL;
  self = (StageObj *)type->tp_alloc(type, 0);
  if (self == NULL) return NULL;
  Py_INCREF(name);
  self->name = name;
  self->ns = self->calls = self->nbytes = 0;
  self->t0 = 0;
  self->parent = NULL;
  return (PyObject *)self;
}

static void Stage_dealloc(StageObj *self) {
  Py_XDECREF(self->name);
  Py_XDECREF((PyObject *)self->parent);
  Py_TYPE(self)->tp_free((PyObject *)self);
}

static PyObject *Stage_enter(StageObj *self, PyObject *noargs) {
  long long now;
  StageObj *parent;
  if (!stage_enabled_flag) {
    Py_INCREF(self);
    return (PyObject *)self;
  }
  now = stage_now_ns();
  parent = stage_current;
  if (parent != NULL) parent->ns += now - parent->t0;
  /* transfer stage_current's ref into self->parent (clearing any
   * stale parent from an enable-toggle abandoning an open stage) */
  Py_XDECREF((PyObject *)self->parent);
  self->parent = parent;
  self->t0 = now;
  Py_INCREF(self);
  stage_current = self;
  Py_INCREF(self);
  return (PyObject *)self;
}

static PyObject *Stage_exit(StageObj *self, PyObject *args) {
  long long now;
  StageObj *parent;
  if (!stage_enabled_flag) Py_RETURN_FALSE;
  now = stage_now_ns();
  self->ns += now - self->t0;
  self->calls += 1;
  parent = self->parent;
  self->parent = NULL;
  if (stage_current == self) {
    Py_DECREF((PyObject *)self);  /* the chain's ref to us */
    stage_current = parent;       /* ownership transfers */
    if (parent != NULL) parent->t0 = now;
  } else {
    /* mismatched nesting (enable toggled mid-block): drop quietly,
     * exactly like the Python marker's abandoned-tail semantics */
    Py_XDECREF((PyObject *)parent);
  }
  Py_RETURN_FALSE;
}

static PyObject *Stage_add_bytes(StageObj *self, PyObject *arg) {
  if (stage_enabled_flag) {
    long long n = PyLong_AsLongLong(arg);
    if (n == -1 && PyErr_Occurred()) return NULL;
    self->nbytes += n;
  }
  Py_RETURN_NONE;
}

static PyMethodDef Stage_methods[] = {
    {"__enter__", (PyCFunction)Stage_enter, METH_NOARGS, NULL},
    {"__exit__", (PyCFunction)Stage_exit, METH_VARARGS, NULL},
    {"add_bytes", (PyCFunction)Stage_add_bytes, METH_O, NULL},
    {NULL, NULL, 0, NULL},
};

static PyMemberDef Stage_members[] = {
    {(char *)"name", T_OBJECT_EX, offsetof(StageObj, name), READONLY,
     NULL},
    {(char *)"ns", T_LONGLONG, offsetof(StageObj, ns), 0, NULL},
    {(char *)"calls", T_LONGLONG, offsetof(StageObj, calls), 0, NULL},
    {(char *)"nbytes", T_LONGLONG, offsetof(StageObj, nbytes), 0, NULL},
    {NULL, 0, 0, 0, NULL},
};

static PyTypeObject StageType = {
    PyVarObject_HEAD_INIT(NULL, 0)
    "_wire_native.Stage",          /* tp_name */
    sizeof(StageObj),              /* tp_basicsize */
};

static PyObject *py_stage_set_enabled(PyObject *self, PyObject *arg) {
  int on = PyObject_IsTrue(arg);
  if (on < 0) return NULL;
  stage_enabled_flag = on;
  if (!on) {
    /* abandon the open chain (test/bench boundary, never a hot op) */
    StageObj *cur = stage_current;
    stage_current = NULL;
    while (cur != NULL) {
      StageObj *p = cur->parent;
      cur->parent = NULL;
      Py_DECREF((PyObject *)cur);
      cur = p;
    }
  }
  Py_RETURN_NONE;
}

static PyObject *py_stage_gc_credit(PyObject *self, PyObject *arg) {
  long long ns = PyLong_AsLongLong(arg);
  if (ns == -1 && PyErr_Occurred()) return NULL;
  if (stage_current != NULL) stage_current->t0 += ns;
  Py_RETURN_NONE;
}

static PyObject *py_stage_current_name(PyObject *self, PyObject *noargs) {
  if (stage_current != NULL) {
    Py_INCREF(stage_current->name);
    return stage_current->name;
  }
  Py_RETURN_NONE;
}

/* -- registration ---------------------------------------------------------- */

static PyObject *py_register(PyObject *self, PyObject *args,
                             PyObject *kwargs) {
  static const char *kwlist_names[] = {
      "ec_sub_write", "ec_sub_write_reply", "ec_sub_read",
      "ec_sub_read_reply", "transaction", "txn_op", "log_entry",
      "mgr_beacon", "mgr_report", "np_integer", NULL};
  static char *kwlist[11];
  PyObject *a, *b, *c, *d2, *e, *f, *g, *h, *i2, *j;
  int i;
  for (i = 0; i < 11; ++i) kwlist[i] = (char *)kwlist_names[i];
  if (!PyArg_ParseTupleAndKeywords(
          args, kwargs, "OOOOOOOOOO", kwlist, &a, &b, &c, &d2, &e, &f,
          &g, &h, &i2, &j))
    return NULL;
  Py_INCREF(a); Py_XSETREF(cls_sub_write, a);
  Py_INCREF(b); Py_XSETREF(cls_sub_write_reply, b);
  Py_INCREF(c); Py_XSETREF(cls_sub_read, c);
  Py_INCREF(d2); Py_XSETREF(cls_sub_read_reply, d2);
  Py_INCREF(e); Py_XSETREF(cls_transaction, e);
  Py_INCREF(f); Py_XSETREF(cls_txn_op, f);
  Py_INCREF(g); Py_XSETREF(cls_log_entry, g);
  Py_INCREF(h); Py_XSETREF(cls_mgr_beacon, h);
  Py_INCREF(i2); Py_XSETREF(cls_mgr_report, i2);
  Py_INCREF(j); Py_XSETREF(cls_np_integer, j);
  Py_RETURN_NONE;
}

static PyMethodDef Methods[] = {
    {"register", (PyCFunction)py_register, METH_VARARGS | METH_KEYWORDS,
     "register(ec_sub_write, ..., np_integer): bind the message types"},
    {"encode_body", py_encode_body, METH_O,
     "encode_body(msg) -> bytes (typed body; wire.encode_message twin)"},
    {"encode_entry", py_encode_entry, METH_VARARGS,
     "encode_entry(head, seq, msg) -> (parts, nbytes, crc)"},
    {"seal_frames", py_seal_frames, METH_VARARGS,
     "seal_frames(entries, ack) -> (bufs, nbytes)"},
    {"parse_burst", py_parse_burst, METH_VARARGS,
     "parse_burst(buf, pos) -> (frames, new_pos, ok)"},
    {"decode_msg", py_decode_msg, METH_VARARGS,
     "decode_msg(rec, offset) -> (seq, msg, back_ack)"},
    {"decode_body", py_decode_body, METH_O,
     "decode_body(body) -> msg (wire.decode_message twin)"},
    {"stage_set_enabled", py_stage_set_enabled, METH_O,
     "stage_set_enabled(on): master switch for C Stage markers"},
    {"stage_gc_credit", py_stage_gc_credit, METH_O,
     "stage_gc_credit(ns): push the current stage's clock past a GC "
     "pause"},
    {"stage_current_name", py_stage_current_name, METH_NOARGS,
     "stage_current_name() -> str | None (the sampler's read)"},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_wire_native",
    "batched native v4 wire codec (frame bodies + envelopes + seal)",
    -1, Methods,
};

PyMODINIT_FUNC PyInit__wire_native(void) {
  PyObject *mod;
  StageType.tp_flags = Py_TPFLAGS_DEFAULT;
  StageType.tp_doc = "C stage marker (ledger.StageMarker twin)";
  StageType.tp_new = Stage_new;
  StageType.tp_dealloc = (destructor)Stage_dealloc;
  StageType.tp_methods = Stage_methods;
  StageType.tp_members = Stage_members;
  if (PyType_Ready(&StageType) < 0) return NULL;
  mod = PyModule_Create(&moduledef);
  if (mod == NULL) return NULL;
  Py_INCREF(&StageType);
  PyModule_AddObject(mod, "Stage", (PyObject *)&StageType);
  FallbackError = PyErr_NewException(
      "_wire_native.FallbackError", NULL, NULL);
  Unknown = PyObject_CallObject((PyObject *)&PyBaseObject_Type, NULL);
  empty_tuple = PyTuple_New(0);
  if (FallbackError == NULL || Unknown == NULL || empty_tuple == NULL)
    goto fail;
  Py_INCREF(FallbackError);
  PyModule_AddObject(mod, "FallbackError", FallbackError);
  Py_INCREF(Unknown);
  PyModule_AddObject(mod, "UNKNOWN", Unknown);

#define INTERN(var, name)                      \
  do {                                         \
    var = PyUnicode_InternFromString(name);    \
    if (var == NULL) goto fail;                \
  } while (0)
  INTERN(s_from_shard, "from_shard");
  INTERN(s_tid, "tid");
  INTERN(s_oid, "oid");
  INTERN(s_transaction, "transaction");
  INTERN(s_at_version, "at_version");
  INTERN(s_log_entries, "log_entries");
  INTERN(s_op_class, "op_class");
  INTERN(s_rollback, "rollback");
  INTERN(s_prev_version, "prev_version");
  INTERN(s_reqid, "reqid");
  INTERN(s_trace, "trace");
  INTERN(s_qos_class, "qos_class");
  INTERN(s_committed, "committed");
  INTERN(s_applied, "applied");
  INTERN(s_current_version, "current_version");
  INTERN(s_missed, "missed");
  INTERN(s_to_read, "to_read");
  INTERN(s_attrs_to_read, "attrs_to_read");
  INTERN(s_subchunks, "subchunks");
  INTERN(s_regen, "regen");
  INTERN(s_buffers_read, "buffers_read");
  INTERN(s_attrs_read, "attrs_read");
  INTERN(s_errors, "errors");
  INTERN(s_name, "name");
  INTERN(s_seq, "seq");
  INTERN(s_interval, "interval");
  INTERN(s_stats, "stats");
  INTERN(s_lag_ms, "lag_ms");
  INTERN(s_ops, "ops");
  INTERN(s_op, "op");
  INTERN(s_offset, "offset");
  INTERN(s_data, "data");
  INTERN(s_attr_name, "attr_name");
  INTERN(s_attr_value, "attr_value");
  INTERN(s_version, "version");
  INTERN(s_prior_size, "prior_size");
  INTERN(s_parts, "parts");
  INTERN(s_crc, "crc");
#undef INTERN
  return mod;
fail:
  Py_DECREF(mod);
  return NULL;
}
