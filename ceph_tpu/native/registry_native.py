"""ctypes harness for the native (dlopen) plugin registry.

Drives libec_registry.so the way the reference's daemons drive
ErasureCodePluginRegistry: load plugins by name from a directory, get a
codec via a profile, run encode/decode through the C vtable.  Used by tests
and available to the OSD layer as a native codec path.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Dict, List, Optional, Sequence

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "libec_registry.so")


class _CodecStruct(ctypes.Structure):
    _fields_ = [
        ("k", ctypes.c_int),
        ("m", ctypes.c_int),
        ("priv", ctypes.c_void_p),
        ("encode", ctypes.c_void_p),
        ("decode", ctypes.c_void_p),
        ("destroy", ctypes.c_void_p),
    ]


_ENCODE_T = ctypes.CFUNCTYPE(
    ctypes.c_int,
    ctypes.POINTER(_CodecStruct),
    ctypes.POINTER(ctypes.c_void_p),
    ctypes.POINTER(ctypes.c_void_p),
    ctypes.c_size_t,
)
_DECODE_T = ctypes.CFUNCTYPE(
    ctypes.c_int,
    ctypes.POINTER(_CodecStruct),
    ctypes.POINTER(ctypes.c_void_p),
    ctypes.POINTER(ctypes.c_int),
    ctypes.c_size_t,
)


def _build() -> None:
    subprocess.run(["make", "-C", _DIR], check=True, capture_output=True)


def _load() -> ctypes.CDLL:
    if not os.path.exists(_SO):
        _build()
    # RTLD_GLOBAL so plugin .so's resolve ec_registry_add from us
    lib = ctypes.CDLL(_SO, mode=ctypes.RTLD_GLOBAL)
    lib.ec_registry_load.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
    lib.ec_registry_load.restype = ctypes.c_int
    lib.ec_registry_load_timeout.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int]
    lib.ec_registry_load_timeout.restype = ctypes.c_int
    lib.ec_registry_factory.argtypes = [
        ctypes.c_char_p,
        ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_char_p),
    ]
    lib.ec_registry_factory.restype = ctypes.POINTER(_CodecStruct)
    lib.ec_registry_last_error.restype = ctypes.c_char_p
    return lib


_lib: Optional[ctypes.CDLL] = None


def lib() -> ctypes.CDLL:
    global _lib
    if _lib is None:
        _lib = _load()
    return _lib


def load(name: str, directory: str = _DIR) -> int:
    """Returns 0 or -errno (mirrors ErasureCodePluginRegistry::load)."""
    return lib().ec_registry_load(name.encode(), directory.encode())


def load_with_timeout(name: str, timeout_ms: int = 5000,
                      directory: str = _DIR) -> int:
    """Watchdog load: -ETIMEDOUT when the plugin hangs in dlopen/init
    (the ErasureCodePluginHangs failure mode)."""
    return lib().ec_registry_load_timeout(
        name.encode(), directory.encode(), timeout_ms)


def last_error() -> str:
    return lib().ec_registry_last_error().decode()


class NativeCodec:
    def __init__(self, struct_ptr):
        self._ptr = struct_ptr
        self.k = struct_ptr.contents.k
        self.m = struct_ptr.contents.m
        self._encode = ctypes.cast(struct_ptr.contents.encode, _ENCODE_T)
        self._decode = ctypes.cast(struct_ptr.contents.decode, _DECODE_T)

    def encode(self, data: Sequence[np.ndarray]) -> List[np.ndarray]:
        n = len(data[0])
        coding = [np.zeros(n, dtype=np.uint8) for _ in range(self.m)]
        dptr = (ctypes.c_void_p * self.k)(
            *[d.ctypes.data_as(ctypes.c_void_p) for d in data]
        )
        cptr = (ctypes.c_void_p * self.m)(
            *[c.ctypes.data_as(ctypes.c_void_p) for c in coding]
        )
        rc = self._encode(self._ptr, dptr, cptr, n)
        if rc:
            raise RuntimeError(f"native encode failed: {rc}")
        return coding

    def decode(
        self, chunks: Dict[int, np.ndarray], erased: Sequence[int], n: int
    ) -> Dict[int, np.ndarray]:
        km = self.k + self.m
        bufs = []
        for i in range(km):
            if i in chunks:
                bufs.append(np.ascontiguousarray(chunks[i], dtype=np.uint8))
            else:
                bufs.append(np.zeros(n, dtype=np.uint8))
        cptr = (ctypes.c_void_p * km)(
            *[b.ctypes.data_as(ctypes.c_void_p) for b in bufs]
        )
        earr = (ctypes.c_int * (len(erased) + 1))(*erased, -1)
        rc = self._decode(self._ptr, cptr, earr, n)
        if rc:
            raise RuntimeError(f"native decode failed: {rc}")
        return {i: bufs[i] for i in range(km)}


def factory(
    name: str, profile: Dict[str, str], directory: str = _DIR
) -> NativeCodec:
    items = [f"{k}={v}".encode() for k, v in profile.items()]
    arr = (ctypes.c_char_p * (len(items) + 1))(*items, None)
    ptr = lib().ec_registry_factory(name.encode(), directory.encode(), arr)
    if not ptr:
        raise RuntimeError(f"factory({name}) failed: {last_error()}")
    return NativeCodec(ptr)
