"""Loader for the batched native wire codec (``_wire_native``).

The extension moves the measured Python wire tax -- typed body
encode/decode, envelope parse, frame seal, burst scan (35% of the
saturated cluster-path wall, PERF_NOTES r19) -- into C; this module
owns the graceful-degradation contract around it:

* **gates**: ``CEPH_TPU_NATIVE=0`` (the master native-extension
  escape hatch, config key ``native``) or
  ``osd_wire_codec_native=false`` force the pure-Python codec in
  ``msg/wire.py``.  Both are re-checked on every :func:`native` call,
  so a runtime ``config set`` takes effect for new messengers.
* **degraded build**: no C toolchain / a failed compile logs ONE
  warning with the reason and runs pure-Python with identical wire
  bytes -- never an error, never a second log line.  The outcome is
  exported as the ``ceph_wire_codec_native`` gauge (mgr /metrics) and
  via :func:`status` for the admin surface.
* **type registration**: the codec constructs the same dataclasses the
  Python codec does; ``msg/wire.py`` hands them over at import time
  (:func:`initialize`), keeping this module import-cycle-free.

Build: ``make -C ceph_tpu/native wire_ext`` (done lazily here, like
``py_binding``/``gf_native``); interop is property-tested both
directions in tests/test_wire_native.py and smoked from a clean tree by
``tools/ci_lint.sh --native-codec-smoke``.
"""

from __future__ import annotations

import importlib.util
import logging
import os
import subprocess
import sysconfig
from typing import Optional

_DIR = os.path.dirname(os.path.abspath(__file__))
_LOG = logging.getLogger("ceph_tpu.native.wire_codec")

_module = None            #: the loaded extension (None until first use)
_load_attempted = False
_load_error: Optional[str] = None
_logged_fallback = False
_types = None             #: kwargs for _wire_native.register()


def initialize(**types) -> None:
    """Hand over the message dataclasses (called by ``msg/wire.py`` at
    import time).  Registration happens on first successful load."""
    global _types
    _types = types
    if _module is not None:
        _module.register(**_types)


def _config_enabled() -> bool:
    """Both gates, re-read each call: the ``native`` master toggle
    (CEPH_TPU_NATIVE env) and the codec-specific option."""
    from ceph_tpu.utils.config import get_config

    cfg = get_config()
    try:
        if not bool(cfg.get_val("native")):
            return False
        return bool(cfg.get_val("osd_wire_codec_native"))
    except KeyError:  # pre-schema config object (tests with stubs)
        return True


def _log_fallback(reason: str) -> None:
    global _logged_fallback
    if not _logged_fallback:
        _logged_fallback = True
        _LOG.warning(
            "native wire codec unavailable (%s); running the pure-Python "
            "codec in msg/wire.py -- wire bytes are identical, the "
            "serialization share of the wall is not", reason)


def _try_load():
    """Build (if needed) + import the extension; one attempt per
    process, failure remembered as the fallback reason."""
    global _module, _load_attempted, _load_error
    if _load_attempted:
        return _module
    _load_attempted = True
    suffix = sysconfig.get_config_var("EXT_SUFFIX")
    so = os.path.join(_DIR, f"_wire_native{suffix}")
    try:
        if not os.path.exists(so):
            subprocess.run(
                ["make", "-C", _DIR, "wire_ext"],
                check=True, capture_output=True,
            )
        spec = importlib.util.spec_from_file_location("_wire_native", so)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
    except (OSError, subprocess.CalledProcessError, ImportError) as e:
        _load_error = f"{type(e).__name__}: {e}"
        return None
    if _types is not None:
        mod.register(**_types)
    _module = mod
    return _module


def native():
    """The extension module, or None when gated off / unbuildable.
    The per-messenger dispatch seam calls this once at construction;
    config changes apply to messengers created after them."""
    if not _config_enabled():
        return None
    mod = _try_load()
    if mod is None:
        _log_fallback(_load_error or "unknown load failure")
    return mod


def enabled() -> bool:
    """Whether the native codec is active (the gauge value)."""
    return native() is not None


def status() -> dict:
    """Loader state for the admin/observability surface."""
    active = enabled()
    return {
        "enabled": active,
        "gated_off": not _config_enabled(),
        "load_error": _load_error,
    }


def main(argv=None) -> int:
    """``python -m ceph_tpu.native.wire_codec --smoke``: the ci_lint
    ``--native-codec-smoke`` arm.  Builds the extension from a clean
    tree (the caller removes the prebuilt .so first), then runs the
    interop round-trip: native and Python codecs must produce
    byte-identical bodies and equal decodes for a typed corpus, and a
    frame must survive a real-TCP hop between a native sender and a
    forced-Python receiver (and back)."""
    import argparse
    import asyncio
    import sys

    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true")
    ap.parse_args(argv)
    # under ``python -m`` this file runs as __main__: go through the
    # canonical module so we see the instance msg/wire.py registered
    # the message types with
    from ceph_tpu.native import wire_codec as _wc
    from ceph_tpu.msg import wire  # registers the types

    nat = _wc.native()
    if nat is None:
        print(f"native wire codec failed to load: {_wc.status()}",
              file=sys.stderr)
        return 1
    from ceph_tpu.osd.types import (ECSubRead, ECSubWrite, LogEntry,
                                    Transaction)

    txn = Transaction().write("o@1", 0, b"x" * 9000)
    txn.setattr("o@1", "hinfo", {"crc": [1, 2], "sz": 4096})
    corpus = [
        ECSubWrite(1, 7, "o@1", txn, (3, "osd.1"),
                   [LogEntry(3, "o@1", "append", 16)],
                   reqid=("c", 12, 34), qos_class="gold"),
        ECSubRead(0, 9, to_read={"a": [(0, 512)]}),
        {"op": "client_op", "tid": 5, "data": b"z" * 16384,
         "reqid": ["c", 1, 2], "snapc": None},
        ("committed", 17), "heartbeat",
    ]
    for msg in corpus:
        py = wire.encode_message(msg)
        na = nat.encode_body(msg)
        assert py == na, f"encode mismatch for {type(msg).__name__}"
        assert wire.decode_message(na) == nat.decode_body(py), \
            f"decode mismatch for {type(msg).__name__}"

    async def tcp_roundtrip():
        from ceph_tpu.msg.cluster_bench import free_ports
        from ceph_tpu.msg.tcp import TCPMessenger

        ports = free_ports(2)
        addr = {"a": ("127.0.0.1", ports[0]),
                "b": ("127.0.0.1", ports[1])}
        a, b = TCPMessenger("a", addr), TCPMessenger("b", addr)
        b._native = None  # forced pure-Python receiver
        await a.start()
        await b.start()
        got = []
        b.register("b", lambda src, msg: got.append(msg) or asyncio.sleep(0))
        a.register("a", lambda src, msg: got.append(msg) or asyncio.sleep(0))
        try:
            for msg in corpus:
                await a.send_message("a", "b", msg)   # native -> python
            for msg in corpus:
                await b.send_message("b", "a", msg)   # python -> native
            for _ in range(200):
                if len(got) == 2 * len(corpus):
                    break
                await asyncio.sleep(0.01)
            assert got[:len(corpus)] == corpus, "native->python hop"
            assert got[len(corpus):] == corpus, "python->native hop"
        finally:
            await a.shutdown()
            await b.shutdown()

    asyncio.new_event_loop().run_until_complete(tcp_roundtrip())
    print("native wire codec smoke: interop round-trip ok",
          file=sys.stderr)
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
