// Native CPU codec kernels for the ceph_tpu framework.
//
// Plays the role the jerasure/gf-complete and isa-l SIMD kernels play in the
// reference (reference: src/erasure-code/jerasure links libjerasure;
// src/erasure-code/isa/xor_op.cc hand-vectorized XOR; isa-l ec_encode_data):
// GF(2^8) region multiply via AVX2 vpshufb nibble tables (the gf-complete
// SPLIT w8/4 scheme), vectorized region XOR, full matrix/bitmatrix encode
// loops, and slice-by-8 + SSE4.2 crc32c.  Exposed with a C ABI consumed via
// ctypes (ceph_tpu/native/gf_native.py).
//
// GF(2^8) polynomial is 0x11D to match ceph_tpu.ops.gf and interoperate with
// jerasure/isa-l chunk formats.

#include <cstdint>
#include <cstring>
#include <cstddef>

#if defined(__AVX2__)
#include <immintrin.h>
#endif
#if defined(__SSE4_2__)
#include <nmmintrin.h>
#endif

namespace {

constexpr unsigned kPoly = 0x11D;

uint8_t gf_mul_slow(uint8_t a, uint8_t b) {
  unsigned r = 0;
  unsigned aa = a;
  for (unsigned bb = b; bb; bb >>= 1) {
    if (bb & 1) r ^= aa;
    aa <<= 1;
    if (aa & 0x100) aa ^= kPoly;
  }
  return static_cast<uint8_t>(r);
}

struct MulTables {
  // full 256x256 product table plus per-constant nibble tables
  uint8_t full[256][256];
  uint8_t lo[256][16];   // lo[c][v] = c * v
  uint8_t hi[256][16];   // hi[c][v] = c * (v << 4)
  MulTables() {
    for (int c = 0; c < 256; ++c) {
      for (int v = 0; v < 256; ++v)
        full[c][v] = gf_mul_slow(static_cast<uint8_t>(c),
                                 static_cast<uint8_t>(v));
      for (int v = 0; v < 16; ++v) {
        lo[c][v] = full[c][v];
        hi[c][v] = full[c][v << 4];
      }
    }
  }
};

const MulTables& tables() {
  static MulTables t;
  return t;
}

// out ^= c * in  (accum) or out = c * in
void mul_region(uint8_t c, const uint8_t* in, uint8_t* out, size_t n,
                bool accum) {
  const MulTables& t = tables();
  if (c == 0) {
    if (!accum) std::memset(out, 0, n);
    return;
  }
  size_t i = 0;
#if defined(__AVX2__)
  if (c == 1) {
    for (; i + 32 <= n; i += 32) {
      __m256i x = _mm256_loadu_si256(reinterpret_cast<const __m256i*>(in + i));
      if (accum) {
        __m256i o = _mm256_loadu_si256(reinterpret_cast<__m256i*>(out + i));
        x = _mm256_xor_si256(x, o);
      }
      _mm256_storeu_si256(reinterpret_cast<__m256i*>(out + i), x);
    }
  } else {
    const __m128i lo128 =
        _mm_loadu_si128(reinterpret_cast<const __m128i*>(t.lo[c]));
    const __m128i hi128 =
        _mm_loadu_si128(reinterpret_cast<const __m128i*>(t.hi[c]));
    const __m256i lotab = _mm256_broadcastsi128_si256(lo128);
    const __m256i hitab = _mm256_broadcastsi128_si256(hi128);
    const __m256i maskn = _mm256_set1_epi8(0x0F);
    for (; i + 32 <= n; i += 32) {
      __m256i x = _mm256_loadu_si256(reinterpret_cast<const __m256i*>(in + i));
      __m256i xl = _mm256_and_si256(x, maskn);
      __m256i xh = _mm256_and_si256(_mm256_srli_epi16(x, 4), maskn);
      __m256i p = _mm256_xor_si256(_mm256_shuffle_epi8(lotab, xl),
                                   _mm256_shuffle_epi8(hitab, xh));
      if (accum) {
        __m256i o = _mm256_loadu_si256(reinterpret_cast<__m256i*>(out + i));
        p = _mm256_xor_si256(p, o);
      }
      _mm256_storeu_si256(reinterpret_cast<__m256i*>(out + i), p);
    }
  }
#endif
  const uint8_t* row = t.full[c];
  for (; i < n; ++i) {
    uint8_t v = row[in[i]];
    out[i] = accum ? static_cast<uint8_t>(out[i] ^ v) : v;
  }
}

}  // namespace

extern "C" {

// Runtime CPU feature probe (reference src/arch/probe.cc ceph_arch_probe
// + src/arch/intel.c: the reference fills ceph_arch_intel_* flags once
// and codecs pick kernels off them).  Bitmask: 1=sse4.2, 2=avx,
// 4=avx2, 8=avx512f.
int ec_arch_probe(void) {
  int f = 0;
#if defined(__x86_64__) || defined(__i386__)
  __builtin_cpu_init();
  if (__builtin_cpu_supports("sse4.2")) f |= 1;
  if (__builtin_cpu_supports("avx")) f |= 2;
  if (__builtin_cpu_supports("avx2")) f |= 4;
  if (__builtin_cpu_supports("avx512f")) f |= 8;
#endif
  return f;
}

// What THIS build was compiled to require (so a library copied onto an
// older machine is rejected at load instead of crashing mid-kernel).
int ec_arch_built(void) {
  int f = 0;
#if defined(__SSE4_2__)
  f |= 1;
#endif
#if defined(__AVX__)
  f |= 2;
#endif
#if defined(__AVX2__)
  f |= 4;
#endif
#if defined(__AVX512F__)
  f |= 8;
#endif
  return f;
}

// GF(2^8) region multiply-accumulate: out (^)= c * in over n bytes.
void ec_gf8_mul_region(uint8_t c, const uint8_t* in, uint8_t* out, size_t n,
                       int accum) {
  mul_region(c, in, out, n, accum != 0);
}

// region XOR of k sources into out (isa region_xor semantics).
void ec_region_xor(const uint8_t* const* srcs, int k, uint8_t* out, size_t n) {
  std::memcpy(out, srcs[0], n);
  for (int j = 1; j < k; ++j) {
    size_t i = 0;
#if defined(__AVX2__)
    for (; i + 32 <= n; i += 32) {
      __m256i a = _mm256_loadu_si256(reinterpret_cast<__m256i*>(out + i));
      __m256i b =
          _mm256_loadu_si256(reinterpret_cast<const __m256i*>(srcs[j] + i));
      _mm256_storeu_si256(reinterpret_cast<__m256i*>(out + i),
                          _mm256_xor_si256(a, b));
    }
#endif
    for (; i < n; ++i) out[i] ^= srcs[j][i];
  }
}

// matrix encode: coding[i] = XOR_j matrix[i*k+j] * data[j]; all regions n
// bytes, matrix row-major m x k, byte entries (GF(2^8)).
void ec_gf8_matrix_encode(const uint8_t* matrix, int k, int m,
                          const uint8_t* const* data, uint8_t* const* coding,
                          size_t n) {
  for (int i = 0; i < m; ++i) {
    bool first = true;
    for (int j = 0; j < k; ++j) {
      uint8_t c = matrix[i * k + j];
      if (c == 0) continue;
      mul_region(c, data[j], coding[i], n, !first);
      first = false;
    }
    if (first) std::memset(coding[i], 0, n);
  }
}

// packetized bitmatrix encode: rows [C] packet rows of n bytes each;
// out rows [R]; bitmat row-major R x C of 0/1 bytes.
void ec_bitmatrix_packet_encode(const uint8_t* bitmat, int r, int c,
                                const uint8_t* const* rows,
                                uint8_t* const* out, size_t n) {
  for (int i = 0; i < r; ++i) {
    const uint8_t* sel[256];
    int cnt = 0;
    for (int j = 0; j < c; ++j)
      if (bitmat[i * c + j]) sel[cnt++] = rows[j];
    if (cnt == 0) {
      std::memset(out[i], 0, n);
    } else {
      ec_region_xor(sel, cnt, out[i], n);
    }
  }
}

// crc32c (castagnoli), matching ceph_crc32c semantics (reference:
// src/common/crc32c.cc dispatch; HashInfo uses bufferlist::crc32c).
uint32_t ec_crc32c(uint32_t crc, const uint8_t* data, size_t n) {
#if defined(__SSE4_2__)
  size_t i = 0;
  uint64_t c = crc;
  for (; i + 8 <= n; i += 8) {
    uint64_t v;
    std::memcpy(&v, data + i, 8);
    c = _mm_crc32_u64(c, v);
  }
  uint32_t c32 = static_cast<uint32_t>(c);
  for (; i < n; ++i) c32 = _mm_crc32_u8(c32, data[i]);
  return c32;
#else
  static uint32_t table[256];
  static bool init = false;
  if (!init) {
    for (uint32_t v = 0; v < 256; ++v) {
      uint32_t x = v;
      for (int b = 0; b < 8; ++b)
        x = (x >> 1) ^ ((x & 1) ? 0x82F63B78u : 0);
      table[v] = x;
    }
    init = true;
  }
  uint32_t c = crc;
  for (size_t i = 0; i < n; ++i) c = table[(c ^ data[i]) & 0xFF] ^ (c >> 8);
  return c;
#endif
}

}  // extern "C"
