"""Async messenger-lite: typed message bus with fault injection.

Plays the role of the reference's Messenger/Connection/Dispatcher stack
(reference: src/msg/Messenger.h:40, AsyncMessenger event loops) for the
in-process mini-cluster: entities register a dispatcher, connections carry
ordered messages, and a config-driven fault injector can drop or delay
messages (the ms_inject_socket_failures / ms_inject_delay analogue,
reference: src/common/options.cc:735-756).

asyncio-based: each entity's dispatch loop is a task; send_message is
fire-and-forget like the reference's lossy client policy, with sequence
numbers preserved per connection (lossless-peer ordering).
"""

from __future__ import annotations

import asyncio
from typing import Awaitable, Callable, Dict, Iterable, Optional, Tuple

# FaultInjector moved to the transport layer in round 8 (the msg -> osd
# layering inversion fix); re-exported here for compatibility.
from ceph_tpu.msg.fault import FaultInjector  # noqa: F401


class Messenger:
    """Process-wide bus; entities are addressed by name ("osd.3", "client")."""

    def __init__(self, fault: Optional[FaultInjector] = None):
        self._queues: Dict[str, asyncio.Queue] = {}
        self._dispatchers: Dict[str, Callable] = {}
        self._tasks: Dict[str, asyncio.Task] = {}
        self._down: set = set()
        self.fault = fault if fault is not None else \
            FaultInjector.from_config()
        self._seq = 0

    def register(self, name: str, dispatcher: Callable[[str, object], Awaitable[None]]):
        """dispatcher(from_name, message) coroutine; starts the entity's
        dispatch loop (the reference's ms_fast_dispatch role)."""
        self._queues[name] = asyncio.Queue()
        self._dispatchers[name] = dispatcher
        self._tasks[name] = asyncio.get_event_loop().create_task(
            self._dispatch_loop(name)
        )

    async def _dispatch_loop(self, name: str):
        queue = self._queues[name]
        while True:
            src, msg = await queue.get()
            if name in self._down:
                continue  # dropped on the floor like a dead OSD
            try:
                await self._dispatchers[name](src, msg)
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 -- a dispatcher crash
                import sys, traceback

                traceback.print_exc(file=sys.stderr)

    async def send_message(self, src: str, dst: str, msg: object) -> None:
        """Ordered, lossy-under-injection delivery."""
        if src in self._down:
            return  # a dead entity cannot send either
        if dst in self._down or dst not in self._queues:
            return  # lossy: messages to dead peers vanish
        if self.fault.maybe_drop():
            return
        await self.fault.maybe_delay()
        self._seq += 1
        await self._queues[dst].put((src, msg))

    async def send_messages(
        self, src: str, pairs: Iterable[Tuple[str, object]]
    ) -> None:
        """Multi-destination submit: publish a whole fan-out (e.g. every
        EC sub-op of one client write) in one call.  On the in-process
        bus this is a plain loop; the TCP messenger uses the single
        submission to cork per-peer frame bursts (one writev + one drain
        per peer instead of one per message)."""
        for dst, msg in pairs:
            await self.send_message(src, dst, msg)

    def adopt_task(self, name: str, task: "asyncio.Task") -> None:
        """Track an auxiliary task (e.g. a daemon's tick loop) so shutdown
        cancels it with the dispatch loops.  Completed tasks prune
        themselves -- per-op tasks (client ops, notify acks) would
        otherwise accumulate without bound -- and log any unhandled
        exception on the way out: a silently-dead tick loop is the same
        outage as a wedged one, just later."""
        from ceph_tpu.utils.aio import log_task_exception

        self._tasks[name] = task

        def _done(t, name=name):
            log_task_exception(t, name)
            if self._tasks.get(name) is t:
                self._tasks.pop(name, None)

        task.add_done_callback(_done)

    # -- failure control (thrasher hooks) ----------------------------------

    def mark_down(self, name: str) -> None:
        self._down.add(name)

    def mark_up(self, name: str) -> None:
        self._down.discard(name)

    def is_down(self, name: str) -> bool:
        return name in self._down

    async def shutdown(self) -> None:
        # Snapshot: the adopt_task done-callbacks prune self._tasks as each
        # cancelled task completes, so iterating the live dict here races
        # with its own mutation (dictionary-changed-size RuntimeError).
        #
        # Cancel in ROUNDS, not once: under py<3.11 asyncio.wait_for can
        # swallow a cancellation that races its future's completion
        # (bpo-42130).  A tick loop whose peering pass lost its one
        # cancel that way keeps running and then blocks forever on a
        # reply future no (cancelled) dispatch loop will ever resolve --
        # the whole-suite wedge the tier-1 run hit.  Re-cancelling lands
        # the next CancelledError at the task's next await point.
        tasks = [t for t in self._tasks.values() if not t.done()]
        for _ in range(50):
            if not tasks:
                return
            for task in tasks:
                task.cancel()
            done, pending = await asyncio.wait(tasks, timeout=0.5)
            tasks = list(pending)
        # a task still alive after 50 cancel rounds is looping over
        # CancelledError; abandon it rather than hang the caller
        import sys

        for task in tasks:
            print(f"messenger shutdown: abandoning {task}", file=sys.stderr)
