"""Tracing-overhead benchmark stage + slow-op forensics proof.

The round-16 trace subsystem (utils/trace.py, utils/optracker.py) is
only shippable if leaving it ON costs nothing measurable: this stage
runs the SAME workload under ``trace_mode`` off / sampled / full and
gates sampled-mode throughput within ``overhead_limit_pct`` of off --
on both measured paths:

* **storage_path**: the coalesced host encode/decode cycle
  (``osd/storage_bench.py`` harness) -- covers the coalescer's span
  capture and batch fan-in bookkeeping;
* **cluster_path**: the full client->primary->k+m fan-out over real
  localhost TCP (``msg/cluster_bench.py`` harness) -- covers the
  Objecter/OSDShard TrackedOps, the wire trace field, the per-stage
  histograms and the ack-lag observer.

Correctness is gated alongside the timing:

* in full mode one write's trace must stitch client -> primary ->
  sub-writes with the batch_encode fan-in span, and its op timeline's
  segments must sum to the span's end-to-end duration (tolerance
  ``SUM_TOLERANCE``);
* with ``osd_op_complaint_time`` shrunk to ~0, ops must be DETECTED as
  slow (counter + ``dump_historic_slow_ops``) -- the forensics lane
  fires end to end;
* after quiescing, ZERO started-but-unfinished spans may remain (the
  leak detector ``tools/ci_lint.sh`` also smokes).

Used by bench.py (``trace_path_host`` + the
``trace_overhead_pct_{sampled,full}`` / ``slow_ops_detected`` headline
keys), ``tools/ec_benchmark.py --workload trace-path``, the tier-1
smoke (tests/test_trace.py, loose limit), and ``python -m
ceph_tpu.osd.trace_bench --smoke`` from tools/ci_lint.sh.
"""

from __future__ import annotations

import asyncio
import time
from typing import Dict, List

from ceph_tpu.utils import trace

#: op-timeline segments must sum to end-to-end within this fraction
SUM_TOLERANCE = 0.02
_MODES = ("off", "sampled", "full")


def _restore(prior: Dict[str, object]) -> None:
    from ceph_tpu.utils.config import get_config

    cfg = get_config()
    for key, val in prior.items():
        cfg.set_val(key, val)
    trace.configure()  # reload the cached knobs


def _snapshot_knobs() -> Dict[str, object]:
    from ceph_tpu.utils.config import get_config

    cfg = get_config()
    return {k: cfg.get_val(k)
            for k in ("trace_mode", "trace_sample_every",
                      "osd_op_complaint_time")}


async def _storage_cycle(harness, payloads: List[bytes],
                         writers: int) -> float:
    from ceph_tpu.osd.storage_bench import StoragePathHarness  # noqa: F401

    t0 = time.perf_counter()
    store = await harness.write_pass(payloads, coalesce=True,
                                     writers=writers)
    await harness.read_pass(store, len(payloads),
                            [len(p) for p in payloads], coalesce=True,
                            readers=writers)
    return time.perf_counter() - t0


async def _cluster_cycle(harness, payloads: Dict[str, bytes],
                         writers: int) -> float:
    write_s = await harness.run_writes(payloads, writers)
    read_s, got = await harness.run_reads(payloads, writers)
    for oid, data in payloads.items():
        if got.get(oid) != data:
            raise AssertionError(f"trace-path: read-back of {oid} "
                                 "mismatched")
    return write_s + read_s


def _verify_stitched_trace() -> dict:
    """The full-mode correctness gate: one trace stitches across the
    daemons and its op timeline sums to the measured end-to-end."""
    spans = trace.dump()
    primary = next((s for s in reversed(spans)
                    if s["name"] == "osd:write"), None)
    if primary is None:
        raise AssertionError("trace-path: no osd:write span collected "
                             "in full mode")
    fam = [s for s in spans if s["trace_id"] == primary["trace_id"]]
    names = [s["name"] for s in fam]
    if "client:write" not in names:
        raise AssertionError("trace-path: client root span missing "
                             f"from trace (got {sorted(set(names))})")
    subs = [s for s in fam if s["name"].endswith(":sub_write")]
    if not subs:
        raise AssertionError("trace-path: no sub_write spans stitched")
    tl = trace.op_timeline(primary["span_id"])
    seg_sum = sum(s["ms"] for s in tl["segments"])
    total = tl["total_ms"]
    if total and abs(seg_sum - total) > max(0.5, SUM_TOLERANCE * total):
        raise AssertionError(
            f"trace-path: timeline segments sum to {seg_sum:.3f}ms but "
            f"the op took {total:.3f}ms")
    batch = next((s for s in fam if s["name"] == "batch_encode"), None)
    return {
        "trace_id": primary["trace_id"],
        "spans": len(fam),
        "sub_writes": len(subs),
        "timeline_total_ms": total,
        "timeline_segment_sum_ms": round(seg_sum, 6),
        "batch_encode_amortized_over":
            batch["amortized_over"] if batch else None,
    }


async def _slow_op_probe(cluster) -> dict:
    """Shrink the complaint time so ordinary ops read as slow: the
    detection lane (counter, warning, historic-slow retention with a
    decomposed timeline) must fire."""
    from ceph_tpu.utils.config import get_config

    cfg = get_config()
    prior = cfg.get_val("osd_op_complaint_time")
    cfg.set_val("osd_op_complaint_time", 1e-6)
    try:
        await cluster.objecter.write("slowprobe", b"s" * 4096)
        await cluster.objecter.read("slowprobe")
    finally:
        cfg.set_val("osd_op_complaint_time", prior)
    detected = sum(o.optracker.slow_ops for o in cluster.osds)
    detected += cluster.objecter.optracker.slow_ops
    dumps = [o.optracker.dump_historic_slow_ops() for o in cluster.osds]
    returned = sum(d["num_ops"] for d in dumps)
    timelined = any(
        op.get("timeline", {}).get("segments")
        for d in dumps for op in d["ops"]
    )
    if not detected:
        raise AssertionError("trace-path: no slow ops detected with "
                             "complaint_time ~0")
    if not returned:
        raise AssertionError("trace-path: dump_historic_slow_ops "
                             "returned nothing")
    return {"slow_ops_detected": detected,
            "historic_slow_returned": returned,
            "decomposed_timeline_present": bool(timelined)}


def run_trace_overhead_bench(ec, *, n_objects: int = 48,
                             obj_bytes: int = 16 << 10, writers: int = 8,
                             iters: int = 2, seed: int = 77,
                             overhead_limit_pct: float = 3.0,
                             retries: int = 3,
                             n_osds=None) -> dict:
    """Off / sampled / full comparison on storage_path + cluster_path,
    correctness-gated (stitched trace, timeline sums, slow-op
    detection, zero unfinished spans); raises if sampled-mode overhead
    stays above ``overhead_limit_pct`` across ``retries`` attempts."""
    from ceph_tpu.msg.cluster_bench import ClusterHarness
    from ceph_tpu.msg.cluster_bench import make_payloads as mk_cluster
    from ceph_tpu.osd.storage_bench import StoragePathHarness
    from ceph_tpu.osd.storage_bench import make_payloads as mk_storage

    if n_osds is None:
        n_osds = ec.get_chunk_count()
    prior = _snapshot_knobs()
    sp = StoragePathHarness(ec)
    sp_payloads = mk_storage(n_objects, obj_bytes, seed)
    cl_payloads = mk_cluster(n_objects, obj_bytes, seed + 1)
    loop = asyncio.new_event_loop()
    best: Dict[str, Dict[str, float]] = {m: {} for m in _MODES}
    extras: Dict[str, object] = {}
    try:
        harness = ClusterHarness(ec, n_osds, cork=True,
                                 pool="tracepool")
        loop.run_until_complete(harness.start())
        for oid in cl_payloads:
            harness.objecter.acting_set(oid)
        try:
            # warm both paths (XLA compile, TCP sessions) off-trace
            trace.configure(mode="off")
            loop.run_until_complete(_storage_cycle(sp, sp_payloads,
                                                   writers))
            loop.run_until_complete(_cluster_cycle(harness, cl_payloads,
                                                   writers))
            attempts = 0
            # per-block overhead RATIOS: each iteration measures the
            # three modes back to back, so a ratio compares walls taken
            # seconds apart -- slow machine drift (noisy neighbors,
            # thermal) cancels, where a global best-wall comparison
            # would pin one mode to a quiet window and another to a
            # loud one.  The gate takes the MIN ratio: one quiet block
            # proving the overhead within bound is evidence enough.
            ratios: Dict[str, List[float]] = {"sampled": [], "full": []}
            while True:
                attempts += 1
                for _ in range(max(1, iters)):
                    walls = {}
                    for mode in _MODES:
                        trace.configure(mode=mode)
                        sp_s = loop.run_until_complete(
                            _storage_cycle(sp, sp_payloads, writers))
                        cl_s = loop.run_until_complete(
                            _cluster_cycle(harness, cl_payloads,
                                           writers))
                        walls[mode] = sp_s + cl_s
                        cur = best[mode]
                        if "storage_s" not in cur or \
                                sp_s < cur["storage_s"]:
                            cur["storage_s"] = sp_s
                        if "cluster_s" not in cur or \
                                cl_s < cur["cluster_s"]:
                            cur["cluster_s"] = cl_s
                    for m in ("sampled", "full"):
                        ratios[m].append(walls[m] / walls["off"])
                overhead = {m: (min(ratios[m]) - 1) * 100
                            for m in ("sampled", "full")}
                if overhead["sampled"] <= overhead_limit_pct or \
                        attempts >= max(1, retries):
                    break
            if overhead["sampled"] > overhead_limit_pct:
                raise AssertionError(
                    f"trace-path: sampled-mode overhead "
                    f"{overhead['sampled']:.2f}% exceeds the "
                    f"{overhead_limit_pct}% gate after {attempts} "
                    "attempts")
            # correctness gates, in full mode on the SAME cluster
            trace.configure(mode="full")
            loop.run_until_complete(
                harness.objecter.write("stitchprobe", b"p" * obj_bytes))
            extras["stitched"] = _verify_stitched_trace()
            extras.update(loop.run_until_complete(
                _slow_op_probe(harness)))
        finally:
            loop.run_until_complete(harness.shutdown())
        # quiesced: nothing may still hold an unfinished span
        unfinished = trace.unfinished_count()
        if unfinished:
            raise AssertionError(
                f"trace-path: {unfinished} unfinished span(s) after "
                f"quiesce: {trace.unfinished_names()}")
        extras["unfinished_spans"] = 0
    finally:
        loop.close()
        _restore(prior)
    nbytes = n_objects * obj_bytes * 2  # write + read, per path
    modes_out = {}
    for m in _MODES:
        modes_out[m] = {
            "storage_wall_s": round(best[m]["storage_s"], 6),
            "cluster_wall_s": round(best[m]["cluster_s"], 6),
            "storage_MiBs": round(
                nbytes / best[m]["storage_s"] / (1 << 20), 3),
            "cluster_MiBs": round(
                nbytes / best[m]["cluster_s"] / (1 << 20), 3),
        }
    return dict({
        "n_objects": n_objects,
        "obj_bytes": obj_bytes,
        "writers": writers,
        "overhead_limit_pct": overhead_limit_pct,
        "modes": modes_out,
        "trace_overhead_pct_sampled": round(overhead["sampled"], 3),
        "trace_overhead_pct_full": round(overhead["full"], 3),
        "attempts": attempts,
    }, **extras)


def main(argv=None) -> int:
    """``python -m ceph_tpu.osd.trace_bench [--smoke]``: the ci_lint
    traced-op smoke -- one traced op end to end, failing on unfinished
    spans, missing stitching, or (non-smoke) overhead regression."""
    import argparse
    import json
    import sys

    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="tiny shapes + a loose overhead gate (the "
                         "ci_lint wrapper; bench.py runs the real gate)")
    args = ap.parse_args(argv)
    from ceph_tpu.plugins import registry as registry_mod

    ec = registry_mod.instance().factory(
        "jerasure",
        {"k": "4", "m": "2", "technique": "reed_sol_van"})
    if args.smoke:
        result = run_trace_overhead_bench(
            ec, n_objects=8, obj_bytes=4096, writers=4, iters=1,
            overhead_limit_pct=50.0)
    else:
        result = run_trace_overhead_bench(ec)
    print(json.dumps(result, indent=2), file=sys.stderr)
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
