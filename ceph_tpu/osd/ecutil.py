"""Stripe math + codec driver + per-shard hash info (ECUtil equivalent).

Reference: src/osd/ECUtil.{h,cc}.

* ``StripeInfo`` -- the logical<->chunk offset algebra (ECUtil.h:26-79).
* ``encode``/``decode`` -- where the reference loops the codec one
  stripe_width at a time (ECUtil.cc:136-148), we hand the codec ALL stripes
  in one call: the chunk arrays are contiguous per shard, and every engine
  (numpy, native C++, XLA/pallas) treats the byte axis as the matmul N
  dimension, so the whole object is one device dispatch.  This is the
  stripe-batching shim SURVEY.md section 6 calls for.
* ``HashInfo`` -- per-shard cumulative crc32c + total size, persisted as a
  shard xattr and checked on every shard read (ECUtil.h:100-158,
  ECUtil.cc:161-235; read-side check ECBackend.cc:1054-1076).
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from ceph_tpu.native.gf_native import crc32c, crc32c_rows
from ceph_tpu.ops import bucketing


class StripeInfo:
    """stripe_info_t: stripe_size = k data chunks per stripe."""

    def __init__(self, stripe_size: int, stripe_width: int):
        assert stripe_width % stripe_size == 0
        self.stripe_width = stripe_width
        self.chunk_size = stripe_width // stripe_size

    def logical_offset_is_stripe_aligned(self, logical: int) -> bool:
        return logical % self.stripe_width == 0

    def logical_to_prev_chunk_offset(self, offset: int) -> int:
        return (offset // self.stripe_width) * self.chunk_size

    def logical_to_next_chunk_offset(self, offset: int) -> int:
        return (
            (offset + self.stripe_width - 1) // self.stripe_width
        ) * self.chunk_size

    def logical_to_prev_stripe_offset(self, offset: int) -> int:
        return offset - (offset % self.stripe_width)

    def logical_to_next_stripe_offset(self, offset: int) -> int:
        rem = offset % self.stripe_width
        return offset - rem + self.stripe_width if rem else offset

    def aligned_logical_offset_to_chunk_offset(self, offset: int) -> int:
        assert offset % self.stripe_width == 0
        return (offset // self.stripe_width) * self.chunk_size

    def aligned_chunk_offset_to_logical_offset(self, offset: int) -> int:
        assert offset % self.chunk_size == 0
        return (offset // self.chunk_size) * self.stripe_width

    def offset_len_to_stripe_bounds(self, off: int, length: int) -> tuple:
        start = self.logical_to_prev_stripe_offset(off)
        length = self.logical_to_next_stripe_offset((off - start) + length)
        return start, length


def as_u8(data) -> np.ndarray:
    """Zero-copy uint8 view of bytes / bytearray / memoryview / ndarray
    input (``np.frombuffer`` shares the caller's buffer; the old
    ``bytes(data)`` round-trip copied memoryviews and bytearrays)."""
    if isinstance(data, np.ndarray):
        return data if data.dtype == np.uint8 else data.view(np.uint8)
    return np.frombuffer(data, dtype=np.uint8)


def to_shard_major(sinfo: StripeInfo, k: int, data) -> np.ndarray:
    """[k, shard_len] shard-major view of a stripe-aligned logical
    buffer: the ONE transpose copy the host write path makes (every
    other step is a view)."""
    buf = as_u8(data)
    assert len(buf) % sinfo.stripe_width == 0, "input must be stripe-aligned"
    n_stripes = len(buf) // sinfo.stripe_width
    # reshape so each shard's stripes are contiguous: [stripes, k, chunk]
    per_stripe = buf.reshape(n_stripes, k, sinfo.chunk_size)
    return np.ascontiguousarray(per_stripe.transpose(1, 0, 2)).reshape(k, -1)


def encode(
    sinfo: StripeInfo,
    ec,
    data: bytes | np.ndarray,
    want: Iterable[int],
) -> Dict[int, np.ndarray]:
    """Encode a stripe-aligned buffer into per-shard chunk arrays.

    One codec call covers every stripe: ec.encode pads/splits per its own
    chunk-size algebra, which for a stripe_width-aligned buffer yields
    chunk_size * n_stripes per shard -- the same bytes as the reference's
    per-stripe loop concatenated (each stripe's chunk is contiguous within
    its shard at offset stripe_index * chunk_size).
    """
    block = to_shard_major(sinfo, ec.get_data_chunk_count(), data)
    return encode_shard_major_many(ec, [block], want)[0]


def encode_shard_major_many(
    ec,
    blocks: List[np.ndarray],
    want: Iterable[int],
) -> List[Dict[int, np.ndarray]]:
    """ONE batched codec dispatch covering many shard-major [k, bs]
    blocks -- the write-path coalescer's dispatch function.

    Pipeline-backed plugins fuse the whole set into granules (one H2D +
    dispatch + D2H ladder covers every block, bounded in-flight depth);
    other codecs fall back to one encode per block.  Same bytes either
    way: each block's flattening is exactly the per-shard chunk split
    the codec's own algebra performs.
    """
    encs, _devs = encode_shard_major_many_resident(ec, blocks, want, None)
    return encs


def encode_shard_major_many_resident(
    ec,
    blocks: List[np.ndarray],
    want: Iterable[int],
    keep_device: Optional[Sequence[bool]] = None,
) -> Tuple[List[Dict[int, np.ndarray]], List[Optional[object]]]:
    """:func:`encode_shard_major_many` plus the device-resident write
    lane: ``keep_device[i]`` asks the codec to ALSO hand back stripe
    i's still-resident ``[k+m, bs]`` device block (promote-from-encode
    -- the cache tier inserts it with zero re-upload).  The second list
    holds those blocks, None wherever the codec/layout cannot compose
    one (callers fall back to the host put path).

    Codecs advertising ``shape_bucketing`` get their blocks padded up
    the shared rung ladder (``ops/bucketing.py``) on the per-block
    fallback path, so even non-batched dispatch compiles a bounded
    shape set; the batched lanes bucket at granule level inside the
    pipeline."""
    want = list(want)
    km = ec.get_chunk_count()
    devs: List[Optional[object]] = [None] * len(blocks)
    if hasattr(ec, "encode_shard_major_batch") and \
            all(b.shape[1] for b in blocks):
        encs, devs = ec.encode_shard_major_batch(blocks, keep_device)
        return [{i: enc[i] for i in want} for enc in encs], devs
    if hasattr(ec, "encode_batch") and all(b.shape[1] for b in blocks):
        encs = ec.encode_batch([b.reshape(-1) for b in blocks])
        return [{i: enc[i] for i in want} for enc in encs], devs
    out = []
    bucket = bool(getattr(ec, "shape_bucketing", False))
    align = getattr(ec, "bucket_align", lambda: 1)() if bucket else 1
    for b in blocks:
        bs = b.shape[1]
        if bs == 0:
            out.append({i: np.zeros(0, dtype=np.uint8) for i in want})
            continue
        if bucket:
            # pad the column axis up the rung ladder (GF parity is
            # columnwise: zero columns encode to zero and trim exactly)
            target = bucketing.bucket_bytes(bs, align)
            if target != bs:
                padded = np.zeros((b.shape[0], target), dtype=np.uint8)
                padded[:, :bs] = b
                enc = ec.encode(set(range(km)), padded.reshape(-1))
                out.append({i: enc[i][:bs] for i in want})
                continue
        enc = ec.encode(set(range(km)), b.reshape(-1))
        out.append({i: enc[i] for i in want})
    return out, devs


def encode_many(
    sinfo: StripeInfo,
    ec,
    bufs: List,
    want: Iterable[int],
) -> List[Dict[int, np.ndarray]]:
    """Batched multi-object encode: one transpose per buffer, one batched
    codec dispatch for the whole set."""
    k = ec.get_data_chunk_count()
    return encode_shard_major_many(
        ec, [to_shard_major(sinfo, k, b) for b in bufs], want
    )


def data_positions(ec) -> List[int]:
    """Positions holding logical data chunks (honors the chunk mapping)."""
    mapping = ec.get_chunk_mapping()
    k = ec.get_data_chunk_count()
    if mapping:
        return list(mapping[:k])
    return list(range(k))


def _reassemble(sinfo: StripeInfo, ec, out: Dict[int, np.ndarray]) -> bytes:
    """Shard-major decode output -> logical bytes (one transpose copy)."""
    k = ec.get_data_chunk_count()
    pos = data_positions(ec)
    shard_len = len(out[pos[0]])
    n_stripes = shard_len // sinfo.chunk_size
    stacked = np.stack([as_u8(out[p]) for p in pos])  # [k, shard_len]
    per_stripe = stacked.reshape(k, n_stripes, sinfo.chunk_size).transpose(
        1, 0, 2
    )
    return per_stripe.tobytes()


def decode_concat(
    sinfo: StripeInfo,
    ec,
    to_decode: Dict[int, np.ndarray],
) -> bytes:
    """Rebuild the logical buffer from per-shard chunk streams."""
    return decode_concat_many(sinfo, ec, [to_decode])[0]


def decode_concat_many(
    sinfo: StripeInfo,
    ec,
    maps: List[Dict[int, np.ndarray]],
) -> List[bytes]:
    """Batched logical reads -- the read-path coalescer's dispatch.

    Stripes sharing an erasure signature share one fused reconstruction
    dispatch (``decode_batch`` groups by available-set and reuses the
    pipeline's per-signature decode stream); codecs without the batched
    API decode per map.  Zero-length maps (zero-byte objects) short-
    circuit without touching the codec.
    """
    pos = data_positions(ec)
    results: List[bytes] = [b""] * len(maps)
    need = [
        i for i, m in enumerate(maps)
        if m and len(next(iter(m.values()))) > 0
    ]
    if not need:
        return results
    if hasattr(ec, "decode_batch"):
        outs = ec.decode_batch([maps[i] for i in need])
    else:
        outs = [ec.decode(set(pos), maps[i]) for i in need]
    for i, out in zip(need, outs):
        results[i] = _reassemble(sinfo, ec, out)
    return results


def decode_shards(
    ec,
    available: Dict[int, np.ndarray],
    want: Iterable[int],
) -> Dict[int, np.ndarray]:
    """Reconstruct specific shards (recovery path)."""
    return ec.decode(set(want), available)


def decode_shards_many(
    ec,
    maps: List[Dict[int, np.ndarray]],
    wants: List[Iterable[int]],
) -> List[Dict[int, np.ndarray]]:
    """Batched shard reconstruction -- the RECOVERY coalescer's fused
    dispatch (peer of :func:`decode_concat_many` on the read path).

    Many objects' source-chunk maps ride one batched codec call;
    ``decode_batch`` groups maps sharing an erasure signature onto one
    reconstruction stream (and the pipeline's rung-bucketed granules),
    so a rebuild of N same-signature objects costs one fused dispatch,
    not N.  Returns per map a dict covering at least ``wants[i]`` (the
    batched path reconstructs every missing position; recovery uses
    the extras for promote-on-recovery's full-block insert).  Codecs
    without the batched API decode per map."""
    results: List[Dict[int, np.ndarray]] = [{}] * len(maps)
    need = [i for i, m in enumerate(maps)
            if m and len(next(iter(m.values()))) > 0]
    if not need:
        return results
    if hasattr(ec, "decode_batch"):
        outs = ec.decode_batch([maps[i] for i in need])
    else:
        outs = [ec.decode(set(wants[i]), dict(maps[i])) for i in need]
    for i, out in zip(need, outs):
        results[i] = out
    return results


class HashInfo:
    """Per-shard cumulative crc32c + total per-shard size."""

    def __init__(self, num_chunks: int):
        self.total_chunk_size = 0
        self.cumulative_shard_hashes: List[int] = [0xFFFFFFFF] * num_chunks

    def append(self, old_size: int, to_append: Dict[int, np.ndarray]) -> None:
        assert old_size == self.total_chunk_size
        appended = 0
        if self.cumulative_shard_hashes and to_append:
            # hashes survive only on pure-append histories; once an
            # overwrite cleared them (ec_overwrites semantics,
            # reference ECUtil.cc hinfo reset) later appends track
            # sizes only -- indexing the empty list was a crash on
            # the append-after-overwrite path.  One batched FFI loop
            # over the k+m chunks (crc32c_rows): at 2 KiB chunks the
            # per-call wrapper cost ~4x the crc itself on the hot
            # commit path
            shards = sorted(to_append)
            chunks = [to_append[s] for s in shards]
            appended = len(chunks[-1])
            hashes = crc32c_rows(
                chunks, [self.cumulative_shard_hashes[s] for s in shards]
            )
            for s, h in zip(shards, hashes):
                self.cumulative_shard_hashes[s] = h
        else:
            for _shard, chunk in to_append.items():
                appended = len(chunk)
        self.total_chunk_size += appended

    def get_chunk_hash(self, shard: int) -> int:
        return self.cumulative_shard_hashes[shard]

    def has_chunk_hash(self) -> bool:
        return bool(self.cumulative_shard_hashes)

    def get_total_chunk_size(self) -> int:
        return self.total_chunk_size

    def get_total_logical_size(self, sinfo: StripeInfo) -> int:
        return self.total_chunk_size * (
            sinfo.stripe_width // sinfo.chunk_size
        )

    # -- wire form (dict-based; the osd layer stores it as a shard xattr) --

    def to_dict(self) -> dict:
        return {
            "total_chunk_size": self.total_chunk_size,
            "cumulative_shard_hashes": list(self.cumulative_shard_hashes),
        }

    @classmethod
    def from_dict(cls, d: dict) -> "HashInfo":
        h = cls(len(d["cumulative_shard_hashes"]))
        h.total_chunk_size = d["total_chunk_size"]
        h.cumulative_shard_hashes = list(d["cumulative_shard_hashes"])
        return h


HINFO_KEY = "hinfo_key"


def is_hinfo_key_string(key: str) -> bool:
    return key == HINFO_KEY
