"""Unified QoS admission: dmClock tags in front of the batched data plane.

Rounds 6-15 built two uncoordinated control layers on the OSD: the
WPQ/mClock op queues (``osd/opqueue.py``) order sub-ops INTO the shard
worker, while the per-PG coalescer (``osd/coalescer.py``) and the
round-14 BackgroundThrottle decide which fused batches actually reach
the device -- so a dequeue was a QoS decision the batching layer then
ignored.  This module fuses them (ROADMAP item 3): the dmClock tag
scheduler becomes the coalescer's ADMISSION stage, so a dispatched
batch IS a QoS decision.

Model (docs/qos.md):

* Every batched dispatch -- a coalesced client encode/decode batch, a
  recovery gather/decode/push cycle, a scrub read round -- first claims
  one of ``osd_qos_slots`` admission slots under its op class
  (``client`` / ``recovery`` / ``scrub`` by default; the profile string
  can add client sub-classes).  Cost is the batch's STRIPE BYTES, and
  the per-class (reservation, weight, limit) triple from
  ``osd_qos_profile`` (MiB/s) spaces the dmClock tags.
* When slots are free and no limit binds, admission is work-conserving:
  a grant costs one tag update, no waiting, no task switch.  Under
  contention the freed slot goes to the eligible class by dmClock
  phase order -- reservation tags first (the floor), then spare
  capacity by proportional tag (weights), limit tags gating both.
* This REPLACES the round-14 BackgroundThrottle preemption gauge
  (``_client_ops_queued > 16`` + bounded backoff rounds): recovery is
  now just a class with a small weight, so it yields to client bursts
  by tag order but can never be starved (its proportional tag is always
  finite) -- the non-starvation property the gauge's MAX_PREEMPT_ROUNDS
  hack approximated.

Deadlock-freedom: a slot holder never waits on another class's grant --
slots are released by the dispatch that claimed them, grants wait only
on slot releases and the injected clock, and the coalescer's dispatch
functions never re-enter admission.  Time comes from ONE injected
monotonic clock (shared with ``MClockQueue``), so tag ordering survives
wall-clock regressions and tests can drive a virtual clock.
"""

from __future__ import annotations

import asyncio
import time
from typing import Callable, Dict, Optional, Tuple

from ceph_tpu.osd.opqueue import MClockQueue
from ceph_tpu.utils.perf import PerfCounters

#: default per-class (reservation MiB/s, weight, limit MiB/s): client
#: traffic owns the weight, recovery holds a small reservation so a
#: rebuild always progresses (the data-loss window argument from round
#: 14), scrub trickles.  0 reservation/limit = none.
DEFAULT_PROFILE = "client:0:100:0,recovery:4:10:0,scrub:1:5:0"

_MIB = float(1 << 20)

#: process-wide fairness gauges (per class), set by the qos bench /
#: scenario runner and exposed by the prometheus mgr module as
#: ``ceph_qos_fairness_spread{qos_class=...}``: max/min achieved
#: per-client throughput within the class (1.0 = perfectly fair)
_fairness_spread: Dict[str, float] = {}


def set_fairness_spread(klass: str, spread: Optional[float]) -> None:
    if spread is None:
        _fairness_spread.pop(klass, None)
    else:
        _fairness_spread[klass] = float(spread)


def fairness_spreads() -> Dict[str, float]:
    return dict(_fairness_spread)


def parse_profile(text: Optional[str] = None
                  ) -> Dict[str, Tuple[float, float, float]]:
    """``osd_qos_profile`` -> {class: (res MiB/s, weight, lim MiB/s)}.

    Grammar: comma/space-separated ``name:res:weight:limit`` entries;
    malformed entries are skipped (config must never wedge a daemon)."""
    if text is None:
        from ceph_tpu.utils.config import get_config

        text = str(get_config().get_val("osd_qos_profile")) or ""
    text = text.strip() or DEFAULT_PROFILE
    out: Dict[str, Tuple[float, float, float]] = {}
    for entry in text.replace(",", " ").split():
        parts = entry.split(":")
        if len(parts) != 4:
            continue
        name, res, wgt, lim = parts
        try:
            out[name] = (float(res), float(wgt), float(lim))
        except ValueError:
            continue
    return out or parse_profile(DEFAULT_PROFILE)


def profile_bytes_per_s(profile: Dict[str, Tuple[float, float, float]]
                        ) -> Dict[str, Tuple[float, float, float]]:
    """MiB/s rates -> bytes/s (the admission layer's cost unit)."""
    return {
        name: (res * _MIB, wgt, lim * _MIB)
        for name, (res, wgt, lim) in profile.items()
    }


class QoSAdmission:
    """dmClock slot admission for batched dispatches (one per OSDShard).

    ``slot(klass, cost_bytes)`` is an async context manager: entering
    claims an admission slot in dmClock tag order, exiting releases it.
    ``admit(klass, cost_bytes)`` is the transient form (claim + release
    immediately): pure ordering/pacing for stages whose occupancy is
    bounded elsewhere (the scrub chunk cursor).

    Not thread-safe; single event loop by construction (the OSD data
    path).  With ``schedule_timers=False`` (virtual-clock tests) the
    caller drives eligibility by calling :meth:`poll` after advancing
    the injected clock.
    """

    def __init__(self, *, slots: Optional[int] = None,
                 classes: Optional[Dict[str, tuple]] = None,
                 clock: Callable[[], float] = time.monotonic,
                 perf: Optional[PerfCounters] = None,
                 perf_classes: Optional[set] = None,
                 schedule_timers: bool = True):
        if slots is None:
            from ceph_tpu.utils.config import get_config

            slots = int(get_config().get_val("osd_qos_slots"))
        if classes is None:
            classes = profile_bytes_per_s(parse_profile())
        self.classes = dict(classes)
        self.slots = max(1, int(slots))
        self._free = self.slots
        self._clock = clock
        self._q = MClockQueue(self.classes, clock=clock)
        self.perf = perf
        #: classes whose grants land in the shared qos_<class>_* perf
        #: namespace (None = all): the op-level and batch-level
        #: instances on one shard share a PerfCounters, so each class
        #: is counted at exactly ONE layer (client classes per op,
        #: recovery/scrub per batch -- docs/qos.md)
        self.perf_classes = perf_classes
        self._timers = schedule_timers
        self._timer_handle = None
        #: per-class QoS-attributed admission-wait histograms (the
        #: round-16 per-stage discipline: prometheus _bucket/_sum/_count
        #: series named <daemon>.qos_wait_<class>_usec)
        self._wait_hist: Dict[str, object] = {}
        #: grants since construction, per class (introspection + tests)
        self.granted: Dict[str, int] = {}
        self.granted_bytes: Dict[str, int] = {}

    # -- introspection ------------------------------------------------------

    def status(self) -> dict:
        return {
            "slots": self.slots,
            "free": self._free,
            "queued": len(self._q),
            "classes": {k: list(v) for k, v in self.classes.items()},
            "granted": dict(self.granted),
            "granted_bytes": dict(self.granted_bytes),
        }

    # -- the admission surface ---------------------------------------------

    def slot(self, klass: str, cost_bytes: int) -> "_Slot":
        """Claim-one-slot context manager (batch dispatches)."""
        return _Slot(self, klass, cost_bytes)

    async def admit(self, klass: str, cost_bytes: int) -> None:
        """Transient admission: tag-ordered grant, slot returned at
        once (ordering + limit pacing without occupancy tracking)."""
        if await self.acquire(klass, cost_bytes):
            self.release_slot()

    async def acquire(self, klass: str, cost_bytes: int) -> bool:
        """Claim a slot under ``klass``; True iff a slot is actually
        held (an unregistered class is counted, never throttled, and
        owes no release) -- the token-free half of :meth:`slot` for
        callers like the BackgroundThrottle whose acquire and release
        sites are different methods."""
        await self._acquire(klass, cost_bytes)
        return klass in self.classes

    def release_slot(self) -> None:
        """Return a slot claimed by :meth:`acquire`."""
        self._release()

    async def _acquire(self, klass: str, cost_bytes: int) -> None:
        if klass not in self.classes:
            # unregistered class: counted, never throttled (the open
            # default -- QoS confines only what the profile names)
            self._count(klass, cost_bytes, waited=False)
            return
        loop = asyncio.get_event_loop()
        fut = loop.create_future()
        self._q.enqueue(klass, max(1, int(cost_bytes)), (fut, klass))
        self.poll()
        if fut.done():
            self._count(klass, cost_bytes, waited=False)
            return
        self._count(klass, cost_bytes, waited=True)
        t0 = self._clock()
        try:
            await fut
        except asyncio.CancelledError:
            # the waiter died before its grant: if the grant already
            # landed, hand the slot straight back (never leak one)
            if fut.done() and not fut.cancelled():
                self._release()
            raise
        if self._counted(klass) and self.perf is not None:
            waited_s = self._clock() - t0
            self.perf.tinc(f"qos_{klass}_wait", waited_s)
            hist = self._wait_hist.get(klass)
            if hist is None:
                from ceph_tpu.utils.perf import stage_histogram

                hist = self._wait_hist[klass] = stage_histogram(
                    f"{self.perf.name}.qos_wait_{klass}_usec")
            hist.inc(waited_s * 1e6, cost_bytes)

    def _release(self) -> None:
        self._free += 1
        self.poll()

    def _counted(self, klass: str) -> bool:
        return self.perf_classes is None or klass in self.perf_classes

    def _count(self, klass: str, cost_bytes: int, waited: bool) -> None:
        self.granted[klass] = self.granted.get(klass, 0) + 1
        self.granted_bytes[klass] = \
            self.granted_bytes.get(klass, 0) + int(cost_bytes)
        if self.perf is not None and self._counted(klass):
            self.perf.inc(f"qos_{klass}_ops")
            self.perf.inc(f"qos_{klass}_bytes", int(cost_bytes))
            if waited:
                self.perf.inc(f"qos_{klass}_throttle_waits")

    # -- the grant pump -----------------------------------------------------

    def poll(self) -> int:
        """Grant eligible waiters into free slots (dmClock phase order);
        returns grants made.  Re-arms the idle timer for limit-blocked
        heads.  Safe to call any time (tests drive it manually after
        advancing a virtual clock)."""
        granted = 0
        while self._free > 0:
            item = self._q.dequeue()
            if item is None:
                break
            fut, _klass = item
            if fut.cancelled():
                continue
            self._free -= 1
            fut.set_result(None)
            granted += 1
        self._arm_timer()
        return granted

    def _arm_timer(self) -> None:
        if not self._timers:
            return
        if self._timer_handle is not None:
            self._timer_handle.cancel()
            self._timer_handle = None
        if self._free <= 0:
            return  # a release will pump; no clock wait is pending
        delay = self._q.idle_for()
        if delay is None or delay <= 0:
            return
        try:
            loop = asyncio.get_event_loop()
        except RuntimeError:
            return
        self._timer_handle = loop.call_later(delay, self._on_timer)

    def _on_timer(self) -> None:
        self._timer_handle = None
        self.poll()


class _Slot:
    """The ``async with admission.slot(...)`` guard."""

    __slots__ = ("_adm", "_klass", "_cost", "_held")

    def __init__(self, adm: QoSAdmission, klass: str, cost: int):
        self._adm = adm
        self._klass = klass
        self._cost = cost
        self._held = False

    async def __aenter__(self):
        # unregistered classes never take a slot; only a real grant
        # owes a release
        self._held = await self._adm.acquire(self._klass, self._cost)
        return self

    async def __aexit__(self, *exc):
        if self._held:
            self._held = False
            self._adm.release_slot()
        return False
