"""Host storage-path benchmark stage (the OSD-layer analogue of the
device-resident ``storage_path_device_GiBs`` metric in bench.py).

Drives the real ECUtil write cycle on HOST data -- assemble (pad the
logical payload), transpose (logical -> shard-major), encode (codec
dispatch), commit (per-shard bytes + cumulative crc32c into a store) --
with N concurrent asyncio writers, in two modes:

* ``coalesce=False``: one synchronous codec dispatch per op (the
  pre-round-6 ECBackend behavior);
* ``coalesce=True``: concurrent ops gather into batched dispatches
  through ``ceph_tpu.osd.coalescer.BatchCoalescer`` + the plugin's
  ``encode_batch`` pipeline (granule fusing, bounded depth) -- the same
  objects ECBackend now uses.

A degraded-read cycle (drop shards -> signature-grouped batched decode ->
logical reassembly) is measured the same way.

Bit-exactness is gated BEFORE timing: both modes run over identical
payloads into separate stores and every shard byte must match, and the
decode output must round-trip the payloads.  Per-stage times are
cumulative across ops (writers overlap, so stage sums can exceed the
wall time; the throughput numbers are wall-clock).

Used by bench.py (round JSON fields ``storage_path_host_*``) and
``tools/ec_benchmark.py --workload storage-path``; the tier-1 smoke test
(tests/test_storage_path.py) runs it at tiny shapes so host-path perf
regressions fail loudly with no device or relay involved.
"""

from __future__ import annotations

import asyncio
import time
from typing import Dict, List, Optional

import numpy as np

from ceph_tpu.osd import ecutil
from ceph_tpu.osd.coalescer import BatchCoalescer


def make_payloads(n_objects: int, obj_bytes: int, seed: int = 0
                  ) -> List[bytes]:
    rng = np.random.RandomState(seed)
    return [
        rng.randint(0, 256, size=obj_bytes, dtype=np.uint8).tobytes()
        for _ in range(n_objects)
    ]


class StoragePathHarness:
    """One codec + stripe geometry; runs timed write / degraded-read
    passes over a payload set."""

    def __init__(self, ec, erasures: int = 2):
        self.ec = ec
        self.k = ec.get_data_chunk_count()
        self.km = ec.get_chunk_count()
        self.m = self.km - self.k
        self.sinfo = ecutil.StripeInfo(self.k, self.k * ec.get_chunk_size(1))
        # fixed erasure signature: the first min(m, erasures) data shards
        # are dropped and rebuilt from the remaining data + parity
        self.erased = list(range(min(self.m, erasures)))

    # -- write cycle -------------------------------------------------------

    async def write_pass(self, payloads: List[bytes], *, coalesce: bool,
                         writers: int = 8,
                         stages: Optional[Dict[str, float]] = None,
                         ) -> Dict[str, bytes]:
        """Run every payload through assemble/transpose/encode/commit;
        returns the committed store {oid@shard: bytes}."""
        sinfo, k, km = self.sinfo, self.k, self.km
        ec = self.ec
        coal = None
        if coalesce:
            coal = BatchCoalescer(
                lambda blocks: ecutil.encode_shard_major_many(
                    ec, blocks, range(km)
                )
            )
        store: Dict[str, bytes] = {}
        queue = list(enumerate(payloads))
        stage = stages if stages is not None else {}
        for name in ("assemble", "transpose", "encode", "commit"):
            stage.setdefault(name, 0.0)

        async def writer():
            while queue:
                idx, data = queue.pop()
                t0 = time.perf_counter()
                padded = sinfo.logical_to_next_stripe_offset(len(data))
                buf = np.zeros(padded, dtype=np.uint8)
                buf[: len(data)] = np.frombuffer(data, dtype=np.uint8)
                t1 = time.perf_counter()
                sm = ecutil.to_shard_major(sinfo, k, buf)
                t2 = time.perf_counter()
                if coal is not None:
                    enc = await coal.submit(sm, sm.nbytes)
                else:
                    enc = ecutil.encode_shard_major_many(
                        ec, [sm], range(km)
                    )[0]
                t3 = time.perf_counter()
                hinfo = ecutil.HashInfo(km)
                hinfo.append(0, enc)
                for s in range(km):
                    store[f"obj{idx}@{s}"] = enc[s].tobytes()
                t4 = time.perf_counter()
                stage["assemble"] += t1 - t0
                stage["transpose"] += t2 - t1
                stage["encode"] += t3 - t2
                stage["commit"] += t4 - t3

        await asyncio.gather(*(writer() for _ in range(max(1, writers))))
        return store

    # -- degraded-read cycle -----------------------------------------------

    async def read_pass(self, store: Dict[str, bytes], n_objects: int,
                        sizes: List[int], *, coalesce: bool,
                        readers: int = 8,
                        stages: Optional[Dict[str, float]] = None,
                        ) -> List[bytes]:
        """Degraded read of every object: the ``self.erased`` shards are
        withheld, the rest decode (one fused dispatch per erasure
        signature when coalesced)."""
        sinfo, km = self.sinfo, self.km
        ec = self.ec
        coal = None
        if coalesce:
            coal = BatchCoalescer(
                lambda maps: ecutil.decode_concat_many(sinfo, ec, maps)
            )
        out: List[Optional[bytes]] = [None] * n_objects
        queue = list(range(n_objects))
        stage = stages if stages is not None else {}
        stage.setdefault("decode", 0.0)

        async def reader():
            while queue:
                idx = queue.pop()
                chunks = {
                    s: np.frombuffer(store[f"obj{idx}@{s}"], dtype=np.uint8)
                    for s in range(km)
                    if s not in self.erased
                }
                t0 = time.perf_counter()
                if coal is not None:
                    data = await coal.submit(
                        chunks, sum(c.nbytes for c in chunks.values())
                    )
                else:
                    data = ecutil.decode_concat(sinfo, ec, chunks)
                stage["decode"] += time.perf_counter() - t0
                out[idx] = bytes(data[: sizes[idx]])

        await asyncio.gather(*(reader() for _ in range(max(1, readers))))
        return out  # type: ignore[return-value]


def _ledger_snapshot() -> Dict[str, int]:
    """Process transfer/retrace/granule counters (the residency ledger
    plus the pipeline's fused-dispatch denominator)."""
    from ceph_tpu.analysis import residency
    from ceph_tpu.ops import pipeline

    snap = dict(residency.counters().snapshot())
    snap["granules"] = pipeline.granules_dispatched()
    return snap


def _ledger_delta(before: Dict[str, int],
                  after: Dict[str, int]) -> Dict[str, int]:
    d = {k: after[k] - before[k] for k in before}
    g = d.get("granules", 0)
    # the driver-grade number: H2D ops per fused granule (<= 1 means the
    # packed upload is the ONLY bus crossing on the way in -- no matrix
    # re-uploads, no per-stripe transfers)
    d["h2d_per_granule"] = round(d["h2d_ops"] / g, 3) if g else None
    return d


async def _timed_cycle(h: StoragePathHarness, payloads: List[bytes], *,
                       coalesce: bool, writers: int) -> dict:
    stages: Dict[str, float] = {}
    nbytes = sum(len(p) for p in payloads)
    l0 = _ledger_snapshot()
    t0 = time.perf_counter()
    store = await h.write_pass(payloads, coalesce=coalesce,
                               writers=writers, stages=stages)
    write_s = time.perf_counter() - t0
    l1 = _ledger_snapshot()
    t0 = time.perf_counter()
    await h.read_pass(store, len(payloads), [len(p) for p in payloads],
                      coalesce=coalesce, readers=writers, stages=stages)
    read_s = time.perf_counter() - t0
    l2 = _ledger_snapshot()
    return {
        "write_GiBs": nbytes / write_s / (1 << 30),
        "read_GiBs": nbytes / read_s / (1 << 30),
        "wall_write_s": write_s,
        "wall_read_s": read_s,
        "stages_s": {k: round(v, 6) for k, v in stages.items()},
        # per-pass transfer ledger: h2d/d2h ops+bytes, retraces,
        # granules -- the residency proof for exactly this cycle
        "residency": {"write": _ledger_delta(l0, l1),
                      "read": _ledger_delta(l1, l2)},
    }


async def _bit_exactness_gate(h: StoragePathHarness,
                              payloads: List[bytes], writers: int) -> None:
    """Coalesced and per-op paths must produce byte-identical shards and
    round-trip the payloads -- gated before any timing."""
    seq = await h.write_pass(payloads, coalesce=False, writers=writers)
    coa = await h.write_pass(payloads, coalesce=True, writers=writers)
    if set(seq) != set(coa):
        raise AssertionError("storage-path: shard sets differ")
    for soid in seq:
        if seq[soid] != coa[soid]:
            raise AssertionError(f"storage-path: shard {soid} differs "
                                 f"between coalesced and per-op encode")
    sizes = [len(p) for p in payloads]
    got = await h.read_pass(coa, len(payloads), sizes, coalesce=True,
                            readers=writers)
    for idx, (data, payload) in enumerate(zip(got, payloads)):
        if data != payload:
            raise AssertionError(
                f"storage-path: degraded decode of obj{idx} mismatched"
            )


def run_storage_path_bench(ec, *, n_objects: int = 64,
                           obj_bytes: int = 1 << 16, writers: int = 8,
                           iters: int = 2, seed: int = 1234,
                           erasures: int = 2) -> dict:
    """Full comparison: bit-exactness gate, then timed per-op vs
    coalesced cycles (best of ``iters``); returns the JSON-ready dict."""
    h = StoragePathHarness(ec, erasures=erasures)
    payloads = make_payloads(n_objects, obj_bytes, seed)
    loop = asyncio.new_event_loop()
    steady_retraces: Dict[str, int] = {}
    try:
        loop.run_until_complete(_bit_exactness_gate(h, payloads, writers))
        best: Dict[str, dict] = {}
        for mode, coalesce in (("per_op", False), ("coalesced", True)):
            # one untimed warm pass per mode: XLA compile / matrix upload
            # happen outside the timed region (bench honesty rule #1)
            loop.run_until_complete(_timed_cycle(
                h, payloads, coalesce=coalesce, writers=writers))
            last = None
            for _ in range(max(1, iters)):
                r = loop.run_until_complete(_timed_cycle(
                    h, payloads, coalesce=coalesce, writers=writers))
                last = r
                if mode not in best or r["write_GiBs"] > \
                        best[mode]["write_GiBs"]:
                    best[mode] = r
            # the steady-state retrace gate: by the LAST timed cycle
            # every batch shape has been bucketed onto an already-
            # compiled rung -- any retrace here is a recompile leak on
            # the hot path, and the stage must FAIL, not shrug
            res = last["residency"]
            steady = (res["write"]["jit_retraces"] +
                      res["read"]["jit_retraces"])
            steady_retraces[mode] = steady
            # steady-state ledger beats best-throughput ledger: report
            # the last cycle's residency with the best cycle's timing
            best[mode] = dict(best[mode], residency=res)
            if steady:
                raise AssertionError(
                    f"storage-path: {steady} steady-state jit retrace(s) "
                    f"in mode {mode} -- a batch shape escaped the "
                    f"bucketing ladder (rungs: see osd_ec_shape_rungs)")
    finally:
        loop.close()
    per_op, coalesced = best["per_op"], best["coalesced"]
    return {
        "n_objects": n_objects,
        "obj_bytes": obj_bytes,
        "writers": writers,
        "k": h.k,
        "m": h.m,
        "erasures": len(h.erased),
        "bit_exact": True,  # the gate raised otherwise
        "steady_jit_retraces": steady_retraces,  # gated == 0
        "per_op": per_op,
        "coalesced": coalesced,
        "write_speedup": round(
            coalesced["write_GiBs"] / per_op["write_GiBs"], 3
        ) if per_op["write_GiBs"] else None,
        "read_speedup": round(
            coalesced["read_GiBs"] / per_op["read_GiBs"], 3
        ) if per_op["read_GiBs"] else None,
    }
