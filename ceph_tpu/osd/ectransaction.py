"""Write planning for partial EC writes (ECTransaction equivalent).

Reference: src/osd/ECTransaction.h:26-33 WritePlan + :40-90 get_write_plan:
a logical write is stripe-aligned; stripes only partially covered by the
new bytes must be read first (RMW), then the aligned region is re-encoded
and written per shard at the chunk offsets.

Hash-info semantics follow the reference's split: pure appends extend the
per-shard cumulative crc32c; overwrites clear the chunk hashes and keep
only sizes (the reference gates overwrites behind `allows_ecoverwrites`,
which disables hinfo crc tracking -- set_total_chunk_size_clear_hash,
src/osd/ECUtil.h:146-149).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

from ceph_tpu.osd.ecutil import StripeInfo


@dataclasses.dataclass
class WritePlan:
    #: logical stripe-aligned region to read before writing (None if pure
    #: append / fully-covering write)
    to_read: Optional[Tuple[int, int]]
    #: logical stripe-aligned region that will be written
    will_write: Tuple[int, int]
    #: logical object size after the write
    new_size: int
    #: True when the write only appends past the old aligned end
    is_append: bool


def get_write_plan(
    sinfo: StripeInfo, object_size: int, offset: int, length: int
) -> WritePlan:
    """Compute the RMW plan for writing [offset, offset+length)."""
    write_start, write_len = sinfo.offset_len_to_stripe_bounds(offset, length)
    write_end = write_start + write_len
    old_aligned_end = sinfo.logical_to_next_stripe_offset(object_size)
    new_size = max(object_size, offset + length)

    is_append = write_start >= old_aligned_end or object_size == 0
    if is_append:
        return WritePlan(
            to_read=None,
            will_write=(write_start, write_len),
            new_size=new_size,
            is_append=True,
        )

    # stripes overlapping existing data must be read unless the new bytes
    # fully cover them
    read_start = write_start
    read_end = min(write_end, old_aligned_end)
    fully_covered = (
        offset <= write_start
        and offset + length >= read_end
    )
    to_read = None if fully_covered else (read_start, read_end - read_start)
    return WritePlan(
        to_read=to_read,
        will_write=(write_start, write_len),
        new_size=new_size,
        is_append=False,
    )
