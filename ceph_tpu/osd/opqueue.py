"""QoS op queues: weighted-priority and mClock (dmClock) scheduling.

Reference: the OSD's pluggable op queue (``osd_op_queue`` =
``wpq`` | ``mclock_opclass`` | ``mclock_client``):

* ``WeightedPriorityQueue`` — src/common/WeightedPriorityQueue.h: ops at or
  above a strict-priority cutoff are served in strict priority order;
  lower-priority buckets are served weighted-round-robin with throughput
  proportional to their priority value.
* ``MClockQueue`` — src/osd/mClock*.{h,cc} over the vendored dmClock
  library (src/dmclock): each op class has a (reservation, weight, limit)
  triple in ops/sec; tag-based scheduling guarantees the reservation floor,
  splits spare capacity by weight, and enforces the limit ceiling
  [Gulati et al., mClock, OSDI'10 — the algorithm dmClock implements].

Both queues are cost-aware: an item's cost scales its tag spacing (a
4 MiB write consumes more of a class's rate than a 4 KiB one).
"""

from __future__ import annotations

import time
from collections import deque
from typing import Callable, Dict, Optional, Tuple


class WeightedPriorityQueue:
    """Strict above the cutoff, weighted round-robin below."""

    def __init__(self, strict_cutoff: int = 196):
        self.strict_cutoff = strict_cutoff
        self._strict: Dict[int, deque] = {}
        self._weighted: Dict[int, deque] = {}
        #: deficit-round-robin credit per weighted bucket
        self._credit: Dict[int, float] = {}
        self._rr: deque = deque()  # round-robin order of weighted priorities
        self._len = 0

    def enqueue(self, priority: int, cost: int, item) -> None:
        buckets = (
            self._strict if priority >= self.strict_cutoff else self._weighted
        )
        if priority not in buckets:
            buckets[priority] = deque()
            if buckets is self._weighted:
                self._rr.append(priority)
                self._credit.setdefault(priority, 0.0)
        buckets[priority].append((max(1, cost), item))
        self._len += 1

    def __len__(self) -> int:
        return self._len

    def empty(self) -> bool:
        return self._len == 0

    def dequeue(self):
        if self._strict:
            prio = max(self._strict)
            q = self._strict[prio]
            cost, item = q.popleft()
            if not q:
                del self._strict[prio]
            self._len -= 1
            return item
        # weighted: deficit round robin, quantum proportional to priority.
        # A bucket serves while its credit lasts; when the head item costs
        # more than the remaining credit, the bucket receives one quantum
        # (= its priority) and the turn passes on, so each full rotation
        # serves ~priority-proportional cost from every bucket.
        while self._rr:
            prio = self._rr[0]
            q = self._weighted.get(prio)
            if not q:
                self._rr.popleft()
                self._weighted.pop(prio, None)
                self._credit.pop(prio, None)
                continue
            cost, item = q[0]
            if self._credit[prio] >= cost:
                self._credit[prio] -= cost
                q.popleft()
                self._len -= 1
                if not q:
                    self._rr.popleft()
                    del self._weighted[prio]
                    self._credit[prio] = 0.0
                return item
            self._credit[prio] += max(1, prio)  # prio<=0 must still progress
            self._rr.rotate(-1)
        raise IndexError("dequeue from empty queue")


class MClockQueue:
    """dmClock tag scheduler over named op classes.

    ``classes`` maps class name -> (reservation, weight, limit) in
    cost-units/sec; reservation/limit of 0 mean none.  Time comes from
    ONE injected monotonic ``clock`` (default ``time.monotonic``) read
    inside every operation -- callers no longer supply ``now`` floats,
    so mixed clock domains (event-loop time vs wall time vs a test's
    virtual clock) can never corrupt tag ordering, and a wall-clock
    regression cannot re-order tags minted under the old time.
    ``dequeue()`` returns the next eligible item or None if every queued
    class is at its limit; ``next_ready()`` says when one becomes
    eligible (absolute, in the injected clock's domain -- compare
    against ``self.clock()``).
    """

    def __init__(self, classes: Dict[str, Tuple[float, float, float]],
                 clock: Callable[[], float] = time.monotonic):
        self.classes = dict(classes)
        self.clock = clock
        self._queues: Dict[str, deque] = {}
        #: per-class last-assigned tags (reservation, proportional, limit)
        self._tags: Dict[str, Tuple[float, float, float]] = {}

    def _params(self, klass: str) -> Tuple[float, float, float]:
        return self.classes.get(klass, (0.0, 1.0, 0.0))

    def enqueue(self, klass: str, cost: int, item) -> None:
        now = self.clock()
        res, wgt, lim = self._params(klass)
        cost = max(1, cost)
        prev = self._tags.get(klass)
        if prev is None:
            # a class's first request is eligible immediately (dmClock
            # initializes tags to the arrival time, not one period out)
            r = now if res > 0 else float("inf")
            p = now
            l = now
        else:
            lr, lp, ll = prev
            r = max(now, lr + cost / res) if res > 0 else float("inf")
            p = max(now, lp + cost / max(wgt, 1e-9))
            l = max(now, ll + cost / lim) if lim > 0 else now
        self._queues.setdefault(klass, deque()).append((r, p, l, item))
        self._tags[klass] = (r, p, l)

    def __len__(self) -> int:
        return sum(len(q) for q in self._queues.values())

    def empty(self) -> bool:
        return not any(self._queues.values())

    def _heads(self):
        for klass, q in self._queues.items():
            if q:
                yield klass, q[0]

    def dequeue(self):
        now = self.clock()
        # phase 1: honor reservations whose tag has come due
        best = None
        for klass, (r, p, l, item) in self._heads():
            if r <= now and (best is None or r < best[0]):
                best = (r, klass)
        if best is not None:
            return self._pop(best[1])
        # phase 2: spare capacity by proportional tag, limit permitting
        best = None
        for klass, (r, p, l, item) in self._heads():
            if l <= now and (best is None or p < best[0]):
                best = (p, klass)
        if best is not None:
            return self._pop(best[1])
        return None

    def _pop(self, klass: str):
        r, p, l, item = self._queues[klass].popleft()
        return item

    def next_ready(self) -> Optional[float]:
        """Earliest time a queued item becomes eligible (None if empty;
        absolute in the injected clock's domain)."""
        t = None
        for klass, (r, p, l, item) in self._heads():
            cand = min(r, l)
            if t is None or cand < t:
                t = cand
        return t

    def idle_for(self) -> Optional[float]:
        """Seconds until the next queued item becomes eligible: the
        shard worker's event-driven idle wakeup (sleep exactly this
        long, or until a new arrival, instead of polling).  None when
        the queue is empty; 0.0 when something is eligible right now."""
        nxt = self.next_ready()
        if nxt is None:
            return None
        return max(0.0, nxt - self.clock())
