"""Failover robustness benchmark stage (bench.py ``failover_path_host``).

Measures the client-visible cost of primary failover on the in-process
mini-cluster -- the tail-latency window the exactly-once work targets
(studies of online EC under failure show role-handoff stalls dominate
p99, arXiv:1709.05365 / arXiv:1906.08602):

* **steady**: op latency with no faults (the baseline);
* **time-to-first-success (TTFS)**: per kill round, the primary of the
  op in flight is killed in the apply/reply window (the
  ``kill_after_apply`` injector) and the wall time until the SAME
  logical op completes -- probe discovery + jittered backoff + resend +
  PG-log dup answer -- is recorded;
* **thrash p99**: op latency tail across the whole kill/revive churn.

Correctness is gated alongside timing: every killed-window op must
complete with its original result exactly once (dup hits observed, no
error surfaces), so the stage fails loudly if the robustness machinery
regresses rather than reporting a fast-but-wrong number.

Used by bench.py (fields ``failover_path_host_*``); the tier-1 smoke
test (tests/test_exactly_once.py) runs it at tiny shapes.
"""

from __future__ import annotations

import asyncio
import time
from typing import Dict, List

PROFILE = {"k": "4", "m": "2", "technique": "reed_sol_van",
           "plugin": "jerasure"}


def _pct(samples: List[float], q: float) -> float:
    if not samples:
        return 0.0
    s = sorted(samples)
    return s[min(len(s) - 1, int(q * len(s)))]


async def _run(n_osds: int, n_objects: int, obj_bytes: int,
               kills: int) -> Dict:
    import json

    from ceph_tpu.msg.fault import FaultInjector
    from ceph_tpu.osd.cluster import ECCluster
    from ceph_tpu.utils.config import get_config
    from ceph_tpu.utils.perf import PerfCounters

    PerfCounters.reset_all()
    cfg = get_config()
    prior_grace = cfg.get_val("client_probe_grace")
    cfg.apply_changes({"client_probe_grace": 0.05})
    fault = FaultInjector(seed=5)
    cluster = ECCluster(n_osds, dict(PROFILE), fault=fault)
    try:
        payload = b"f" * obj_bytes
        oids = [f"fo{i}" for i in range(n_objects)]
        steady: List[float] = []
        for oid in oids:
            t0 = time.perf_counter()
            await cluster.write(oid, payload)
            steady.append(time.perf_counter() - t0)

        thrash: List[float] = []
        ttfs: List[float] = []
        down: List[int] = []
        for round_no in range(kills):
            for osd in down:
                cluster.revive_osd(osd)
            down.clear()
            oid = oids[round_no % len(oids)]
            victim = int(cluster.backend.primary_of(oid).split(".")[1])
            fault.schedule_kill_after_apply("write")
            t0 = time.perf_counter()
            await cluster.write(oid, payload)
            dt = time.perf_counter() - t0
            ttfs.append(dt)
            thrash.append(dt)
            down.append(victim)
            # traffic during the degraded window feeds the p99 tail
            for other in oids[:8]:
                t0 = time.perf_counter()
                if other == oid:
                    await cluster.read(other)
                else:
                    await cluster.write(other, payload)
                thrash.append(time.perf_counter() - t0)
        for osd in down:
            cluster.revive_osd(osd)

        dump = json.loads(PerfCounters.dump())
        dup_hits = sum(v.get("dup_op_hit", 0)
                       for name, v in dump.items()
                       if name.startswith("osd."))
        resends = dump.get("client", {}).get("op_resend", 0)
        if fault.apply_kills != kills:
            raise RuntimeError(
                f"injector fired {fault.apply_kills}/{kills} kills"
            )
        if dup_hits < 1:
            raise RuntimeError("no replay was answered from the PG log")
        return {
            "steady_p50_ms": round(_pct(steady, 0.50) * 1e3, 3),
            "steady_p99_ms": round(_pct(steady, 0.99) * 1e3, 3),
            "ttfs_mean_ms": round(sum(ttfs) / len(ttfs) * 1e3, 3),
            "ttfs_max_ms": round(max(ttfs) * 1e3, 3),
            "thrash_p99_ms": round(_pct(thrash, 0.99) * 1e3, 3),
            "kills": kills,
            "op_resend": resends,
            "dup_op_hit": dup_hits,
        }
    finally:
        cfg.apply_changes({"client_probe_grace": prior_grace})
        await cluster.shutdown()


def run_failover_bench(*, n_osds: int = 8, n_objects: int = 16,
                       obj_bytes: int = 16 << 10, kills: int = 5) -> Dict:
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(
            _run(n_osds, n_objects, obj_bytes, kills)
        )
    finally:
        loop.close()
