"""Per-PG codec batching for the OSD EC data path (the encode coalescer).

Round-2 built the async stripe-batching pipeline (``ceph_tpu/ops/
pipeline.py``) but wired it only into the plugin/tool surface
(``encode_batch``/``decode_batch``); every ECBackend client op still made
one synchronous per-op codec call.  With the device kernel closed at
~45 GiB/s (PERF_NOTES round 4) the per-op dispatch overhead IS the
storage-path bottleneck -- exactly the pattern "Understanding System
Characteristics of Online Erasure Coding" documents: once the codec is
fast, datapath overheads dominate.

This module is the seam that closes the gap: concurrent in-flight client
ops on one PG gather their codec work into batched dispatches.  It is
also the mesh data plane's dispatch seam (``osd_mesh_data_plane``): the
fused batch a tick gathers here is exactly what
``parallel/mesh_plane.py`` places PG-sliced over the device mesh, so
batching and mesh parallelism compose without a second queue.

Flush policy (documented in docs/ec-storage-path.md):

* **queue-drain**: the first submission of a batch schedules a flush via
  ``loop.call_soon``, i.e. the batch dispatches at the end of the current
  event-loop tick, after every already-runnable task has had its chance
  to add its stripe.  Latency cost is bounded by one loop tick; a lone
  write is dispatched immediately on the next callback slot.
* **size threshold**: a batch that reaches ``max_batch`` items or
  ``max_bytes`` payload bytes dispatches immediately (bounded memory).
* **bounded depth**: at most ``depth`` batched dispatches run
  concurrently; excess batches queue behind a semaphore.

Deadlock-freedom argument (mirrors the round-4 dispatch throttle's
scoping): only CLIENT ops route through the coalescer -- recovery,
scrub and peering reconstruction keep their direct codec calls -- and a
flush depends on nothing but the event loop running (``call_soon`` always
fires; it never waits on another op's completion, an ack, or a quota
held by a queued op).  Submitters await only their own future, and the
dispatch function never re-enters the coalescer, so no cycle of waits
can form.
"""

from __future__ import annotations

import asyncio
from typing import Awaitable, Callable, List, Optional, Sequence

from ceph_tpu.profiling import ledger as _profiler
from ceph_tpu.utils import trace
from ceph_tpu.utils.perf import PerfCounters

#: wire-tax cost centers on the submit path (ceph_tpu/profiling/):
#: the sync gather bookkeeping and the fused dispatch call.  The
#: dispatch marker uses the paired stage_enter/stage_exit form because
#: dispatch_many may return a coroutine that must be awaited OUTSIDE
#: the stage (a stage spanning an await would bill other tasks' work
#: to itself) -- the cephlint rule `profile-stage-unpaired` checks
#: every enter reaches an exit on all CFG paths.
_PS_SUBMIT = _profiler.stage("coalescer.submit")
_PS_DISPATCH = _profiler.stage("coalescer.dispatch")

#: default flush thresholds: a batch larger than this dispatches without
#: waiting for the tick to end
DEFAULT_MAX_BATCH = 64
DEFAULT_MAX_BYTES = 64 << 20
#: bounded in-flight batched dispatches (the pipeline overlaps granules
#: internally; this bounds whole-batch concurrency)
DEFAULT_DEPTH = 2


class BatchCoalescer:
    """Gathers same-kind work items submitted in one event-loop tick into
    one batched dispatch.

    ``dispatch_many(items) -> results`` is called with every item of a
    batch (in submission order) and must return one result per item; it
    may be sync or async.  ``submit(item, nbytes)`` awaits that item's
    result.  Per-instance, single-event-loop; not thread-safe (the OSD
    data path is asyncio-single-threaded by construction).
    """

    def __init__(
        self,
        dispatch_many: Callable[[List], "Sequence | Awaitable[Sequence]"],
        *,
        max_batch: int = DEFAULT_MAX_BATCH,
        max_bytes: int = DEFAULT_MAX_BYTES,
        depth: int = DEFAULT_DEPTH,
        perf: Optional[PerfCounters] = None,
        counter: str = "coalesce",
    ):
        self._dispatch_many = dispatch_many
        self.max_batch = max_batch
        self.max_bytes = max_bytes
        self._sem = asyncio.Semaphore(max(1, depth))
        #: unified QoS admission (osd/qos.py, set by the hosting
        #: OSDShard): when present, a gathered batch claims one
        #: admission slot under ``qos_class`` with cost = its stripe
        #: bytes BEFORE dispatching -- the dequeue that frees the slot
        #: to this batch IS the dmClock decision, so batching and QoS
        #: are one layer.  None (client-side engines, unified QoS off)
        #: dispatches on the depth semaphore alone.
        self.admission = None
        self.qos_class = "client"
        self._pending: List[tuple] = []  # (item, future, nbytes, span)
        self._pending_bytes = 0
        self._flush_scheduled = False
        self.perf = perf
        self._counter = counter
        #: trace stage name ("encode"/"decode"/...): op spans record
        #: <stage>_submit/<stage>_done and the shared dispatch becomes
        #: one batch_<stage> fan-in span (docs/observability.md)
        self._stage = "encode" if "encode" in counter else (
            "decode" if "decode" in counter else counter)
        # precomputed event/span names: the unsampled fast path must
        # not pay a per-submit f-string
        self._ev_submit = f"{self._stage}_submit"
        self._ev_done = f"{self._stage}_done"
        self._span_name = f"batch_{self._stage}"

    # -- submission ---------------------------------------------------------

    async def submit(self, item, nbytes: int = 0):
        """Queue one work item; resolves with its dispatch result."""
        with _PS_SUBMIT:
            loop = asyncio.get_event_loop()
            fut = loop.create_future()
            # batch fan-in tracing: remember the submitting op's span so
            # the shared dispatch becomes ONE span child of every rider
            # (cheap: a contextvar read; NULL_SPAN rides as False)
            span = trace.current()
            span.event(self._ev_submit)
            self._pending.append((item, fut, nbytes, span))
            self._pending_bytes += nbytes
            if (
                len(self._pending) >= self.max_batch
                or self._pending_bytes >= self.max_bytes
            ):
                self._spawn_flush(loop)
            elif not self._flush_scheduled:
                # queue-drain flush: end of the current tick, so every
                # task runnable RIGHT NOW can still join this batch
                self._flush_scheduled = True
                loop.call_soon(self._on_tick_end, loop)
        return await fut

    def _on_tick_end(self, loop) -> None:
        self._flush_scheduled = False
        if self._pending:
            self._spawn_flush(loop)

    def _spawn_flush(self, loop) -> None:
        # the swap and the byte-counter reset are one indivisible step:
        # a task switch between them would let a submit() land in the
        # NEW pending list while its bytes are zeroed away with the old
        # one (declared so the rule fires if this ever grows an await)
        # cephlint: atomic-section coalescer-pending-swap
        batch, self._pending = self._pending, []
        self._pending_bytes = 0
        # cephlint: end-atomic-section
        task = loop.create_task(self._run_batch(batch))
        # keep a strong reference until the batch lands (asyncio tasks
        # are otherwise collectable mid-flight)
        refs = getattr(self, "_tasks", None)
        if refs is None:
            refs = self._tasks = set()
        refs.add(task)
        task.add_done_callback(refs.discard)

    def _dispatch_staged(self, items: List):
        """The staged dispatch call, paired-marker form: the
        synchronous ``dispatch_many`` invocation is a cost center; a
        coroutine result is awaited by the CALLER, outside the stage
        (stages never span a yield -- a suspended stage would bill
        other tasks' work to itself).  profile-stage-unpaired checks
        the enter reaches the exit on every CFG path."""
        _profiler.stage_enter(_PS_DISPATCH)
        try:
            results = self._dispatch_many(items)
        finally:
            _profiler.stage_exit(_PS_DISPATCH)
        return results

    async def _run_batch(self, batch: List[tuple]) -> None:
        admission = self.admission
        if admission is not None:
            # the QoS admission stage: one slot per batched dispatch,
            # cost = the batch's payload bytes.  Waits only on slot
            # releases and the clock (never on another op's completion),
            # so the coalescer's deadlock-freedom argument holds intact.
            async with admission.slot(
                self.qos_class,
                sum(nb for _i, _f, nb, _sp in batch),
            ):
                await self._run_batch_admitted(batch)
        else:
            await self._run_batch_admitted(batch)

    async def _run_batch_admitted(self, batch: List[tuple]) -> None:
        async with self._sem:
            items = [item for item, _fut, _nb, _sp in batch]
            # the shared stage is ONE fan-in span, child of every
            # sampled rider (amortized_over = batch size); it is also
            # the task-current span while dispatching, so the dispatch
            # lane (mesh plane, pipeline) can annotate it
            fanin = trace.batch_span(
                self._span_name, [sp for _i, _f, _nb, sp in batch])
            try:
                with trace.use_span(fanin):
                    results = self._dispatch_staged(items)
                    if asyncio.iscoroutine(results):
                        results = await results
            except asyncio.CancelledError:
                fanin.finish()
                raise
            except Exception as e:  # noqa: BLE001 -- each waiter gets the
                # failure; the coalescer itself stays serviceable
                fanin.tag_set("error", type(e).__name__)
                fanin.finish()
                for _item, fut, _nb, sp in batch:
                    sp.event(self._ev_done)
                    if not fut.done():
                        fut.set_exception(
                            type(e)(*e.args) if e.args else IOError(str(e))
                        )
                return
            fanin.tag_set("items", len(batch))
            fanin.finish()
            if self.perf is not None:
                self.perf.inc(self._counter)
                self.perf.inc(f"{self._counter}_items", len(batch))
                self.perf.inc(f"{self._counter}_bytes",
                              sum(nb for _i, _f, nb, _sp in batch))
                if len(batch) > 1:
                    self.perf.inc(f"{self._counter}_batched",
                                  len(batch))
                # largest fused batch this coalescer ever dispatched:
                # the mesh data plane slices a batch over the pg axis,
                # so this is the "how much parallelism did one tick
                # actually gather" number the mesh bench reads
                self.perf.hwm(f"{self._counter}_batch_hwm", len(batch))
            for (_item, fut, _nb, sp), res in zip(batch, results):
                sp.event(self._ev_done)
                if not fut.done():
                    fut.set_result(res)
