"""Recovery-path benchmark stage (bench.py ``recovery_path_host``).

The round-14 background-data-plane metric: rebuild a wiped OSD's shards
through the per-object windowed path vs the batched recovery coalescer
(osd/recovery.py), with a CONCURRENT client workload riding the same
mClock op queues -- the scenario the refactor exists for ("rebalance
under heavy client traffic").

Per mode it reports rebuild throughput (authoritative bytes re-pushed /
time-to-clean after the kill+wipe), the client workload's p50/p99
DURING the rebuild, and the background counters
(``recovery_ops_batched``, ``recovery_bytes``, ``recovery_preempted``)
plus a residency-ledger snapshot so recovery's transfer contract is
visible like the write lane's.

Correctness is gated before any number is reported: every object must
read back bit-exact after the rebuild in BOTH modes, the two modes'
recovered shard stores must match byte-for-byte, the batched mode must
actually have used the batched lane, and the batched mode's client p99
must stay under ``client_p99_bound_ms`` (the mClock enforcement
assertion) -- a fast-but-starving rebuild fails the stage.

Used by bench.py (fields ``recovery_path_host_*``) and
``tools/ec_benchmark.py --workload recovery-path``; the tier-1 smoke
runs it at tiny shapes in tests/test_recovery_path.py.
"""

from __future__ import annotations

import asyncio
import time
from typing import Dict, List

import numpy as np

#: the tpu plugin (cpu-fallback safe): its ``decode_batch`` is what the
#: recovery coalescer fuses -- per-object recovery pays one engine
#: dispatch per object, the batched lane one per erasure signature
PROFILE = {"k": "4", "m": "2", "plugin": "tpu"}


def _pct(samples: List[float], q: float) -> float:
    if not samples:
        return 0.0
    s = sorted(samples)
    return s[min(len(s) - 1, int(q * len(s)))]


def _ledger_snapshot() -> Dict[str, int]:
    from ceph_tpu.analysis import residency

    return dict(residency.counters().snapshot())


def _bg_counters() -> Dict[str, int]:
    import json

    from ceph_tpu.utils.perf import PerfCounters

    dump = json.loads(PerfCounters.dump())
    out: Dict[str, int] = {}
    for key in ("recovery_ops_batched", "recovery_bytes",
                "recovery_batches", "recovery_preempted", "recover",
                "recover_window", "scrub_chunks",
                "tier_promote_from_recovery"):
        out[key] = sum(v.get(key, 0) for v in dump.values()
                       if isinstance(v, dict))
    return out


async def _run_mode(batched: bool, *, n_osds: int, n_objects: int,
                    obj_bytes: int, payloads: List[bytes]) -> Dict:
    from ceph_tpu.osd.cluster import ECCluster
    from ceph_tpu.utils.config import get_config
    from ceph_tpu.utils.perf import PerfCounters

    PerfCounters.reset_all()
    cfg = get_config()
    prior = cfg.get_val("osd_recovery_batched")
    cfg.apply_changes({"osd_recovery_batched": batched})
    cluster = ECCluster(n_osds, dict(PROFILE), op_queue="mclock")
    try:
        oids = [f"rb{i}" for i in range(n_objects)]
        for oid, data in zip(oids, payloads):
            await cluster.write(oid, data)
        # a separate hot set keeps the concurrent client load off the
        # recovering objects (deterministic rebuild work in both modes)
        hot = [f"hot{i}" for i in range(8)]
        for oid in hot:
            await cluster.write(oid, payloads[0])

        # steady client latency baseline
        steady: List[float] = []
        for oid in hot:
            t0 = time.perf_counter()
            await cluster.read(oid)
            steady.append(time.perf_counter() - t0)

        victims = (0, 1)  # m=2: two replaced disks, still k readable
        for victim in victims:
            cluster.kill_osd(victim)
            cluster.wipe_osd(victim)
            cluster.revive_osd(victim)

        lat: List[float] = []
        stop = asyncio.Event()

        async def client_load():
            i = 0
            while not stop.is_set():
                oid = hot[i % len(hot)]
                t0 = time.perf_counter()
                if i % 3 == 0:
                    await cluster.write(oid, payloads[0])
                else:
                    await cluster.read(oid)
                lat.append(time.perf_counter() - t0)
                i += 1
                await asyncio.sleep(0)

        load_task = asyncio.get_event_loop().create_task(client_load())
        t0 = time.perf_counter()
        try:
            # rebuild until a full pass round reports zero recovery
            # actions (the all-clean confirmation round is part of the
            # timed region in both modes); the degraded scan below is
            # harness bookkeeping, verified OUTSIDE the timed region
            for _pass in range(10):
                n_actions = 0
                for osd in cluster.osds:
                    for backend in osd.pools.values():
                        n_actions += await backend.peering_pass()
                if n_actions == 0:
                    break
        finally:
            stop.set()
            await load_task
        time_to_clean = time.perf_counter() - t0
        if await cluster.degraded_report():
            raise AssertionError(
                f"recovery-path ({'batched' if batched else 'per-object'})"
                ": cluster never reached clean")

        # bit-exactness gate: every object reads back exactly
        for oid, data in zip(oids, payloads):
            got = await cluster.read(oid)
            if got != data:
                raise AssertionError(
                    f"recovery-path: {oid} mismatched after rebuild")
        # the recovered shard stores, for cross-mode byte comparison
        store = {}
        for victim in victims:
            for stored in cluster.osds[victim].store.list_objects():
                store[f"osd{victim}/{stored}"] = \
                    cluster.osds[victim].store.read(stored)

        counters = _bg_counters()
        rebuilt_bytes = sum(len(v) for v in store.values())
        return {
            "time_to_clean_s": round(time_to_clean, 4),
            "rebuild_MiBs": round(
                sum(len(v) for v in store.values())
                / max(time_to_clean, 1e-9) / (1 << 20), 3),
            "rebuilt_bytes": rebuilt_bytes,
            "client_p50_ms": round(_pct(lat, 0.50) * 1e3, 3),
            "client_p99_ms": round(_pct(lat, 0.99) * 1e3, 3),
            "client_ops_during_rebuild": len(lat),
            "steady_p99_ms": round(_pct(steady, 0.99) * 1e3, 3),
            "counters": counters,
            "store": store,
        }
    finally:
        cfg.apply_changes({"osd_recovery_batched": prior})
        await cluster.shutdown()


def run_recovery_path_bench(*, n_osds: int = 8, n_objects: int = 96,
                            obj_bytes: int = 32 << 10,
                            client_p99_bound_ms: float = 2000.0,
                            seed: int = 77) -> Dict:
    rng = np.random.RandomState(seed)
    payloads = [
        rng.randint(0, 256, size=obj_bytes, dtype=np.uint8).tobytes()
        for _ in range(n_objects)
    ]
    loop = asyncio.new_event_loop()
    try:
        l0 = _ledger_snapshot()
        per_obj = loop.run_until_complete(_run_mode(
            False, n_osds=n_osds, n_objects=n_objects,
            obj_bytes=obj_bytes, payloads=payloads))
        l1 = _ledger_snapshot()
        batched = loop.run_until_complete(_run_mode(
            True, n_osds=n_osds, n_objects=n_objects,
            obj_bytes=obj_bytes, payloads=payloads))
        l2 = _ledger_snapshot()
    finally:
        loop.close()

    # cross-mode gate: both rebuild paths must leave the wiped OSD with
    # byte-identical shard objects
    ps, bs = per_obj.pop("store"), batched.pop("store")
    if set(ps) != set(bs):
        raise AssertionError("recovery-path: rebuilt shard sets differ "
                             "between batched and per-object modes")
    for soid in ps:
        if ps[soid] != bs[soid]:
            raise AssertionError(
                f"recovery-path: rebuilt shard {soid} differs between "
                "batched and per-object modes")
    if batched["counters"]["recovery_ops_batched"] <= 0:
        raise AssertionError(
            "recovery-path: batched mode never used the batched lane")
    if batched["client_p99_ms"] > client_p99_bound_ms:
        raise AssertionError(
            f"recovery-path: client p99 {batched['client_p99_ms']}ms "
            f"exceeded the {client_p99_bound_ms}ms bound during the "
            "batched rebuild (mClock enforcement regressed)")
    return {
        "n_osds": n_osds,
        "n_objects": n_objects,
        "obj_bytes": obj_bytes,
        "bit_exact": True,  # the gates raised otherwise
        "client_p99_bound_ms": client_p99_bound_ms,
        "per_object": per_obj,
        "batched": batched,
        "rebuild_speedup": round(
            per_obj["time_to_clean_s"]
            / max(batched["time_to_clean_s"], 1e-9), 3),
        "residency": {
            "per_object": {k: l1[k] - l0[k] for k in l0},
            "batched": {k: l2[k] - l1[k] for k in l1},
        },
    }
