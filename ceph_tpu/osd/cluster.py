"""Mini EC cluster harness (vstart.sh / ceph-helpers.sh analogue).

Boots N in-process OSD shard daemons on an async messenger, creates an EC
"pool" from a profile via the plugin registry, and exposes the client write/
read/recover surface.  The reference equivalent is a vstart cluster plus the
qa standalone helpers (reference: src/vstart.sh, qa/standalone/
ceph-helpers.sh:417 run_mon / :571 run_osd / :507 create_pool) reduced to
the EC data path.
"""

from __future__ import annotations

import asyncio
from typing import Dict, List, Optional

from ceph_tpu.osd.ecbackend import ECBackend, OSDShard
from ceph_tpu.osd.messenger import FaultInjector, Messenger
from ceph_tpu.osd.objecter import Objecter
from ceph_tpu.plugins import registry as registry_mod


class ECCluster:
    """Round-3 architecture: every OSD hosts a primary engine for the
    pool (``OSDShard.host_pool``); ``self.backend`` is a thin Objecter
    that routes each op to the object's current primary OSD, which fans
    out sub-ops -- the reference's client/primary split (SURVEY.md §3.2).
    """

    def __init__(
        self,
        n_osds: int,
        profile: Dict[str, str],
        plugin: Optional[str] = None,
        fault: Optional[FaultInjector] = None,
        use_crush: bool = True,
        hosts=None,
        op_queue: str = "wpq",
        objectstore: str = "memstore",
        data_path: str = "",
        pool: str = "ecpool",
        pool_type: str = "erasure",
        min_size: Optional[int] = None,
    ):
        self.messenger = Messenger(fault)
        # kept for elastic add_osd: new daemons clone the boot shape
        self._op_queue = op_queue
        self._objectstore = objectstore
        self._data_path = data_path
        self.osds: List[OSDShard] = [
            OSDShard(i, self.messenger, op_queue=op_queue,
                     objectstore=objectstore, data_path=data_path)
            for i in range(n_osds)
        ]
        self.pool_type = pool_type
        if pool_type == "replicated":
            # TYPE_REPLICATED pool: profile carries {"size": N}
            # (reference build_pg_backend, src/osd/PGBackend.cc:533-570)
            self.ec = None
            km = int(profile.get("size", 3))
        else:
            plugin = plugin or profile.pop("plugin", "jerasure")
            registry = registry_mod.instance()
            self.ec = registry.factory(plugin, profile)
            km = self.ec.get_chunk_count()
        placement = None
        if use_crush:
            from ceph_tpu.osd.placement import CrushPlacement

            placement = CrushPlacement(n_osds, km, hosts=hosts)
        self.placement = placement
        self.pool = pool
        # one primary engine per OSD; in-process they share the codec and
        # the placement object (weight updates propagate to everyone)
        for osd in self.osds:
            osd.host_pool(pool, self.ec, n_osds, placement,
                          pool_type=pool_type, size=km, min_size=min_size)
        self.backend = Objecter(
            self.messenger, km, n_osds, placement=placement, pool=pool,
        )

    def primary_backend(self, oid: str) -> ECBackend:
        """The hosted primary engine currently serving ``oid`` (test and
        introspection hook)."""
        acting = self.backend.acting_set(oid)
        for s in range(self.backend.km):
            if self.backend._shard_up(acting, s):
                return self.osds[acting[s]].pools[self.pool]
        raise IOError(f"no up primary for {oid}")

    def add_pool(self, name: str, profile: Optional[Dict[str, str]] = None,
                 pool_type: str = "erasure", size: int = 3,
                 hosts=None) -> Objecter:
        """Host an ADDITIONAL pool on the same OSD daemons and return an
        Objecter bound to it -- the reference's normal shape (every OSD
        serves PGs of many pools; metadata pools replicated, data pools
        EC).  Object pool-membership tags (ceph_tpu/osd/pg.py POOL_KEY)
        keep the co-hosted pools' scrub/peering disjoint."""
        if name in (self.pool,) or any(
            name in osd.pools for osd in self.osds
        ):
            raise ValueError(f"pool {name} exists")
        if pool_type == "replicated":
            ec = None
            km = int((profile or {}).get("size", size))
        else:
            prof = dict(profile or {})
            plugin = prof.pop("plugin", "jerasure")
            ec = registry_mod.instance().factory(plugin, prof)
            km = ec.get_chunk_count()
        placement = None
        if self.placement is not None:
            from ceph_tpu.osd.placement import CrushPlacement

            placement = CrushPlacement(len(self.osds), km, hosts=hosts)
        for osd in self.osds:
            osd.host_pool(name, ec, len(self.osds), placement,
                          pool_type=pool_type, size=km)
        return Objecter(
            self.messenger, km, len(self.osds), placement=placement,
            pool=name, name=f"client.{name}",
            # distinct stored-object namespace per additional pool: the
            # flat per-OSD stores would otherwise collide on "oid@shard"
            oid_prefix=f"{name}/",
        )

    def set_tier_mode(self, mode: str, pool: Optional[str] = None) -> None:
        """Configure a pool's device cache-tier mode on every hosted
        engine -- the in-process analogue of the mon's
        ``osd tier cache-mode`` (writeback | readproxy | none)."""
        from ceph_tpu.tier import CACHE_MODES

        if mode not in CACHE_MODES:
            raise ValueError(f"bad cache mode {mode!r}")
        pool = pool or self.pool
        for osd in self.osds:
            backend = osd.pools.get(pool)
            if backend is not None:
                backend.tier_mode = mode

    def new_client(self, name: str) -> Objecter:
        """A second client handle on the same cluster (librados: another
        Rados instance)."""
        return Objecter(
            self.messenger, self.backend.km, len(self.osds),
            placement=self.placement, name=name, pool=self.pool,
        )

    # -- client surface ----------------------------------------------------

    async def write(self, oid: str, data: bytes) -> None:
        await self.backend.write(oid, data)

    async def read(self, oid: str) -> bytes:
        return await self.backend.read(oid)

    async def write_range(self, oid: str, offset: int, data: bytes) -> None:
        await self.backend.write_range(oid, offset, data)

    async def read_range(self, oid: str, offset: int, length: int) -> bytes:
        return await self.backend.read_range(oid, offset, length)

    # -- auto recovery (peering-driven; qa wait_for_clean surface) ---------

    def start_auto_recovery(self, interval: float = None) -> None:
        """Enable background peering + recovery on every OSD (daemons run
        this by default; the in-process harness opts in so unit tests can
        hold a cluster in a degraded state deliberately)."""
        for osd in self.osds:
            osd.start_tick(interval)

    async def degraded_report(self) -> List[str]:
        """Objects with a missing/stale placed copy relative to the
        authoritative (assemblable) version -- the PG_DEGRADED accounting
        the qa helpers' wait_for_clean polls.  Mirrors the peering
        authority rules so 'clean' here == 'no actions' there."""
        from ceph_tpu.osd.ecbackend import VERSION_KEY, shard_oid, vt
        from ceph_tpu.osd.pg import POOL_KEY

        km = self.backend.km
        k = 1 if self.ec is None else self.ec.get_data_chunk_count()
        degraded = []
        oids = set()
        metas = set()
        for osd in self.osds:
            if self.messenger.is_down(osd.name):
                continue
            for stored in osd.store.list_objects():
                base, _, tag = stored.rpartition("@")
                if not base:
                    continue
                # report on THIS (default) pool only; co-hosted pools'
                # objects (meta twins included) carry their POOL_KEY tag
                ptag = osd.store.getattr(stored, POOL_KEY)
                if ptag is not None and ptag != self.pool:
                    continue
                (metas if tag == "meta" else oids).add(base)
        for oid in sorted(oids):
            acting = self.backend.acting_set(oid)
            counts: Dict[tuple, int] = {}
            unseen = 0
            placed: Dict[int, tuple] = {}
            for s in range(km):
                if acting[s] is None:
                    continue
                osd = self.osds[acting[s]]
                if self.messenger.is_down(osd.name):
                    unseen += 1
                    continue
                try:
                    v = vt(osd.store.getattr(shard_oid(oid, s), VERSION_KEY))
                except FileNotFoundError:
                    placed[s] = None
                    continue
                placed[s] = v
                counts[v] = counts.get(v, 0) + 1
            if not counts:
                continue
            authoritative = None
            for v in sorted(counts, reverse=True):
                if counts[v] >= k:
                    authoritative = v
                    break
                if counts[v] + unseen >= k:
                    break
            if authoritative is None:
                continue  # incomplete/debris: not recoverable right now
            if any(cur != authoritative for cur in placed.values()):
                degraded.append(oid)
        for oid in sorted(metas):
            acting = self.backend.acting_set(oid)
            vers = []
            for s in range(km):
                if acting[s] is None:
                    continue
                osd = self.osds[acting[s]]
                if self.messenger.is_down(osd.name):
                    continue
                try:
                    vers.append(
                        osd.store.getattr(f"{oid}@meta", "_meta_version") or 0
                    )
                except FileNotFoundError:
                    vers.append(0)
            if vers and min(vers) != max(vers):
                degraded.append(f"{oid}@meta")
        return degraded

    # -- failure control (thrasher surface) --------------------------------

    def _notify_peering(self) -> None:
        """OSD up/down/weight events wake every peering loop immediately
        (event-driven peering; the reference re-peers on OSDMap change)."""
        for osd in self.osds:
            osd.request_peering()

    def _primary_backend_for(self, pool: str, oid: str):
        """The hosted engine currently leading ``oid`` in ``pool``
        (None while no up OSD can lead it)."""
        for osd in self.osds:
            b = osd.pools.get(pool)
            if b is None:
                continue
            acting = b.acting_set(oid)
            for s in range(b.km):
                if b._shard_up(acting, s):
                    return self.osds[acting[s]].pools.get(pool)
            return None
        return None

    def _mark_down_victims(self, osd_id: int, reason: str) -> None:
        """Liveness-event degraded accounting: walk the victim OSD's
        holdings ONCE (event time, never scrape time) and record each
        base object on its current primary's incremental pg_stats.
        This is what keeps ``ClusterState.degraded_objects()`` O(degraded)
        per call -- the per-object census happens only when an OSD
        actually dies or loses its disk."""
        from ceph_tpu.osd.pg import POOL_KEY

        osd = self.osds[osd_id]
        for stored in osd.store.list_objects():
            base, _, tag = stored.rpartition("@")
            if not base:
                continue
            pool = osd.store.getattr(stored, POOL_KEY) or self.pool
            primary = self._primary_backend_for(pool, base)
            if primary is not None:
                primary.pg_stats.note_down_victims(reason, [base])

    def note_remap(self, before: Dict[int, list]) -> None:
        """Event-time misplaced census after a CRUSH change (the
        round-18 discipline: account where the event happens, never at
        scrape time).  ``before`` is the default pool's pg->acting
        snapshot taken BEFORE the map mutated; every stored object whose
        pg moved is marked misplaced on its (new) primary, so the
        misplaced peak is visible the moment the map commits and drains
        monotonically as backfill completes."""
        if self.placement is None:
            return
        from ceph_tpu.osd.placement import movement_plan
        from ceph_tpu.osd.pg import POOL_KEY

        moved_pgs = {
            pg for pg, _pos, _src, _dst
            in movement_plan(before, self.placement.pg_actings())
        }
        if not moved_pgs:
            return
        seen: set = set()
        for osd in self.osds:
            if self.messenger.is_down(osd.name):
                continue
            for stored in osd.store.list_objects():
                base, _, tag = stored.rpartition("@")
                if not base or tag == "meta" or base in seen:
                    continue
                ptag = osd.store.getattr(stored, POOL_KEY)
                if ptag is not None and ptag != self.pool:
                    continue  # other pools have their own placements
                if self.placement.pg_of(base) not in moved_pgs:
                    continue
                seen.add(base)
                primary = self._primary_backend_for(self.pool, base)
                if primary is not None:
                    primary.pg_stats.misplaced.add(base)

    def kill_osd(self, osd_id: int) -> None:
        self.messenger.mark_down(f"osd.{osd_id}")
        self._mark_down_victims(osd_id, f"osd.{osd_id}")
        self._notify_peering()

    def wipe_osd(self, osd_id: int) -> None:
        """Replacement-disk semantics: empty the OSD's object store and
        device tier (its PG log survives -- the daemon kept running,
        the disk was swapped), and reset every peer engine's watermark
        for it (the new-incarnation signal an osdmap epoch bump carries
        in the reference).  The next peering pass then takes the
        backfill path and discovers every shard the OSD lost, which is
        exactly the 'rebuild a killed OSD' scenario the recovery-path
        bench and thrash tests drive."""
        from ceph_tpu.osd.types import Transaction

        osd = self.osds[osd_id]
        # the lost holdings become degraded the moment the disk is
        # swapped (recorded BEFORE the store empties; cleared per object
        # as recovery completes, so the count drains monotonically)
        self._mark_down_victims(osd_id, f"wipe:osd.{osd_id}")
        txn = Transaction()
        for stored in osd.store.list_objects():
            txn.remove(stored)
        osd.store.queue_transaction(txn)
        osd._applied_version.clear()
        osd.tier.clear()
        osd._store_nonempty = False
        osd._scrub_bases = None
        for other in self.osds:
            for backend in other.pools.values():
                backend._peer_seq.pop(osd.name, None)
                backend._peer_dup_seq.pop(osd.name, None)
        self._notify_peering()

    def revive_osd(self, osd_id: int) -> None:
        self.messenger.mark_up(f"osd.{osd_id}")
        # the revived OSD's copies are back: drop exactly the degraded
        # markings its death caused (wipe markings stay -- that data is
        # genuinely gone until recovery rebuilds it)
        for osd in self.osds:
            for backend in osd.pools.values():
                backend.pg_stats.clear_down_reason(f"osd.{osd_id}")
        self._notify_peering()

    def out_osd(self, osd_id: int) -> None:
        """Mark an OSD out: CRUSH remaps its shards (weight -> 0)."""
        if self.placement is not None:
            before = self.placement.pg_actings()
            self.placement.mark_out(osd_id)
            self.note_remap(before)
        self._notify_peering()

    def in_osd(self, osd_id: int, weight: float = 1.0) -> None:
        if self.placement is not None:
            before = self.placement.pg_actings()
            self.placement.mark_in(osd_id, weight)
            self.note_remap(before)
        self._notify_peering()

    # -- elastic membership (online add/remove) ----------------------------

    def add_osd(self, weight: float = 1.0,
                update_placement: bool = True) -> int:
        """Online expansion: spawn a new OSD daemon, host every existing
        pool on it, and widen every engine's membership view -- all while
        the cluster keeps serving.  With ``update_placement`` the shared
        CRUSH map grows and the osd weights in immediately (harness
        mode); mon-backed clusters pass False and let the ``osd add``
        broadcast drive placement growth through apply_map_view's epoch
        gate, so data only moves once the committed map says so."""
        new_id = len(self.osds)
        shard = OSDShard(
            new_id, self.messenger, op_queue=self._op_queue,
            objectstore=self._objectstore, data_path=self._data_path,
        )
        # engines first, membership second: peering must never route to
        # an id whose daemon has no engine for the pool yet
        template = self.osds[0]
        for pool_name, b in template.pools.items():
            ec = getattr(b, "ec", None)
            if ec is not None:
                shard.host_pool(pool_name, ec, new_id + 1, b.placement,
                                pool_type="erasure", size=b.km,
                                min_size=b.min_size)
            else:
                shard.host_pool(pool_name, None, new_id + 1, b.placement,
                                pool_type="replicated", size=b.size,
                                min_size=b.min_size)
            shard.pools[pool_name].tier_mode = b.tier_mode
        self.osds.append(shard)
        for osd in self.osds[:-1]:
            for b in osd.pools.values():
                if new_id not in b.osds:
                    b.osds.append(new_id)
        self.backend.n_osds = len(self.osds)
        if update_placement and self.placement is not None:
            before = self.placement.pg_actings()
            self.placement.add_osd(new_id, weight)
            self.note_remap(before)
        self._notify_peering()
        return new_id

    def drain_osd(self, osd_id: int) -> None:
        """Begin graceful contraction: the osd's CRUSH weight drops to 0
        so every PG it serves remaps (primaries hand off first in map
        order); data migrates off via backfill while the daemon keeps
        answering, so clients never see its departure."""
        if self.placement is not None:
            before = self.placement.pg_actings()
            self.placement.remove_osd(osd_id)
            self.note_remap(before)
        self._notify_peering()

    def retire_osd(self, osd_id: int) -> None:
        """Final departure of a DRAINED osd: mark it down without the
        degraded census kill_osd runs -- its acting positions were
        already handed off, so nothing it still stores is load-bearing."""
        self.messenger.mark_down(f"osd.{osd_id}")
        self._notify_peering()

    # -- monitor-backed cluster (mon quorum owns the osdmap) ---------------

    @classmethod
    async def create_with_mons(
        cls,
        n_osds: int,
        profile: Dict[str, str],
        n_mons: int = 3,
        pool: str = "ecpool",
        plugin: Optional[str] = None,
        fault: Optional[FaultInjector] = None,
        hosts=None,
        op_queue: str = "wpq",
        objectstore: str = "memstore",
        data_path: str = "",
    ) -> "ECCluster":
        """Full control-plane bring-up: elect a mon quorum, register OSDs,
        validate + store the EC profile, create the pool — all through
        paxos-committed osdmap epochs — then attach the data path with
        placement driven by mon map broadcasts.

        Reference flow: vstart.sh boots mons before osds; pools/profiles
        are created via `ceph osd ...` commands that OSDMonitor validates
        and commits (SURVEY.md §3.5)."""
        from ceph_tpu.mon.monitor import MonClient, MonCluster

        plugin = plugin or dict(profile).pop("plugin", "jerasure")
        profile = {k: v for k, v in profile.items() if k != "plugin"}
        self = cls(
            n_osds, dict(profile), plugin=plugin, fault=fault,
            use_crush=True, hosts=hosts, op_queue=op_queue,
            objectstore=objectstore, data_path=data_path,
        )
        self.mons = MonCluster(n_mons, self.messenger)
        await self.mons.form_quorum()
        self.monc = MonClient(self.messenger, n_mons, self.backend.name)
        # route mon replies and map broadcasts through the client dispatcher
        backend = self.backend

        map_state: Dict = {}

        async def mon_hook(msg: dict) -> None:
            if await self.monc.handle_reply(msg):
                return
            if msg.get("type") == "osdmap" and backend.placement is not None:
                from ceph_tpu.mon.osdmap import apply_map_view

                # pg->acting snapshot BEFORE the epoch applies: if the
                # map moved acting sets, the diff drives the event-time
                # misplaced census (O(changes) accounting)
                before = backend.placement.pg_actings()
                # messenger=None: the in-process harness owns its own
                # liveness view (kill_osd/revive_osd mark it directly)
                if apply_map_view(msg["map"], map_state, None,
                                  placements=[backend.placement]):
                    self.note_remap(before)
                    self._notify_peering()  # re-peer on every map epoch
        backend.mon_hook = mon_hook
        full_profile = dict(profile)
        full_profile["plugin"] = plugin
        for cmd in (
            {"prefix": "osd create", "n": n_osds},
            {
                "prefix": "osd erasure-code-profile set",
                "name": f"{pool}-profile",
                "profile": full_profile,
            },
            {
                "prefix": "osd pool create",
                "name": pool,
                "profile": f"{pool}-profile",
                "hosts": hosts,
            },
        ):
            rc, out = await self.monc.command(cmd)
            if rc != 0:
                raise RuntimeError(f"bootstrap {cmd['prefix']}: {out}")
        await self.monc.subscribe()
        return self

    async def mon_command(self, cmd: Dict) -> tuple:
        return await self.monc.command(cmd)

    async def recover_object_shard(
        self, oid: str, shard: int, target_osd: int
    ) -> None:
        await self.backend.recover_shard(oid, shard, target_osd)

    async def deep_scrub(self, oid: str) -> dict:
        return await self.backend.deep_scrub(oid)

    # -- failure detection (OSD heartbeat / mon mark-down analogue) --------

    async def heartbeat_round(self, timeout: float = 0.2) -> list:
        """Ping every OSD; mark unresponsive ones down and return them
        (the OSD↔OSD heartbeat + OSDMonitor mark-down roles, reference
        src/osd/OSD.cc:4612 handle_osd_ping, failure reports to the mon)."""
        import asyncio as _asyncio

        name = "heartbeat-monitor"
        self._hb_pongs: set = set()
        if name not in self.messenger._queues:

            async def collect(src, msg):
                if isinstance(msg, tuple) and msg[0] == "pong":
                    self._hb_pongs.add(msg[1])

            self.messenger.register(name, collect)
        for osd in self.osds:
            await self.messenger.send_message(name, osd.name, "ping")
        await _asyncio.sleep(timeout)
        newly_down = []
        for osd in self.osds:
            if (
                osd.name not in self._hb_pongs
                and not self.messenger.is_down(osd.name)
            ):
                self.messenger.mark_down(osd.name)
                newly_down.append(osd.osd_id)
        return newly_down

    async def shutdown(self) -> None:
        await self.messenger.shutdown()
        for osd in self.osds:
            # settle the shared HBM ledger: a dead daemon's resident
            # tier bytes must not stay charged against live ones
            osd.tier.clear()
            umount = getattr(osd.store, "umount", None)
            if umount is not None:
                umount()
