"""Repair-path benchmark stage (bench.py ``repair_path_host``).

The regenerating-code repair metric: rebuild a wiped OSD on a
product-matrix MSR pool (plugin ``regen``, d = 2k-2) through the
beta-fractional repair lane vs the classic full-stripe gather on the
SAME pool (``osd_ec_fractional_repair`` off) -- identical data,
identical plugin, only the repair plan differs.

Per mode it reports time-to-clean after the kill+wipe, the measured
gather bytes (``recovery_gather_bytes``: what survivors actually put on
the wire), the bytes-saved accounting and the chaos drain profile
(degraded count per peering round).

Correctness is gated before any number is reported, per mode and
across modes:

- chaos sequence: the wipe must show a degraded PEAK, the degraded
  count must drain MONOTONICALLY round over round, and the pool must
  end clean (the HEALTH_OK analogue: zero actions + empty degraded
  report);
- every object reads back bit-exact after the rebuild in BOTH modes,
  and the rebuilt victim stores match byte-for-byte across modes (a
  regenerated shard is the same bytes a full-stripe decode produces);
- the fractional mode must actually have used the regen lane
  (``recovery_bytes_saved`` > 0, helpers served) and the classic mode
  must not have;
- ``repair_bytes_ratio`` (fractional gather / classic gather) must be
  <= ``bytes_ratio_bound`` (default 0.75; MSR at k=4 measures ~0.5) and
  ``time_to_clean_ratio`` must stay <= ``time_ratio_bound`` (repair
  must not get slower for its bandwidth savings).

Used by bench.py (fields ``repair_path_*``) and
``tools/ec_benchmark.py --workload repair-path``; the tier-1 smoke
runs it at tiny shapes via ``--smoke``.
"""

from __future__ import annotations

import asyncio
import time
from typing import Dict, List

import numpy as np

#: product-matrix MSR pool: d = 2k-2 = 6 helpers, alpha = k-1 = 3
#: sub-chunks per shard, repair moves d*beta = 2 chunks vs k = 4 classic
PROFILE = {"k": "4", "m": "3", "plugin": "regen"}


def _bg_counters() -> Dict[str, int]:
    import json

    from ceph_tpu.utils.perf import PerfCounters

    dump = json.loads(PerfCounters.dump())
    out: Dict[str, int] = {}
    for key in ("recovery_ops_batched", "recovery_bytes",
                "recovery_gather_bytes", "recovery_bytes_saved",
                "regen_helpers_served", "recovery_batches"):
        out[key] = sum(v.get(key, 0) for v in dump.values()
                       if isinstance(v, dict))
    return out


async def _run_mode(fractional: bool, *, n_osds: int, n_objects: int,
                    obj_bytes: int, payloads: List[bytes],
                    victim: int) -> Dict:
    from ceph_tpu.osd.cluster import ECCluster
    from ceph_tpu.utils.config import get_config
    from ceph_tpu.utils.perf import PerfCounters

    PerfCounters.reset_all()
    cfg = get_config()
    prior = cfg.get_val("osd_ec_fractional_repair")
    cfg.apply_changes({"osd_ec_fractional_repair": fractional,
                       "osd_recovery_batched": True})
    cluster = ECCluster(n_osds, dict(PROFILE), op_queue="mclock")
    mode = "fractional" if fractional else "classic"
    try:
        oids = [f"rp{i}" for i in range(n_objects)]
        for oid, data in zip(oids, payloads):
            await cluster.write(oid, data)

        # chaos sequence: wipe -> degraded peak -> monotone drain ->
        # clean.  The degraded poll between rounds is harness
        # bookkeeping paid equally by both modes.
        cluster.kill_osd(victim)
        cluster.wipe_osd(victim)
        cluster.revive_osd(victim)
        peak = len(await cluster.degraded_report())
        if peak == 0:
            raise AssertionError(
                f"repair-path ({mode}): wipe produced no degraded peak")

        drain: List[int] = [peak]
        t0 = time.perf_counter()
        for _round in range(16):
            n_actions = 0
            for osd in cluster.osds:
                for backend in osd.pools.values():
                    n_actions += await backend.peering_pass()
            degraded = len(await cluster.degraded_report())
            drain.append(degraded)
            if n_actions == 0 and degraded == 0:
                break
        time_to_clean = time.perf_counter() - t0
        if drain[-1] != 0:
            raise AssertionError(
                f"repair-path ({mode}): never reached clean "
                f"(drain={drain})")
        if any(b > a for a, b in zip(drain, drain[1:])):
            raise AssertionError(
                f"repair-path ({mode}): degraded count regressed "
                f"mid-drain (drain={drain})")

        # bit-exactness gate: every object reads back exactly
        for oid, data in zip(oids, payloads):
            got = await cluster.read(oid)
            if got != data:
                raise AssertionError(
                    f"repair-path ({mode}): {oid} mismatched after "
                    "rebuild")
        # the victim's rebuilt shard store, for cross-mode comparison
        store = {
            stored: cluster.osds[victim].store.read(stored)
            for stored in cluster.osds[victim].store.list_objects()
        }
        counters = _bg_counters()
        return {
            "time_to_clean_s": round(time_to_clean, 4),
            "degraded_peak": peak,
            "drain": drain,
            "rebuilt_bytes": counters["recovery_bytes"],
            "gather_bytes": counters["recovery_gather_bytes"],
            "counters": counters,
            "store": store,
        }
    finally:
        cfg.apply_changes({"osd_ec_fractional_repair": prior})
        await cluster.shutdown()


def run_repair_path_bench(*, n_osds: int = 8, n_objects: int = 48,
                          obj_bytes: int = 24 << 10,
                          bytes_ratio_bound: float = 0.75,
                          time_ratio_bound: float = 1.25,
                          seed: int = 91) -> Dict:
    rng = np.random.RandomState(seed)
    payloads = [
        rng.randint(0, 256, size=obj_bytes, dtype=np.uint8).tobytes()
        for _ in range(n_objects)
    ]
    victim = 0
    loop = asyncio.new_event_loop()
    try:
        classic = loop.run_until_complete(_run_mode(
            False, n_osds=n_osds, n_objects=n_objects,
            obj_bytes=obj_bytes, payloads=payloads, victim=victim))
        fractional = loop.run_until_complete(_run_mode(
            True, n_osds=n_osds, n_objects=n_objects,
            obj_bytes=obj_bytes, payloads=payloads, victim=victim))
    finally:
        loop.close()

    # cross-mode gate: regeneration must produce the exact bytes a
    # full-stripe decode does
    cs, fs = classic.pop("store"), fractional.pop("store")
    if set(cs) != set(fs):
        raise AssertionError("repair-path: rebuilt shard sets differ "
                             "between fractional and classic modes")
    for soid in cs:
        if cs[soid] != fs[soid]:
            raise AssertionError(
                f"repair-path: rebuilt shard {soid} differs between "
                "fractional and classic modes")
    if fractional["counters"]["recovery_bytes_saved"] <= 0:
        raise AssertionError(
            "repair-path: fractional mode never engaged the regen lane")
    if fractional["counters"]["regen_helpers_served"] <= 0:
        raise AssertionError(
            "repair-path: no survivor served a helper symbol")
    if classic["counters"]["recovery_bytes_saved"] != 0:
        raise AssertionError(
            "repair-path: classic baseline rode the regen lane")
    if classic["gather_bytes"] <= 0:
        raise AssertionError("repair-path: classic mode gathered nothing")

    bytes_ratio = round(
        fractional["gather_bytes"] / classic["gather_bytes"], 4)
    time_ratio = round(
        fractional["time_to_clean_s"]
        / max(classic["time_to_clean_s"], 1e-9), 3)
    if bytes_ratio > bytes_ratio_bound:
        raise AssertionError(
            f"repair-path: gather ratio {bytes_ratio} exceeds the "
            f"{bytes_ratio_bound} repair-bandwidth gate")
    if time_ratio > time_ratio_bound:
        raise AssertionError(
            f"repair-path: time-to-clean ratio {time_ratio} exceeds "
            f"{time_ratio_bound} -- the fractional lane made repair "
            "slower")
    return {
        "n_osds": n_osds,
        "n_objects": n_objects,
        "obj_bytes": obj_bytes,
        "bit_exact": True,  # the gates raised otherwise
        "repair_bytes_ratio": bytes_ratio,
        "time_to_clean_ratio": time_ratio,
        "bytes_saved": fractional["counters"]["recovery_bytes_saved"],
        "classic": classic,
        "fractional": fractional,
    }
