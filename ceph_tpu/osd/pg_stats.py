"""Incremental per-PG statistics (the pg_stat_t / PGMap-feed role).

Reference: src/osd/osd_types.h pg_stat_t -- every PG maintains its
object/degraded/misplaced counts and state bits *where the events
happen* (apply, peering, recovery completion) and ships them to the mgr
in MPGStats; nobody ever walks the object store to answer ``ceph -s``.

The seed's ``ClusterState.degraded_objects()`` did exactly that walk --
O(objects x shards) per prometheus scrape.  This tracker replaces it:

* **mutation seams** -- a write that missed a down shard already adds
  the oid to the engine's ``_dirty`` set (pg.py); dirty objects ARE
  degraded objects, so no extra bookkeeping is needed there;
* **liveness seams** -- the cluster harness marks a killed/wiped OSD's
  former holdings as down-victims (``note_down_victims``) once per
  event, with the reason recorded so a revive clears exactly what the
  kill caused;
* **peering** -- ``note_recovering`` marks the pass's action objects
  while they rebuild (``_peering_apply``), ``note_backfilling`` brackets
  the full-scan path, and ``end_pass`` drops every tracked object that
  finished the pass clean;
* **recovery completions** -- the batched plane (osd/recovery.py) and
  the per-object windowed path call ``note_recovered`` per object, so
  the degraded count *drains monotonically* while a rebuild runs -- the
  signal the chaos health gate asserts.

``degraded_oids()`` is the union of those sources; computing it is
O(degraded), never O(objects).  ``pg_stat()`` renders the ceph-style
state-bit string for the report frame.
"""

from __future__ import annotations

from typing import Dict, Iterable, Set


class PGStats:
    """Incremental stats for one hosted (pool, primary-engine) slice."""

    def __init__(self, backend):
        self._backend = backend
        #: oid -> liveness reasons ("osd.3", "wipe:osd.1") that made it
        #: degraded; cleared per-reason on revive, per-oid on a clean
        #: peering pass
        self._down_victims: Dict[str, Set[str]] = {}
        #: objects a running peering pass is actively rebuilding
        self._recovering: Set[str] = set()
        #: objects whose data exists but (at least partly) on
        #: non-acting holders -- remap leftovers awaiting backfill
        self.misplaced: Set[str] = set()
        #: the full-scan (backfill) peering path is in flight
        self.backfilling = False

    # -- event seams -------------------------------------------------------

    def note_down_victims(self, reason: str, oids: Iterable[str]) -> None:
        """A liveness event (kill/wipe/out) cost these objects a copy."""
        for oid in oids:
            self._down_victims.setdefault(oid, set()).add(reason)

    def clear_down_reason(self, reason: str) -> None:
        """The event was undone (revive): drop exactly its markings."""
        for oid in list(self._down_victims):
            reasons = self._down_victims[oid]
            reasons.discard(reason)
            if not reasons:
                del self._down_victims[oid]

    def note_recovering(self, oids: Iterable[str]) -> None:
        self._recovering.update(oids)

    def note_recovered(self, oid: str) -> None:
        """One object's rebuild completed: the draining tick."""
        self._recovering.discard(oid)
        self._down_victims.pop(oid, None)
        self.misplaced.discard(oid)

    def end_pass(self, tracked: Iterable[str],
                 still_dirty: Iterable[str]) -> None:
        """Peering-pass epilogue, mirroring the engine's dirty-set
        maintenance: tracked objects that ended the pass clean drop
        every degraded marking; unfinished ones stay."""
        dirty = set(still_dirty)
        for oid in tracked:
            if oid not in dirty:
                self._down_victims.pop(oid, None)
                self.misplaced.discard(oid)
            self._recovering.discard(oid)

    # -- read side ---------------------------------------------------------

    def degraded_oids(self) -> Set[str]:
        """Objects currently degraded from this primary's view: the
        engine's dirty sets (writes that missed shards, pending
        recoveries) + liveness victims + in-flight rebuilds."""
        b = self._backend
        return (set(self._down_victims) | self._recovering
                | b._dirty | b._dirty_meta)

    def degraded_count(self) -> int:
        return len(self.degraded_oids())

    def state_bits(self) -> list:
        """ceph-style PG state bits for this slice."""
        b = self._backend
        shard = getattr(b, "_host_shard", None)
        bits = []
        pool = b.pool_name
        if shard is not None and \
                shard.pg_states.get(pool) == "peering":
            bits.append("peering")
        else:
            bits.append("active")
        undersized = any(
            b.messenger.is_down(f"osd.{i}") for i in range(len(b.osds))
        )
        if undersized:
            bits.append("undersized")
        if self.degraded_count():
            bits.append("degraded")
        if self.misplaced:
            bits.append("remapped")
        if self.backfilling:
            bits.append("backfilling")
        elif self._recovering:
            bits.append("recovering")
        if not bits[1:] and bits[0] == "active":
            bits.append("clean")
        return bits

    def pg_stat(self) -> dict:
        """The per-PG slice of a MgrReport frame (value()-encodable)."""
        return {
            "state": "+".join(self.state_bits()),
            "degraded": self.degraded_count(),
            "misplaced": len(self.misplaced),
            "recovering": len(self._recovering),
            "scrub_errors": len(self._backend.scrub_errors),
        }
