"""Batched background data plane: recovery coalescing + scrub cursors.

Rounds 6-13 made the CLIENT write lane batched (per-PG coalescer),
corked (multi-submit messenger bursts) and device-resident, but
background data movement -- recovery pushes, backfill, deep-scrub reads
-- still flowed one object, one message at a time (ROADMAP item 5).
Online-EC studies show recovery I/O dominates degraded-mode cost
(arXiv "Understanding System Characteristics of Online Erasure
Coding...", "Exploring Fault-Tolerant Erasure Codes..."), so a rebuild
storm was both slow AND able to starve client p99.

This module routes background movement through the same batched
shard-major plane the client path uses:

* **RecoveryCoalescer** -- groups a peering pass's missing objects into
  batches: ONE corked multi-read burst gathers every batch object's
  source chunks (one ``ECSubRead`` per (source OSD, shard position)
  covering all its objects), one fused ``decode_shards_many`` dispatch
  reconstructs every lost shard (signature-grouped, riding the
  rung-bucketed pipeline from PR 8), and ONE corked multi-push burst
  ships the rebuilt shards (``ECSubWrite`` op_class="recovery").
  Objects the batch cannot prove consistent (version races, oversized
  shards past the per-object byte share) fall back to the per-object
  windowed path -- correctness never rides the fast lane.
* **promote-on-recovery** -- a rebuilt hot (or previously-resident)
  object's FULL [km, shard_len] block is already in hand after the
  fused decode; in writeback mode it lands straight in the device tier
  (``tier_promote_from_recovery``), so the rebuilt object serves its
  next read from HBM instead of going cold and waiting for the agent
  to re-gather it from the shards it was just pushed to.
* **BackgroundThrottle** -- every batch is admitted against an active
  budget (``osd_recovery_max_active`` concurrent batches,
  ``osd_recovery_batch_bytes`` gathered bytes each) and backs off while
  the hosting OSD's client queue is saturated (``recovery_preempted``),
  with bounded preemption so degraded objects that BLOCK client ops
  still make forced progress; ``osd_recovery_sleep`` paces between
  batches.  Receiving OSDs additionally queue every sub-op under the
  mClock/WPQ ``recovery``/``scrub`` op classes as before.
* **scrub_read_many** -- deep scrub's reads ride the same batched lane
  with a chunked cursor (``osd_scrub_chunk_max`` bytes per shard per
  round, ``scrub_chunks`` counted), instead of one whole-shard read
  fan-out per object.

cephlint's ``async-background-unthrottled`` rule pins the discipline:
a background-class loop issuing pushes/reads must admit through the
throttle or await pacing between batches.
"""

from __future__ import annotations

import asyncio
from typing import Dict, List, Optional, Tuple

import numpy as np

from ceph_tpu.osd import ecutil
from ceph_tpu.osd.pg import (SIZE_KEY, SNAPSET_KEY, VERSION_KEY,
                             WHITEOUT_KEY, shard_oid, vt)
from ceph_tpu.osd.types import ECSubRead, ECSubWrite, Transaction
from ceph_tpu.utils import trace

#: queued client ops above which background batches back off (the
#: saturation signal; _cop_sem bounds execution at 64, so half a
#: worker's width of queued-but-unserved clients means contention)
CLIENT_PRESSURE_OPS = 16
#: preemption rounds before a batch is forced through anyway: a
#: degraded object can BLOCK the very client ops saturating the queue
#: (reads needing the missing shard), so recovery must never be
#: starved forever by the load it exists to unblock
MAX_PREEMPT_ROUNDS = 20
#: objects per batched dispatch (the byte budget is the real bound;
#: this caps the per-batch fan-out bookkeeping)
MAX_BATCH_OBJECTS = 32


def _cfg():
    from ceph_tpu.utils.config import get_config

    return get_config()


class BackgroundThrottle:
    """Primary-side admission for background batches (recovery, scrub).

    Bounds concurrent batches (``osd_recovery_max_active``) and backs
    off while the hosting OSD's client queue is saturated; preemption
    is bounded (forced progress) and every backoff round is counted
    (``recovery_preempted``)."""

    def __init__(self, backend):
        self._backend = backend
        self._sem: Optional[asyncio.Semaphore] = None
        self._sem_width = 0
        #: unified-QoS slots currently held (admit/release pairing is
        #: caller-side positional, and every slot is the same
        #: "recovery"-class token, so a simple count suffices)
        self._qos_held = 0

    def _semaphore(self) -> asyncio.Semaphore:
        width = max(1, int(_cfg().get_val("osd_recovery_max_active")))
        if self._sem is None or width != self._sem_width:
            self._sem = asyncio.Semaphore(width)
            self._sem_width = width
        return self._sem

    def _qos(self):
        """The hosting shard's unified admission (osd/qos.py), when the
        engine is shard-hosted and osd_qos_unified is on."""
        shard = getattr(self._backend, "_host_shard", None)
        return getattr(shard, "qos", None)

    def _client_pressure(self) -> bool:
        shard = getattr(self._backend, "_host_shard", None)
        if shard is None:
            return False
        return getattr(shard, "_client_ops_queued", 0) > CLIENT_PRESSURE_OPS

    async def admit(self, force: bool = False, cost: int = 0) -> None:
        """Claim one background-batch slot.  Under unified QoS the
        claim is a dmClock "recovery"-class admission (osd/qos.py) with
        ``cost`` = the batch's byte budget: client bursts win the freed
        slots by weight, recovery's reservation guarantees forward
        progress -- replacing the legacy client-pressure gauge loop,
        which remains the fallback (osd_qos_unified=false, client-side
        engines) with its bounded preemption."""
        await self._semaphore().acquire()
        qos = self._qos()
        if qos is not None:
            try:
                held = await qos.acquire("recovery", max(1, int(cost)))
            except BaseException:
                self._sem.release()
                raise
            if held:
                self._qos_held += 1
            return
        rounds = 0
        while not force and rounds < MAX_PREEMPT_ROUNDS \
                and self._client_pressure():
            self._backend.perf.inc("recovery_preempted")
            rounds += 1
            await asyncio.sleep(max(
                0.005, float(_cfg().get_val("osd_recovery_sleep"))))

    def release(self) -> None:
        if self._qos_held > 0:
            qos = self._qos()
            if qos is not None:
                self._qos_held -= 1
                qos.release_slot()
        if self._sem is not None:
            self._sem.release()

    async def pace(self) -> None:
        """Awaited pacing between batches (osd_recovery_sleep; 0 still
        yields once so queued client ops interleave)."""
        await asyncio.sleep(float(_cfg().get_val("osd_recovery_sleep")))


# -- batched sub-op transport helpers ------------------------------------
#
# One pending-state entry per message, ONE send_messages submit for the
# whole set: the TCP messenger's per-peer cork queues gather each peer's
# share into a single scatter-gather burst (the PR-3 corked wire,
# previously reserved for client fan-outs).

async def batched_sub_reads(
    backend,
    reads: List[tuple],
    op_class: str,
    timeout: float,
) -> List[Optional[object]]:
    """``reads``: (osd_name, from_shard, {oid: extents}, attrs_to_read
    [, regen]) per message -- the optional 5th element is the
    regenerating-repair coefficient map ({oid: phi_f}) carried on the
    ECSubRead wire field.  Returns one ECSubReadReply (or None on
    loss/timeout) per entry, in order."""
    loop = asyncio.get_event_loop()
    wire_ctx = trace.current_wire()  # stitch into the batch span
    pend = []
    subs = []
    for entry in reads:
        osd_name, s, to_read, attrs = entry[:4]
        regen = entry[4] if len(entry) > 4 else None
        tid = backend._new_tid()
        done = loop.create_future()
        backend._pending[tid] = {
            "replies": {}, "outstanding": {s}, "done": done,
        }
        pend.append((tid, s, done))
        subs.append((osd_name, ECSubRead(
            from_shard=s, tid=tid,
            to_read={oid: list(ext) for oid, ext in to_read.items()},
            attrs_to_read=list(attrs), op_class=op_class,
            trace=wire_ctx, regen=regen,
        )))
    await backend.messenger.send_messages(backend.name, subs)
    if pend:
        try:
            await asyncio.wait_for(
                asyncio.gather(*(d for _t, _s, d in pend)), timeout)
        except asyncio.TimeoutError:
            pass  # missing replies surface as None below
    out = []
    for tid, s, _done in pend:
        state = backend._pending.pop(tid, None)
        out.append(state["replies"].get(s) if state else None)
    return out


async def batched_pushes(
    backend,
    pushes: List[Tuple[str, ECSubWrite]],
    timeout: float,
) -> List[bool]:
    """Ship every (target osd, sub-write) as ONE corked multi-submit
    burst; returns per-push commit success, in order."""
    loop = asyncio.get_event_loop()
    wire_ctx = trace.current_wire()  # stitch into the batch span
    pend = []
    for target, _sub in pushes:
        if wire_ctx is not None and getattr(_sub, "trace", None) is None:
            _sub.trace = wire_ctx
        done = loop.create_future()
        backend._pending[_sub.tid] = {
            "committed": set(), "expected": {target}, "done": done,
        }
        pend.append((_sub.tid, done))
    await backend.messenger.send_messages(backend.name, pushes)
    if pend:
        try:
            # return_exceptions: one refused push must not abandon the
            # rest of the burst's accounting
            await asyncio.wait_for(
                asyncio.gather(*(d for _t, d in pend),
                               return_exceptions=True), timeout)
        except asyncio.TimeoutError:
            pass
    out = []
    for tid, done in pend:
        state = backend._pending.pop(tid, None)
        ok = bool(state and state["committed"])
        if done.done() and not done.cancelled() and \
                done.exception() is not None:
            ok = False
        out.append(ok)
    return out


# -- the recovery coalescer ----------------------------------------------

class RecoveryCoalescer:
    """Per-PG batched recovery driver (the background analogue of the
    client-op BatchCoalescer).  Owned lazily by the PG engine; all
    state is per-call, so concurrent peering passes just share the
    throttle."""

    def __init__(self, backend):
        self.backend = backend
        self.throttle = BackgroundThrottle(backend)

    # -- entry point from the peering pass --------------------------------

    async def recover_actions(self, actions: List[tuple]) -> set:
        """Run a peering pass's recovery actions (oid, shard, target,
        authoritative, rollback) through the batched plane; returns the
        oids whose recovery failed (kept dirty for the next pass)."""
        backend = self.backend
        failed: set = set()
        plain: Dict[str, List[tuple]] = {}
        for oid, s, target, authoritative, rb in actions:
            if rb and await backend._try_log_rollback(
                oid, s, target, authoritative
            ):
                continue  # the shard healed itself from its own log
            if tuple(authoritative) == (0, ""):
                # torn copy with no assemblable object behind it: the
                # rollback target is non-existence (rare; per-object)
                try:
                    await backend._remove_shard_copy(oid, s, target)
                except asyncio.CancelledError:
                    raise
                except Exception:  # noqa: BLE001 -- retried next pass
                    backend.perf.inc("recover_failed")
                    failed.add(oid)
                continue
            plain.setdefault(oid, []).append((s, target, rb))

        batch_cost = max(1, int(_cfg().get_val("osd_recovery_batch_bytes")))
        oids = sorted(plain)
        for i in range(0, len(oids), MAX_BATCH_OBJECTS):
            group = {oid: plain[oid] for oid in oids[i:i + MAX_BATCH_OBJECTS]}
            await self.throttle.admit(cost=batch_cost)
            try:
                fell_back = await self._recover_batch(group)
            finally:
                self.throttle.release()
            # objects the batch could not prove consistent (version
            # races, oversized shards, too few sources) take the
            # windowed per-object path -- correctness over speed
            for oid in fell_back:
                for s, target, rb in group[oid]:
                    try:
                        await backend.recover_shard(
                            oid, s, target, rollback=rb)
                    except asyncio.CancelledError:
                        raise
                    except Exception:  # noqa: BLE001 -- next pass retries
                        backend.perf.inc("recover_failed")
                        failed.add(oid)
            # per-group completion tick: the incremental degraded count
            # (pg_stats) drains as each batch lands, not at pass end --
            # what makes the chaos gate's drain curve monotone
            for oid in group:
                if oid not in failed:
                    backend.pg_stats.note_recovered(oid)
            await self.throttle.pace()
        return failed

    # -- one batch ---------------------------------------------------------

    async def _recover_batch(self, group: Dict[str, List[tuple]]) -> set:
        """Gather + fused rebuild + corked push for one object group;
        returns oids that must fall back to the per-object path.  Holds
        every batch object's write lock (sorted acquisition order) so
        client writes queue briefly behind the push instead of racing
        it -- the same pinning recover_shard does, batch-wide."""
        from contextlib import AsyncExitStack

        backend = self.backend
        # one batch span for the whole multi-read/decode/multi-push
        # cycle (background root: rolls its own sampling decision);
        # amortized over the batch's objects like a coalescer fan-in
        span = trace.new_trace("recovery_batch")
        if span.sampled:
            span.amortized_over = max(1, len(group))
            span.tag_set("objects", len(group))
        try:
            with trace.use_span(span):
                async with AsyncExitStack() as stack:
                    for oid in sorted(group):
                        await stack.enter_async_context(
                            backend._object_lock(oid))
                    span.event("locks_acquired")
                    return await self._recover_batch_locked(group)
        finally:
            span.finish()

    async def _recover_batch_locked(self,
                                    group: Dict[str, List[tuple]]) -> set:
        backend = self.backend
        cfg = _cfg()
        fall_back: set = set()
        # per-object source plan: chunk window sized so the whole
        # batch's gathered bytes stay under osd_recovery_batch_bytes
        batch_bytes = max(1, int(cfg.get_val("osd_recovery_batch_bytes")))
        cs = backend.sinfo.chunk_size
        share = batch_bytes // max(1, len(group)) // max(1, backend.k)
        win = max(cs, share // cs * cs)

        # sub-chunk geometry: every codec's minimum_to_decode speaks
        # (offset, count) plans over get_sub_chunk_count() sub-chunks;
        # converting them to byte extents here means a fractional plan
        # (regenerating codes, future Clay-style codecs) gathers ONLY
        # the bytes the fused decode consumes
        scc = max(1, int(getattr(backend.ec, "get_sub_chunk_count",
                                 lambda: 1)()))
        sub_bytes = win // scc if win % scc == 0 else 0

        plans: Dict[str, dict] = {}
        reads: Dict[Tuple[str, int], Dict[str, list]] = {}
        attr_reads: Dict[Tuple[str, int], Dict[str, list]] = {}
        regen_maps: Dict[Tuple[str, int], Dict[str, list]] = {}
        for oid, jobs in group.items():
            acting = backend.acting_set(oid)
            want = sorted({s for s, _t, _rb in jobs})
            up = [
                s for s in range(backend.km)
                if s not in want and backend._shard_up(acting, s)
            ]
            try:
                mtd = backend.ec.minimum_to_decode(list(want), up)
            except Exception:  # noqa: BLE001 -- unassemblable right now
                fall_back.add(oid)
                continue
            src = sorted(mtd.keys())
            plans[oid] = {"acting": acting, "want": want, "src": src}
            # regenerating-repair lane: the codec advertises fractional
            # repair AND handed back a sub-chunk plan strictly below a
            # whole shard per helper -- the gather carries phi_f and the
            # survivors reply beta-sized helper symbols (d * chunk/alpha
            # bytes on the wire instead of k whole chunks)
            fractional = (
                bool(getattr(backend.ec, "fractional_repair", False))
                and bool(cfg.get_val("osd_ec_fractional_repair"))
                and len(want) == 1 and sub_bytes > 0
                and all(sum(ln for _o, ln in ext) < scc
                        for ext in mtd.values())
                and not self._want_promote(oid, 1)
            )
            if fractional:
                lost = want[0]
                coeffs = backend.ec.repair_coeffs(lost)
                plans[oid]["regen"] = {"lost": lost, "helpers": src,
                                       "coeffs": coeffs}
                for s in src:
                    key = (f"osd.{acting[s]}", s)
                    reads.setdefault(key, {})[oid] = [(0, sub_bytes)]
                    regen_maps.setdefault(key, {})[oid] = coeffs
            else:
                for s in src:
                    key = (f"osd.{acting[s]}", s)
                    ext = mtd.get(s) or [(0, scc)]
                    reads.setdefault(key, {})[oid] = [
                        (off * sub_bytes, ln * sub_bytes)
                        for off, ln in ext
                    ] if sub_bytes and scc > 1 else [(0, win)]
            for s in up:
                if s in src:
                    continue
                # attr-only round from the remaining up shards: the
                # minimum source set alone cannot prove the
                # authoritative version (same rule as _gather_consistent)
                key = (f"osd.{acting[s]}", s)
                attr_reads.setdefault(key, {})[oid] = [(0, 0)]

        read_list = [
            (osd, s, to_read, sorted(to_read), regen_maps.get((osd, s)))
            for (osd, s), to_read in list(reads.items())
            + list(attr_reads.items())
        ]
        timeout = float(cfg.get_val("osd_read_gather_timeout"))
        replies = await batched_sub_reads(
            backend, read_list, "recovery", timeout)
        trace.event("gather_done")

        # collate per (oid, shard): chunks / helpers / versions / sizes
        per_oid: Dict[str, dict] = {
            oid: {"chunks": {}, "helpers": {}, "versions": {},
                  "sizes": {}, "attrs": {}}
            for oid in plans
        }
        gather_bytes = 0
        for (osd, s, to_read, _attrs, regen), reply in zip(
                read_list, replies):
            if reply is None:
                continue
            for oid in to_read:
                if oid not in per_oid or oid in reply.errors:
                    continue
                slot = per_oid[oid]
                bufs = reply.buffers_read.get(oid)
                if bufs and len(bufs[0][1]):
                    gather_bytes += sum(len(b) for _off, b in bufs)
                    arr = np.frombuffer(bufs[0][1], dtype=np.uint8)
                    if regen and oid in regen:
                        # beta-sized helper symbol, not shard bytes
                        slot["helpers"][s] = arr
                    else:
                        slot["chunks"][s] = arr
                attrs = reply.attrs_read.get(oid) or {}
                if attrs:
                    slot["attrs"][s] = attrs
                    if attrs.get(SIZE_KEY) is not None:
                        slot["sizes"][s] = attrs[SIZE_KEY]
                    slot["versions"][s] = vt(attrs.get(VERSION_KEY))

        if gather_bytes:
            backend.perf.inc("recovery_gather_bytes", gather_bytes)

        # -- per-object consistency election, then ONE fused decode ------
        maps: List[Dict[int, np.ndarray]] = []
        wants: List[List[int]] = []
        ready: List[str] = []
        regen_groups: Dict[tuple, List[str]] = {}
        for oid, plan in plans.items():
            slot = per_oid[oid]
            if not slot["versions"]:
                fall_back.add(oid)
                continue
            rg = plan.get("regen")
            if rg is not None:
                geom = self._elect_regen(plan, slot, win)
                if geom is None:
                    # stale/short/missing helper: the classic
                    # full-gather path re-runs this object
                    fall_back.add(oid)
                elif geom[0]:
                    key = (rg["lost"], tuple(rg["helpers"]), geom[1])
                    regen_groups.setdefault(key, []).append(oid)
                # chunk_total == 0 rides the attrs-only push below
                continue
            target_v = max(slot["versions"].values())
            holders = [s for s, v in slot["versions"].items()
                       if v == target_v]
            have = {s: slot["chunks"][s] for s in holders
                    if s in slot["chunks"]}
            size = next((slot["sizes"][s] for s in holders
                         if slot["sizes"].get(s) is not None), None)
            zero_len = size == 0 and not have
            if size is None or (len(have) < backend.k and not zero_len):
                # stale mix / missing size / newest version not
                # assemblable from this cut: the windowed path's full
                # version-authoritative gather decides
                fall_back.add(oid)
                continue
            chunk_total = backend._shard_bytes_total(size)
            if have and len(next(iter(have.values()))) < chunk_total:
                # object larger than the batch's per-object window:
                # recover it windowed (bounded batch memory)
                fall_back.add(oid)
                continue
            plan["version"] = target_v
            plan["size"] = size
            plan["chunk_total"] = chunk_total
            plan["attrs"] = next(
                (slot["attrs"][s] for s in holders if s in slot["attrs"]),
                {},
            )
            plan["have"] = have
            if chunk_total:
                # promote-on-recovery wants the FULL km block; the
                # fused dispatch reconstructs every missing position in
                # the same pass when the tier will take it
                missing = [s for s in range(backend.km) if s not in have]
                rebuild = missing if self._want_promote(oid, size) \
                    else sorted(set(plan["want"]) - set(have))
                maps.append(dict(have))
                wants.append(rebuild)
                ready.append(oid)
        if maps:
            trace.event("decode_submit")
            decoded = ecutil.decode_shards_many(backend.ec, maps, wants)
            trace.event("decode_done")
        else:
            decoded = []

        # -- fused regenerating dispatch ---------------------------------
        # one device matmul per (lost shard, helper set, beta) signature:
        # the d stacked helper symbols of EVERY object in the group ride
        # a single batched repair-matrix apply
        for (lost, helpers, beta), g_oids in regen_groups.items():
            stacks = [
                np.stack([per_oid[o]["helpers"][s] for s in helpers])
                for o in g_oids
            ]
            try:
                regenerated = backend.ec.regenerate_batch(
                    lost, list(helpers), stacks)
            except Exception:  # noqa: BLE001 -- refuse -> full gather
                fall_back.update(g_oids)
                continue
            for o, shard in zip(g_oids, regenerated):
                ready.append(o)
                decoded.append({lost: shard})
                # classic repair reads k whole chunks; this one read
                # d beta-sized helper symbols
                plans[o]["bytes_saved"] = (
                    backend.k * plans[o]["chunk_total"]
                    - len(helpers) * beta)
        if regen_groups:
            trace.event("regen_done")

        # -- corked multi-push burst --------------------------------------
        pushes: List[Tuple[str, ECSubWrite]] = []
        push_oids: List[str] = []
        full: Dict[str, Dict[int, np.ndarray]] = {}
        for oid, rebuilt in zip(ready, decoded):
            plan = plans[oid]
            chunks = dict(plan["have"])
            chunks.update(rebuilt)
            full[oid] = chunks
            for s, target, rb in group[oid]:
                piece = chunks[s].tobytes() if plan["chunk_total"] else b""
                pushes.append((f"osd.{target}", self._push_sub(
                    oid, s, piece, plan, rb)))
                push_oids.append(oid)
        for oid in plans:
            if oid not in ready and oid not in fall_back \
                    and plans[oid].get("chunk_total") == 0:
                # zero-byte object: attrs-only push, no codec involved
                plan = plans[oid]
                for s, target, rb in group[oid]:
                    pushes.append((f"osd.{target}", self._push_sub(
                        oid, s, b"", plan, rb)))
                    push_oids.append(oid)
                full[oid] = {}
        commit_t = float(cfg.get_val("osd_client_op_commit_timeout"))
        results = await batched_pushes(backend, pushes, commit_t)
        trace.event("push_done")

        ok_oids: set = set()
        bad_oids: set = set()
        nbytes = 0
        backfill_bytes = 0
        misplaced = backend.pg_stats.misplaced
        for oid, (target, sub), ok in zip(push_oids, pushes, results):
            if ok:
                ok_oids.add(oid)
                for top in sub.transaction.ops:
                    if top.op == "write":
                        nbytes += len(top.data)
                        if oid in misplaced:
                            # migration (not rebuild) traffic: the copy
                            # exists elsewhere, it's just mis-placed --
                            # feeds the data-moved-ratio elasticity gate
                            backfill_bytes += len(top.data)
            else:
                bad_oids.add(oid)
        ok_oids -= bad_oids
        fall_back |= bad_oids
        if ok_oids:
            backend.perf.inc("recovery_ops_batched", len(ok_oids))
            backend.perf.inc("recovery_batches")
            backend.perf.inc("recover", len(ok_oids))
        if nbytes:
            backend.perf.inc("recovery_bytes", nbytes)
        if backfill_bytes:
            backend.perf.inc("recovery_backfill_bytes", backfill_bytes)
        saved = sum(plans[o].get("bytes_saved", 0)
                    for o in ok_oids if o in plans)
        if saved > 0:
            backend.perf.inc("recovery_bytes_saved", saved)

        # -- promote-on-recovery ------------------------------------------
        for oid in sorted(ok_oids):
            plan = plans.get(oid)
            if plan is None or not plan.get("chunk_total"):
                continue
            chunks = full.get(oid)
            if chunks and len(chunks) == backend.km and \
                    self._want_promote(oid, plan["size"]):
                block = np.stack([
                    np.asarray(chunks[s], dtype=np.uint8)
                    for s in range(backend.km)
                ])
                backend._tier.put(
                    backend.pool_name, oid, block, plan["version"],
                    plan["size"], dirty=False, promote_from_recovery=True,
                )
        return fall_back

    def _elect_regen(self, plan: dict, slot: dict, win: int):
        """Consistency election for one regenerating-repair object: ALL
        d planned helpers must have answered at the authoritative
        version with helper symbols spanning the FULL stored shard
        (beta * alpha == chunk_total).  Returns (chunk_total, beta) or
        None -- None sends the object back through the classic
        full-gather path, never a partial regeneration."""
        backend = self.backend
        rg = plan["regen"]
        target_v = max(slot["versions"].values())
        holders = {s for s, v in slot["versions"].items()
                   if v == target_v}
        size = next((slot["sizes"][s] for s in sorted(holders)
                     if slot["sizes"].get(s) is not None), None)
        if size is None:
            return None
        plan["version"] = target_v
        plan["size"] = size
        plan["attrs"] = next(
            (slot["attrs"][s] for s in sorted(holders)
             if s in slot["attrs"]), {})
        chunk_total = backend._shard_bytes_total(size)
        plan["chunk_total"] = chunk_total
        plan["have"] = {}
        if chunk_total == 0:
            return (0, 0)
        alpha = max(1, int(getattr(backend.ec, "alpha", 1)))
        if chunk_total % alpha or chunk_total > win:
            return None
        beta = chunk_total // alpha
        hs = slot["helpers"]
        if any(s not in holders or s not in hs or len(hs[s]) != beta
               for s in rg["helpers"]):
            return None
        return (chunk_total, beta)

    def _want_promote(self, oid: str, logical: int) -> bool:
        """Promote-on-recovery predicate: writeback tier, toggle on,
        and the object is hot or was resident (mirrors the write lane's
        ``_want_resident``)."""
        backend = self.backend
        if not logical or backend._tier is None or \
                backend.tier_mode != "writeback":
            return False
        if not bool(_cfg().get_val("osd_tier_promote_on_recovery")):
            return False
        return backend._tier.contains(backend.pool_name, oid) or \
            backend._tier_hot(oid)

    def _push_sub(self, oid: str, s: int, piece: bytes, plan: dict,
                  rollback: bool) -> ECSubWrite:
        """Full-shard recovery push transaction: bytes + truncate +
        the authoritative attr re-stamp (version, size, hinfo, snapset,
        whiteout, pool tag) -- the single-window analogue of the
        windowed path's final window."""
        backend = self.backend
        soid = shard_oid(oid, s)
        attrs = plan["attrs"] or {}
        txn = Transaction().write(soid, 0, piece)
        txn = backend._pool_stamp(
            txn.truncate(soid, plan["chunk_total"])
            .setattr(soid, ecutil.HINFO_KEY, attrs.get(ecutil.HINFO_KEY))
            .setattr(soid, SIZE_KEY, plan["size"])
            .setattr(soid, VERSION_KEY, plan["version"])
            .setattr(soid, SNAPSET_KEY, attrs.get(SNAPSET_KEY))
            .setattr(soid, WHITEOUT_KEY, attrs.get(WHITEOUT_KEY)),
            soid,
        )
        return ECSubWrite(
            from_shard=s, tid=backend._new_tid(), oid=oid,
            transaction=txn, at_version=plan["version"],
            op_class="recovery", rollback=rollback,
        )


# -- batched deep-scrub reads (chunked cursor) ---------------------------

async def scrub_read_many(
    backend, oids: List[str],
) -> Dict[str, Dict[int, dict]]:
    """Chunked, batched deep-scrub read of many objects: each round
    reads ``osd_scrub_chunk_max`` bytes per shard for every object
    still in progress as ONE corked multi-read burst (op_class
    "scrub"), so a scrub slice costs one burst per round instead of one
    whole-shard fan-out per object.

    Returns {oid: {shard: {"data": bytes|None, "attrs": dict,
    "error": int|None}}} over every up shard (shards whose OSD never
    answered are absent -- the caller classifies them missing)."""
    cfg = _cfg()
    chunk_max = max(backend.sinfo.chunk_size,
                    int(cfg.get_val("osd_scrub_chunk_max")))
    chunk_max = chunk_max // backend.sinfo.chunk_size * \
        backend.sinfo.chunk_size
    timeout = float(cfg.get_val("osd_read_gather_timeout"))
    state: Dict[str, Dict[int, dict]] = {}
    plans: Dict[str, dict] = {}
    for oid in oids:
        acting = backend.acting_set(oid)
        up = [s for s in range(backend.km)
              if backend._shard_up(acting, s)]
        plans[oid] = {"acting": acting, "up": up, "off": 0,
                      "total": None}
        state[oid] = {}

    throttle = backend._recovery().throttle
    pending = set(plans)
    first = True
    while pending:
        reads: Dict[Tuple[str, int], Dict[str, list]] = {}
        attr_want: Dict[Tuple[str, int], List[str]] = {}
        for oid in sorted(pending):
            plan = plans[oid]
            for s in plan["up"]:
                key = (f"osd.{plan['acting'][s]}", s)
                reads.setdefault(key, {})[oid] = [
                    (plan["off"], chunk_max)]
                attr_want.setdefault(key, []).append(oid)
        read_list = [
            (osd, s, to_read, attr_want[(osd, s)])
            for (osd, s), to_read in reads.items()
        ]
        qos = getattr(getattr(backend, "_host_shard", None), "qos", None)
        if qos is not None:
            # unified admission, transient form: the scrub round is
            # tag-ordered (and limit-paced) against client/recovery
            # classes but occupies no slot across its reads -- the
            # chunk cursor already bounds its footprint
            await qos.admit(
                "scrub", chunk_max * max(1, sum(
                    len(p["up"]) for o, p in plans.items()
                    if o in pending)))
        replies = await batched_sub_reads(
            backend, read_list, "scrub", timeout)
        backend.perf.inc("scrub_chunks")
        for (osd, s, to_read, _attrs), reply in zip(read_list, replies):
            if reply is None:
                continue  # never answered: the shard reads as missing
            for oid in to_read:
                slot = state[oid].setdefault(
                    s, {"data": b"", "attrs": {}, "error": None})
                if oid in reply.errors:
                    slot["error"] = reply.errors[oid]
                    slot["data"] = None
                    continue
                attrs = reply.attrs_read.get(oid) or {}
                if attrs:
                    # re-read each round: a version that MOVES between
                    # chunks marks a mid-scrub write (deferral, not a
                    # false parity error)
                    slot.setdefault("versions", set())
                    slot["versions"].add(vt(attrs.get(VERSION_KEY)))
                    if first:
                        slot["attrs"] = attrs
                    total = plans[oid]["total"]
                    if attrs.get(SIZE_KEY) is not None and total is None:
                        plans[oid]["total"] = backend._shard_bytes_total(
                            attrs[SIZE_KEY])
                bufs = reply.buffers_read.get(oid)
                if bufs is not None and slot["data"] is not None:
                    slot["data"] += bufs[0][1]
                    slot["had_buf"] = True
        done = set()
        for oid in pending:
            plan = plans[oid]
            plan["off"] += chunk_max
            total = plan["total"]
            if total is None or plan["off"] >= total:
                done.add(oid)
        pending -= done
        first = False
        if pending and throttle is not None:
            await throttle.pace()  # chunk-cursor pacing between rounds
    return state
