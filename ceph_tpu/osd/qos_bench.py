"""QoS-path benchmark stage (bench.py ``qos_path_host``): the unified
admission layer under scenario-diverse load at scale, fairness as a
first-class metric.

Two sub-stages, both over the real-TCP path (ceph_tpu/loadgen):

* **overload** -- the reservation-floor proof.  A ``gold`` client class
  holds a dmClock reservation calibrated from an uncontended run of the
  same clients (floor = RESERVATION_FRACTION of measured capacity) but
  carries negligible weight (1 vs 100), then a 10x ``bulk`` demand storm
  is thrown at the same cluster with execution slots deliberately
  scarce.  GATE: gold's achieved throughput stays within 10% of its
  reservation (phase-1 tags beat the weight storm), and bulk still gets
  the remainder (the floor is a floor, not a takeover).
* **scale** -- the million-client-direction proof: >= ``SCALE_CLIENTS``
  concurrent Objecters (hub-multiplexed over a handful of sockets)
  driving mixed RGW/RBD/CephFS/transactional profiles with thrash
  kills, a mid-run OSD wipe (background rebuild through the same
  admission layer) and writeback tier promotion running concurrently.
  GATES: the exactly-once audit is exact (every transactional client's
  counters equal its acked successes, zero unexplained drift), every
  closed-loop client made progress, and the saturation p99 + per-class
  fairness spread are reported as headline keys.

* **scale10x** (round 22) -- SCALE_CLIENTS x 10 concurrent clients
  (10^4 full): a closed-loop transactional cohort carries the
  exactly-once audit and fairness spread, an open-loop bulk offered at
  half the same-run 1k-stage throughput carries the concurrency.
  GATES: client count, exact audit, bounded closed-loop starvation,
  and pooled p99 no worse than the same-run 1k closed-loop p99.

``--smoke`` (tools/ec_benchmark.py --workload qos-path --smoke, wired
into tools/ci_lint.sh) shrinks the stages to a few hundred clients and
a few seconds; the full stage is the ROADMAP item-3 acceptance run.
"""

from __future__ import annotations

import asyncio
from typing import Dict, Optional

#: fraction of measured capacity the gold class reserves in the
#: overload stage -- low enough that uneven CRUSH spread of gold
#: demand over primaries (the reservation is enforced per OSD) and
#: mid-run XLA compiles of storm-sized encode batches cannot eat the
#: 10% tolerance the floor is gated against
RESERVATION_FRACTION = 0.15
#: the full stage's concurrent-client floor (the acceptance criterion)
SCALE_CLIENTS = 1000
#: the scale10x stage's multiplier over SCALE_CLIENTS (round 22: 10^3
#: -> 10^4 hub-multiplexed clients)
SCALE10X_FACTOR = 10
#: scale10x offered load as a fraction of the same-run 1k-stage
#: measured throughput -- open-loop at half capacity keeps the
#: p99-no-worse gate honest (closed-loop p99 scales with client count
#: by queueing arithmetic alone, proving nothing about the stack)
SCALE10X_LOAD_FRACTION = 0.5


def _apply_profile(cfg, gold_res_mibs: float) -> Dict[str, object]:
    """Install the bench QoS profile (gold reservation, token weight;
    bulk all-weight) + scarce execution slots; returns priors."""
    keys = ("osd_qos_profile", "osd_qos_op_slots", "osd_qos_slots")
    prior = {key: cfg.get_val(key) for key in keys}
    cfg.apply_changes({
        "osd_qos_profile": (
            f"client:0:100:0,gold:{gold_res_mibs:.4f}:1:0,"
            "bulk:0:100:0,recovery:4:10:0,scrub:1:5:0"),
        # scarcity makes admission the scheduler: ~2 execution slots
        # per OSD forces the 10x storm to queue at the dmClock tags
        "osd_qos_op_slots": 2,
        "osd_qos_slots": 2,
    })
    return prior


async def _overload_stage(smoke: bool) -> Dict:
    from ceph_tpu.loadgen import ClientGroup, Scenario, run_scenario
    from ceph_tpu.utils.config import get_config

    cfg = get_config()
    gold_n = 6 if smoke else 8
    bulk_n = 10 * gold_n
    calib_s = 3.0 if smoke else 5.0
    load_s = 6.0 if smoke else 10.0

    # -- calibrate: the gold clients alone, closed-loop, under the SAME
    # scarce-slot regime the storm will run in (no reservation yet --
    # uncontended admission is work-conserving, so none is needed)
    prior = _apply_profile(cfg, 0.0)
    try:
        calib = await run_scenario(Scenario(
            name="qos-calibrate", duration_s=calib_s,
            groups=(ClientGroup(count=gold_n, profile="put8k",
                                qos_class="gold"),),
            seed=101,
        ), n_osds=6)
        gold_alone = calib.groups[0]
        capacity_bps = gold_alone["ops"] * (8 << 10) / calib.wall_s
        floor_bps = RESERVATION_FRACTION * capacity_bps
        # per-OSD reservation: the admission instances are per daemon,
        # so the cluster-wide floor divides over the OSDs gold lands on
        res_mibs = floor_bps / 6 / (1 << 20)
        # arm the reservation (slots stay scarce from _apply_profile)
        cfg.apply_changes({"osd_qos_profile": (
            f"client:0:100:0,gold:{res_mibs:.4f}:1:0,"
            "bulk:0:100:0,recovery:4:10:0,scrub:1:5:0")})

        # -- the storm: gold demand is OPEN-LOOP at 1.4x its floor, so
        # demand provably exceeds the reservation regardless of latency
        # (closed-loop demand is latency-coupled and would understate
        # the floor exactly when the storm inflates latency)
        gold_rate = 2.0 * floor_bps / (8 << 10) / gold_n
        overload = await run_scenario(Scenario(
            name="qos-overload", duration_s=load_s,
            groups=(
                ClientGroup(count=gold_n, profile="put8k",
                            qos_class="gold", mode="open",
                            rate_ops_s=gold_rate),
                ClientGroup(count=bulk_n, profile="put8k",
                            qos_class="bulk"),
            ),
            seed=102,
        ), n_osds=6, op_timeout=60.0,
           tuning={"client_probe_grace": 15.0})
    finally:
        cfg.apply_changes(prior)
    gold = next(g for g in overload.groups if g["qos_class"] == "gold")
    bulk = next(g for g in overload.groups if g["qos_class"] == "bulk")
    gold_bps = gold["ops"] * (8 << 10) / overload.wall_s
    ratio = gold_bps / floor_bps if floor_bps else 0.0
    result = {
        "capacity_MiBs": round(capacity_bps / (1 << 20), 3),
        "floor_MiBs": round(floor_bps / (1 << 20), 3),
        "gold_clients": gold_n,
        "bulk_clients": bulk_n,
        "gold_achieved_MiBs": round(gold_bps / (1 << 20), 3),
        "reservation_ratio": round(ratio, 3),
        "gold_p99_ms": gold["p99_ms"],
        "bulk_p99_ms": bulk["p99_ms"],
        "bulk_ops": bulk["ops"],
        "throttle_waits": overload.qos_counters.get(
            "qos_gold_throttle_waits", 0) + overload.qos_counters.get(
            "qos_bulk_throttle_waits", 0),
    }
    # GATE: the floor held within 10% under the 10x weight storm, the
    # storm was real (admission waits observed), and bulk still ran
    if ratio < 0.9:
        raise AssertionError(
            f"qos-path: gold reservation floor violated: achieved "
            f"{gold_bps / (1 << 20):.3f} MiB/s vs floor "
            f"{floor_bps / (1 << 20):.3f} MiB/s (ratio {ratio:.3f})")
    if result["throttle_waits"] == 0:
        raise AssertionError(
            "qos-path: overload never queued at admission -- the "
            "storm did not saturate the slots, the gate proves nothing")
    if bulk["ops"] == 0:
        raise AssertionError("qos-path: reservation starved bulk out")
    return result


def _mixed_groups(n: int):
    from ceph_tpu.loadgen import ClientGroup

    rgw = int(n * 0.55)
    rbd = int(n * 0.15)
    fs = int(n * 0.20)
    txn = n - rgw - rbd - fs
    return (
        ClientGroup(count=rgw, profile="rgw"),
        ClientGroup(count=rbd, profile="rbd"),
        ClientGroup(count=fs, profile="cephfs", mode="open",
                    rate_ops_s=1.0),
        ClientGroup(count=txn, profile="txn"),
    )


async def _chaos_stage(smoke: bool) -> Dict:
    """Thrash kills + rebuild + tier promotion at MODERATE scale: the
    probe grace sits below the loaded p99 so TCP kills are actually
    DETECTED and failed over inside the run -- the regime where the
    exactly-once machinery does real work."""
    from ceph_tpu.loadgen import Scenario, run_scenario

    n = 120 if smoke else 300
    scn = Scenario(
        name="qos-chaos", duration_s=5.0 if smoke else 8.0,
        groups=_mixed_groups(n),
        chaos=("thrash", "rebuild", "promote"),
        seed=77,
    )
    res = await run_scenario(
        scn, n_osds=6, op_timeout=25.0,
        tuning={"client_probe_grace": 1.0 if smoke else 2.5},
    )
    out = res.to_dict()
    if res.kills < 1 or res.wipes < 1:
        raise AssertionError("qos-path: chaos never fired")
    if not res.cas_exact:
        raise AssertionError(
            f"qos-path: exactly-once audit failed under thrash "
            f"({res.cas_mismatches} counter(s) off the acked books)")
    if res.ops == 0:
        raise AssertionError("qos-path: chaos scenario moved no ops")
    return out


async def _scale_stage(smoke: bool) -> Dict:
    """>= SCALE_CLIENTS concurrent clients, saturation regime: rebuild
    + promotion chaos run along (thrash lives in the chaos stage -- at
    saturation the probe grace must clear the loaded p99, which makes
    sub-grace kill detection a contradiction in terms)."""
    from ceph_tpu.loadgen import Scenario, run_scenario

    n = 250 if smoke else SCALE_CLIENTS
    scn = Scenario(
        name="qos-scale-smoke" if smoke else "qos-scale",
        duration_s=4.0 if smoke else 12.0,
        groups=_mixed_groups(n),
        chaos=("rebuild", "promote"),
        seed=78,
    )
    # probe grace must clear the SATURATED p99 (~9s at 1000 clients on
    # cpu-fallback): a grace below it makes every queued op probe, and
    # each probe tears down the hub's shared connection -- the measured
    # self-livelock mode of hub-multiplexed clients
    res = await run_scenario(
        scn, n_osds=6, op_timeout=30.0 if smoke else 90.0,
        tuning={"client_probe_grace": 6.0 if smoke else 30.0},
    )
    out = res.to_dict()
    # GATES: the acceptance criteria of ROADMAP item 3 / ISSUE 12
    if res.n_clients < n:
        raise AssertionError("qos-path: client count shortfall")
    if not res.cas_exact:
        raise AssertionError(
            f"qos-path: exactly-once audit failed "
            f"({res.cas_mismatches} counter(s) off the acked books)")
    if res.ops == 0:
        raise AssertionError("qos-path: the scenario moved no ops")
    # fairness floor: at saturation each closed-loop client only gets a
    # handful of ops, so the honest gate is a FRACTION bound -- a real
    # fairness collapse zeroes whole cohorts, ordinary queueing
    # variance strands at most a few stragglers
    closed = [g for g in out["groups"] if g["mode"] == "closed"]
    starved = sum(g["clients_at_zero"] for g in closed)
    total_closed = sum(g["clients"] for g in closed)
    if not smoke and total_closed and \
            starved > max(2, total_closed // 50):
        raise AssertionError(
            f"qos-path: {starved}/{total_closed} closed-loop clients "
            "finished zero ops -- fairness collapse")
    return out


async def _scale10x_stage(smoke: bool, ref: Optional[Dict]) -> Dict:
    """10x the scale stage's client count (10^4 full), round 22: the
    hub-multiplexed transport must carry an order of magnitude more
    CONCURRENT clients without the tail degrading past the same-run
    1k closed-loop saturation p99.

    Two cohorts: a closed-loop transactional group (the exactly-once
    audit and fairness-spread carriers -- closed loops give every
    client a comparable ops budget, so the spread means something) and
    an open-loop bulk carrying the concurrency, offered at
    SCALE10X_LOAD_FRACTION of the 1k stage's MEASURED throughput.
    GATES: client count >= 10x, exactly-once audit exact, closed-loop
    starvation bounded, and pooled p99 <= the same-run 1k-stage p99
    (skipped, and recorded null, when the 1k stage did not run)."""
    from ceph_tpu.loadgen import ClientGroup, Scenario, run_scenario

    n = 500 if smoke else SCALE_CLIENTS * SCALE10X_FACTOR
    closed_n = 64 if smoke else 256
    open_n = n - closed_n
    ref_ops_s = float((ref or {}).get("ops_per_s") or 0.0)
    ref_p99 = float((ref or {}).get("p99_ms") or 0.0)
    # offered aggregate = half the measured 1k capacity, spread evenly
    # over the open cohort (floor keeps the run non-degenerate when the
    # reference is missing or tiny)
    agg = max(20.0, SCALE10X_LOAD_FRACTION * ref_ops_s)
    rate = agg / max(1, open_n)
    scn = Scenario(
        name="qos-scale10x-smoke" if smoke else "qos-scale10x",
        duration_s=5.0 if smoke else 12.0,
        groups=(
            ClientGroup(count=closed_n, profile="txn"),
            ClientGroup(count=open_n, profile="rgw", mode="open",
                        rate_ops_s=rate),
        ),
        seed=79,
    )
    res = await run_scenario(
        scn, n_osds=6, op_timeout=30.0 if smoke else 90.0,
        tuning={"client_probe_grace": 6.0 if smoke else 30.0},
    )
    out = res.to_dict()
    out["offered_ops_s"] = round(agg, 3)
    out["ref_1k_ops_per_s"] = ref_ops_s or None
    out["ref_1k_p99_ms"] = ref_p99 or None
    if res.n_clients < n:
        raise AssertionError("qos-path scale10x: client count shortfall")
    if not res.cas_exact:
        raise AssertionError(
            f"qos-path scale10x: exactly-once audit failed "
            f"({res.cas_mismatches} counter(s) off the acked books)")
    if res.ops == 0:
        raise AssertionError("qos-path scale10x: the scenario moved no ops")
    closed = [g for g in out["groups"] if g["mode"] == "closed"]
    starved = sum(g["clients_at_zero"] for g in closed)
    total_closed = sum(g["clients"] for g in closed)
    if not smoke and total_closed and \
            starved > max(2, total_closed // 50):
        raise AssertionError(
            f"qos-path scale10x: {starved}/{total_closed} closed-loop "
            "clients finished zero ops -- fairness collapse")
    if not smoke and ref_p99 and res.p99_ms > ref_p99:
        raise AssertionError(
            f"qos-path scale10x: p99 {res.p99_ms:.1f}ms at 10x client "
            f"count exceeds the same-run 1k-stage p99 {ref_p99:.1f}ms")
    return out


def run_qos_path_bench(*, smoke: bool = False,
                       stages: Optional[str] = None) -> Dict:
    """The stage entry point; ``stages`` limits to "overload"/"scale"
    (None = both).  Returns the JSON-ready dict with headline keys."""
    loop = asyncio.new_event_loop()
    try:
        result: Dict = {"smoke": smoke}
        if stages in (None, "overload"):
            result["overload"] = loop.run_until_complete(
                _overload_stage(smoke))
        if stages in (None, "chaos"):
            result["chaos"] = loop.run_until_complete(
                _chaos_stage(smoke))
        if stages in (None, "scale"):
            result["scale"] = loop.run_until_complete(
                _scale_stage(smoke))
        if stages in (None, "scale10x"):
            result["scale10x"] = loop.run_until_complete(
                _scale10x_stage(smoke, result.get("scale")))
    finally:
        loop.close()
    scale = result.get("scale") or {}
    chaos = result.get("chaos") or {}
    overload = result.get("overload") or {}
    spreads = [g["fairness_spread"] for g in scale.get("groups", [])
               if g.get("fairness_spread")]
    result.update({
        "qos_path_clients": scale.get("n_clients"),
        "qos_path_saturation_p99_ms": scale.get("p99_ms"),
        "qos_path_fairness_spread_max": max(spreads) if spreads else None,
        "qos_path_reservation_ratio": overload.get("reservation_ratio"),
        "qos_path_cas_exact": (
            scale.get("cas_exact") and chaos.get("cas_exact", True)
            if scale else chaos.get("cas_exact")),
        "qos_path_kills": chaos.get("kills"),
        "qos_path_dup_op_hits": chaos.get("dup_op_hits"),
        "qos_path_inflight_hwm": scale.get("inflight_hwm"),
    })
    scale10x = result.get("scale10x") or {}
    if scale10x:
        result.update({
            "qos_path_scale10x_clients": scale10x.get("n_clients"),
            "qos_path_scale10x_p99_ms": scale10x.get("p99_ms"),
            "qos_path_scale10x_ops_per_s": scale10x.get("ops_per_s"),
            "qos_path_scale10x_cas_exact": scale10x.get("cas_exact"),
        })
    return result


if __name__ == "__main__":
    import json
    import sys

    smoke = "--smoke" in sys.argv
    out = run_qos_path_bench(smoke=smoke)
    print(json.dumps(out))
