"""Objecter: thin client-side op router to the primary OSD.

Reference: src/osdc/Objecter.{h,cc} -- the librados client computes
placement from the osdmap (``_calc_target``, Objecter.cc:2784), sends ONE
op to the primary OSD of the object's PG (``_send_op`` :3223), and
retries/redirects when the map changes or the primary dies.  The primary
OSD hosts the EC engine (``OSDShard.host_pool`` -> ``ECBackend``) and
fans out sub-ops to the acting set; this class never touches chunks.

Failover: while waiting for a reply the Objecter probes the primary
(``client_probe_retries`` attempts of ``client_probe_grace`` each); an
unreachable primary is marked down and the op is resent -- same reqid,
exponential backoff with jitter between attempts -- to the next up shard
of the acting set (the reference's analogue: a new osdmap epoch promotes
a new primary and the Objecter re-targets).  Every op carries an
``osd_reqid_t``-style reqid ``(client, incarnation, tid)``; the OSDs
persist applied ops' reqids + results as PG-log dup entries, so a resend
that races a completed-but-unacknowledged op is answered with the
ORIGINAL result instead of re-executing -- exactly-once across primary
failover, for non-idempotent ops (omap_cas, exec, snap_rollback)
included.  A shard whose PG is peering answers ``backoff`` instead of
queueing; the op parks until that OSD's ``backoff_release`` (or the op
deadline) and then resends (the RADOS PG backoff protocol).
WriteConflict refusals -- possible only transiently around a failover,
when an engine with a cold version view serves its first write -- are
retried once under a FRESH reqid (the refusal teaches the engine the
winning version; the losing attempt's dups must not answer the retry).
See docs/resilience.md.
"""

from __future__ import annotations

import asyncio
import itertools
import os
import random
from typing import Dict, List, Optional

from ceph_tpu.osd.ecbackend import ObjectIncomplete
from ceph_tpu.profiling import ledger as _profiler
from ceph_tpu.utils import trace

#: wire-tax cost center: the client-side synchronous submit work
#: (reqid/tid mint, op-dict build, trace stamping) per send attempt
_PS_SUBMIT = _profiler.stage("objecter.submit")
from ceph_tpu.utils.optracker import OpTracker
from ceph_tpu.utils.perf import PerfCounters

#: error type names coming back over the wire -> local exception classes
_EXCEPTIONS = {
    "ObjectIncomplete": ObjectIncomplete,
    "FileNotFoundError": FileNotFoundError,
    "KeyError": KeyError,
    "ValueError": ValueError,
    "RuntimeError": RuntimeError,
    "PermissionError": PermissionError,  # OSDCap denial (-EACCES)
}

#: per-process Objecter incarnation source: the reqid's middle field.
#: Two Objecters sharing a name (client restart, parallel harnesses)
#: must never mint colliding reqids -- the incarnation tie-breaks, the
#: role of the client's global_id + inc in the reference osd_reqid_t.
#: seeded with process-unique entropy: two PROCESSES sharing an entity
#: name (sequential rados_cli runs) must not mint colliding reqids
#: either, or the OSDs' replicated dup logs treat the second process's
#: first mutation as a replay of the first's (exactly-once working
#: exactly as designed against accidentally-identical ids)
_INCARNATIONS = itertools.count(int.from_bytes(os.urandom(6), "big"))


def deliver_notify_event(messenger, name: str, callbacks: Dict, src: str,
                         msg: dict) -> None:
    """Run a watch callback as its own task, then ack the watch authority
    (shared by the Objecter and a standalone client-side ECBackend --
    librados semantics: notify completes when handlers have run)."""

    async def run_cb():
        cb = callbacks.get(msg["oid"])
        if cb is not None:
            try:
                res = cb(msg["oid"], msg.get("payload"))
                if asyncio.iscoroutine(res):
                    await res
            except Exception:  # noqa: BLE001 -- a watcher callback crash
                # must not lose the ack
                import traceback

                traceback.print_exc()
        await messenger.send_message(name, src, {
            "op": "notify_ack", "notify_id": msg["notify_id"],
            "watcher": name,
        })

    messenger.adopt_task(
        f"{name}.watchcb{msg['notify_id']}",
        asyncio.get_event_loop().create_task(run_cb()),
    )


class Objecter:
    """Routes each client op to the object's current primary OSD."""

    def __init__(
        self,
        messenger,
        km: int,
        n_osds: int,
        placement=None,
        name: str = "client",
        pool: str = "",
        op_timeout: float = 30.0,
        oid_prefix: str = "",
        qos_class: Optional[str] = None,
    ):
        self.messenger = messenger
        #: per-client QoS class (docs/qos.md): stamped on every op as
        #: ``qos_class`` so the primary's unified admission layer and
        #: mclock op queue schedule it under that class's
        #: reservation/weight/limit triple; None = the base "client"
        #: class (no field on the wire)
        self.qos_class = qos_class
        self.km = km
        self.n_osds = n_osds
        self.placement = placement
        self.name = name
        self.pool = pool
        self.op_timeout = op_timeout
        #: per-pool object namespace: co-hosted pools share each OSD's
        #: flat store, so without a distinct prefix two pools' shard
        #: objects for the same client oid would collide ("obj@1" from
        #: both) -- the reference scopes names by PG collection (spg_t
        #: embeds the pool id, src/osd/osd_types.h).  Empty for the
        #: first/only pool (legacy names).
        self.oid_prefix = oid_prefix
        self.perf = PerfCounters(name)
        #: client-side op tracking: every logical op is a TrackedOp
        #: whose span (when sampled) roots the cross-daemon trace --
        #: dump_ops_in_flight/dump_historic_ops work client-side too
        self.optracker = OpTracker(perf=self.perf, name=name)
        #: tid base: random 48-bit offset per Objecter.  Tids exist in
        #: replies, sub-op frames and the lossless replay queues of
        #: long-lived daemons; two client PROCESSES sharing an entity
        #: name (rados_cli invocations against one vstart cluster) both
        #: starting at tid 1 let a REPLAYED stale reply from the dead
        #: process's session match the live process's pending op -- the
        #: op was acked without ever executing (observed as rados_cli
        #: put "succeeding" with no sub-writes anywhere).  A random
        #: base makes cross-process tid collisions vanishingly rare.
        self._tid = int.from_bytes(os.urandom(6), "big")
        #: reqid incarnation (osd_reqid_t role): (name, inc, tid)
        #: identifies each logical op across any number of resends
        self.incarnation = next(_INCARNATIONS)
        self._pending: Dict[int, asyncio.Future] = {}
        #: tids whose primary this client demoted on failed probes; a
        #: late reply arriving for one proves the demotion false
        #: (observability: the false_demotion perf counter)
        self._demoted: set = set()
        #: per-OSD backoff gates: cleared when that OSD backs an op off,
        #: set again by its backoff_release (ops park on the event)
        self._backoff_gates: Dict[str, asyncio.Event] = {}
        #: oid -> callback for watch/notify events (events are sent by the
        #: watch authority OSD straight to this client)
        self._watch_callbacks: Dict[str, object] = {}
        #: optional monitor-traffic hook (command replies, map broadcasts)
        self.mon_hook = None
        messenger.register(name, self.dispatch)

    # -- placement (the _calc_target role) ---------------------------------

    def _acting_abs(self, oid_abs: str) -> List[Optional[int]]:
        """Placement of an already-namespaced oid."""
        oid_abs = oid_abs.split("~", 1)[0]  # clones place with their head
        if self.placement is not None:
            return self.placement.acting(oid_abs)
        from ceph_tpu.osd.placement import fallback_acting

        return fallback_acting(oid_abs, self.n_osds, self.km)

    def acting_set(self, oid: str) -> List[Optional[int]]:
        return self._acting_abs(self.oid_prefix + oid)

    def _shard_up(self, acting, s: int) -> bool:
        return acting[s] is not None and not self.messenger.is_down(
            f"osd.{acting[s]}"
        )

    def _primary_abs(self, oid_abs: str) -> str:
        acting = self._acting_abs(oid_abs)
        for s in range(self.km):
            if self._shard_up(acting, s):
                return f"osd.{acting[s]}"
        raise IOError(f"no up OSD to serve {oid_abs}")

    def primary_of(self, oid: str) -> str:
        """The object's current primary: the first up shard of the acting
        set (the reference's primary is acting[0]; on its death a map
        change promotes the next shard)."""
        return self._primary_abs(self.oid_prefix + oid)

    # -- dispatch ----------------------------------------------------------

    async def dispatch(self, src: str, msg) -> None:
        if not isinstance(msg, dict):
            return
        op = msg.get("op")
        if op == "client_reply":
            fut = self._pending.get(msg.get("tid"))
            if fut is not None and not fut.done():
                fut.set_result(msg)
            elif msg.get("tid") in self._demoted:
                # the "dead" primary answered after all: the probe-driven
                # demotion was false (host load, not death) -- count it
                # so the grace/retry knobs can be tuned from telemetry
                self._demoted.discard(msg.get("tid"))
                self.perf.inc("false_demotion")
            return
        if op == "backoff":
            # RADOS PG backoff: the PG is peering; park the op until the
            # OSD's release.  clear-before-resolve ordering + per-conn
            # FIFO delivery make the later release visible even if it
            # is processed before the op task starts waiting.
            gate = self._backoff_gates.setdefault(src, asyncio.Event())
            gate.clear()
            self.perf.inc("backoff_received")
            fut = self._pending.get(msg.get("tid"))
            if fut is not None and not fut.done():
                fut.set_result(dict(msg, _backoff_from=src))
            return
        if op == "backoff_release":
            gate = self._backoff_gates.get(src)
            if gate is not None:
                gate.set()
            self.perf.inc("backoff_release_received")
            return
        if op == "notify_event":
            deliver_notify_event(
                self.messenger, self.name, self._watch_callbacks, src, msg
            )
            return
        if self.mon_hook is not None:
            await self.mon_hook(msg)

    # -- op submission with primary failover -------------------------------

    async def _probe(self, entity: str, timeout: float = 1.0) -> bool:
        probe = getattr(self.messenger, "probe", None)
        if probe is not None:
            try:
                return await probe(entity, timeout=timeout)
            except TypeError:
                return await probe(entity)
        return not self.messenger.is_down(entity)

    def _new_reqid(self) -> tuple:
        """Mint an osd_reqid_t: (client name, incarnation, tid).  One
        per LOGICAL op -- failover resends reuse it, which is what lets
        the OSDs' PG-log dup entries recognize the replay."""
        self._tid += 1
        return (self.name, self.incarnation, self._tid)

    async def _backoff_wait(self, osd: str, deadline: float) -> None:
        """Park until ``osd`` releases its PG backoff (or the deadline):
        the op resends the moment the PG goes active instead of polling
        probe slices against a peering primary."""
        gate = self._backoff_gates.setdefault(osd, asyncio.Event())
        remain = deadline - asyncio.get_event_loop().time()
        if remain <= 0:
            return
        try:
            # deadline-capped: a lost release (the OSD died while we
            # were parked) degrades to the normal failover path
            await asyncio.wait_for(gate.wait(), timeout=remain)
        except asyncio.TimeoutError:
            pass

    async def _submit(self, kind: str, oid: str, timeout: float = None,
                      **fields):
        """Send one op to the primary; fail over -- with exponential
        backoff plus jitter, under the op deadline -- to the next up
        shard if the primary becomes unreachable before replying.  Safe
        for every op kind: resends carry the op's reqid and a primary
        that already applied it answers from its PG log dups instead of
        re-executing."""
        from ceph_tpu.utils.config import get_config

        oid = self.oid_prefix + oid  # enter the pool's namespace
        loop = asyncio.get_event_loop()
        deadline = loop.time() + (
            timeout if timeout is not None else self.op_timeout
        )
        cfg = get_config()
        backoff_base = float(cfg.get_val("client_backoff_base"))
        backoff_max = float(cfg.get_val("client_backoff_max"))
        conflict_retries = 1
        reqid = self._new_reqid()
        resends = 0
        # the trace ROOT: the sampling roll happens once, here, and the
        # decision travels with the op (unsampled ops carry no wire
        # context and cost nothing downstream)
        span = trace.new_trace(f"client:{kind}")
        op = self.optracker.create_request(f"{kind} {oid}", span=span)
        wire_ctx = span.to_wire() if span else None
        try:
            return await self._submit_tracked(
                kind, oid, fields, loop, deadline, cfg, backoff_base,
                backoff_max, conflict_retries, reqid, resends, op,
                wire_ctx)
        finally:
            op.finish()

    async def _submit_tracked(self, kind, oid, fields, loop, deadline,
                              cfg, backoff_base, backoff_max,
                              conflict_retries, reqid, resends, op,
                              wire_ctx):
        while True:
            with _PS_SUBMIT:
                self._tid += 1
                tid = self._tid
                fut = loop.create_future()
                self._pending[tid] = fut
                msg = dict(fields, op="client_op", tid=tid, kind=kind,
                           oid=oid, pool=self.pool, reqid=list(reqid))
                if self.qos_class is not None:
                    msg["qos_class"] = self.qos_class
                if wire_ctx is not None:
                    msg["trace"] = wire_ctx
            try:
                primary = self._primary_abs(oid)
                await self.messenger.send_message(self.name, primary, msg)
                op.mark_event("sent" if not resends else "resent")
                reply = await self._await_reply(fut, tid, primary, deadline)
                op.mark_event("reply_received")
            finally:
                self._pending.pop(tid, None)
            if reply is None:
                # primary unreachable: the messenger marked it down, so
                # primary_of() now promotes the next up shard.  Resend
                # the SAME reqid after a jittered exponential backoff --
                # an instant blind retry would hammer a cluster that is
                # mid-role-handoff (and every client would do it in
                # lockstep), while an unbounded wait would blow the op
                # deadline.
                self.perf.inc("primary_failover")
                remain = deadline - loop.time()
                if remain <= 0:
                    raise IOError(f"{kind} {oid}: op timed out")
                delay = min(backoff_max, backoff_base * (2 ** resends))
                delay *= 0.5 + random.random() * 0.5  # jitter
                await asyncio.sleep(min(delay, max(0.0, remain - 0.001)))
                resends += 1
                self.perf.inc("op_resend")
                continue
            if reply.get("op") == "backoff":
                # the PG is peering: park until its release, then resend
                # (same reqid) -- no probe slices, no blind retries
                await self._backoff_wait(
                    reply.get("_backoff_from", primary), deadline
                )
                if loop.time() >= deadline:
                    raise IOError(f"{kind} {oid}: op timed out in backoff")
                resends += 1
                self.perf.inc("op_resend")
                continue
            if reply["ok"]:
                self.perf.inc(kind)
                return reply.get("result")
            etype = reply.get("etype", "IOError")
            if etype == "WriteConflict" and conflict_retries > 0:
                # the engine learned the winning version from the refusal;
                # one replay lands on top of it (see ECBackend.write).
                # FRESH reqid: this is a new execution by design -- the
                # refused attempt's dup entries (shards that applied
                # before the conflict surfaced) must not answer it.
                conflict_retries -= 1
                reqid = self._new_reqid()
                self.perf.inc("write_conflict_retry")
                continue
            if (self._primary_abs(oid) != primary
                    or self.messenger.is_down(primary)):
                # the serving primary LOST its role mid-op (died, or the
                # map moved the object away): its error was computed
                # against a stale acting view and is not authoritative.
                # Re-dispatch to the current primary -- same reqid, so a
                # shard that already applied the op answers from its
                # dup entries (the reference resends in-flight ops on
                # every osdmap epoch change, Objecter::handle_osd_map).
                remain = deadline - loop.time()
                if remain > 0:
                    resends += 1
                    self.perf.inc("op_resend_stale_primary")
                    continue
            exc = _EXCEPTIONS.get(etype, IOError)
            raise exc(reply.get("error", f"{kind} {oid} failed"))

    async def _await_reply(self, fut, tid: int, primary: str,
                           deadline: float):
        """Wait for the reply in probe-sized slices; None when the primary
        is found dead (caller fails over).  Probe cadence is config-driven
        (client_probe_grace seconds per slice/probe, client_probe_retries
        consecutive failures to demote): one missed connect under host
        load must not demote a live primary -- the reference needs
        several missed heartbeats before an osd is reported failed
        (OSD.cc handle_osd_ping grace).  Demotions are remembered so a
        late reply increments the false_demotion counter."""
        from ceph_tpu.utils.config import get_config

        cfg = get_config()
        grace = float(cfg.get_val("client_probe_grace"))
        retries = max(1, int(cfg.get_val("client_probe_retries")))
        loop = asyncio.get_event_loop()
        while True:
            remain = deadline - loop.time()
            if remain <= 0:
                return None
            try:
                return await asyncio.wait_for(
                    asyncio.shield(fut), timeout=min(grace, remain)
                )
            except asyncio.TimeoutError:
                if self.messenger.is_down(primary):
                    return None
                for _ in range(retries):
                    if await self._probe(primary, timeout=grace):
                        break
                else:
                    # every probe failed: demote.  Remember the tid so a
                    # reply that still arrives is counted as a false
                    # demotion (bounded: stale tids evicted FIFO-ish)
                    self._demoted.add(tid)
                    while len(self._demoted) > 256:
                        self._demoted.pop()
                    self.perf.inc("probe_demotion")
                    return None

    # -- vectorized submit (the round-20 residual attack) ------------------

    async def submit_many(self, ops, timeout: float = None,
                          return_exceptions: bool = False) -> list:
        """Batched submit: ``ops`` is a sequence of ``(kind, oid,
        fields)`` triples.  The whole batch is prepared under ONE
        ``objecter.submit`` stage crossing (reqids/tids minted, op
        dicts built, trace roots rolled) and handed to the messenger as
        ONE multi-destination ``send_messages`` call, so each primary's
        cork queue gathers this client's share of the batch into a
        single wire burst -- and the primary's dispatch loop drains it
        in one wakeup, handing the per-PG coalescer whole op batches
        instead of N interleaved singles.  Replies resolve
        concurrently.

        Failure semantics are IDENTICAL to N sequential ``_submit``
        calls: any op that cannot complete from its batch send
        (primary failover, PG backoff, write conflict) falls back to
        the per-op retry loop carrying its already-minted reqid, so
        the PG-log dup entries recognize resends exactly as before.
        Returns one result per op, in order; the first failed op's
        exception is raised after every op has settled (no sibling is
        cancelled mid-flight), or -- with ``return_exceptions`` -- each
        failure is returned in its slot (the loadgen accounting
        surface)."""
        from ceph_tpu.utils.config import get_config

        loop = asyncio.get_event_loop()
        deadline = loop.time() + (
            timeout if timeout is not None else self.op_timeout
        )
        cfg = get_config()
        backoff_base = float(cfg.get_val("client_backoff_base"))
        backoff_max = float(cfg.get_val("client_backoff_max"))
        prepared = []
        pairs = []
        with _PS_SUBMIT:
            for kind, oid, fields in ops:
                oid_abs = self.oid_prefix + oid
                reqid = self._new_reqid()
                span = trace.new_trace(f"client:{kind}")
                op = self.optracker.create_request(
                    f"{kind} {oid_abs}", span=span)
                wire_ctx = span.to_wire() if span else None
                self._tid += 1
                tid = self._tid
                fut = loop.create_future()
                self._pending[tid] = fut
                msg = dict(fields, op="client_op", tid=tid, kind=kind,
                           oid=oid_abs, pool=self.pool, reqid=list(reqid))
                if self.qos_class is not None:
                    msg["qos_class"] = self.qos_class
                if wire_ctx is not None:
                    msg["trace"] = wire_ctx
                try:
                    primary = self._primary_abs(oid_abs)
                except IOError:
                    primary = None  # no up OSD now: the retry loop probes
                prepared.append((kind, oid_abs, fields, reqid, tid, fut,
                                 op, wire_ctx, primary))
                if primary is not None:
                    pairs.append((primary, msg))
        await self.messenger.send_messages(self.name, pairs)
        settled = await asyncio.gather(
            *(self._resolve_batched(p, loop, deadline, cfg, backoff_base,
                                    backoff_max) for p in prepared),
            return_exceptions=True,
        )
        if not return_exceptions:
            for r in settled:
                if isinstance(r, BaseException):
                    raise r
        return list(settled)

    async def _resolve_batched(self, p, loop, deadline, cfg,
                               backoff_base, backoff_max):
        """Await one batched op's reply; divert to the per-op retry
        machinery (same reqid) on failover/backoff/conflict."""
        kind, oid_abs, fields, reqid, tid, fut, op, wire_ctx, primary = p
        try:
            try:
                if primary is not None:
                    op.mark_event("sent")
                    reply = await self._await_reply(
                        fut, tid, primary, deadline)
                    if reply is not None:
                        op.mark_event("reply_received")
                else:
                    reply = None
            finally:
                self._pending.pop(tid, None)
            if reply is not None and reply.get("op") != "backoff":
                if reply["ok"]:
                    self.perf.inc(kind)
                    return reply.get("result")
                etype = reply.get("etype", "IOError")
                if etype == "WriteConflict":
                    # the refusal taught the engine the winning version;
                    # one replay under a FRESH reqid (the refused
                    # attempt's dups must not answer it), with no
                    # further conflict retries -- the _submit budget
                    self.perf.inc("write_conflict_retry")
                    return await self._submit_tracked(
                        kind, oid_abs, fields, loop, deadline, cfg,
                        backoff_base, backoff_max, 0, self._new_reqid(),
                        0, op, wire_ctx)
                exc = _EXCEPTIONS.get(etype, IOError)
                raise exc(reply.get("error", f"{kind} {oid_abs} failed"))
            if reply is None:
                # batch send never reached a live primary: jittered
                # backoff before the retry loop resends (same reqid),
                # exactly like a first _submit attempt failing over
                self.perf.inc("primary_failover")
                remain = deadline - loop.time()
                if remain <= 0:
                    raise IOError(f"{kind} {oid_abs}: op timed out")
                delay = backoff_base * (0.5 + random.random() * 0.5)
                await asyncio.sleep(
                    min(delay, max(0.0, remain - 0.001)))
            else:
                # PG backoff: park until the OSD's release, then let
                # the retry loop resend under the same reqid
                await self._backoff_wait(
                    reply.get("_backoff_from", primary), deadline)
                if loop.time() >= deadline:
                    raise IOError(
                        f"{kind} {oid_abs}: op timed out in backoff")
            self.perf.inc("op_resend")
            return await self._submit_tracked(
                kind, oid_abs, fields, loop, deadline, cfg,
                backoff_base, backoff_max, 1, reqid, 1, op, wire_ctx)
        finally:
            op.finish()

    async def write_many(self, items, snapc=None) -> None:
        """Batched ``write``: ``items`` is an iterable of ``(oid,
        data)`` pairs -- one submit_many stage crossing, one wire burst
        per primary."""
        await self.submit_many([
            ("write", oid, {"data": bytes(data), "snapc": snapc})
            for oid, data in items
        ])

    async def read_many(self, oids, snap=None) -> List[bytes]:
        """Batched ``read``: results in ``oids`` order."""
        return await self.submit_many([
            ("read", oid, {"snap": snap}) for oid in oids
        ])

    # -- I/O surface (librados IoCtx ops, one round trip each) -------------

    async def write(self, oid: str, data: bytes, snapc=None) -> None:
        await self._submit("write", oid, data=bytes(data), snapc=snapc)

    async def read(self, oid: str, snap=None) -> bytes:
        return await self._submit("read", oid, snap=snap)

    async def write_range(self, oid: str, offset: int, data: bytes,
                          snapc=None) -> None:
        await self._submit("write_range", oid, offset=offset,
                           data=bytes(data), snapc=snapc)

    async def read_range(self, oid: str, offset: int, length: int,
                         snap=None) -> bytes:
        return await self._submit("read_range", oid, offset=offset,
                                  length=length, snap=snap)

    async def remove_object(self, oid: str, snapc=None) -> None:
        await self._submit("remove", oid, snapc=snapc)

    # -- snapshots (librados selfmanaged snap surface) ---------------------

    async def snap_rollback(self, oid: str, snapid: int, snapc=None) -> None:
        await self._submit("snap_rollback", oid, snapid=snapid, snapc=snapc)

    async def snap_trim(self, oid: str, live_snaps) -> int:
        return await self._submit("snap_trim", oid,
                                  live_snaps=list(live_snaps))

    async def list_snaps(self, oid: str) -> dict:
        return await self._submit("list_snaps", oid)

    async def stat(self, oid: str):
        """(logical size, hinfo dict | None) from the primary."""
        size, hinfo = await self._submit("stat", oid)
        return size, hinfo

    async def deep_scrub(self, oid: str) -> dict:
        return await self._submit("scrub", oid)

    async def recover_shard(self, oid: str, shard: int,
                            target_osd: int) -> None:
        await self._submit("recover", oid, shard=shard, target=target_osd)

    # -- metadata plane ----------------------------------------------------

    async def omap_set(self, oid: str, kvs: Dict[str, bytes]) -> None:
        await self._submit("omap_set", oid, kvs=dict(kvs))

    async def omap_get(self, oid: str, keys=None) -> Dict[str, bytes]:
        return await self._submit(
            "omap_get", oid, keys=list(keys) if keys is not None else None
        )

    async def omap_rm(self, oid: str, keys) -> None:
        await self._submit("omap_rm", oid, keys=list(keys))

    async def omap_clear(self, oid: str) -> None:
        await self._submit("omap_clear", oid)

    async def omap_cas(self, oid: str, key: str, expect, new):
        ok, cur = await self._submit(
            "omap_cas", oid, key=key, expect=expect, new=new
        )
        return ok, cur

    async def exec(self, oid: str, cls: str, method: str, inp: bytes = b""):
        ret, out = await self._submit(
            "exec", oid, cls=cls, method=method, inp=bytes(inp)
        )
        return ret, out

    async def watch(self, oid: str, callback) -> None:
        # callbacks key on the namespaced oid (notify events carry the
        # engine's name) but are INVOKED with the oid the caller
        # registered -- the namespace is this Objecter's private affair
        if self.oid_prefix and callback is not None:
            orig, prefix = callback, self.oid_prefix

            def callback(o, payload, _cb=orig, _p=prefix):
                return _cb(o[len(_p):] if o.startswith(_p) else o, payload)

        self._watch_callbacks[self.oid_prefix + oid] = callback
        try:
            await self._submit("watch", oid, watcher=self.name)
        except Exception:
            self._watch_callbacks.pop(self.oid_prefix + oid, None)
            raise

    async def unwatch(self, oid: str) -> None:
        self._watch_callbacks.pop(self.oid_prefix + oid, None)
        await self._submit("unwatch", oid, watcher=self.name)

    async def notify(self, oid: str, payload=None, timeout: float = 5.0):
        return await self._submit(
            "notify", oid, payload=payload,
            timeout_ms=int(timeout * 1000),
            # the authority gathers acks for up to ``timeout``; give the
            # round trip headroom past that
            timeout=timeout + 4.0,
        )
