"""Objecter: thin client-side op router to the primary OSD.

Reference: src/osdc/Objecter.{h,cc} -- the librados client computes
placement from the osdmap (``_calc_target``, Objecter.cc:2784), sends ONE
op to the primary OSD of the object's PG (``_send_op`` :3223), and
retries/redirects when the map changes or the primary dies.  The primary
OSD hosts the EC engine (``OSDShard.host_pool`` -> ``ECBackend``) and
fans out sub-ops to the acting set; this class never touches chunks.

Failover: while waiting for a reply the Objecter probes the primary; an
unreachable primary is marked down and the op is resent to the next up
shard of the acting set (the reference's analogue: a new osdmap epoch
promotes a new primary and the Objecter re-targets).  WriteConflict
refusals -- possible only transiently around a failover, when an engine
with a cold version view serves its first write -- are retried once (the
refusal teaches the engine the winning version).
"""

from __future__ import annotations

import asyncio
from typing import Dict, List, Optional

from ceph_tpu.osd.ecbackend import ObjectIncomplete
from ceph_tpu.utils.perf import PerfCounters

#: error type names coming back over the wire -> local exception classes
_EXCEPTIONS = {
    "ObjectIncomplete": ObjectIncomplete,
    "FileNotFoundError": FileNotFoundError,
    "KeyError": KeyError,
    "ValueError": ValueError,
    "RuntimeError": RuntimeError,
    "PermissionError": PermissionError,  # OSDCap denial (-EACCES)
}

#: op kinds that must NOT be silently resent after a primary died with the
#: op possibly executed: a CAS (or a cls method wrapping one) that applied
#: on the dead primary would report a false failure when replayed against
#: the new authority.  The reference dedups via reqids persisted in the pg
#: log; until an equivalent exists these surface an indeterminate-outcome
#: error instead of lying (librados analogue: ETIMEDOUT, caller re-checks).
_NON_IDEMPOTENT = frozenset({"omap_cas", "exec", "snap_rollback"})


class OpIndeterminate(IOError):
    """The primary died after the op was sent; it may or may not have
    executed.  The caller must re-check state before retrying."""


def deliver_notify_event(messenger, name: str, callbacks: Dict, src: str,
                         msg: dict) -> None:
    """Run a watch callback as its own task, then ack the watch authority
    (shared by the Objecter and a standalone client-side ECBackend --
    librados semantics: notify completes when handlers have run)."""

    async def run_cb():
        cb = callbacks.get(msg["oid"])
        if cb is not None:
            try:
                res = cb(msg["oid"], msg.get("payload"))
                if asyncio.iscoroutine(res):
                    await res
            except Exception:  # noqa: BLE001 -- a watcher callback crash
                # must not lose the ack
                import traceback

                traceback.print_exc()
        await messenger.send_message(name, src, {
            "op": "notify_ack", "notify_id": msg["notify_id"],
            "watcher": name,
        })

    messenger.adopt_task(
        f"{name}.watchcb{msg['notify_id']}",
        asyncio.get_event_loop().create_task(run_cb()),
    )


class Objecter:
    """Routes each client op to the object's current primary OSD."""

    def __init__(
        self,
        messenger,
        km: int,
        n_osds: int,
        placement=None,
        name: str = "client",
        pool: str = "",
        op_timeout: float = 30.0,
        oid_prefix: str = "",
    ):
        self.messenger = messenger
        self.km = km
        self.n_osds = n_osds
        self.placement = placement
        self.name = name
        self.pool = pool
        self.op_timeout = op_timeout
        #: per-pool object namespace: co-hosted pools share each OSD's
        #: flat store, so without a distinct prefix two pools' shard
        #: objects for the same client oid would collide ("obj@1" from
        #: both) -- the reference scopes names by PG collection (spg_t
        #: embeds the pool id, src/osd/osd_types.h).  Empty for the
        #: first/only pool (legacy names).
        self.oid_prefix = oid_prefix
        self.perf = PerfCounters(name)
        self._tid = 0
        self._pending: Dict[int, asyncio.Future] = {}
        #: oid -> callback for watch/notify events (events are sent by the
        #: watch authority OSD straight to this client)
        self._watch_callbacks: Dict[str, object] = {}
        #: optional monitor-traffic hook (command replies, map broadcasts)
        self.mon_hook = None
        messenger.register(name, self.dispatch)

    # -- placement (the _calc_target role) ---------------------------------

    def _acting_abs(self, oid_abs: str) -> List[Optional[int]]:
        """Placement of an already-namespaced oid."""
        oid_abs = oid_abs.split("~", 1)[0]  # clones place with their head
        if self.placement is not None:
            return self.placement.acting(oid_abs)
        from ceph_tpu.osd.placement import fallback_acting

        return fallback_acting(oid_abs, self.n_osds, self.km)

    def acting_set(self, oid: str) -> List[Optional[int]]:
        return self._acting_abs(self.oid_prefix + oid)

    def _shard_up(self, acting, s: int) -> bool:
        return acting[s] is not None and not self.messenger.is_down(
            f"osd.{acting[s]}"
        )

    def _primary_abs(self, oid_abs: str) -> str:
        acting = self._acting_abs(oid_abs)
        for s in range(self.km):
            if self._shard_up(acting, s):
                return f"osd.{acting[s]}"
        raise IOError(f"no up OSD to serve {oid_abs}")

    def primary_of(self, oid: str) -> str:
        """The object's current primary: the first up shard of the acting
        set (the reference's primary is acting[0]; on its death a map
        change promotes the next shard)."""
        return self._primary_abs(self.oid_prefix + oid)

    # -- dispatch ----------------------------------------------------------

    async def dispatch(self, src: str, msg) -> None:
        if not isinstance(msg, dict):
            return
        op = msg.get("op")
        if op == "client_reply":
            fut = self._pending.get(msg.get("tid"))
            if fut is not None and not fut.done():
                fut.set_result(msg)
            return
        if op == "notify_event":
            deliver_notify_event(
                self.messenger, self.name, self._watch_callbacks, src, msg
            )
            return
        if self.mon_hook is not None:
            await self.mon_hook(msg)

    # -- op submission with primary failover -------------------------------

    async def _probe(self, entity: str) -> bool:
        probe = getattr(self.messenger, "probe", None)
        if probe is not None:
            try:
                return await probe(entity, timeout=1.0)
            except TypeError:
                return await probe(entity)
        return not self.messenger.is_down(entity)

    async def _submit(self, kind: str, oid: str, timeout: float = None,
                      **fields):
        """Send one op to the primary; fail over to the next up shard if
        the primary becomes unreachable before replying."""
        oid = self.oid_prefix + oid  # enter the pool's namespace
        deadline = asyncio.get_event_loop().time() + (
            timeout if timeout is not None else self.op_timeout
        )
        conflict_retries = 1
        while True:
            self._tid += 1
            tid = self._tid
            fut = asyncio.get_event_loop().create_future()
            self._pending[tid] = fut
            msg = dict(fields, op="client_op", tid=tid, kind=kind, oid=oid,
                       pool=self.pool)
            try:
                primary = self._primary_abs(oid)
                await self.messenger.send_message(self.name, primary, msg)
                reply = await self._await_reply(fut, primary, deadline)
            finally:
                self._pending.pop(tid, None)
            if reply is None:
                # primary unreachable: the messenger marked it down, so
                # primary_of() now promotes the next up shard
                self.perf.inc("primary_failover")
                if kind in _NON_IDEMPOTENT:
                    raise OpIndeterminate(
                        f"{kind} {oid}: primary {primary} died with the op "
                        "in flight; it may have executed -- re-check state"
                    )
                if asyncio.get_event_loop().time() >= deadline:
                    raise IOError(f"{kind} {oid}: op timed out")
                continue
            if reply["ok"]:
                self.perf.inc(kind)
                return reply.get("result")
            etype = reply.get("etype", "IOError")
            if etype == "WriteConflict" and conflict_retries > 0:
                # the engine learned the winning version from the refusal;
                # one replay lands on top of it (see ECBackend.write)
                conflict_retries -= 1
                self.perf.inc("write_conflict_retry")
                continue
            exc = _EXCEPTIONS.get(etype, IOError)
            raise exc(reply.get("error", f"{kind} {oid} failed"))

    async def _await_reply(self, fut, primary: str, deadline: float):
        """Wait for the reply in probe-sized slices; None when the primary
        is found dead (caller fails over)."""
        loop = asyncio.get_event_loop()
        while True:
            remain = deadline - loop.time()
            if remain <= 0:
                return None
            try:
                return await asyncio.wait_for(
                    asyncio.shield(fut), timeout=min(1.0, remain)
                )
            except asyncio.TimeoutError:
                if self.messenger.is_down(primary):
                    return None
                if not await self._probe(primary):
                    # re-probe before failing over: one missed connect
                    # under host load must not demote a live primary
                    # (the reference needs several missed heartbeats
                    # before an osd is reported failed, OSD.cc
                    # handle_osd_ping grace)
                    if not await self._probe(primary):
                        return None

    # -- I/O surface (librados IoCtx ops, one round trip each) -------------

    async def write(self, oid: str, data: bytes, snapc=None) -> None:
        await self._submit("write", oid, data=bytes(data), snapc=snapc)

    async def read(self, oid: str, snap=None) -> bytes:
        return await self._submit("read", oid, snap=snap)

    async def write_range(self, oid: str, offset: int, data: bytes,
                          snapc=None) -> None:
        await self._submit("write_range", oid, offset=offset,
                           data=bytes(data), snapc=snapc)

    async def read_range(self, oid: str, offset: int, length: int,
                         snap=None) -> bytes:
        return await self._submit("read_range", oid, offset=offset,
                                  length=length, snap=snap)

    async def remove_object(self, oid: str, snapc=None) -> None:
        await self._submit("remove", oid, snapc=snapc)

    # -- snapshots (librados selfmanaged snap surface) ---------------------

    async def snap_rollback(self, oid: str, snapid: int, snapc=None) -> None:
        await self._submit("snap_rollback", oid, snapid=snapid, snapc=snapc)

    async def snap_trim(self, oid: str, live_snaps) -> int:
        return await self._submit("snap_trim", oid,
                                  live_snaps=list(live_snaps))

    async def list_snaps(self, oid: str) -> dict:
        return await self._submit("list_snaps", oid)

    async def stat(self, oid: str):
        """(logical size, hinfo dict | None) from the primary."""
        size, hinfo = await self._submit("stat", oid)
        return size, hinfo

    async def deep_scrub(self, oid: str) -> dict:
        return await self._submit("scrub", oid)

    async def recover_shard(self, oid: str, shard: int,
                            target_osd: int) -> None:
        await self._submit("recover", oid, shard=shard, target=target_osd)

    # -- metadata plane ----------------------------------------------------

    async def omap_set(self, oid: str, kvs: Dict[str, bytes]) -> None:
        await self._submit("omap_set", oid, kvs=dict(kvs))

    async def omap_get(self, oid: str, keys=None) -> Dict[str, bytes]:
        return await self._submit(
            "omap_get", oid, keys=list(keys) if keys is not None else None
        )

    async def omap_rm(self, oid: str, keys) -> None:
        await self._submit("omap_rm", oid, keys=list(keys))

    async def omap_clear(self, oid: str) -> None:
        await self._submit("omap_clear", oid)

    async def omap_cas(self, oid: str, key: str, expect, new):
        ok, cur = await self._submit(
            "omap_cas", oid, key=key, expect=expect, new=new
        )
        return ok, cur

    async def exec(self, oid: str, cls: str, method: str, inp: bytes = b""):
        ret, out = await self._submit(
            "exec", oid, cls=cls, method=method, inp=bytes(inp)
        )
        return ret, out

    async def watch(self, oid: str, callback) -> None:
        # callbacks key on the namespaced oid (notify events carry the
        # engine's name) but are INVOKED with the oid the caller
        # registered -- the namespace is this Objecter's private affair
        if self.oid_prefix and callback is not None:
            orig, prefix = callback, self.oid_prefix

            def callback(o, payload, _cb=orig, _p=prefix):
                return _cb(o[len(_p):] if o.startswith(_p) else o, payload)

        self._watch_callbacks[self.oid_prefix + oid] = callback
        try:
            await self._submit("watch", oid, watcher=self.name)
        except Exception:
            self._watch_callbacks.pop(self.oid_prefix + oid, None)
            raise

    async def unwatch(self, oid: str) -> None:
        self._watch_callbacks.pop(self.oid_prefix + oid, None)
        await self._submit("unwatch", oid, watcher=self.name)

    async def notify(self, oid: str, payload=None, timeout: float = 5.0):
        return await self._submit(
            "notify", oid, payload=payload,
            timeout_ms=int(timeout * 1000),
            # the authority gathers acks for up to ``timeout``; give the
            # round trip headroom past that
            timeout=timeout + 4.0,
        )
