"""In-flight write extent cache (ExtentCache equivalent).

Reference: src/osd/ExtentCache.h (491 LoC).  During an EC read-modify-write
the primary pins the logical extents a write op will touch; ops whose
extents overlap an in-flight pin must wait for it to release, and RMW reads
of recently written extents are served from the primary's cache instead of
re-reading shards.  Two roles here:

* ``pin(oid, start, end)`` — async context manager serializing overlapping
  writes per object (the reference defers conflicting ops on the pinned
  extent set);
* a bounded read-through cache of committed logical bytes, consulted by
  the RMW read so a write immediately following another does not fan out a
  shard read for data the primary just encoded.

All writes flow through the primary, so the cache is coherent by
construction; killing/recovering OSDs never bypasses it.
"""

from __future__ import annotations

import asyncio
from typing import Dict, List, Optional, Tuple


class _ObjectState:
    def __init__(self) -> None:
        #: active pins as (start, end) half-open logical intervals
        self.pins: List[Tuple[int, int]] = []
        self.cond: Optional[asyncio.Condition] = None
        #: coroutines blocked in cond.wait() — idle state (no pins, no
        #: waiters, no cached bytes) is pruned so the per-oid dict does not
        #: grow without bound over a cluster's lifetime
        self.waiters = 0
        #: committed cache: sorted non-overlapping (start, bytes)
        self.extents: List[Tuple[int, bytes]] = []

    def idle(self) -> bool:
        return not self.pins and self.waiters == 0 and not self.extents

    def condition(self) -> asyncio.Condition:
        if self.cond is None:
            self.cond = asyncio.Condition()
        return self.cond


class _Pin:
    def __init__(self, cache: "ExtentCache", oid: str, start: int, end: int):
        self._cache = cache
        self._oid = oid
        self._span = (start, end)

    async def __aenter__(self) -> "_Pin":
        await self._cache._acquire(self._oid, self._span)
        return self

    async def __aexit__(self, *exc) -> bool:
        await self._cache._release(self._oid, self._span)
        return False

    def commit(self, offset: int, data: bytes) -> None:
        """Publish the written logical bytes to the read-through cache."""
        self._cache._insert(self._oid, offset, data)


class ExtentCache:
    def __init__(self, max_object_bytes: int = 4 << 20,
                 max_cached_objects: int = 256):
        self._objects: Dict[str, _ObjectState] = {}
        self.max_object_bytes = max_object_bytes
        self.max_cached_objects = max_cached_objects
        self.hits = 0
        self.misses = 0

    def _state(self, oid: str) -> _ObjectState:
        if oid not in self._objects:
            self._objects[oid] = _ObjectState()
        return self._objects[oid]

    # -- pinning (write-write serialization) --------------------------------

    def pin(self, oid: str, start: int, end: int) -> _Pin:
        return _Pin(self, oid, start, end)

    @staticmethod
    def _overlaps(a: Tuple[int, int], b: Tuple[int, int]) -> bool:
        return a[0] < b[1] and b[0] < a[1]

    async def _acquire(self, oid: str, span: Tuple[int, int]) -> None:
        st = self._state(oid)
        cond = st.condition()
        try:
            async with cond:
                while any(self._overlaps(span, p) for p in st.pins):
                    st.waiters += 1
                    try:
                        await cond.wait()
                    finally:
                        st.waiters -= 1
                st.pins.append(span)
        except BaseException:
            # a cancelled waiter may be the last reference to this state
            if st.idle() and self._objects.get(oid) is st:
                self._objects.pop(oid, None)
            raise

    async def _release(self, oid: str, span: Tuple[int, int]) -> None:
        st = self._state(oid)
        st.pins.remove(span)
        cond = st.condition()
        async with cond:
            cond.notify_all()
        # woken waiters still count in st.waiters until they resume, so
        # this only fires once the object is truly quiescent
        if st.idle():
            self._objects.pop(oid, None)

    # -- committed-byte cache ----------------------------------------------

    def _insert(self, oid: str, offset: int, data: bytes) -> None:
        st = self._state(oid)
        end = offset + len(data)
        merged: List[Tuple[int, bytes]] = []
        for s, buf in st.extents:
            e = s + len(buf)
            if e <= offset or s >= end:
                merged.append((s, buf))
                continue
            # trim the old extent around the new write (newest wins)
            if s < offset:
                merged.append((s, buf[: offset - s]))
            if e > end:
                merged.append((end, buf[end - s :]))
        merged.append((offset, bytes(data)))
        merged.sort()
        # bound memory: drop lowest-offset extents beyond the cap
        total = sum(len(b) for _, b in merged)
        while merged and total > self.max_object_bytes:
            s, b = merged.pop(0)
            total -= len(b)
        st.extents = merged
        # bound the object population too: evict other objects' cached
        # bytes LRU-ish (pin state is kept — only cache memory is freed)
        cached = [o for o, s in self._objects.items() if s.extents and o != oid]
        while len(cached) + 1 > self.max_cached_objects:
            victim = cached.pop(0)
            vs = self._objects[victim]
            vs.extents = []
            if vs.idle():
                self._objects.pop(victim, None)

    def get(self, oid: str, offset: int, length: int) -> Optional[bytes]:
        """The cached bytes for [offset, offset+length) iff fully covered
        by one committed extent; None on any gap."""
        st = self._objects.get(oid)
        if st is None:
            self.misses += 1
            return None
        end = offset + length
        for s, buf in st.extents:
            if s <= offset and s + len(buf) >= end:
                self.hits += 1
                return buf[offset - s : end - s]
        self.misses += 1
        return None

    def invalidate(self, oid: str) -> None:
        """Drop cached bytes only — active pin/waiter state must survive
        (popping the whole object state would orphan in-flight pins and
        break overlap serialization)."""
        st = self._objects.get(oid)
        if st is not None:
            st.extents = []
            if st.idle():
                self._objects.pop(oid, None)
