"""HitSet: object-access tracking (reference: src/osd/HitSet.{h,cc}).

The reference records which objects a PG touched during a time period
so the cache-tiering agent can rank hotness; implementations trade
memory for precision -- ExplicitHashHitSet (exact set of hashes),
BloomHitSet (bloom filter with a target false-positive probability) --
behind one insert/contains interface, with periodic rollover keeping
the last N archived sets (pg_pool_t hit_set_period / hit_set_count).

The tracker lives on the OSD and feeds from the client-op path; the
admin socket exposes the same introspection the reference's
``ceph osd pool set hit_set_*`` + tier agent consume.
"""

from __future__ import annotations

import hashlib
import math
import time
from collections import deque
from typing import Deque, List, Optional


class ExplicitHitSet:
    """Exact membership (ExplicitHashHitSet role)."""

    kind = "explicit_hash"

    def __init__(self):
        self._hashes = set()

    def insert(self, oid: str) -> None:
        self._hashes.add(hash(oid) & 0xFFFFFFFF)

    def contains(self, oid: str) -> bool:
        return (hash(oid) & 0xFFFFFFFF) in self._hashes

    def __len__(self) -> int:
        return len(self._hashes)


class BloomHitSet:
    """Bloom filter sized for ``target_size`` insertions at ``fpp``
    false-positive probability (BloomHitSet over compressible_bloom_
    filter; the reference sizes from hit_set_fpp the same way)."""

    kind = "bloom"

    def __init__(self, target_size: int = 10_000, fpp: float = 0.01):
        self.fpp = fpp
        # standard bloom sizing: m = -n ln p / (ln 2)^2, k = m/n ln 2
        m = max(64, int(-target_size * math.log(fpp) / (math.log(2) ** 2)))
        self.nbits = m
        self.nhash = max(1, round(m / target_size * math.log(2)))
        self._bits = bytearray((m + 7) // 8)
        self._count = 0

    def _positions(self, oid: str) -> List[int]:
        # double hashing: h1 + i*h2 gives k independent-enough probes
        d = hashlib.blake2b(oid.encode(), digest_size=16).digest()
        h1 = int.from_bytes(d[:8], "little")
        h2 = int.from_bytes(d[8:], "little") | 1
        return [(h1 + i * h2) % self.nbits for i in range(self.nhash)]

    def insert(self, oid: str) -> None:
        # one _positions walk serves both the membership probe and the
        # bit sets (insert-via-contains paid the blake2b twice; this
        # runs on every client op through the hit-set tracker)
        bits = self._bits
        seen = True
        for p in self._positions(oid):
            mask = 1 << (p & 7)
            if not bits[p >> 3] & mask:
                seen = False
                bits[p >> 3] |= mask
        if not seen:
            self._count += 1  # approx DISTINCT count, comparable to
            # ExplicitHitSet's len and to the fpp sizing basis

    def contains(self, oid: str) -> bool:
        return all(self._bits[p >> 3] & (1 << (p & 7))
                   for p in self._positions(oid))

    def __len__(self) -> int:
        return self._count


def make_hitset(kind: str, **kw):
    if kind == "bloom":
        return BloomHitSet(**kw)
    if kind == "explicit_hash":
        return ExplicitHitSet()
    raise ValueError(f"unknown hitset type {kind!r}")


class HitSetTracker:
    """Per-OSD periodic tracker (the PG hit_set machinery): the current
    set absorbs accesses; every ``period`` seconds it is archived and a
    fresh one started, keeping the newest ``count`` archives -- the
    window the tiering agent scans to estimate object temperature."""

    def __init__(self, kind: str = "bloom", period: float = 600.0,
                 count: int = 4, **kw):
        self.kind = kind
        self.period = period
        self.count = count
        self._kw = kw
        self.current = make_hitset(kind, **kw)
        self.current_start = time.time()
        self.archived: Deque[tuple] = deque(maxlen=count)

    def _maybe_roll(self, now: Optional[float] = None) -> None:
        now = time.time() if now is None else now
        # after a long idle gap, skip straight to the retention window:
        # every older period is an empty archive anyway, and one roll
        # per elapsed period keeps archive spans honest (a single roll
        # spanning N idle periods would keep a stale object "hot" for
        # the whole window)
        horizon = self.period * (self.count + 1)
        if now - self.current_start > horizon + self.period:
            self.archived.append((
                self.current_start, self.current_start + self.period,
                self.current))
            self.current = make_hitset(self.kind, **self._kw)
            self.current_start = now - horizon
        while now - self.current_start >= self.period:
            self.archived.append((
                self.current_start, self.current_start + self.period,
                self.current))
            self.current = make_hitset(self.kind, **self._kw)
            self.current_start += self.period

    def record(self, oid: str, now: Optional[float] = None) -> None:
        self._maybe_roll(now)
        self.current.insert(oid)

    def record_many(self, oids, now: Optional[float] = None) -> None:
        """Batch form of :meth:`record` (the OSD's array-batched op
        path): one roll check covers the whole run."""
        self._maybe_roll(now)
        insert = self.current.insert
        for oid in oids:
            insert(oid)

    def temperature(self, oid: str, now: Optional[float] = None) -> float:
        """Fraction of retained periods (newest weighted heaviest) in
        which the object appears -- the agent's hotness estimate."""
        self._maybe_roll(now)
        sets = [h for _s, _e, h in self.archived] + [self.current]
        if not sets:
            return 0.0
        weight = total = 0.0
        for i, hs in enumerate(sets):
            w = float(i + 1)  # newest last, heaviest
            total += w
            if hs.contains(oid):
                weight += w
        return weight / total

    def dump(self) -> dict:
        return {
            "kind": self.kind,
            "period": self.period,
            "current_entries": len(self.current),
            "archived": [
                {"start": s, "end": e, "entries": len(h)}
                for s, e, h in self.archived
            ],
        }
