"""PG log: per-OSD sequence-numbered op log powering delta peering and
divergent-entry rollback.

Reference: src/osd/PGLog.{h,cc} and the EC rollback design
(doc/dev/osd_internals/erasure_coding/ecbackend.rst:9-27, ECSubWrite
trim_to/roll_forward_to ECMsgTypes.h:33-35).  Two roles:

* **Delta peering** (the GetLog/missing-set exchange of src/osd/PG.cc):
  every applied sub-write gets a per-OSD monotonic sequence number; a
  primary remembers the last sequence it processed per peer and fetches
  only ``entries_after(watermark)`` -- peering traffic proportional to
  new writes, zero on a clean cluster.  A watermark below ``tail_seq``
  means the log was trimmed past the gap: the peer must be backfilled
  (full scan), the reference's log-vs-backfill distinction.

* **Rollback** (divergent entries): each entry snapshots the pre-apply
  state (size, version/size/hash attrs, existence) of the shard object.
  EC writes are creates/appends in the default append-only mode, so a
  torn write (landed on < k shards) rolls back locally by truncating and
  restoring attrs -- no network push needed.  Overwrite-style entries
  (bytes below the prior size modified) are marked non-rollbackable and
  fall back to a recovery push from the authoritative shards.

* **Reqid dup detection** (the pg_log_dup_t role, src/osd/osd_types.h):
  every applied client op records its reqid ``(client, incarnation,
  tid)`` and client-visible result as a dup entry.  A resent op whose
  reqid is already recorded is answered with the original result
  instead of re-executing -- the exactly-once guarantee across primary
  failover.  Dups live OUTSIDE the entry list: ``trim()`` never drops
  them (the reference keeps a separate dups list past log trim,
  bounded by ``osd_pg_log_dups_tracked``); divergent-entry rollback
  prunes the dups of the rolled-back versions so a torn write's replay
  re-executes instead of reporting a success that was undone.
"""

from __future__ import annotations

import dataclasses
from collections import OrderedDict
from typing import Dict, List, Optional

from ceph_tpu.osd.types import Transaction

#: attr key holding the version tuple in prior_attrs snapshots (matches
#: ecbackend.VERSION_KEY; duplicated to avoid the import cycle)
_VERSION_ATTR = "_version"


@dataclasses.dataclass
class PGLogEntry:
    seq: int  # per-OSD monotonic sequence (assigned by PGLog.append)
    oid: str  # shard object id ("base@shard" or "base@meta")
    op: str  # "write" | "delete"
    obj_version: tuple  # version tuple this entry stamped
    existed: bool = True  # object existed before this entry
    prior_size: int = 0  # rollback point for appends
    prior_attrs: Optional[Dict[str, object]] = None  # pre-apply attr snapshot
    rollbackable: bool = True


@dataclasses.dataclass
class PGLogDup:
    """One replayed-op detection record (pg_log_dup_t role): the reqid
    that stamped a client op, the client-visible result it produced, and
    the object/version it landed on (for rollback pruning)."""

    seq: int  # per-OSD monotonic dup sequence (peering delta exchange)
    reqid: tuple  # (client name, incarnation, tid)
    result: object = None  # wire-encodable client_reply result
    oid: str = ""  # base object id the op mutated
    version: Optional[tuple] = None  # version tuple the op stamped


class PGLog:
    """Ordered per-OSD log with head/tail, delta queries, trim,
    per-object rollback, and the reqid dup registry."""

    def __init__(self, trim_target: int = 1000,
                 dups_tracked: Optional[int] = None):
        self.entries: List[PGLogEntry] = []
        #: newest sequence dropped by trim (entries <= tail_seq are gone)
        self.tail_seq = 0
        self._next_seq = 0
        self.trim_target = trim_target
        #: reqid -> PGLogDup, insertion-ordered for bounded eviction;
        #: NOT touched by trim() (see module docstring)
        self.dups: "OrderedDict[tuple, PGLogDup]" = OrderedDict()
        self._dup_seq = 0
        #: None = read osd_pg_log_dups_tracked per insert (runtime
        #: changes honored); an explicit bound pins it (tests)
        self._dups_tracked = dups_tracked

    @property
    def head_seq(self) -> int:
        return self.entries[-1].seq if self.entries else self._next_seq

    def append(self, oid: str, op: str, obj_version: tuple, *,
               existed: bool = True, prior_size: int = 0,
               prior_attrs: Optional[dict] = None,
               rollbackable: bool = True) -> PGLogEntry:
        self._next_seq += 1
        e = PGLogEntry(
            seq=self._next_seq, oid=oid, op=op, obj_version=obj_version,
            existed=existed, prior_size=prior_size, prior_attrs=prior_attrs,
            rollbackable=rollbackable,
        )
        self.entries.append(e)
        return e

    # -- reqid dup registry (pg_log_dup_t role) ---------------------------

    def _dup_bound(self) -> int:
        if self._dups_tracked is not None:
            return self._dups_tracked
        from ceph_tpu.utils.config import get_config

        return int(get_config().get_val("osd_pg_log_dups_tracked"))

    def record_dup(self, reqid, result=None, *, oid: str = "",
                   version: Optional[tuple] = None) -> PGLogDup:
        """Remember that the op identified by ``reqid`` applied here.
        Idempotent: a reqid seen twice keeps its first record, except a
        None result is upgraded once the full client-visible result is
        known (the sub-op fan-out records before the primary learns the
        final result of e.g. an exec)."""
        reqid = tuple(reqid)
        ent = self.dups.get(reqid)
        if ent is not None:
            if ent.result is None and result is not None:
                ent.result = result
            return ent
        self._dup_seq += 1
        ent = PGLogDup(
            seq=self._dup_seq, reqid=reqid, result=result, oid=oid,
            version=tuple(version) if version is not None else None,
        )
        self.dups[reqid] = ent
        bound = self._dup_bound()
        while len(self.dups) > max(1, bound):
            self.dups.popitem(last=False)  # oldest first
        return ent

    def lookup_dup(self, reqid) -> Optional[PGLogDup]:
        return self.dups.get(tuple(reqid))

    def lookup_dups_batch(self, reqids) -> List[Optional[PGLogDup]]:
        """Batch dup resolution for the OSD's array-batched client-op
        fast path (osd/shard.py): one bound-method fetch + one pass of
        dict gets over the whole batch instead of a ``lookup_dup`` call
        per op.  ``None`` rows (non-dedupable ops) pass through as
        ``None`` misses; semantics per row are exactly
        :meth:`lookup_dup`."""
        get = self.dups.get
        return [None if r is None else get(tuple(r)) for r in reqids]

    @property
    def dup_head_seq(self) -> int:
        return self._dup_seq

    def dups_after(self, seq: int) -> List[PGLogDup]:
        """Dup entries newer than ``seq`` (peering delta exchange; the
        whole registry is bounded, so seq 0 fetches everything)."""
        return [d for d in self.dups.values() if d.seq > seq]

    def merge_dup(self, reqid, result, oid: str,
                  version: Optional[tuple]) -> None:
        """Adopt a peer's dup entry (peering exchange).  The entry gets
        a LOCAL seq -- dup sequences are per-OSD, never forwarded."""
        self.record_dup(reqid, result, oid=oid, version=version)

    # -- delta peering queries --------------------------------------------

    def entries_after(self, seq: int) -> List[PGLogEntry]:
        return [e for e in self.entries if e.seq > seq]

    def covers(self, seq: int) -> bool:
        """True if the log retains every entry above ``seq`` (a primary
        holding watermark ``seq`` can delta-sync; False -> backfill)."""
        return seq >= self.tail_seq

    # -- trim --------------------------------------------------------------

    def trim(self, to_seq: int) -> None:
        """Drop entries <= to_seq (durable everywhere); trimmed entries
        can no longer be rolled back or delta-served
        (reference ECSubWrite.trim_to).  Dup entries are NOT trimmed:
        replay detection must outlive the log window (a client may
        resend long after the write became durable everywhere), so dups
        ride their own osd_pg_log_dups_tracked bound instead."""
        keep = [e for e in self.entries if e.seq > to_seq]
        if len(keep) != len(self.entries):
            self.tail_seq = max(self.tail_seq, to_seq)
            self.entries = keep

    def maybe_trim(self) -> None:
        if len(self.entries) > self.trim_target:
            self.trim(self.entries[-self.trim_target].seq)

    # -- rollback ----------------------------------------------------------

    def object_entries(self, oid: str) -> List[PGLogEntry]:
        return [e for e in self.entries if e.oid == oid]

    def rollback_object_to(self, oid: str, to_version: tuple,
                           store) -> bool:
        """Undo this object's entries newer than ``to_version`` by applying
        their inverses (truncate to prior size, restore attr snapshot,
        remove a rolled-back create).  Returns True on success; False if
        the log cannot prove a clean rollback (missing/trimmed history or
        a non-rollbackable overwrite) -- caller falls back to a recovery
        push.  Reference: PGLog divergent-entry handling via the rollback
        info EC transactions record (src/osd/ECTransaction.cc:97)."""
        to_version = tuple(to_version)
        doomed = [e for e in self.object_entries(oid)
                  if tuple(e.obj_version) > to_version]
        if not doomed or not all(e.rollbackable for e in doomed):
            return False
        # the oldest doomed entry must sit exactly on the rollback target,
        # else history between them was trimmed and the snapshot is wrong
        oldest = min(doomed, key=lambda e: e.seq)
        if oldest.existed:
            prior_ver = (oldest.prior_attrs or {}).get(_VERSION_ATTR)
            if tuple(prior_ver or ()) != to_version:
                return False
        elif to_version != (0, ""):
            # a create entry proves rollback only to NON-EXISTENCE; if the
            # authoritative version is real history this shard never had
            # (it was down for it), only a recovery push can restore it
            return False
        for e in sorted(doomed, key=lambda e: e.seq, reverse=True):
            if not e.existed:
                store.queue_transaction(Transaction().remove(e.oid))
                continue
            txn = Transaction().truncate(e.oid, e.prior_size)
            for key, val in (e.prior_attrs or {}).items():
                txn = txn.setattr(e.oid, key, val)
            store.queue_transaction(txn)
        doomed_ids = {id(e) for e in doomed}
        self.entries = [e for e in self.entries if id(e) not in doomed_ids]
        # the rolled-back versions' dup records must go with them: a
        # replay of an op peering just proved torn has to RE-EXECUTE,
        # not report a success that was undone (the reference prunes
        # divergent entries' dups the same way, src/osd/PGLog.cc)
        base = oid.rpartition("@")[0] or oid
        dead = [
            r for r, d in self.dups.items()
            if d.oid == base and d.version is not None
            and tuple(d.version) > to_version
        ]
        for r in dead:
            del self.dups[r]
        return True
