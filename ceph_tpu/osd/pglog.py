"""PG-log-lite: bounded per-object op log with append rollback.

Reference: src/osd/PGLog.{h,cc} and the EC-specific rollback design
(doc/dev/osd_internals/erasure_coding/ecbackend.rst:9-27, ECSubWrite
trim_to/roll_forward_to ECMsgTypes.h:33-35): EC writes are logged with
enough metadata (prior append sizes) that a divergent shard can ROLL BACK
an uncommitted append by truncating, instead of needing the other shards.
This is the storage-system checkpoint/resume mechanism: after a restart a
shard replays/trims its log to converge with the authoritative log.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

from ceph_tpu.osd.memstore import MemStore
from ceph_tpu.osd.types import Transaction


@dataclasses.dataclass
class PGLogEntry:
    version: int
    oid: str  # shard object id
    op: str  # "append" | "touch" | "delete"
    prior_size: int = 0  # rollback point for appends
    rollbackable: bool = True


class PGLog:
    """Ordered log with head/tail, divergence trim, and rollback apply."""

    def __init__(self, trim_target: int = 1000):
        self.entries: List[PGLogEntry] = []
        self.tail_version = 0
        self.trim_target = trim_target

    @property
    def head_version(self) -> int:
        return self.entries[-1].version if self.entries else self.tail_version

    def append(self, entry: PGLogEntry) -> None:
        # monotonic, not dense: a shard only logs writes it participates in
        assert entry.version > self.head_version, "log must be ordered"
        self.entries.append(entry)

    def trim(self, to_version: int) -> None:
        """Drop entries <= to_version (they are durable everywhere);
        trimmed entries can no longer be rolled back
        (reference ECSubWrite.trim_to)."""
        keep = [e for e in self.entries if e.version > to_version]
        if keep != self.entries:
            self.tail_version = max(self.tail_version, to_version)
            self.entries = keep

    def maybe_trim(self) -> None:
        if len(self.entries) > self.trim_target:
            self.trim(self.entries[-(self.trim_target)].version)

    def rollback_to(self, version: int, store: MemStore) -> List[PGLogEntry]:
        """Undo entries with version > `version` (newest first), applying the
        inverse operation to the local store. Returns the rolled-back
        entries. Raises if any is non-rollbackable (would need backfill)."""
        doomed = [e for e in self.entries if e.version > version]
        for e in reversed(doomed):
            if not e.rollbackable:
                raise ValueError(
                    f"entry v{e.version} not rollbackable; needs backfill"
                )
            if e.op == "append":
                store.queue_transaction(
                    Transaction().truncate(e.oid, e.prior_size)
                )
            elif e.op == "touch":
                store.queue_transaction(Transaction().remove(e.oid))
            elif e.op == "delete":
                raise ValueError("delete rollback requires a backfill source")
        self.entries = [e for e in self.entries if e.version <= version]
        return doomed

    def merge_authoritative(
        self, auth_head: int, store: MemStore
    ) -> List[PGLogEntry]:
        """Converge on the authoritative head: roll back any local entries
        beyond it (the divergent-shard path after a primary change)."""
        if self.head_version <= auth_head:
            return []
        return self.rollback_to(auth_head, store)
