"""Minimized EC storage backend: shard daemons + primary write/read engine.

Reference: src/osd/ECBackend.{h,cc} reduced to the EC essentials:

* writes are append-only (the reference's default without ec_overwrites,
  src/osd/osd_types.h:1516) and run a fan-out/2-phase-ack pipeline with
  in-order completion (ECBackend.h:522-573 write pipeline,
  ECBackend.cc:1976-2030 sub-write fan-out, :2043 try_finish_rmw);
* reads pick the cheapest shard set via minimum_to_decode and reconstruct
  when degraded (ECBackend.cc:2284 objects_read_and_reconstruct, :1569
  get_min_avail_to_read_shards);
* every shard read cross-checks the stored per-shard crc32c
  (handle_sub_read, ECBackend.cc:1054-1076) and reports EIO on mismatch,
  which the primary treats as a missing shard (send_all_remaining_reads
  analogue);
* recovery reconstructs lost shards from the minimum available set and
  pushes them to the replacement OSD (continue_recovery_op,
  ECBackend.cc:535-700).

Shard objects are stored as "<oid>@<shard>" in each OSD's MemStore with the
HashInfo + logical size as xattrs.
"""

from __future__ import annotations

import asyncio
from contextlib import asynccontextmanager
from typing import Dict, List, Optional, Tuple

import numpy as np

from ceph_tpu.osd import ecutil
from ceph_tpu.osd.messenger import Messenger
from ceph_tpu.osd.types import (
    ECSubRead,
    ECSubReadReply,
    ECSubWrite,
    ECSubWriteReply,
    LogEntry,
    Transaction,
)
from ceph_tpu.native.gf_native import crc32c
from ceph_tpu.utils.perf import PerfCounters

SIZE_KEY = "_size"
#: per-shard object version xattr (the object_info_t version role): every
#: write stamps it, reads drop shards whose version lags the newest seen,
#: so a shard that missed updates while down can never contribute a stale
#: chunk to a decode (the PG-log/peering consistency guarantee, reduced
#: to a read-time check)
VERSION_KEY = "_version"
#: per-object snapshot set xattr (the SnapSet role, src/osd/osd_types.h):
#: {"seq": newest snap context seen, "clones": [{"id", "size"}, ...]}
SNAPSET_KEY = "_snapset"
#: head deleted under a snap context but clones survive (the snapdir
#: object role, src/osd/PrimaryLogPG.cc)
WHITEOUT_KEY = "_whiteout"


def shard_oid(oid: str, shard: int) -> str:
    return f"{oid}@{shard}"


def snap_oid(oid: str, clone_id: int) -> str:
    """Clone object name; '~' is reserved so clones co-place with their
    head (placement strips the suffix, mirroring how the reference keeps
    clones in the head's PG via the ghobject snap field)."""
    return f"{oid}~{clone_id}"


def vt(v) -> tuple:
    """Order object/metadata versions.  Stored/wire form is
    ``(counter, writer)`` (legacy plain ints order as writer "").  The
    writer name breaks ties when two primaries race to the same counter:
    every shard/replica then picks the SAME winner and two writes can
    never share a version, so a read-time consistent cut cannot mix
    chunks from different writes (the role the reference gets from one
    primary OSD serializing the PG, src/osd/ECBackend.h:522-573)."""
    if v is None:
        return (0, "")
    if isinstance(v, int):
        return (v, "")
    return (v[0], v[1])


#: backward-compatible name (the metadata plane used this first)
meta_vt = vt


#: osd_client_op_priority / osd_recovery_op_priority defaults
OP_PRIORITY = {"client": 63, "recovery": 10, "scrub": 5}

#: mclock_opclass-style defaults: (reservation, weight, limit) items/sec;
#: clients get a floor and most of the weight, background work is capped
MCLOCK_DEFAULTS = {
    "client": (1000.0, 100.0, 0.0),
    "recovery": (100.0, 10.0, 2000.0),
    "scrub": (50.0, 5.0, 1000.0),
}


class OSDShard:
    """One OSD daemon holding one shard position per object it stores.

    Incoming EC sub-ops pass through a QoS op queue served by a worker
    loop — the ShardedOpWQ role (reference src/osd/OSD.h:1566), with the
    queue discipline selected like ``osd_op_queue``: ``wpq`` (default) or
    ``mclock`` (src/osd/mClockOpClassQueue).  Heartbeat pings bypass the
    queue (the reference's fast-dispatch path).
    """

    def __init__(self, osd_id: int, messenger: Messenger,
                 op_queue: str = "wpq", objectstore: str = "memstore",
                 data_path: str = ""):
        from ceph_tpu.osd.opqueue import MClockQueue, WeightedPriorityQueue
        from ceph_tpu.osd.pglog import PGLog
        from ceph_tpu.utils.optracker import OpTracker

        self.osd_id = osd_id
        self.name = f"osd.{osd_id}"
        # reference ObjectStore::create (src/os/ObjectStore.cc:63): backend
        # chosen by name, data under the osd's own dir.  An empty data_path
        # propagates as-is so the factory rejects pathless persistent
        # backends instead of writing under the filesystem root.
        from ceph_tpu import objectstore as os_mod

        self.store = os_mod.create(
            objectstore, f"{data_path}/osd.{osd_id}" if data_path else ""
        )
        self.messenger = messenger
        self.perf = PerfCounters(f"osd.{osd_id}")
        self.pglog = PGLog()
        #: per-shard-object applied version tuple (counter, writer): the
        #: QoS queue may legally reorder a low-priority recovery push
        #: behind a newer client write, and racing primaries may deliver
        #: writes out of version order, so applies are version-gated
        #: (reference: recovery pushes carry the object version and PG
        #: logic discards stale ones; primaries racing is impossible in
        #: the reference because one primary OSD serializes a PG)
        self._applied_version: Dict[str, tuple] = {}
        #: watch/notify state (reference src/osd/Watch.cc): oid -> watchers
        self.watches: Dict[str, Dict[str, bool]] = {}
        self._notify_seq = 0
        self._notify_pending: Dict[int, tuple] = {}
        #: OSD-side meta_apply fan-out acks (CAS replication authority)
        self._meta_tid = 0
        self._meta_pending: Dict[int, tuple] = {}
        self.optracker = OpTracker()
        #: entity -> OSDCap; entities absent here run with the open
        #: default (client.admin allow *).  Populated via
        #: set_client_caps from keyring "caps osd" strings.
        self.client_caps: Dict[str, object] = {}
        # 2D latency x size grid (PerfHistogram<2>, dumped by the
        # admin-socket `perf histogram dump` like l_osd_op_*_lat_*)
        from ceph_tpu.utils.perf import HistogramAxis, PerfHistogram

        self.op_hist = PerfHistogram(
            f"osd.{osd_id}.op_latency_size",
            HistogramAxis("latency_usec", 0, 64, 32, "log2"),
            HistogramAxis("size_bytes", 0, 512, 24, "log2"),
        )
        # object-access temperature tracking (src/osd/HitSet.h; feeds
        # the tiering-agent role and the admin-socket hit_set commands)
        from ceph_tpu.osd.hitset import HitSetTracker

        self.hitsets = HitSetTracker()
        self.op_queue_type = op_queue
        if op_queue == "mclock":
            self.opq = MClockQueue(dict(MCLOCK_DEFAULTS))
        else:
            self.opq = WeightedPriorityQueue()
        self._op_event = asyncio.Event()
        #: background-scrub rotating cursor (PG scrub scheduling role)
        self._scrub_cursor = 0
        #: simulates a hung daemon: alive on the wire but never responding
        #: (what OSD heartbeats exist to catch, reference OSD.cc:4612
        #: handle_osd_ping / HeartbeatMap suicide timeouts)
        self.frozen = False
        #: pools this OSD can act as PRIMARY for: pool name -> hosted
        #: ECBackend engine (the PrimaryLogPG role; reference
        #: src/osd/PGBackend.cc:533 build_pg_backend per PG)
        self.pools: Dict[str, "ECBackend"] = {}
        #: shared tid space across hosted backends so a forwarded reply
        #: matches exactly one engine's pending op
        self._host_tid = 0
        #: bound on concurrently executing client ops (the osd_op_tp
        #: thread-count role)
        self._cop_sem = asyncio.Semaphore(64)
        self._cop_seq = 0
        messenger.register(self.name, self.dispatch)
        messenger.adopt_task(
            f"{self.name}.opwq",
            asyncio.get_event_loop().create_task(self._op_worker()),
        )

    def _next_host_tid(self) -> int:
        self._host_tid += 1
        return self._host_tid

    def host_pool(self, pool: str, ec, n_osds: int, placement=None) -> "ECBackend":
        """Attach a primary engine for ``pool`` to this OSD.  Every OSD in
        the cluster hosts one; clients route each op to the object's
        current primary (first up shard of the acting set)."""
        backend = ECBackend(
            ec, list(range(n_osds)), self.messenger, name=self.name,
            placement=placement, register=False,
            tid_alloc=self._next_host_tid, perf=self.perf,
        )
        self.pools[pool] = backend
        return backend

    def set_client_caps(self, entity: str, caps: str) -> None:
        """Confine ``entity``'s client ops to an OSDCap string (the
        keyring 'caps osd' line, ref src/osd/OSDCap.h)."""
        from ceph_tpu.auth.caps import OSDCap

        self.client_caps[entity] = OSDCap.parse(caps)

    # -- background tick: peering-driven recovery (OSD::tick role) ---------

    def start_tick(self, interval: float = None) -> None:
        """Start the background tick loop (reference OSD::tick,
        src/osd/OSD.cc): each tick runs a peering pass over the hosted
        pools, auto-recovering missing/stale shards.  Idempotent."""
        if getattr(self, "_tick_task", None) is not None:
            return
        if interval is None:
            from ceph_tpu.utils.config import get_config

            interval = float(get_config().get_val("osd_tick_interval"))
        self._tick_interval = interval
        self._peer_event = asyncio.Event()
        self._tick_task = asyncio.get_event_loop().create_task(
            self._tick_loop()
        )
        self.messenger.adopt_task(f"{self.name}.tick", self._tick_task)

    def request_peering(self) -> None:
        """Wake the peering loop NOW (event-driven peering: OSDMap epoch
        change, OSD up/down -- the reference re-peers on every map change,
        src/osd/PG.cc peering state machine, instead of waiting out a
        timer).  No-op until start_tick has run."""
        ev = getattr(self, "_peer_event", None)
        if ev is not None:
            ev.set()

    async def _tick_loop(self) -> None:
        while True:
            try:
                await self.peering_tick()
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 -- a failed pass must not
                # kill the loop; state is retried next tick
                import sys
                import traceback

                traceback.print_exc(file=sys.stderr)
            # sleep until the next scheduled tick OR a peering event
            # (up/down/map change) -- whichever comes first
            try:
                await asyncio.wait_for(
                    self._peer_event.wait(), timeout=self._tick_interval
                )
            except asyncio.TimeoutError:
                pass
            self._peer_event.clear()

    async def peering_tick(self) -> int:
        """One peering round over every hosted pool, then a rate-limited
        background deep-scrub slice; returns the number of recovery
        actions attempted."""
        if self.frozen or self.messenger.is_down(self.name):
            return 0
        total = 0
        for backend in self.pools.values():
            total += await backend.peering_pass()
        total += await self.scrub_tick()
        return total

    def _scrub_base_list(self):
        """Base-oid list for the scrub cursor; rebuilt only when the
        cursor wraps (a fresh listing every tick would pay O(objects)
        to pick osd_scrub_objects_per_tick of them)."""
        cached = getattr(self, "_scrub_bases", None)
        if cached is None or self._scrub_cursor == 0 or                 self._scrub_cursor >= len(cached):
            cached = sorted({
                base
                for stored in self.store.list_objects()
                for base, _, tag in [stored.rpartition("@")]
                if base and tag.isdigit()
            })
            self._scrub_bases = cached
            self._scrub_cursor = min(self._scrub_cursor, len(cached))                 if cached else 0
        return cached

    async def scrub_tick(self) -> int:
        """Background deep-scrub scheduler (reference: PG scrub
        reservation/scheduling, src/osd/PG.cc): each tick deep-scrubs up
        to ``osd_scrub_objects_per_tick`` objects this OSD is currently
        PRIMARY for (rotating cursor over the local store), tagged with
        the mClock ``scrub`` op class, and feeds any inconsistency
        straight into shard recovery -- the cluster heals silent
        corruption with no manual call (qa test-erasure-eio role)."""
        from ceph_tpu.utils.config import get_config

        limit = int(get_config().get_val("osd_scrub_objects_per_tick"))
        if limit <= 0 or not self.pools:
            return 0
        # error records for objects this OSD no longer leads pin mgr
        # health forever (the new primary re-detects real damage): drop
        for backend in self.pools.values():
            for e_oid in list(backend.scrub_errors):
                e_acting = backend.acting_set(e_oid)
                lead = None
                for sh in range(backend.km):
                    if backend._shard_up(e_acting, sh):
                        lead = f"osd.{e_acting[sh]}"
                        break
                if lead != self.name:
                    backend.scrub_errors.pop(e_oid, None)
        bases = self._scrub_base_list()
        if not bases:
            return 0
        repaired = 0
        scanned = 0
        n = len(bases)
        start = self._scrub_cursor % n
        for i in range(n):
            if scanned >= limit:
                break
            base = bases[(start + i) % n]
            self._scrub_cursor = (start + i + 1) % n
            for backend in self.pools.values():
                acting = backend.acting_set(base)
                primary = None
                for sh in range(backend.km):
                    if backend._shard_up(acting, sh):
                        primary = f"osd.{acting[sh]}"
                        break
                if primary != self.name:
                    continue
                scanned += 1
                try:
                    report = await backend.deep_scrub(base)
                except asyncio.CancelledError:
                    raise
                except Exception:  # noqa: BLE001 -- scrub must not kill
                    # the tick (e.g. a degraded object mid-recovery)
                    self.perf.inc("scrub_failed")
                    break
                if not report["ok"]:
                    repaired += await backend.scrub_repair(base, report)
                break
        return repaired

    def _op_cost(self, msg) -> int:
        if isinstance(msg, ECSubWrite):
            return max(
                1,
                sum(len(op.data) for op in msg.transaction.ops) // 4096,
            )
        return 1

    async def dispatch(self, src: str, msg) -> None:
        if self.frozen:
            return
        if msg == "ping":
            # fast dispatch: heartbeats never sit behind the op queue
            await self.messenger.send_message(self.name, src, ("pong", self.name))
            return
        if isinstance(msg, (ECSubWriteReply, ECSubReadReply)):
            # this OSD is acting as a primary: forward sub-op replies to
            # the hosted engines (shared tid space -> exactly one matches)
            for backend in self.pools.values():
                await backend.dispatch(src, msg)
            return
        if isinstance(msg, dict) and "op" in msg:
            op = msg["op"]
            if op == "client_op":
                # a client op lands in the QoS queue like any other work
                # (reference: ms_fast_dispatch -> enqueue_op, OSD.cc:6439)
                claim = msg.pop("_budget_claim", None)
                if claim is not None:
                    # keep the messenger's dispatch-throttle budget held
                    # until the op EXECUTES (released in _run_client_op)
                    # so queued bytes stay under the daemon's cap
                    claim()
                cost = max(1, len(msg.get("data") or b"") // 4096)
                if self.op_queue_type == "mclock":
                    self.opq.enqueue(
                        "client", cost, (src, msg),
                        asyncio.get_event_loop().time(),
                    )
                else:
                    self.opq.enqueue(
                        OP_PRIORITY["client"], cost, (src, msg)
                    )
                self.perf.inc("queued_client_op")
                self._op_event.set()
                return
            if op.endswith("_reply"):
                # meta-plane replies for a hosted primary engine
                for backend in self.pools.values():
                    await backend.dispatch(src, msg)
                return
            await self._handle_meta_op(src, msg)
            return
        if isinstance(msg, (ECSubWrite, ECSubRead)):
            klass = getattr(msg, "op_class", "client")
            cost = self._op_cost(msg)
            if self.op_queue_type == "mclock":
                self.opq.enqueue(
                    klass, cost, (src, msg), asyncio.get_event_loop().time()
                )
            else:
                self.opq.enqueue(OP_PRIORITY.get(klass, 63), cost, (src, msg))
            self.perf.inc(f"queued_{klass}")
            self._op_event.set()

    async def _handle_meta_op(self, src: str, msg: dict) -> None:
        """Metadata-plane ops served fast-dispatch (single-threaded, so
        compare-and-swap is atomic without extra locking):

        * ``omap_cas`` -- the atomicity primitive cls_lock-style classes
          need: this OSD (the object's primary-shard holder) is the CAS
          authority (the reference runs cls methods on the primary OSD,
          src/osd/ClassHandler.cc; our primary engine is client-side, so
          atomic read-modify-write is delegated here).
        * ``watch`` / ``unwatch`` / ``notify`` -- watch/notify semantics
          (reference src/osd/Watch.cc): watchers register here; notify
          fans an event to every watcher and gathers acks.
        * ``meta_get`` -- omap + xattrs + meta version for the replicated
          metadata object.
        """
        op = msg["op"]
        oid = msg.get("oid", "")
        soid = f"{oid}@meta"
        if op == "pg_log_info":
            # O(1) peering poll: log head/tail only.  A primary whose
            # watermark is current skips this OSD entirely (reference
            # GetInfo, src/osd/PG.cc peering).  "nonempty" distinguishes a
            # brand-new OSD from one RESTARTED on a persistent store whose
            # in-memory log is empty but whose holdings need a backfill
            # comparison (memoized once true; a stale true only costs an
            # extra backfill).
            if not getattr(self, "_store_nonempty", False):
                self._store_nonempty = bool(self.store.list_objects())
            self.perf.inc("pg_log_info_serve")
            await self.messenger.send_message(self.name, src, {
                "op": "pg_log_info_reply", "tid": msg["tid"],
                "from": self.name,
                "head_seq": self.pglog.head_seq,
                "tail_seq": self.pglog.tail_seq,
                "nonempty": self._store_nonempty,
            })
            return
        if op == "pg_log_entries":
            # delta peering: entries above the requester's watermark
            # (reference GetLog / missing-set computation).  complete=False
            # means the log was trimmed past the gap -> backfill.
            from_seq = int(msg.get("from_seq", 0))
            complete = self.pglog.covers(from_seq)
            ents = []
            if complete:
                for e in self.pglog.entries_after(from_seq):
                    base, _, tag = e.oid.rpartition("@")
                    ents.append((e.seq, base, tag, tuple(e.obj_version)))
            self.perf.inc("pg_log_entries_serve")
            await self.messenger.send_message(self.name, src, {
                "op": "pg_log_entries_reply", "tid": msg["tid"],
                "from": self.name, "complete": complete,
                "head_seq": self.pglog.head_seq, "entries": ents,
            })
            return
        if op == "pg_rollback":
            # divergent-entry rollback: undo this shard's torn entries
            # locally from the log instead of re-pushing the whole shard
            # (reference PGLog rollback via EC transaction rollback info,
            # src/osd/ECTransaction.cc:97).
            target_soid = msg["soid"]
            to_version = vt(tuple(msg["to_version"]))
            ok = self.pglog.rollback_object_to(
                target_soid, to_version, self.store
            )
            if ok:
                try:
                    self.store.stat(target_soid)
                    self._applied_version[target_soid] = to_version
                except FileNotFoundError:
                    self._applied_version.pop(target_soid, None)
                self.perf.inc("pglog_rollback")
            await self.messenger.send_message(self.name, src, {
                "op": "pg_rollback_reply", "tid": msg["tid"],
                "from": self.name, "ok": ok,
            })
            return
        if op == "obj_versions":
            # targeted peering probe: versions for NAMED objects only
            # (per-object GetInfo; the clean-path replacement for the
            # pg_list full scan).
            out = {}
            for base in msg.get("oids", []):
                shards = {}
                for s in range(msg.get("km", 0)):
                    so = shard_oid(base, s)
                    try:
                        self.store.stat(so)
                    except FileNotFoundError:
                        continue
                    shards[s] = tuple(vt(self.store.getattr(so, VERSION_KEY)))
                mv = None
                try:
                    self.store.stat(f"{base}@meta")
                    mv = self.store.getattr(f"{base}@meta", "_meta_version") or 0
                except FileNotFoundError:
                    pass
                out[base] = {"shards": shards, "meta": mv}
            self.perf.inc("obj_versions_serve")
            await self.messenger.send_message(self.name, src, {
                "op": "obj_versions_reply", "tid": msg["tid"],
                "from": self.name, "objects": out,
            })
            return
        if op == "pg_list":
            self.perf.inc("pg_list_serve")
            # peering scan: report every shard object this OSD holds with
            # its version stamp (the role of the peering Query/log+missing
            # exchange, reference src/osd/PG.cc GetInfo/GetLog).  Shard
            # entries are (oid, shard, (counter, writer)); meta replicas
            # report shard -1 with their meta version.
            objects = []
            for stored in self.store.list_objects():
                base, _, tag = stored.rpartition("@")
                if not base:
                    continue
                if tag == "meta":
                    mv = self.store.getattr(stored, "_meta_version") or 0
                    objects.append((base, -1, (mv, "")))
                else:
                    try:
                        shard = int(tag)
                    except ValueError:
                        continue
                    ver = vt(self.store.getattr(stored, VERSION_KEY))
                    objects.append((base, shard, tuple(ver)))
            await self.messenger.send_message(self.name, src, {
                "op": "pg_list_reply", "tid": msg["tid"],
                "from": self.name, "objects": objects,
            })
        elif op == "meta_get":
            try:
                omap = self.store.omap_get(soid)
                ver = self.store.getattr(soid, "_meta_version") or 0
                removed = bool(self.store.getattr(soid, "_meta_removed"))
            except FileNotFoundError:
                omap, ver, removed = None, 0, False
            await self.messenger.send_message(self.name, src, {
                "op": "meta_get_reply", "tid": msg["tid"],
                "omap": omap, "version": ver, "removed": removed,
                "from": self.name,
            })
        elif op == "meta_apply":
            # replicated metadata write: the message carries the FULL
            # resulting omap, not a delta, so a replica that missed any
            # number of earlier versions (it was down) converges to the
            # complete state in one application -- a delta under a
            # version-gap gate would either be rejected forever or stamp
            # a newer version over incomplete contents
            ver = msg["version"]
            try:
                cur = self.store.getattr(soid, "_meta_version") or 0
            except FileNotFoundError:
                cur = 0
            if msg.get("remove"):
                # object removal leaves a VERSIONED TOMBSTONE (cleared
                # omap + removed flag), not a bare delete: a replica
                # that missed the remove holds the old keys at a lower
                # version, and highest-version-wins recovery must
                # propagate the removal, never resurrect the keys.
                # Written even when no twin exists here: the removal
                # record must survive somewhere, or a down replica's
                # stale keys would be the only (hence winning) state
                # when it revives.
                if ver >= cur:
                    self.pglog.append(soid, "remove", (ver, ""),
                                      rollbackable=False)
                    self.pglog.maybe_trim()
                    self.store.queue_transaction(
                        Transaction()
                        .omap_clear(soid)
                        .setattr(soid, "_meta_version", ver)
                        .setattr(soid, "_meta_removed", True)
                    )
                await self.messenger.send_message(self.name, src, {
                    "op": "meta_apply_reply", "tid": msg["tid"],
                    "from": self.name, "applied": ver >= cur,
                })
                return
            if ver >= cur:
                txn = (
                    Transaction()
                    .omap_clear(soid)
                    .omap_setkeys(soid, msg["omap"])
                    .setattr(soid, "_meta_version", ver)
                    .setattr(soid, "_meta_removed", False)
                )
                # log the apply so delta peering discovers meta staleness
                # the same way it does chunk staleness (full-state omap
                # replication is not log-rollbackable; peering re-applies
                # the newest replica instead)
                self.pglog.append(
                    soid, "write", (ver, ""), rollbackable=False,
                )
                self.pglog.maybe_trim()
                self.store.queue_transaction(txn)
            await self.messenger.send_message(self.name, src, {
                "op": "meta_apply_reply", "tid": msg["tid"],
                "from": self.name, "applied": ver >= cur,
            })
        elif op == "omap_cas":
            key, expect, new = msg["key"], msg["expect"], msg["new"]
            try:
                omap = self.store.omap_get(soid)
            except FileNotFoundError:
                omap = {}
            cur = omap.get(key)
            success = cur == expect
            ver = (self.store.getattr(soid, "_meta_version") or 0
                   if self.store.exists(soid) else 0)
            if success:
                ver += 1
                if new is None:
                    omap.pop(key, None)
                else:
                    omap[key] = new
                txn = (
                    Transaction()
                    .omap_clear(soid)
                    .omap_setkeys(soid, omap)
                    .setattr(soid, "_meta_version", ver)
                )
                self.store.queue_transaction(txn)
            await self.messenger.send_message(self.name, src, {
                "op": "omap_cas_reply", "tid": msg["tid"],
                "success": success, "current": cur, "version": ver,
                # full state for replication fan-out by the caller
                "omap": omap,
            })
        elif op == "watch":
            self.watches.setdefault(oid, {})[msg["watcher"]] = True
            await self.messenger.send_message(self.name, src, {
                "op": "watch_reply", "tid": msg["tid"], "ok": True,
            })
        elif op == "unwatch":
            self.watches.get(oid, {}).pop(msg["watcher"], None)
            await self.messenger.send_message(self.name, src, {
                "op": "watch_reply", "tid": msg["tid"], "ok": True,
            })
        elif op == "notify":
            self._notify_seq += 1
            notify_id = self._notify_seq
            watchers = list(self.watches.get(oid, {}))
            if not watchers:
                await self.messenger.send_message(self.name, src, {
                    "op": "notify_reply", "tid": msg["tid"],
                    "acks": [], "timeouts": [],
                })
                return
            pending = set(watchers)
            acked: list = []
            fut = asyncio.get_event_loop().create_future()
            self._notify_pending[notify_id] = (pending, acked, fut)
            for w in watchers:
                await self.messenger.send_message(self.name, w, {
                    "op": "notify_event", "oid": oid,
                    "payload": msg.get("payload"),
                    "notify_id": notify_id, "notifier": self.name,
                })

            async def gather_acks(tid=msg["tid"]):
                # runs as its own task: the dispatch loop must stay free
                # to deliver the very notify_acks being awaited here
                try:
                    await asyncio.wait_for(
                        fut, timeout=msg.get("timeout", 5.0)
                    )
                except asyncio.TimeoutError:
                    pass
                self._notify_pending.pop(notify_id, None)
                await self.messenger.send_message(self.name, src, {
                    "op": "notify_reply", "tid": tid,
                    "acks": list(acked), "timeouts": sorted(pending),
                })

            self.messenger.adopt_task(
                f"{self.name}.notify{notify_id}",
                asyncio.get_event_loop().create_task(gather_acks()),
            )
        elif op == "notify_ack":
            state = self._notify_pending.get(msg["notify_id"])
            if state is not None:
                pending, acked, fut = state
                if msg["watcher"] in pending:
                    pending.discard(msg["watcher"])
                    acked.append(msg["watcher"])
                if not pending and not fut.done():
                    fut.set_result(True)

    async def _op_worker(self) -> None:
        """Dequeue-and-execute loop (the osd_op_tp worker thread role)."""
        loop = asyncio.get_event_loop()
        while True:
            await self._op_event.wait()
            self._op_event.clear()
            while True:
                if self.op_queue_type == "mclock":
                    now = loop.time()
                    item = self.opq.dequeue(now)
                    if item is None:
                        nxt = self.opq.next_ready(now)
                        if nxt is None:
                            break
                        # wait for the tag to come due OR a new arrival
                        # (whose reservation may be eligible right away)
                        try:
                            await asyncio.wait_for(
                                self._op_event.wait(),
                                timeout=max(0.0, nxt - now),
                            )
                            self._op_event.clear()
                        except asyncio.TimeoutError:
                            pass
                        continue
                else:
                    if self.opq.empty():
                        break
                    item = self.opq.dequeue()
                # a daemon frozen or marked down after enqueue must not
                # execute (a "hung" OSD mutating its store would defeat
                # the fault model the flag simulates)
                if self.frozen or self.messenger.is_down(self.name):
                    # a dropped op must still return its claimed
                    # dispatch-throttle budget or repeated freeze cycles
                    # would shrink the messenger's byte cap forever
                    dropped = item[1]
                    if isinstance(dropped, dict):
                        release = dropped.pop("_budget_release", None)
                        if release is not None:
                            release()
                    continue
                src, msg = item
                try:
                    await self._execute_op(src, msg)
                except asyncio.CancelledError:
                    raise
                except Exception:  # noqa: BLE001 — op failure must not
                    # kill the worker; log and keep serving (the reference
                    # logs and drops misbehaving ops too)
                    import sys
                    import traceback

                    traceback.print_exc(file=sys.stderr)

    async def _execute_op(self, src: str, msg) -> None:
        if isinstance(msg, dict):
            # client op: runs as its own task -- it awaits sub-ops that
            # this very worker loop must stay free to execute (the
            # reference gets the same effect from multiple osd_op_tp
            # threads; concurrency is bounded by _cop_sem)
            self._cop_seq += 1
            task = asyncio.get_event_loop().create_task(
                self._run_client_op(src, msg)
            )
            self.messenger.adopt_task(f"{self.name}.cop{self._cop_seq}", task)
            return
        kind = "sub_write" if isinstance(msg, ECSubWrite) else "sub_read"
        op = self.optracker.create_request(
            f"{kind}(tid={msg.tid} oid={next(iter(msg.to_read), '?') if isinstance(msg, ECSubRead) else msg.oid} shard={msg.from_shard})"
        )
        op.mark_event("dequeued")
        try:
            if isinstance(msg, ECSubWrite):
                await self.handle_sub_write(src, msg)
            else:
                await self.handle_sub_read(src, msg)
            op.mark_event("replied")
        finally:
            op.finish()

    async def _run_client_op(self, src: str, msg: dict) -> None:
        """Execute one client op on the hosted primary engine and reply.

        Reference: the osd_op_tp worker calling PrimaryLogPG::do_request
        -> do_op -> execute_ctx, with the MOSDOpReply back to the client
        (src/osd/OSD.cc:9072, src/osd/PrimaryLogPG.cc:1649)."""
        op = self.optracker.create_request(
            f"client_op({msg.get('kind')} oid={msg.get('oid')} from={src})"
        )
        reply = {"op": "client_reply", "tid": msg["tid"]}
        try:
            await self._run_client_op_inner(src, msg, op, reply)
        finally:
            release = msg.pop("_budget_release", None)
            if release is not None:
                release()  # claimed messenger dispatch-throttle budget

    async def _run_client_op_inner(self, src: str, msg: dict, op,
                                   reply: dict) -> None:
        async with self._cop_sem:
            op.mark_event("started")
            pool_name = msg.get("pool") or ""
            backend = self.pools.get(pool_name)
            if backend is None and self.pools:
                # fall back to the hosted pool -- and make the cap
                # check below use the pool the op will actually RUN on,
                # never the requested name (a grant on an unhosted name
                # must not leak onto the hosted pool)
                pool_name = next(iter(self.pools))
                backend = self.pools[pool_name]
            cap = self.client_caps.get(src.split("[")[0])
            if cap is not None and backend is not None:
                # OSDCap enforcement (PrimaryLogPG
                # op_has_sufficient_caps): an entity with registered
                # caps is confined to them; unregistered entities keep
                # the open-cluster default (client.admin allow *)
                from ceph_tpu.auth.caps import op_capable

                if not op_capable(cap, pool_name,
                                  msg.get("oid", ""), msg.get("kind", "")):
                    reply.update(
                        ok=False, etype="PermissionError",
                        error=f"{src} caps do not permit "
                              f"{msg.get('kind')} on {msg.get('oid')}",
                    )
                    backend = None
                    self.perf.inc("cap_denied")
            if backend is None and "etype" not in reply:
                reply.update(
                    ok=False, etype="IOError",
                    error=f"{self.name} hosts no pool",
                )
            elif backend is not None:
                try:
                    reply.update(ok=True, result=await backend.client_op(msg))
                except asyncio.CancelledError:
                    raise
                except Exception as e:  # noqa: BLE001 -- every failure
                    # travels back to the client as a typed error
                    reply.update(
                        ok=False, etype=type(e).__name__, error=str(e)
                    )
            op.mark_event("replied")
        op.finish()
        self.op_hist.inc(op.duration * 1e6,
                         len(msg.get("data") or b""))
        if msg.get("oid"):
            self.hitsets.record(msg["oid"])
        if self.frozen or self.messenger.is_down(self.name):
            return
        await self.messenger.send_message(self.name, src, reply)

    async def handle_sub_write(self, src: str, msg: ECSubWrite) -> None:
        """reference ECBackend::handle_sub_write (:922): log the operation,
        then apply the transaction (log_operation + queue_transactions)."""
        soid = shard_oid(msg.oid, msg.from_shard)
        new_vt = vt(msg.at_version)
        cur_vt = self._applied_version.get(soid)
        if cur_vt is None:
            # fresh process (daemon restart): the applied version lives in
            # the object's xattr, not just this map — the gate must
            # survive restarts on persistent stores
            try:
                cur_vt = vt(self.store.getattr(soid, VERSION_KEY))
            except FileNotFoundError:
                cur_vt = vt(None)
        if (
            msg.prev_version is not None
            and cur_vt[0] != vt(msg.prev_version)[0]
            and new_vt >= cur_vt
        ):
            # incremental (RMW extent) write, but this shard is not on the
            # base version it was computed against: it missed history
            # (down/revived hollow).  Applying just the extent would stamp
            # the new version over mostly-stale bytes.  Skip; the shard
            # stays behind until peering recovers it (pg_missing_t role).
            self.perf.inc("sub_write_missed_base")
            await self.messenger.send_message(self.name, src, ECSubWriteReply(
                from_shard=msg.from_shard, tid=msg.tid,
                committed=False, applied=False, missed=True,
            ))
            return
        if msg.rollback and msg.op_class == "recovery":
            # peering proved this shard's newer copy a torn write (held by
            # < k shards): the primary rolls it back to the authoritative
            # version, bypassing the stale gate (divergent-entry rollback)
            self.perf.inc("sub_write_rollback")
        elif new_vt < cur_vt:
            # dequeued behind a newer write to the same object (priority
            # reordering or a racing primary).  Applying would clobber
            # newer bytes with stale ones.
            self.perf.inc("sub_write_stale")
            if msg.op_class == "client":
                # a racing client write lost: refuse loudly so the writer
                # retries at a higher version instead of believing a
                # commit that never applied (split-brain fix)
                reply = ECSubWriteReply(
                    from_shard=msg.from_shard, tid=msg.tid,
                    committed=False, applied=False,
                    current_version=cur_vt,
                )
            else:
                # a recovery/scrub push made obsolete by a newer client
                # write is genuinely done: the shard holds newer data
                reply = ECSubWriteReply(
                    from_shard=msg.from_shard, tid=msg.tid,
                    committed=True, applied=False,
                )
            await self.messenger.send_message(self.name, src, reply)
            return
        self._applied_version[soid] = new_vt
        # log_operation before queue_transactions (reference order,
        # ECBackend.cc:922): snapshot the pre-apply state so a torn write
        # can be rolled back locally (divergent-entry rollback) and give
        # the entry this OSD's monotonic sequence for delta peering.
        try:
            prior = self.store.stat(soid)
            existed = True
        except FileNotFoundError:
            prior = 0
            existed = False
        prior_attrs: Dict[str, object] = {}
        rollbackable = True
        for top in msg.transaction.ops:
            if top.op == "setattr" and top.oid == soid:
                prior_attrs[top.attr_name] = (
                    self.store.getattr(soid, top.attr_name) if existed
                    else None
                )
            elif existed and top.op == "write" and top.offset < prior:
                rollbackable = False  # overwrites prior bytes: needs push
            elif existed and top.op == "truncate" and top.offset < prior:
                rollbackable = False
            elif top.op in ("remove", "omap_set", "omap_rm", "omap_clear"):
                rollbackable = False
        self.pglog.append(
            soid, "write", new_vt,
            existed=existed, prior_size=prior,
            prior_attrs=prior_attrs or None, rollbackable=rollbackable,
        )
        self.pglog.maybe_trim()
        self.store.queue_transaction(msg.transaction)
        self.perf.inc("sub_write")
        reply = ECSubWriteReply(
            from_shard=msg.from_shard, tid=msg.tid, committed=True, applied=True
        )
        await self.messenger.send_message(self.name, src, reply)

    async def handle_sub_read(self, src: str, msg: ECSubRead) -> None:
        """reference ECBackend::handle_sub_read (:987): serve extents and
        crc-verify full-shard reads against HashInfo."""
        reply = ECSubReadReply(from_shard=msg.from_shard, tid=msg.tid)
        for oid, extents in msg.to_read.items():
            soid = shard_oid(oid, msg.from_shard)
            try:
                bufs = []
                for off, length in extents:
                    data = self.store.read(soid, off, length)
                    bufs.append((off, data))
                # full-shard read -> verify cumulative crc (ECBackend.cc:1054)
                hinfo_d = self.store.getattr(soid, ecutil.HINFO_KEY)
                if hinfo_d is not None:
                    hinfo = ecutil.HashInfo.from_dict(hinfo_d)
                    # overwrites clear chunk hashes (ec_overwrites mode):
                    # only crc-check shards that still track them
                    if hinfo.has_chunk_hash():
                        full = self.store.read(soid)
                        if len(full) == hinfo.get_total_chunk_size():
                            if crc32c(full) != hinfo.get_chunk_hash(
                                msg.from_shard
                            ):
                                self.perf.inc("read_crc_error")
                                reply.errors[oid] = -5  # EIO
                                continue
                reply.buffers_read[oid] = bufs
            except FileNotFoundError:
                reply.errors[oid] = -2  # ENOENT
        for oid in msg.attrs_to_read:
            soid = shard_oid(oid, msg.from_shard)
            try:
                reply.attrs_read[oid] = {
                    ecutil.HINFO_KEY: self.store.getattr(soid, ecutil.HINFO_KEY),
                    SIZE_KEY: self.store.getattr(soid, SIZE_KEY),
                    VERSION_KEY: self.store.getattr(soid, VERSION_KEY),
                    SNAPSET_KEY: self.store.getattr(soid, SNAPSET_KEY),
                    WHITEOUT_KEY: self.store.getattr(soid, WHITEOUT_KEY),
                }
            except FileNotFoundError:
                pass
        self.perf.inc("sub_read")
        await self.messenger.send_message(self.name, src, reply)


class WriteConflict(IOError):
    """A shard refused a client write as stale: a racing primary committed
    a newer version first.  Carries the winning version tuple."""

    def __init__(self, winner: tuple):
        super().__init__(f"write lost to concurrent version {winner}")
        self.winner = winner


class ObjectIncomplete(IOError):
    """The newest observed version might have been acked but cannot
    assemble k chunks from up shards — serving an older version would be a
    read-after-ack consistency violation (the reference's peering would
    block or mark the PG incomplete, src/osd/PG.cc)."""


class ECBackend:
    """Primary-side engine: placement, write pipeline, read/reconstruct.

    Since round 3 this engine is HOSTED INSIDE the primary OSD daemon
    (``OSDShard.host_pool``) -- the reference architecture, where the
    client's Objecter sends one op to the primary OSD which owns the PG
    and fans out sub-ops (src/osd/PrimaryLogPG.cc, dispatch at
    src/osd/OSD.cc:6439, fan-out src/osd/ECBackend.cc:1976-2030).  A
    standalone client-side instance (``register=True``) remains possible
    and is what the multi-primary race tests exercise.
    """

    def __init__(
        self,
        ec,
        osds: List[OSDShard],
        messenger: Messenger,
        name: str = "client",
        placement=None,
        register: bool = True,
        tid_alloc=None,
        perf: Optional[PerfCounters] = None,
    ):
        self.ec = ec
        self.k = ec.get_data_chunk_count()
        self.km = ec.get_chunk_count()
        self.m = self.km - self.k
        stripe_width = self.k * ec.get_chunk_size(1)
        self.sinfo = ecutil.StripeInfo(self.k, stripe_width)
        self.osds = osds
        self.messenger = messenger
        self.name = name
        # a hosted engine shares its OSD's counter instance (one daemon,
        # one perf registry entry -- the reference's per-daemon logger)
        self.perf = perf if perf is not None else PerfCounters(name)
        self._tid = 0
        #: co-hosted backends on one OSD share a tid space so replies
        #: forwarded to every pool match exactly one pending op
        self._tid_alloc = tid_alloc
        self._pending: Dict[int, dict] = {}
        if register:
            messenger.register(name, self.dispatch)
        # per-object version counter (pg-log-lite); bounded: entries are
        # evicted LRU and relearned via _stat on the next touch
        from collections import OrderedDict

        self._versions: "OrderedDict[str, int]" = OrderedDict()
        #: high-water mark of every version ever assigned or learned --
        #: survives _versions eviction so the pg-wide counter (the
        #: eversion role) never regresses
        self._version_head = 0
        self.log: List[LogEntry] = []
        # in-flight RMW extent pinning + read-through byte cache
        # (reference src/osd/ExtentCache.h)
        from ceph_tpu.osd.extent_cache import ExtentCache

        self.extent_cache = ExtentCache()
        #: per-object write mutex: version-assignment + fan-out + commit
        #: wait run under it, so writes to one object from this primary
        #: complete in version order (the reference's in-order write
        #: pipeline, ECBackend.h:522-541).  Without it two disjoint-extent
        #: RMWs could interleave across awaits and a shard could apply
        #: them newest-first, silently discarding the older one's extent.
        #: Entries are refcounted and dropped when uncontended (round-2
        #: verdict: unbounded growth).
        self._oid_locks: Dict[str, asyncio.Lock] = {}
        self._oid_lock_refs: Dict[str, int] = {}
        #: replicated-metadata version sequence per oid (meta plane is
        #: versioned separately from the chunk plane)
        self._meta_versions: Dict[str, int] = {}
        #: oid -> callback for watch/notify events
        self._watch_callbacks: Dict[str, object] = {}
        # CRUSH placement engine (ceph_tpu.osd.placement.CrushPlacement);
        # None falls back to the seeded-permutation CRUSH-lite below.
        self.placement = placement
        # -- delta peering state (pg_missing_t / peer_info roles) ----------
        #: last log sequence processed per peer OSD; a peer whose head
        #: equals its watermark contributes zero peering traffic
        self._peer_seq: Dict[str, int] = {}
        #: objects known to need attention (writes that missed shards,
        #: recoveries pending on down OSDs) -- the pg_missing_t analogue
        self._dirty: set = set()
        #: replicated-metadata objects in the same state
        self._dirty_meta: set = set()
        #: last inconsistent deep-scrub reports (ScrubStore role);
        #: cleared when a re-scrub comes back clean
        self.scrub_errors: Dict[str, dict] = {}
        #: per-object SnapSet cache learned via _stat:
        #: {"seq", "clones", "exists", "size"}
        self._snapsets: Dict[str, dict] = {}

    # -- placement (CRUSH-lite) --------------------------------------------

    def acting_set(self, oid: str) -> List[int]:
        """Stable pseudorandom placement of the km shards over OSDs.

        Clone objects ("oid~<cloneid>") place WITH their head object --
        the suffix is stripped before hashing -- so snapshots live in the
        head's PG exactly like the reference's ghobject snap ids.

        With a CrushPlacement attached this is the real thing: oid -> pg ->
        crush indep rule over the map (src/crush/mapper.c crush_choose_indep;
        src/osd/OSDMap.cc _pg_to_raw_osds).  The fallback is a deterministic
        permutation seeded by the object name.
        """
        oid = oid.split("~", 1)[0]
        if self.placement is not None:
            return self.placement.acting(oid)
        from ceph_tpu.osd.placement import fallback_acting

        # stable: down OSDs keep their slot (degraded) until recovery moves
        # the shard, mirroring up/acting set semantics
        return fallback_acting(oid, len(self.osds), self.km)

    def _shard_up(self, acting, s: int) -> bool:
        """A shard position is usable iff it mapped (no CRUSH hole) and its
        OSD is not down."""
        return acting[s] is not None and not self.messenger.is_down(
            f"osd.{acting[s]}"
        )

    async def _reconfirm_up(self, acting, up_shards):
        """Probe down-looking acting holders (concurrently, at most once
        per second) and return the refreshed up set.  No-op on
        messengers without a probe (the in-process bus's is_down is
        authoritative).  A genuinely-dead cluster pays one probe round
        per second, not one per read."""
        probe = getattr(self.messenger, "probe", None)
        if probe is None:
            return up_shards
        now = asyncio.get_event_loop().time()
        if now - getattr(self, "_last_reconfirm", 0.0) < 1.0:
            # rate-limit the probe I/O only -- the liveness VIEW must
            # still be recomputed, or an op arriving just after another
            # op's probe round would fail on the stale argument even
            # though that round (or a background reprobe) healed it
            return [s for s in range(self.km)
                    if self._shard_up(acting, s)]
        self._last_reconfirm = now

        async def one(entity):
            try:
                # generous timeout: under host load this process's
                # event loop can stall past a short deadline while the
                # peer is perfectly alive
                await probe(entity, timeout=2.5)
            except TypeError:
                await probe(entity)
            except (OSError, asyncio.TimeoutError):
                pass

        await asyncio.gather(*(
            one(f"osd.{acting[s]}") for s in range(self.km)
            if s not in up_shards and acting[s] is not None
        ))
        return [s for s in range(self.km) if self._shard_up(acting, s)]

    # -- write path --------------------------------------------------------

    async def dispatch(self, src: str, msg) -> None:
        if isinstance(msg, dict):
            op = msg.get("op")
            if op in ("meta_get_reply", "meta_apply_reply",
                      "omap_cas_reply", "watch_reply", "notify_reply",
                      "pg_list_reply", "pg_log_info_reply",
                      "pg_log_entries_reply", "pg_rollback_reply",
                      "obj_versions_reply"):
                state = self._pending.get(msg.get("tid"))
                if state is not None:
                    state["replies"][src] = msg
                    state["outstanding"].discard(src)
                    if not state["outstanding"] and not state["done"].done():
                        state["done"].set_result(True)
                return
            if op == "notify_event":
                from ceph_tpu.osd.objecter import deliver_notify_event

                deliver_notify_event(
                    self.messenger, self.name, self._watch_callbacks,
                    src, msg,
                )
                return
            # monitor traffic (command replies, osdmap broadcasts)
            hook = getattr(self, "mon_hook", None)
            if hook is not None:
                await hook(msg)
            return
        if isinstance(msg, ECSubWriteReply):
            state = self._pending.get(msg.tid)
            if state is None:
                return
            if msg.missed:
                # the shard skipped an incremental write (missed base):
                # degrade the fan-out as if it were down — it must not
                # count toward the quorum, and _await_commits verifies
                # enough real appliers remain
                state["expected"].discard(src)
                if (
                    state["committed"] >= state["expected"]
                    and not state["done"].done()
                ):
                    state["done"].set_result(True)
                return
            if not msg.committed and msg.current_version is not None:
                # stale-write refusal: a racing primary won this object.
                # Fail the op now so the writer retries at a higher
                # version; waiting out the commit quorum would hang.
                if not state["done"].done():
                    state["done"].set_exception(
                        WriteConflict(vt(msg.current_version))
                    )
                return
            if msg.committed:
                state["committed"].add(src)
            if state["committed"] >= state["expected"]:
                if not state["done"].done():
                    state["done"].set_result(True)
        elif isinstance(msg, ECSubReadReply):
            state = self._pending.get(msg.tid)
            if state is None:
                return
            state["replies"][msg.from_shard] = msg
            state["outstanding"].discard(msg.from_shard)
            if not state["outstanding"] and not state["done"].done():
                state["done"].set_result(True)

    def _new_tid(self) -> int:
        if self._tid_alloc is not None:
            return self._tid_alloc()
        self._tid += 1
        return self._tid

    @asynccontextmanager
    async def _object_lock(self, oid: str):
        """Acquire the per-object write mutex; the entry is dropped once
        no writer holds or waits for it (bounded state, verdict #10).
        With the ``lockdep`` option on, acquisition order is tracked per
        lock class ("object:head" vs "object:clone" -- the legitimate
        nesting direction) and cycles raise before they can deadlock."""
        lock = self._oid_locks.get(oid)
        if lock is None:
            from ceph_tpu.utils import lockdep

            if lockdep.enabled():
                cls = "object:clone" if "~" in oid else "object:head"
                lock = self._oid_locks[oid] = lockdep.TrackedLock(cls)
            else:
                lock = self._oid_locks[oid] = asyncio.Lock()
        self._oid_lock_refs[oid] = self._oid_lock_refs.get(oid, 0) + 1
        try:
            async with lock:
                yield
        finally:
            refs = self._oid_lock_refs[oid] - 1
            if refs:
                self._oid_lock_refs[oid] = refs
            else:
                del self._oid_lock_refs[oid]
                self._oid_locks.pop(oid, None)

    #: bound on the per-object version cache; evicted oids are relearned
    #: from shard attrs by _stat on the next write
    _VERSION_CACHE_MAX = 8192

    def _next_version(self, oid: str) -> tuple:
        """pg-wide dense version counter + this primary's name: the
        eversion analogue with a writer tiebreak (see vt())."""
        self._version_head += 1
        self._versions[oid] = self._version_head
        self._versions.move_to_end(oid)
        while len(self._versions) > self._VERSION_CACHE_MAX:
            self._versions.popitem(last=False)
        return (self._version_head, self.name)

    def _learn_version(self, oid: str, seen: tuple) -> None:
        if seen[0] > self._versions.get(oid, 0):
            self._versions[oid] = seen[0]
            self._versions.move_to_end(oid)
            # the read/stat path inserts here too: enforce the cap on
            # every insert, not just on writes
            while len(self._versions) > self._VERSION_CACHE_MAX:
                self._versions.popitem(last=False)
        if seen[0] > self._version_head:
            self._version_head = seen[0]

    async def write(self, oid: str, data: bytes, snapc=None) -> None:
        """Append-only full-object write (create or replace).

        ``snapc`` = {"seq": int, "snaps": [ids]} (librados SnapContext):
        when seq is newer than the object's SnapSet seq, the current head
        is cloned shard-by-shard in the SAME transaction before the new
        bytes land (PrimaryLogPG::make_writeable).

        A WriteConflict (a shard refused the version as stale) propagates
        to the caller: with the primary hosted in the OSD, one primary
        serializes each PG, so a conflict means this engine's version
        view was cold (e.g. the op was routed here right after failover).
        The Objecter retries once after the refusal teaches this primary
        the winning version -- the round-2 4-attempt race loop is gone
        with the architecture that made it necessary."""
        # serialize writes per object (in-order pipeline) and conflict with
        # any in-flight RMW on the object via the whole-object pin
        async with self._object_lock(oid):
            async with self.extent_cache.pin(oid, 0, 1 << 62):
                try:
                    await self._write_pinned(oid, data, snapc)
                except WriteConflict as wc:
                    # adopt the winning version so a retry lands on top
                    self._learn_version(oid, wc.winner)
                    self.perf.inc("write_conflict")
                    raise
                finally:
                    # invalidate even on a partial/failed replace: some
                    # shards may have applied, so cached pre-replace
                    # bytes are stale
                    self.extent_cache.invalidate(oid)

    async def _write_pinned(self, oid: str, data: bytes,
                            snapc=None) -> None:
        # a primary that has never touched this object must learn its
        # current version first: overwriting with a regressed version
        # would be refused by the shards' stale-write gate
        if oid not in self._versions or (
            snapc and oid not in self._snapsets
        ):
            await self._stat(oid)
        snapset, clone_id = self._snap_prepare(oid, snapc)
        version = self._next_version(oid)
        logical = len(data)
        padded_len = self.sinfo.logical_to_next_stripe_offset(logical)
        buf = np.zeros(padded_len, dtype=np.uint8)
        buf[:logical] = np.frombuffer(data, dtype=np.uint8)

        from ceph_tpu.utils import trace

        span = trace.new_trace("ec write")
        span.event("start_rmw")
        if padded_len:
            encoded = ecutil.encode(self.sinfo, self.ec, buf, range(self.km))
        else:
            # zero-byte object (S3 markers, touch): no stripes to encode
            encoded = [np.zeros(0, dtype=np.uint8) for _ in range(self.km)]
        span.event("encoded")
        hinfo = ecutil.HashInfo(self.km)
        if padded_len:
            hinfo.append(0, encoded)

        acting = self.acting_set(oid)
        up = [
            s
            for s in range(self.km)
            if self._shard_up(acting, s)
        ]
        # min_size: an EC pool needs at least k live shards to accept writes
        if len(up) < self.k:
            up = await self._reconfirm_up(acting, up)  # stale liveness?
        if len(up) < self.k:
            raise IOError(f"cannot write {oid}: only {len(up)} shards up")
        placed = [s for s in range(self.km) if acting[s] is not None]
        if len(up) < len(placed):
            # writing degraded: the down holders miss this version
            self._dirty.add(oid)
        tid = self._new_tid()
        done = asyncio.get_event_loop().create_future()
        self._pending[tid] = {
            "committed": set(),
            "expected": {f"osd.{acting[s]}" for s in up},
            "done": done,
        }
        entry = LogEntry(version=version[0], oid=oid, op="append",
                         prior_size=0)
        self.log.append(entry)
        for s in range(self.km):
            if acting[s] is None:
                continue  # CRUSH hole: no device for this position
            soid = shard_oid(oid, s)
            txn = Transaction()
            if clone_id is not None:
                txn.clone(soid, shard_oid(snap_oid(oid, clone_id), s))
            txn = (
                txn
                .write(soid, 0, encoded[s].tobytes())
                .truncate(soid, len(encoded[s]))
                .setattr(soid, ecutil.HINFO_KEY, hinfo.to_dict())
                .setattr(soid, SIZE_KEY, logical)
                .setattr(soid, VERSION_KEY, version)
            )
            txn.setattr(soid, WHITEOUT_KEY, None)
            if snapset is not None:
                txn.setattr(soid, SNAPSET_KEY, snapset)
            sub = ECSubWrite(
                from_shard=s,
                tid=tid,
                oid=oid,
                transaction=txn,
                at_version=version,
                log_entries=[entry],
            )
            with span.child("ec sub write") as sub_span:
                sub_span.event(f"shard {s} -> osd.{acting[s]}")
                await self.messenger.send_message(
                    self.name, f"osd.{acting[s]}", sub
                )
        self.perf.inc("write")
        try:
            await self._await_commits(oid, tid, done, min_acks=self.k)
            span.event("all_commit")
            self._snap_committed(oid, snapset, logical)
        finally:
            span.finish()

    async def _await_commits(
        self, oid: str, tid: int, done: "asyncio.Future", min_acks: int
    ) -> None:
        """Wait for the fan-out's commit acks, pruning shards discovered
        dead during the send (e.g. a TCP connect refused) so the op
        completes on the surviving set.  Skipped shards hold stale bytes
        until recovered -- the VERSION_KEY read-time cut keeps them out of
        decodes.  If fewer than ``min_acks`` shard targets survive, the op
        fails.  A write that already fully committed (done resolved) is
        never failed by late deaths.  Shared by every fan-out path (full
        write, RMW write, recovery push)."""
        state = self._pending[tid]
        orig_expected = set(state["expected"])
        try:
            if not done.done():
                state["expected"] = {
                    n for n in state["expected"]
                    if not self.messenger.is_down(n)
                }
                if len(state["expected"]) < min_acks:
                    raise IOError(
                        f"write {oid} lost shards mid-flight: "
                        f"only {len(state['expected'])} up"
                    )
                if state["committed"] >= state["expected"]:
                    done.set_result(True)
            from ceph_tpu.utils.config import get_config as _gc

            await asyncio.wait_for(
                done, timeout=float(_gc().get_val(
                    "osd_client_op_commit_timeout"))
            )
            # shards may have dropped out mid-op (missed-base skips): the
            # write only durably exists if enough shards actually applied
            if len(state["committed"]) < min_acks:
                raise IOError(
                    f"write {oid}: only {len(state['committed'])} shards "
                    f"applied (need {min_acks})"
                )
        finally:
            # pg_missing_t bookkeeping: any fan-out that did not reach its
            # full expected set leaves a shard behind -- remember the
            # object so event-driven peering probes it without a scan
            if state["committed"] != orig_expected:
                self._dirty.add(oid)
            del self._pending[tid]

    # -- read path ---------------------------------------------------------

    async def _read_shards(
        self,
        oid: str,
        shards: List[int],
        acting: List[int],
        extents: Optional[List[Tuple[int, int]]] = None,
        op_class: str = "client",
    ) -> Dict[int, ECSubReadReply]:
        shards = [s for s in shards if acting[s] is not None]
        tid = self._new_tid()
        done = asyncio.get_event_loop().create_future()
        self._pending[tid] = {
            "replies": {},
            "outstanding": set(shards),
            "done": done,
        }
        for s in shards:
            sub = ECSubRead(
                from_shard=s,
                tid=tid,
                to_read={oid: list(extents) if extents else [(0, -1)]},
                attrs_to_read=[oid],
                op_class=op_class,
            )
            await self.messenger.send_message(
                self.name, f"osd.{acting[s]}", sub
            )
        try:
            # config-driven (osd_op_thread_timeout role): 5s starves
            # freshly-revived peers on a contended host and a read that
            # gathers < k shards fails outright -- give stragglers the
            # headroom the client op budget already allows
            from ceph_tpu.utils.config import get_config

            await asyncio.wait_for(done, timeout=float(
                get_config().get_val("osd_read_gather_timeout")))
        except asyncio.TimeoutError:
            pass  # missing shards handled by the caller
        state = self._pending.pop(tid)
        return state["replies"]

    @staticmethod
    def _collect_read(replies, oid, chunks, versions, sizes, failed,
                      hinfos=None) -> None:
        """Merge one _read_shards round into per-shard chunk/version/size
        maps (absent VERSION_KEY decodes as vt(0): pre-versioning or
        never-written objects)."""
        for s, reply in replies.items():
            if oid in reply.errors:
                failed.append(s)
                continue
            bufs = reply.buffers_read.get(oid)
            if bufs:
                chunks[s] = np.frombuffer(bufs[0][1], dtype=np.uint8)
            attrs = reply.attrs_read.get(oid) or {}
            if attrs.get(SIZE_KEY) is not None:
                sizes[s] = attrs[SIZE_KEY]
            if hinfos is not None and attrs.get(ecutil.HINFO_KEY) is not None:
                hinfos[s] = attrs[ecutil.HINFO_KEY]
            versions[s] = vt(attrs.get(VERSION_KEY))

    async def _gather_consistent(
        self, oid, shards, acting, extents=None, op_class="client",
        up_shards=None, allow_incomplete=False,
    ):
        """Version-authoritative gather, shared by read / read_range /
        recovery so the staleness rules cannot diverge between them.

        Round 1 reads data from ``shards`` and, concurrently, version
        attrs from EVERY other up shard -- the minimum data set alone
        cannot establish the authoritative version (it might consist
        entirely of same-version stale shards that missed a degraded
        write).  Versions are tried newest first.  A version that cannot
        assemble k chunks is skipped ONLY if it provably was never acked
        (its up holders plus every unreachable shard still total < k
        commits — a write that died mid-flight below min_size; log
        rollback semantics).  If it MIGHT have been acked, the object is
        reported incomplete instead of silently serving older data — the
        read-after-ack guarantee (the reference's peering would block or
        mark the PG incomplete rather than answer).  Recovery passes
        ``allow_incomplete`` to reconstruct the newest assemblable
        version (its job is exactly to repair such objects).
        Returns (chunks, size_hint, hinfo_hint, version_tuple)."""
        if up_shards is None:
            up_shards = [
                s for s in range(self.km) if self._shard_up(acting, s)
            ]
        chunks: Dict[int, np.ndarray] = {}
        versions: Dict[int, tuple] = {}
        sizes: Dict[int, int] = {}
        hinfos: Dict[int, dict] = {}
        failed: List[int] = []
        others = [s for s in up_shards if s not in shards]
        data_coro = self._read_shards(
            oid, shards, acting, extents=extents, op_class=op_class
        )
        if others:
            attr_coro = self._read_shards(
                oid, others, acting, extents=[(0, 0)], op_class=op_class
            )
            data_replies, attr_replies = await asyncio.gather(
                data_coro, attr_coro
            )
        else:
            data_replies, attr_replies = await data_coro, {}
        self._collect_read(data_replies, oid, chunks, versions, sizes,
                           failed, hinfos)
        # attr-only round: versions/sizes/hinfos, never chunk content
        attr_chunks: Dict[int, np.ndarray] = {}
        self._collect_read(attr_replies, oid, attr_chunks, versions, sizes,
                           failed, hinfos)

        counts: Dict[tuple, int] = {}
        for s, v in versions.items():
            if s not in failed:
                counts[v] = counts.get(v, 0) + 1
        if not counts:
            return {}, None, None, (0, "")
        # shards that might hold a newer version we cannot see: mapped
        # positions whose OSD is down/unreachable, plus shards that
        # errored (their stamp is unknown)
        unseen = sum(
            1 for s in range(self.km)
            if acting[s] is not None and s not in versions
        )

        ordered = sorted(counts, reverse=True)
        last = ordered[-1]
        for target in ordered:
            if counts[target] < self.k and target != last:
                if counts[target] + unseen >= self.k and not allow_incomplete:
                    # might have reached k commits (the missing holders
                    # may be among the unreachable shards): serving an
                    # older version could violate read-after-ack
                    raise ObjectIncomplete(
                        f"{oid}: newest version {target} has only "
                        f"{counts[target]} reachable holders (+{unseen} "
                        f"unreachable); refusing possibly-stale read"
                    )
                # provably never acked (< k commits possible): the write
                # died mid-flight below min_size — roll back to the
                # previous version
                self.perf.inc("rolled_back_version_skipped")
                continue
            holders = [
                s for s in up_shards
                if versions.get(s) == target and s not in failed
            ]
            need = [s for s in holders if s not in chunks]
            if need:
                self.perf.inc("degraded_read")
                more = await self._read_shards(
                    oid, need, acting, extents=extents, op_class=op_class
                )
                self._collect_read(more, oid, chunks, versions, sizes,
                                   failed, hinfos)
            have = {
                s: chunks[s] for s in holders
                if s in chunks and versions.get(s) == target
            }
            if len(have) >= self.k or target == last:
                if len(chunks) != len(have):
                    self.perf.inc("stale_shards_dropped")
                size = next(
                    (sizes[s] for s in holders if sizes.get(s) is not None),
                    None,
                )
                hinfo = next(
                    (hinfos[s] for s in holders if s in hinfos), None
                )
                return have, size, hinfo, target
            if not allow_incomplete:
                # the candidate had >= k stamped holders but fewer than k
                # produced chunks (read failures mid-gather): it may have
                # been acked, so do not fall through to older data
                raise ObjectIncomplete(
                    f"{oid}: version {target} assembled only "
                    f"{len(have)}/{self.k} chunks"
                )
        return {}, None, None, (0, "")  # unreachable: loop always returns

    async def read(self, oid: str) -> bytes:
        """objects_read_and_reconstruct: minimum shards, degraded fallback."""
        acting = self.acting_set(oid)
        up_shards = [
            s
            for s in range(self.km)
            if self._shard_up(acting, s)
        ]
        if len(up_shards) < self.k:
            # don't fail on a possibly-stale liveness view: probe the
            # down-looking holders once (the reference re-peers on
            # heartbeat recovery; a just-revived primary's messenger may
            # carry unreachable marks from boot-time connect races)
            up_shards = await self._reconfirm_up(acting, up_shards)
        want = ecutil.data_positions(self.ec)
        minimum = self.ec.minimum_to_decode(want, up_shards)
        chunks, logical_size, _, _ = await self._gather_consistent(
            oid, sorted(minimum.keys()), acting, up_shards=up_shards
        )
        if len(chunks) < self.k:
            raise IOError(f"cannot read {oid}: only {len(chunks)} shards")
        if logical_size is None:
            raise IOError(f"no size metadata for {oid}")
        data = ecutil.decode_concat(self.sinfo, self.ec, chunks)
        self.perf.inc("read")
        return data[:logical_size]

    # -- partial I/O (ECTransaction write plan + sub-chunk range reads) ----

    async def _stat(self, oid: str) -> Tuple[int, Optional[dict]]:
        """(logical size, hinfo dict) from shard attrs; size 0 if absent.

        Queries every up shard's attrs in one parallel round and answers
        from the highest-versioned reply: a shard that was down during
        writes may hold stale size/hinfo, and planning an RMW from stale
        metadata would corrupt the object.  Also teaches this primary the
        object's current version (``self._versions``) so a fresh client
        process continues the version sequence instead of restarting it
        (which the shards' stale-write gate would silently discard)."""
        acting = self.acting_set(oid)
        up = [
            s
            for s in range(self.km)
            if self._shard_up(acting, s)
        ]
        replies = await self._read_shards(oid, up, acting, extents=[(0, 0)])
        best = None  # (version_tuple, size, hinfo, snapset, whiteout)
        for r in replies.values():
            attrs = r.attrs_read.get(oid) or {}
            if attrs.get(SIZE_KEY) is None:
                continue
            ver = vt(attrs.get(VERSION_KEY))
            if best is None or ver > best[0]:
                best = (ver, attrs[SIZE_KEY], attrs.get(ecutil.HINFO_KEY),
                        attrs.get(SNAPSET_KEY), attrs.get(WHITEOUT_KEY))
        if best is None:
            self._snapsets[oid] = {"seq": 0, "clones": [],
                                   "exists": False, "size": 0}
            return 0, None
        self._learn_version(oid, best[0])
        ss = best[3] or {"seq": 0, "clones": []}
        self._snapsets[oid] = {
            "seq": ss["seq"], "clones": list(ss["clones"]),
            "exists": not best[4], "size": best[1],
        }
        if best[4]:
            return 0, None  # whiteout head: absent to plain stat/readers
        return best[1], best[2]

    async def stat(self, oid: str):
        """Public stat: (logical size, hinfo dict | None) -- the same
        surface the Objecter exposes, so rbd/cls callers work against
        either a local engine or the remote-routed client."""
        return await self._stat(oid)

    async def read_range(self, oid: str, offset: int, length: int) -> bytes:
        """Read only the stripes covering [offset, offset+length)
        (reference: get_write_plan stripe algebra + sub-chunk reads,
        ECBackend.cc:1021-1037 fragmented shard reads)."""
        size, _ = await self._stat(oid)
        if offset >= size:
            return b""
        length = min(length, size - offset)
        cached = self.extent_cache.get(oid, offset, length)
        if cached is not None:
            self.perf.inc("read_cache_hit")
            return cached
        start, span = self.sinfo.offset_len_to_stripe_bounds(offset, length)
        chunk_off = self.sinfo.aligned_logical_offset_to_chunk_offset(start)
        chunk_len = (span // self.sinfo.stripe_width) * self.sinfo.chunk_size

        acting = self.acting_set(oid)
        up = [
            s
            for s in range(self.km)
            if self._shard_up(acting, s)
        ]
        want = ecutil.data_positions(self.ec)
        minimum = self.ec.minimum_to_decode(want, up)
        chunks, _, _, _ = await self._gather_consistent(
            oid, sorted(minimum.keys()), acting,
            extents=[(chunk_off, chunk_len)], up_shards=up,
        )
        if len(chunks) < self.k:
            raise IOError(f"cannot range-read {oid}")
        data = ecutil.decode_concat(self.sinfo, self.ec, chunks)
        lo = offset - start
        self.perf.inc("read_range")
        return data[lo : lo + length]

    async def write_range(self, oid: str, offset: int, data: bytes,
                          snapc=None) -> None:
        """Partial write with RMW (the ECTransaction get_write_plan path).

        Appends extend the cumulative hash info; overwrites clear the chunk
        hashes like the reference's ec_overwrites mode.
        """
        # serialize per object: version-assignment + fan-out + commit wait
        # must not interleave with another write's (in-order pipeline)
        async with self._object_lock(oid):
            # pin the write span: publishes committed bytes for read-through
            lo_pin, _ = self.sinfo.offset_len_to_stripe_bounds(
                offset, max(1, len(data))
            )
            hi_pin = self.sinfo.logical_to_next_stripe_offset(offset + len(data))
            async with self.extent_cache.pin(oid, lo_pin, hi_pin) as pin:
                try:
                    await self._write_range_pinned(
                        oid, offset, data, pin, snapc
                    )
                except WriteConflict as wc:
                    # this primary's version view was cold (see write());
                    # learn the winner so the Objecter-level retry replays
                    # the WHOLE RMW (re-stat, re-read, re-merge) on top
                    self._learn_version(oid, wc.winner)
                    self.extent_cache.invalidate(oid)
                    self.perf.inc("write_conflict")
                    raise
                except Exception:
                    # a partially-acked write leaves shard state ahead
                    # of the cache: cached pre-write bytes would serve
                    # stale reads
                    self.extent_cache.invalidate(oid)
                    raise

    async def _write_range_pinned(
        self, oid: str, offset: int, data: bytes, pin, snapc=None
    ) -> None:
        from ceph_tpu.osd.ectransaction import get_write_plan

        size, hinfo_d = await self._stat(oid)
        snapset, clone_id = self._snap_prepare(oid, snapc)
        # the version counter this RMW is computed on top of: shards not
        # on this base missed history and must skip the extent write
        base_version = self._versions.get(oid, 0)
        plan = get_write_plan(self.sinfo, size, offset, len(data))
        start, span = plan.will_write

        buf = np.zeros(span, dtype=np.uint8)
        if plan.to_read is not None:
            r_off, r_len = plan.to_read
            old = await self.read_range(oid, r_off, r_len)
            buf[r_off - start : r_off - start + len(old)] = np.frombuffer(
                old, dtype=np.uint8
            )
        buf[offset - start : offset - start + len(data)] = np.frombuffer(
            data, dtype=np.uint8
        )

        encoded = ecutil.encode(self.sinfo, self.ec, buf, range(self.km))
        chunk_off = self.sinfo.aligned_logical_offset_to_chunk_offset(start)

        if plan.is_append and hinfo_d is not None and chunk_off == (
            ecutil.HashInfo.from_dict(hinfo_d).get_total_chunk_size()
        ):
            hinfo = ecutil.HashInfo.from_dict(hinfo_d)
            hinfo.append(chunk_off, encoded)
        elif plan.is_append and hinfo_d is None and chunk_off == 0:
            hinfo = ecutil.HashInfo(self.km)
            hinfo.append(0, encoded)
        else:
            # overwrite: sizes only, hashes cleared (ec_overwrites semantics)
            hinfo = ecutil.HashInfo(0)
            hinfo.total_chunk_size = max(
                chunk_off + len(encoded[0]),
                ecutil.HashInfo.from_dict(hinfo_d).get_total_chunk_size()
                if hinfo_d
                else 0,
            )

        version = self._next_version(oid)
        acting = self.acting_set(oid)
        up = [
            s
            for s in range(self.km)
            if self._shard_up(acting, s)
        ]
        if len(up) < self.k:
            up = await self._reconfirm_up(acting, up)  # stale liveness?
        if len(up) < self.k:
            raise IOError(f"cannot write {oid}: only {len(up)} shards up")
        if len(up) < len([s for s in range(self.km) if acting[s] is not None]):
            self._dirty.add(oid)  # down holders miss this version
        tid = self._new_tid()
        done = asyncio.get_event_loop().create_future()
        self._pending[tid] = {
            "committed": set(),
            "expected": {f"osd.{acting[s]}" for s in up},
            "done": done,
        }
        entry = LogEntry(version=version[0], oid=oid, op="append",
                         prior_size=size)
        self.log.append(entry)
        for s in range(self.km):
            soid = shard_oid(oid, s)
            txn = Transaction()
            if clone_id is not None:
                txn.clone(soid, shard_oid(snap_oid(oid, clone_id), s))
            txn = (
                txn
                .write(soid, chunk_off, encoded[s].tobytes())
                .setattr(soid, ecutil.HINFO_KEY, hinfo.to_dict())
                .setattr(soid, SIZE_KEY, plan.new_size)
                .setattr(soid, VERSION_KEY, version)
                .setattr(soid, WHITEOUT_KEY, None)
            )
            if snapset is not None:
                txn.setattr(soid, SNAPSET_KEY, snapset)
            sub = ECSubWrite(
                from_shard=s, tid=tid, oid=oid, transaction=txn,
                at_version=version, log_entries=[entry],
                prev_version=base_version,
            )
            await self.messenger.send_message(
                self.name, f"osd.{acting[s]}", sub
            )
        self.perf.inc("write_range")
        await self._await_commits(oid, tid, done, min_acks=self.k)
        self._snap_committed(oid, snapset, plan.new_size)
        # publish committed bytes for read-through (padding included: those
        # bytes are logically zero up to new_size and real data below it)
        pin.commit(start, buf.tobytes())

    async def remove_object(self, oid: str, snapc=None) -> None:
        """Delete every shard of an object (librados remove role).

        Under a snap context newer than the SnapSet seq the head is
        cloned first and then WHITEOUT'd (truncated to zero with the
        whiteout attr) instead of removed, so snap reads keep resolving
        through the head's SnapSet -- the reference's snapdir object.
        The whiteout disappears when snap_trim drops the last clone."""
        async with self._object_lock(oid):
            await self._remove_object_locked(oid, snapc)

    async def _remove_object_locked(self, oid: str, snapc=None) -> None:
        acting = self.acting_set(oid)
        up = [s for s in range(self.km) if self._shard_up(acting, s)]
        if not up:
            raise IOError(f"cannot remove {oid}: no shards up")
        if len(up) < len([s for s in range(self.km) if acting[s] is not None]):
            self._dirty.add(oid)  # down holders keep a doomed copy
        if oid not in self._versions or (
            snapc and oid not in self._snapsets
        ):
            await self._stat(oid)
        snapset, clone_id = self._snap_prepare(oid, snapc)
        if clone_id is not None:
            # snap-preserving delete: clone + whiteout in one transaction
            if len(up) < self.k:
                raise IOError(f"cannot remove {oid}: only {len(up)} up")
            version = self._next_version(oid)
            tid = self._new_tid()
            done = asyncio.get_event_loop().create_future()
            self._pending[tid] = {
                "committed": set(),
                "expected": {f"osd.{acting[s]}" for s in up},
                "done": done,
            }
            for s in up:
                soid = shard_oid(oid, s)
                txn = (
                    Transaction()
                    .clone(soid, shard_oid(snap_oid(oid, clone_id), s))
                    .truncate(soid, 0)
                    .setattr(soid, SIZE_KEY, 0)
                    .setattr(soid, VERSION_KEY, version)
                    .setattr(soid, WHITEOUT_KEY, True)
                    .setattr(soid, SNAPSET_KEY, snapset)
                )
                await self.messenger.send_message(
                    self.name, f"osd.{acting[s]}",
                    ECSubWrite(from_shard=s, tid=tid, oid=oid,
                               transaction=txn, at_version=version),
                )
            await self._await_commits(oid, tid, done, min_acks=self.k)
            self._snap_committed(oid, snapset, 0, exists=False)
            self.extent_cache.invalidate(oid)
            return
        self._snapsets.pop(oid, None)
        # tombstone the meta twin BEFORE destroying data: if the
        # tombstone cannot land anywhere the remove fails cleanly with
        # the object intact, instead of leaving deleted data whose
        # stale omap resurrects at the next recovery pass (the
        # reference orders its delete the same way: the PG-log entry
        # is durable before the objects go)
        await self._meta_remove(oid)
        version = self._next_version(oid)
        tid = self._new_tid()
        done = asyncio.get_event_loop().create_future()
        self._pending[tid] = {
            "committed": set(),
            "expected": {f"osd.{acting[s]}" for s in up},
            "done": done,
        }
        for s in up:
            await self.messenger.send_message(
                self.name, f"osd.{acting[s]}",
                ECSubWrite(
                    from_shard=s, tid=tid, oid=oid,
                    transaction=Transaction().remove(shard_oid(oid, s)),
                    at_version=version,
                ),
            )
        # resurrection guard: a removal acked by fewer than m+1 shards
        # could leave >= k same-version chunks on revived OSDs, making a
        # "removed" object readable again.  m+1 deletions cap survivors
        # at k-1 (the reference gets this from PG-log replay at peering).
        await self._await_commits(oid, tid, done, min_acks=self.m + 1)
        self.extent_cache.invalidate(oid)

    # -- metadata plane: replicated omap / CAS / watch-notify / cls --------
    #
    # The reference keeps object metadata (cls state, rbd headers, locks)
    # in omap on replicated pools and runs cls methods + watch/notify on
    # the primary OSD.  Here the metadata object "<oid>@meta" is fully
    # replicated to every up shard OSD (metadata is small; survival under
    # any k-available scenario matters more than space), versioned on its
    # own sequence; the acting[0] OSD is the atomicity (CAS) and
    # watch/notify authority.

    def _meta_targets(self, oid: str, mark_dirty: bool = False):
        acting = self.acting_set(oid)
        up = [
            f"osd.{acting[s]}"
            for s in range(self.km)
            if self._shard_up(acting, s)
        ]
        if not up:
            raise IOError(f"no up OSDs for {oid} metadata")
        if mark_dirty and len(up) < len(
            [s for s in range(self.km) if acting[s] is not None]
        ):
            self._dirty_meta.add(oid)  # down replicas miss this version
        return up

    async def _meta_roundtrip(self, targets, payload: dict,
                              timeout: float = 5.0) -> Dict[str, dict]:
        """Send one dict op to each target, gather replies by sender."""
        tid = self._new_tid()
        done = asyncio.get_event_loop().create_future()
        self._pending[tid] = {
            "replies": {}, "outstanding": set(targets), "done": done,
        }
        for t in targets:
            await self.messenger.send_message(
                self.name, t, dict(payload, tid=tid)
            )
        try:
            await asyncio.wait_for(done, timeout=timeout)
        except asyncio.TimeoutError:
            pass
        state = self._pending.pop(tid)
        return state["replies"]

    async def _meta_read_full(self, oid: str):
        """(omap, version, removed) of the highest-versioned replica
        (+ learn the version).  A removed tombstone reads as empty."""
        targets = self._meta_targets(oid)
        replies = await self._meta_roundtrip(
            targets, {"op": "meta_get", "oid": oid}
        )
        best_ver, best, removed = 0, None, False
        for r in replies.values():
            if r.get("omap") is not None and r["version"] >= best_ver:
                best_ver, best = r["version"], r["omap"]
                removed = bool(r.get("removed"))
        if best_ver > self._meta_versions.get(oid, 0):
            self._meta_versions[oid] = best_ver
        if removed or best is None:
            return {}, best_ver, removed
        return best, best_ver, removed

    async def _meta_read(self, oid: str) -> Dict[str, bytes]:
        omap, _ver, _removed = await self._meta_read_full(oid)
        return omap

    async def _meta_write(self, oid: str, sets=None, rms=None,
                          clear=False) -> None:
        """Read-modify-write of the FULL replicated omap.  Full-state
        replication lets a replica that missed versions converge in one
        step; concurrent plain writers are last-writer-wins (atomic
        read-modify-write goes through omap_cas / cls methods, as in the
        reference)."""
        targets = self._meta_targets(oid, mark_dirty=True)
        omap = {} if clear else await self._meta_read(oid)
        if rms:
            for k in rms:
                omap.pop(k, None)
        if sets:
            omap.update(sets)
        ver = self._meta_versions.get(oid, 0) + 1
        self._meta_versions[oid] = ver
        replies = await self._meta_roundtrip(targets, {
            "op": "meta_apply", "oid": oid, "version": ver, "omap": omap,
        })
        if not replies:
            raise IOError(f"metadata write for {oid} reached no OSD")
        if len(replies) < len(targets):
            self._dirty_meta.add(oid)  # a replica missed this version

    #: tombstones jump a whole version GENERATION: a down replica whose
    #: solo-acked writes put it a few versions ahead of what the remover
    #: could read must still lose to the tombstone under highest-version
    #: recovery.  Packing the generation into the integer keeps every
    #: existing comparison (peering tuples included) working unchanged.
    TOMBSTONE_GEN = 1 << 32

    async def _meta_remove(self, oid: str) -> None:
        """Tombstone the meta twin on every replica (object removal).
        Versioned like any meta write so a replica that missed it is
        repaired by highest-version-wins recovery -- towards the
        tombstone, never back to the deleted keys."""
        targets = self._meta_targets(oid, mark_dirty=True)
        await self._meta_read(oid)  # learn the current version
        ver = self._meta_versions.get(oid, 0) + self.TOMBSTONE_GEN
        self._meta_versions[oid] = ver
        replies = await self._meta_roundtrip(targets, {
            "op": "meta_apply", "oid": oid, "version": ver,
            "remove": True, "omap": {},
        })
        if not replies:
            raise IOError(f"metadata remove for {oid} reached no OSD")
        if len(replies) < len(targets):
            self._dirty_meta.add(oid)  # a replica missed the tombstone

    async def omap_set(self, oid: str, kvs: Dict[str, bytes]) -> None:
        await self._meta_write(oid, sets=dict(kvs))

    async def omap_rm(self, oid: str, keys) -> None:
        await self._meta_write(oid, rms=list(keys))

    async def omap_clear(self, oid: str) -> None:
        await self._meta_write(oid, clear=True)

    async def omap_get(self, oid: str, keys=None) -> Dict[str, bytes]:
        omap = await self._meta_read(oid)
        if keys is None:
            return omap
        return {k: omap[k] for k in keys if k in omap}

    async def omap_cas(self, oid: str, key: str, expect, new):
        """Atomic compare-and-swap on the primary-shard OSD, then
        replicate the outcome to the remaining replicas."""
        acting = self.acting_set(oid)
        primary = None
        for s in range(self.km):
            if self._shard_up(acting, s):
                primary = f"osd.{acting[s]}"
                break
        if primary is None:
            raise IOError(f"no up OSDs for {oid} CAS")
        replies = await self._meta_roundtrip(
            [primary],
            {"op": "omap_cas", "oid": oid, "key": key,
             "expect": expect, "new": new},
        )
        r = replies.get(primary)
        if r is None:
            raise IOError(f"CAS on {oid} got no reply from {primary}")
        if r["success"]:
            # propagate the authority's full state to the other replicas
            self._meta_versions[oid] = r["version"]
            others = [t for t in self._meta_targets(oid) if t != primary]
            if others:
                await self._meta_roundtrip(others, {
                    "op": "meta_apply", "oid": oid,
                    "version": r["version"], "omap": r["omap"],
                })
        return r["success"], r["current"]

    async def watch(self, oid: str, callback=None, watcher: str = None) -> None:
        """Register for notify events on oid (librados watch role).

        ``watcher`` names the entity that receives notify events; when a
        client routes its watch through the primary OSD (the reference
        path), it is the *client's* messenger name and events go to it
        directly, bypassing this engine."""
        targets = self._meta_targets(oid)[:1]
        watcher = watcher or self.name
        if watcher == self.name:
            self._watch_callbacks[oid] = callback
        replies = await self._meta_roundtrip(
            targets, {"op": "watch", "oid": oid, "watcher": watcher}
        )
        if not replies:
            self._watch_callbacks.pop(oid, None)
            raise IOError(f"watch {oid}: no reply")

    async def unwatch(self, oid: str, watcher: str = None) -> None:
        targets = self._meta_targets(oid)[:1]
        watcher = watcher or self.name
        if watcher == self.name:
            self._watch_callbacks.pop(oid, None)
        await self._meta_roundtrip(
            targets, {"op": "unwatch", "oid": oid, "watcher": watcher}
        )

    async def notify(self, oid: str, payload=None, timeout: float = 5.0):
        """Notify every watcher; returns {"acks": [...], "timeouts": [...]}
        once all ack or the timeout passes (librados notify role)."""
        targets = self._meta_targets(oid)[:1]
        replies = await self._meta_roundtrip(
            targets,
            {"op": "notify", "oid": oid, "payload": payload,
             "timeout": timeout},
            # the OSD gathers watcher acks for up to ``timeout`` before it
            # replies; give the round-trip headroom past that
            timeout=timeout + 2.0,
        )
        for r in replies.values():
            return {"acks": r["acks"], "timeouts": r["timeouts"]}
        raise IOError(f"notify {oid}: no reply")

    async def exec(self, oid: str, cls: str, method: str, inp: bytes = b""):
        """Run a server-side object class method (cls exec role).

        The reference dlopens cls plugins on the OSD (ClassHandler); our
        primary engine hosts the class registry and methods run against
        this backend's object surface, with omap_cas as the atomicity
        primitive where a method needs read-modify-write."""
        from ceph_tpu.cls import call_method

        return await call_method(self, oid, cls, method, inp)

    # -- snapshots (SnapMapper / make_writeable roles) ---------------------

    def _snap_prepare(self, oid: str, snapc):
        """(new snapset attr value, clone id) for a write under ``snapc``;
        (None, None) when no snap context.  Must run after _stat primed
        the SnapSet cache.  Reference: PrimaryLogPG::make_writeable."""
        if not snapc:
            return None, None
        cur = self._snapsets.get(oid) or {
            "seq": 0, "clones": [], "exists": False, "size": 0
        }
        snapset = {"seq": max(cur["seq"], snapc["seq"]),
                   "clones": list(cur["clones"])}
        clone_id = None
        if cur.get("exists") and snapc["seq"] > cur["seq"]:
            clone_id = snapc["seq"]
            snapset["clones"].append(
                {"id": clone_id, "size": cur.get("size", 0)}
            )
        return snapset, clone_id

    def _snap_committed(self, oid: str, snapset, new_size: int,
                        exists: bool = True) -> None:
        """Update the SnapSet cache after a committed snap-context op."""
        if snapset is None:
            ent = self._snapsets.get(oid)
            if ent is not None:
                ent["exists"] = exists
                ent["size"] = new_size
            return
        self._snapsets[oid] = {
            "seq": snapset["seq"], "clones": list(snapset["clones"]),
            "exists": exists, "size": new_size,
        }

    async def resolve_snap(self, oid: str, snap: int) -> str:
        """Object name serving reads at snap id ``snap``: the oldest clone
        whose id >= snap, else the head (librados snap read resolution,
        SnapSet::get_clone_bytes / PrimaryLogPG::find_object_context)."""
        if oid not in self._snapsets:
            await self._stat(oid)
        ss = self._snapsets.get(oid)
        if not ss or not ss["clones"]:
            return oid
        cands = sorted(c["id"] for c in ss["clones"] if c["id"] >= snap)
        return snap_oid(oid, cands[0]) if cands else oid

    async def list_snaps(self, oid: str) -> dict:
        """The object's SnapSet (rados listsnaps role)."""
        await self._stat(oid)  # refresh
        ss = self._snapsets.get(oid) or {"seq": 0, "clones": [],
                                         "exists": False}
        return {"seq": ss["seq"], "clones": list(ss["clones"]),
                "head_exists": bool(ss.get("exists"))}

    async def snap_rollback(self, oid: str, snap: int, snapc=None) -> None:
        """Restore the head to its state at ``snap`` (librados
        selfmanaged_snap_rollback; reference PrimaryLogPG::_rollback_to).
        Implemented as read-at-snap + write-as-new-version, so the
        rollback itself is snapshotted under ``snapc`` like any write."""
        src = await self.resolve_snap(oid, snap)
        if src == oid:
            return  # head already is the snap state
        data = await self.read(src)
        await self.write(oid, data, snapc=snapc)

    async def snap_trim(self, oid: str, live_snaps) -> int:
        """Drop clones no longer needed by any live snap (SnapMapper +
        snap trim role).  A clone with id C covers snaps in
        (previous clone id, C]; when none of those are alive the clone is
        removed and the head's SnapSet shrinks.  A whiteout head whose
        last clone goes is removed outright.  Returns clones dropped."""
        await self._stat(oid)
        cur = self._snapsets.get(oid)
        if not cur or not cur["clones"]:
            return 0
        live = sorted(live_snaps)
        keep, drop = [], []
        prev = 0
        for c in sorted(cur["clones"], key=lambda c: c["id"]):
            if any(prev < sn <= c["id"] for sn in live):
                keep.append(c)
            else:
                drop.append(c)
            prev = c["id"]
        if not drop:
            return 0
        # the whole read-modify-write of the SnapSet runs under the head's
        # object lock so a concurrent snap-context write cannot append a
        # clone entry that the stale stamp below would erase
        async with self._object_lock(oid):
            cur = self._snapsets.get(oid) or cur  # re-read under the lock
            keep = [c for c in cur["clones"]
                    if not any(d["id"] == c["id"] for d in drop)]
            for c in drop:
                try:
                    await self.remove_object(snap_oid(oid, c["id"]))
                except IOError:
                    pass  # already gone; peering will converge
            self.perf.inc("snap_trim", len(drop))
            if not keep and not cur.get("exists"):
                # whiteout head, no clones left: the object is fully dead
                await self._remove_object_locked(oid)
                self._snapsets.pop(oid, None)
                return len(drop)
            new_ss = {"seq": cur["seq"], "clones": keep}
            await self._set_snapset_locked(oid, new_ss)
        return len(drop)

    async def _set_snapset_locked(self, oid: str, snapset: dict) -> None:
        """Attr-only fan-out updating the head's SnapSet (version-stamped
        so the stale gates order it like any write).  Caller holds the
        object lock."""
        acting = self.acting_set(oid)
        up = [s for s in range(self.km) if self._shard_up(acting, s)]
        if len(up) < self.k:
            raise IOError(f"cannot update snapset of {oid}")
        version = self._next_version(oid)
        tid = self._new_tid()
        done = asyncio.get_event_loop().create_future()
        self._pending[tid] = {
            "committed": set(),
            "expected": {f"osd.{acting[s]}" for s in up},
            "done": done,
        }
        for s in up:
            soid = shard_oid(oid, s)
            txn = (
                Transaction()
                .setattr(soid, SNAPSET_KEY, snapset)
                .setattr(soid, VERSION_KEY, version)
            )
            await self.messenger.send_message(
                self.name, f"osd.{acting[s]}",
                ECSubWrite(from_shard=s, tid=tid, oid=oid,
                           transaction=txn, at_version=version),
            )
        await self._await_commits(oid, tid, done, min_acks=self.k)
        ent = self._snapsets.get(oid)
        if ent is not None:
            ent["seq"] = snapset["seq"]
            ent["clones"] = list(snapset["clones"])

    # -- scrub -------------------------------------------------------------

    async def deep_scrub(self, oid: str) -> dict:
        """Read every shard, verify per-shard crc32c and parity consistency
        (re-encode data shards and compare coding) -- the EC deep-scrub role
        (reference: PG scrub + ECBackend crc checks; inconsistency report
        shape follows ScrubStore's per-object errors)."""
        acting = self.acting_set(oid)
        up = [
            s
            for s in range(self.km)
            if self._shard_up(acting, s)
        ]
        replies = await self._read_shards(oid, up, acting, op_class="scrub")
        report = {
            "oid": oid,
            "crc_errors": [],
            "missing": [],
            "parity_mismatch": [],
            "ok": True,
        }
        chunks: Dict[int, np.ndarray] = {}
        seen_versions = set()
        for s in up:
            reply = replies.get(s)
            if reply is None or oid in (reply.errors if reply else {}):
                (report["crc_errors"] if reply else report["missing"]).append(s)
                continue
            attrs = reply.attrs_read.get(oid) or {}
            seen_versions.add(vt(attrs.get(VERSION_KEY)))
            bufs = reply.buffers_read.get(oid)
            if bufs:
                chunks[s] = np.frombuffer(bufs[0][1], dtype=np.uint8)
            else:
                report["missing"].append(s)
        if len(seen_versions) > 1:
            # mixed versions: an in-flight write or a stale shard --
            # that is peering's jurisdiction, not a scrub inconsistency;
            # report clean-with-deferral instead of a false parity error
            # (the reference scrubber blocks on in-progress writes)
            self.perf.inc("scrub_deferred")
            report["deferred"] = True
            self.scrub_errors.pop(oid, None)
            return report
        dpos = ecutil.data_positions(self.ec)
        if all(p in chunks for p in dpos):
            data = np.stack([chunks[p] for p in dpos])
            fresh = self.ec.encode(set(range(self.km)), data.reshape(-1))
            for s in range(self.km):
                if s in dpos:
                    continue
                if s in chunks and not np.array_equal(fresh[s], chunks[s]):
                    report["parity_mismatch"].append(s)
        report["ok"] = not (
            report["crc_errors"] or report["missing"] or report["parity_mismatch"]
        )
        if report["ok"]:
            self.scrub_errors.pop(oid, None)
        else:
            self.scrub_errors[oid] = report
            self.perf.inc("scrub_inconsistent")
        self.perf.inc("deep_scrub")
        return report

    async def scrub_repair(self, oid: str, report: dict) -> int:
        """Repair every shard a deep scrub flagged (crc error / missing /
        parity mismatch) by reconstructing it from the consistent set and
        pushing it back -- the scrub-driven auto-repair loop (reference:
        PG repair + qa/standalone/erasure-code/test-erasure-eio.sh)."""
        acting = self.acting_set(oid)
        bad = sorted(
            set(report["crc_errors"]) | set(report["missing"])
            | set(report["parity_mismatch"])
        )
        repaired = 0
        for s in bad:
            if not self._shard_up(acting, s):
                continue
            try:
                await self.recover_shard(oid, s, acting[s], rollback=True)
                repaired += 1
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 -- a failed repair stays in
                # scrub_errors/_dirty; the next scrub or peering retries
                self.perf.inc("scrub_repair_failed")
                self._dirty.add(oid)
        if repaired:
            self.perf.inc("scrub_repair", repaired)
            # confirm: a clean re-scrub clears the error record
            report2 = await self.deep_scrub(oid)
            if report2["ok"]:
                self.scrub_errors.pop(oid, None)
        return repaired

    # -- recovery ----------------------------------------------------------

    async def recover_shard(
        self, oid: str, shard: int, target_osd: int, rollback: bool = False
    ) -> None:
        """Reconstruct one lost/stale shard and push it to the target OSD
        in bounded windows (the READING->WRITING recovery state machine,
        ECBackend.h:256-300, chunked like get_recovery_chunk_size :213 so
        a 64 MiB object never needs 64 MiB of primary memory).  A client
        write landing mid-recovery changes the object version; that is
        detected at the next window's gather and the recovery restarts.
        ``rollback=True`` lets the final stamp overwrite a torn
        higher-versioned copy (peering's divergent-entry rollback).

        The whole recovery holds the object's write lock, so client
        writes to a HOT object queue briefly behind the push instead of
        restarting it forever (the reference pins the object context for
        the duration of the push, src/osd/ECBackend.cc:535-700).  The
        version-moved restart loop remains as a safety net for writes
        from a racing primary, which does not share this lock."""
        from ceph_tpu.utils.config import get_config

        window = max(1, int(get_config().get_val("osd_recovery_max_chunk")))
        async with self._object_lock(oid):
            for attempt in range(3):
                if await self._recover_shard_once(
                    oid, shard, target_osd, window, rollback
                ):
                    self.perf.inc("recover")
                    return
                self.perf.inc("recover_restart")
        raise IOError(
            f"recovery of {oid}@{shard} kept losing to concurrent writes"
        )

    async def _recover_shard_once(
        self, oid: str, shard: int, target_osd: int, window: int,
        rollback: bool,
    ) -> bool:
        """One windowed recovery attempt; False = restart (the object's
        version moved under us)."""
        acting = self.acting_set(oid)
        up_shards = [
            s
            for s in range(self.km)
            if s != shard
            and self._shard_up(acting, s)
        ]
        minimum = self.ec.minimum_to_decode([shard], up_shards)
        src = sorted(minimum.keys())
        cs = self.sinfo.chunk_size
        # per-source-chunk bytes per round, whole per-stripe chunks only
        # (a stripe decodes independently for every technique)
        win = max(cs, (window // self.k) // cs * cs)
        chunks, logical_size, hinfo_d, vmax = await self._gather_consistent(
            oid, src, acting, extents=[(0, win)], op_class="recovery",
            up_shards=up_shards, allow_incomplete=True,
        )
        if len(chunks) < self.k:
            raise IOError(f"cannot recover {oid}@{shard}: too few sources")
        if logical_size is None:
            raise IOError(f"cannot recover {oid}@{shard}: no size metadata")
        chunk_total = self.sinfo.aligned_logical_offset_to_chunk_offset(
            self.sinfo.logical_to_next_stripe_offset(logical_size)
        )
        soid = shard_oid(oid, shard)
        off = 0
        while True:
            rec = ecutil.decode_shards(self.ec, chunks, [shard])
            piece = rec[shard].tobytes()
            last = off + len(piece) >= chunk_total
            if not last and not piece:
                # sources hold less data than the size metadata claims
                # (inconsistent mid-write state): restart, don't spin
                return False
            txn = Transaction().write(soid, off, piece)
            if last:
                # attrs (incl. the version stamp) land ONLY on the final
                # window: a half-recovered shard must never claim the
                # authoritative version.  Truncate drops any longer stale
                # tail from a shrinking overwrite the target missed.
                txn = (
                    txn.truncate(soid, chunk_total)
                    .setattr(soid, ecutil.HINFO_KEY, hinfo_d)
                    .setattr(soid, SIZE_KEY, logical_size)
                    .setattr(soid, VERSION_KEY, vmax)
                )
            tid = self._new_tid()
            done = asyncio.get_event_loop().create_future()
            self._pending[tid] = {
                "committed": set(),
                "expected": {f"osd.{target_osd}"},
                "done": done,
            }
            sub = ECSubWrite(
                from_shard=shard,
                tid=tid,
                oid=oid,
                transaction=txn,
                # the consistent sources' version, NOT this primary's
                # possibly cold _versions map: a lower number would be
                # silently no-op'd by the target's stale-write gate
                at_version=vmax,
                op_class="recovery",
                rollback=rollback,
            )
            await self.messenger.send_message(
                self.name, f"osd.{target_osd}", sub
            )
            # min_acks=1: the push has exactly one target; if it died,
            # fail loudly instead of reporting a recovery that never ran
            await self._await_commits(oid, tid, done, min_acks=1)
            self.perf.inc("recover_window")
            if last:
                return True
            off += len(piece)
            chunks, _, _, v2 = await self._gather_consistent(
                oid, src, acting, extents=[(off, win)], op_class="recovery",
                up_shards=up_shards, allow_incomplete=True,
            )
            if v2 != vmax or len(chunks) < self.k:
                return False

    # -- peering (PG.h:2122 Peering + start_recovery_ops role) -------------

    def _peering_authoritative(self, counts: Dict[tuple, int],
                               unseen: int,
                               counts_any: Optional[Dict[tuple, int]] = None,
                               all_visible: bool = False,
                               ) -> Optional[tuple]:
        """Pick the version to recover toward from placed-copy counts.

        Newest version with >= k placed holders wins (assemblable).  A
        newer version with fewer holders is either *possibly acked*
        (holders + unreporting placed positions could reach k) -- then we
        must NOT recover toward older data, return None and wait -- or
        *provably torn* (could never have reached k commits), in which
        case its copies are divergent log entries to roll back.  This is
        the log-authority computation of peering
        (doc/dev/osd_internals/log_based_pg.rst)."""
        for v in sorted(counts, reverse=True):
            if counts[v] >= self.k:
                return v
            if counts[v] + unseen >= self.k:
                return None  # possibly acked, unassemblable now: wait
        # No acting version is assemblable.  Before declaring the object
        # absent, consult copies on up-but-NON-acting holders (remap
        # leftovers): if any version could have reached k commits counting
        # those, the write was real -- wait for remap recovery instead of
        # destroying the surviving copies.
        if counts_any:
            for v, n in counts_any.items():
                if n + unseen >= self.k:
                    return None
        if not all_visible:
            # an unreporting OSD anywhere in the cluster could hide
            # committed copies (e.g. remap sources that died): the torn
            # proof is incomplete -- wait, never destroy
            return None
        # every observed version is PROVABLY torn (could not have reached
        # k commits even counting non-acting holders and unreporting
        # placed holders, with every cluster OSD visible): the object's
        # authoritative state is "absent".  Divergent creates and remove
        # leftovers roll back / get removed (the reference rolls back
        # divergent log entries the same way).
        return (0, "")

    async def peering_pass(self, max_active: int = None,
                           backfill: bool = False) -> int:
        """One event/delta-driven peering + recovery round for objects
        whose PRIMARY this engine's OSD currently is.

        Three stages mirroring the reference peering state machine
        (src/osd/PG.cc GetInfo -> GetLog -> GetMissing -> recovery):

        1. **GetInfo**: poll every up OSD's pg-log head/tail (O(1) each).
           Peers whose head equals this primary's watermark contribute
           nothing further -- a clean, quiet cluster costs one tiny
           round-trip per OSD and NO object traffic.
        2. **GetLog**: for peers that advanced, fetch only the log entries
           above the watermark; the named objects (plus the engine's own
           missing-set of writes that skipped down shards) are the only
           candidates.  A watermark below the peer's log tail means the
           history was trimmed: fall back to a full ``pg_list`` scan --
           the reference's log-recovery vs backfill distinction.
        3. **GetMissing/recover**: probe versions for candidate objects
           only (``obj_versions``), compute the authoritative version,
           then roll back divergent (torn) entries via the target's own
           PG log where possible and push full shards otherwise.

        Returns the number of recovery actions attempted (0 == clean from
        this primary's perspective)."""
        from ceph_tpu.utils.config import get_config

        if max_active is None:
            max_active = int(get_config().get_val("osd_recovery_max_active"))
        n_osds = len(self.osds)
        up_osds = [
            f"osd.{i}" for i in range(n_osds)
            if not self.messenger.is_down(f"osd.{i}")
        ]

        # -- stage 1: GetInfo ---------------------------------------------
        infos = await self._meta_roundtrip(
            up_osds, {"op": "pg_log_info"}, timeout=3.0
        )
        self.perf.inc("peering_info_poll")
        candidates = set(self._dirty)
        meta_candidates = set(self._dirty_meta)
        pre_heads: Dict[str, int] = {}
        need_backfill = backfill
        fetches = []
        for osd_name, info in infos.items():
            head, tail = info["head_seq"], info["tail_seq"]
            pre_heads[osd_name] = head
            last = self._peer_seq.get(osd_name)
            if last is not None and head <= last:
                continue  # quiet peer
            if last is None:
                if head == 0 and not info.get("nonempty"):
                    self._peer_seq[osd_name] = 0  # brand-new empty OSD
                    continue
                need_backfill = True  # unknown history (daemon restart on
                continue              # a persistent store, revived peer)
            if last < tail:
                need_backfill = True  # log trimmed past the watermark
                continue
            fetches.append((osd_name, last))

        # -- stage 2: GetLog deltas (independent peers, one round-trip) ---
        if not need_backfill and fetches:
            results = await asyncio.gather(*(
                self._meta_roundtrip(
                    [osd_name],
                    {"op": "pg_log_entries", "from_seq": last},
                    timeout=3.0,
                )
                for osd_name, last in fetches
            ))
            for (osd_name, last), r in zip(fetches, results):
                rep = r.get(osd_name)
                if rep is None:
                    continue  # peer died mid-pass; the event retries
                if not rep["complete"]:
                    need_backfill = True
                    break
                maxseq = last
                for seq, base, tag, ver in rep["entries"]:
                    if tag == "meta":
                        meta_candidates.add(base)
                    else:
                        candidates.add(base)
                    maxseq = max(maxseq, seq)
                self._peer_seq[osd_name] = maxseq
                self.perf.inc("peering_delta_entries", len(rep["entries"]))

        if need_backfill:
            return await self._peering_backfill(up_osds, max_active, pre_heads)

        if not candidates and not meta_candidates:
            self.perf.inc("peering_pass")
            return 0

        # -- stage 3: targeted probe --------------------------------------
        oids = sorted(candidates | meta_candidates)
        replies = await self._meta_roundtrip(
            up_osds, {"op": "obj_versions", "oids": oids, "km": self.km},
            timeout=3.0,
        )
        self.perf.inc("peering_probe")
        have: Dict[str, Dict[int, Dict[str, tuple]]] = {}
        meta: Dict[str, Dict[str, int]] = {}
        for osd_name, r in replies.items():
            for base, info in r.get("objects", {}).items():
                for sh, ver in info["shards"].items():
                    have.setdefault(base, {}).setdefault(int(sh), {})[
                        osd_name
                    ] = vt(tuple(ver))
                if info["meta"] is not None and base in meta_candidates:
                    meta.setdefault(base, {})[osd_name] = info["meta"]
        # candidate objects with no copies anywhere (e.g. fully removed)
        for base in candidates:
            have.setdefault(base, {})
        return await self._peering_apply(
            have, meta, set(replies), max_active,
            tracked=candidates, tracked_meta=meta_candidates,
        )

    async def _peering_backfill(self, up_osds, max_active,
                                pre_heads: Dict[str, int]) -> int:
        """Full-scan peering (the backfill path): every up OSD serializes
        its holdings via ``pg_list``.  Needed when the log cannot prove
        completeness -- primary restart, revived peer, trimmed log.  On
        success the per-peer watermarks jump to the pre-scan log heads, so
        subsequent passes are delta-driven again."""
        self.perf.inc("peering_backfill")
        replies = await self._meta_roundtrip(
            up_osds, {"op": "pg_list"}, timeout=3.0
        )
        have: Dict[str, Dict[int, Dict[str, tuple]]] = {}
        meta: Dict[str, Dict[str, int]] = {}
        for osd_name, r in replies.items():
            for base, shard, ver in r.get("objects", []):
                if shard == -1:
                    meta.setdefault(base, {})[osd_name] = ver[0]
                else:
                    have.setdefault(base, {}).setdefault(shard, {})[
                        osd_name
                    ] = vt(tuple(ver))
        n = await self._peering_apply(
            have, meta, set(replies), max_active,
            tracked=set(have) | self._dirty,
            tracked_meta=set(meta) | self._dirty_meta,
        )
        # entries at or below the pre-scan heads are covered by the scan
        for osd_name in replies:
            h = pre_heads.get(osd_name)
            if h is not None:
                self._peer_seq[osd_name] = max(
                    self._peer_seq.get(osd_name, 0), h
                )
        return n

    async def _peering_apply(self, have, meta, reporting, max_active,
                             tracked=frozenset(),
                             tracked_meta=frozenset()) -> int:
        """Authoritative-version election + recovery execution over the
        gathered shard/meta version maps; maintains the engine's dirty
        sets (objects in ``tracked``/``tracked_meta`` that end the pass
        clean are dropped; unfinished ones are kept for the next event)."""

        def is_my_object(acting) -> bool:
            for s in range(self.km):
                if self._shard_up(acting, s):
                    return f"osd.{acting[s]}" == self.name
            return False

        actions = []  # (oid, shard, target_osd, authoritative, rollback)
        unfinished: set = set()
        for oid in sorted(have):
            acting = self.acting_set(oid)
            if not is_my_object(acting):
                continue  # another OSD is this object's primary
            shardmap = have[oid]
            # placed copies only: a copy on a non-acting OSD (remap
            # leftover) cannot feed _gather_consistent
            counts: Dict[tuple, int] = {}
            unseen = 0
            placed: Dict[int, Optional[tuple]] = {}
            placed_down = False
            for s in range(self.km):
                if acting[s] is None:
                    continue
                holder = f"osd.{acting[s]}"
                if holder not in reporting:
                    unseen += 1
                    placed_down = True
                    continue
                v = shardmap.get(s, {}).get(holder)
                placed[s] = v
                if v is not None:
                    counts[v] = counts.get(v, 0) + 1
            # every copy anywhere (incl. non-acting remap leftovers), one
            # per distinct shard position, for the absent-object proof
            counts_any: Dict[tuple, int] = {}
            for s, holders in shardmap.items():
                best = max(holders.values(), default=None)
                if best is not None:
                    counts_any[best] = counts_any.get(best, 0) + 1
            if placed_down:
                unfinished.add(oid)  # probe again when the holder returns
            if not counts:
                continue
            authoritative = self._peering_authoritative(
                counts, unseen, counts_any,
                all_visible=len(reporting) >= len(self.osds),
            )
            if authoritative is None:
                self.perf.inc("peering_wait")
                unfinished.add(oid)
                continue
            for s, cur in placed.items():
                if cur == authoritative:
                    continue
                if cur is None and tuple(authoritative) == (0, ""):
                    continue  # absent object, absent copy: nothing to do
                actions.append(
                    (oid, s, acting[s], authoritative,
                     cur is not None and cur > authoritative)
                )

        meta_actions = []  # (oid, stale_targets)
        unfinished_meta: set = set()
        for oid, holders in meta.items():
            acting = self.acting_set(oid)
            if not is_my_object(acting):
                continue
            newest = max(holders.values())
            try:
                targets = self._meta_targets(oid)
            except IOError:
                unfinished_meta.add(oid)
                continue
            if any(
                acting[s] is not None and not self._shard_up(acting, s)
                for s in range(self.km)
            ):
                unfinished_meta.add(oid)  # a down replica will need this
            stale = [t for t in targets if holders.get(t, 0) < newest]
            if stale:
                meta_actions.append((oid, stale))

        failed: set = set()
        if actions or meta_actions:
            sem = asyncio.Semaphore(max_active)

            async def recover_one(oid, s, target, authoritative, rb):
                async with sem:
                    try:
                        if rb and await self._try_log_rollback(
                            oid, s, target, authoritative
                        ):
                            return
                        if tuple(authoritative) == (0, ""):
                            # no assemblable object behind the torn copy:
                            # nothing to reconstruct, just drop it
                            await self._remove_shard_copy(oid, s, target)
                            return
                        await self.recover_shard(
                            oid, s, target, rollback=rb
                        )
                    except asyncio.CancelledError:
                        raise
                    except Exception:  # noqa: BLE001 -- a failed recovery
                        # stays pending; the next peering pass retries
                        self.perf.inc("recover_failed")
                        failed.add(oid)

            async def recover_meta(oid, stale):
                async with sem:
                    try:
                        # full-state re-apply: replicas converge in one
                        # step; a removal tombstone propagates AS a
                        # tombstone (re-applying it as a plain write
                        # would resurrect the deleted name)
                        omap, ver, removed = await self._meta_read_full(oid)
                        await self._meta_roundtrip(stale, {
                            "op": "meta_apply", "oid": oid,
                            "version": ver, "omap": omap,
                            "remove": removed,
                        })
                    except asyncio.CancelledError:
                        raise
                    except Exception:  # noqa: BLE001
                        self.perf.inc("recover_failed")
                        failed.add(oid)

            await asyncio.gather(
                *(recover_one(*a) for a in actions),
                *(recover_meta(*m) for m in meta_actions),
            )

        # dirty-set maintenance (pg_missing_t bookkeeping)
        for oid in tracked:
            if oid in unfinished or oid in failed:
                self._dirty.add(oid)
            else:
                self._dirty.discard(oid)
        for oid in tracked_meta:
            if oid in unfinished_meta or oid in failed:
                self._dirty_meta.add(oid)
            else:
                self._dirty_meta.discard(oid)
        self.perf.inc("peering_pass")
        return len(actions) + len(meta_actions)

    async def _remove_shard_copy(self, oid: str, s: int,
                                 target: int) -> None:
        """Remove a provably-torn or leftover shard copy whose object has
        no assemblable authoritative version (divergent create / remove
        leftover): the rollback target is non-existence."""
        soid = shard_oid(oid, s)
        tid = self._new_tid()
        done = asyncio.get_event_loop().create_future()
        self._pending[tid] = {
            "committed": set(),
            "expected": {f"osd.{target}"},
            "done": done,
        }
        sub = ECSubWrite(
            from_shard=s, tid=tid, oid=oid,
            transaction=Transaction().remove(soid),
            at_version=(0, ""), op_class="recovery", rollback=True,
        )
        await self.messenger.send_message(self.name, f"osd.{target}", sub)
        await self._await_commits(oid, tid, done, min_acks=1)
        self.perf.inc("remove_torn_copy")

    async def _try_log_rollback(self, oid: str, s: int, target: int,
                                to_version: tuple) -> bool:
        """Ask the divergent shard's OSD to roll its torn entries back
        from its own PG log (truncate + attr restore); True on success.
        False (missing/trimmed/overwrite history) -> caller re-pushes the
        shard.  Reference: divergent-entry rollback,
        src/osd/PGLog.h / ECTransaction rollback records."""
        r = await self._meta_roundtrip(
            [f"osd.{target}"],
            {"op": "pg_rollback", "soid": shard_oid(oid, s),
             "to_version": tuple(to_version)},
            timeout=3.0,
        )
        rep = r.get(f"osd.{target}")
        return bool(rep and rep.get("ok"))

    # -- client-op service (the PrimaryLogPG do_op role) -------------------

    async def client_op(self, msg: dict):
        """Execute one client op routed here by an Objecter.

        Reference: PrimaryLogPG::do_op (src/osd/PrimaryLogPG.cc:1844) --
        the primary OSD owns the PG and executes the op, fanning sub-ops
        to the acting set.  Returns the op's wire-encodable result."""
        kind = msg["kind"]
        oid = msg.get("oid", "")
        snap = msg.get("snap")
        if snap is not None and kind in ("read", "read_range", "stat"):
            # snap reads resolve to the serving clone (find_object_context)
            oid = await self.resolve_snap(oid, snap)
        if kind == "write":
            await self.write(oid, msg["data"], snapc=msg.get("snapc"))
        elif kind == "read":
            return await self.read(oid)
        elif kind == "write_range":
            await self.write_range(oid, msg["offset"], msg["data"],
                                   snapc=msg.get("snapc"))
        elif kind == "read_range":
            return await self.read_range(oid, msg["offset"], msg["length"])
        elif kind == "remove":
            await self.remove_object(oid, snapc=msg.get("snapc"))
        elif kind == "stat":
            size, hinfo = await self._stat(oid)
            return (size, hinfo)
        elif kind == "snap_rollback":
            await self.snap_rollback(oid, msg["snapid"],
                                     snapc=msg.get("snapc"))
        elif kind == "snap_trim":
            return await self.snap_trim(oid, msg["live_snaps"])
        elif kind == "list_snaps":
            return await self.list_snaps(oid)
        elif kind == "scrub":
            return await self.deep_scrub(oid)
        elif kind == "recover":
            await self.recover_shard(oid, msg["shard"], msg["target"])
        elif kind == "omap_set":
            await self.omap_set(oid, msg["kvs"])
        elif kind == "omap_get":
            return await self.omap_get(oid, msg.get("keys"))
        elif kind == "omap_rm":
            await self.omap_rm(oid, msg["keys"])
        elif kind == "omap_clear":
            await self.omap_clear(oid)
        elif kind == "omap_cas":
            ok, cur = await self.omap_cas(
                oid, msg["key"], msg["expect"], msg["new"]
            )
            return (ok, cur)
        elif kind == "exec":
            ret, out = await self.exec(
                oid, msg["cls"], msg["method"], msg["inp"]
            )
            return (ret, out)
        elif kind == "watch":
            await self.watch(oid, watcher=msg["watcher"])
        elif kind == "unwatch":
            await self.unwatch(oid, watcher=msg["watcher"])
        elif kind == "notify":
            return await self.notify(
                oid, msg.get("payload"),
                msg.get("timeout_ms", 5000) / 1000.0,
            )
        else:
            raise ValueError(f"unknown client op {kind!r}")
        return None
