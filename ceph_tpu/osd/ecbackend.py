"""Erasure-coded storage strategy for the PG engine.

Reference: src/osd/ECBackend.{h,cc} reduced to the EC essentials:

* writes are append-only (the reference's default without ec_overwrites,
  src/osd/osd_types.h:1516) and run a fan-out/2-phase-ack pipeline with
  in-order completion (ECBackend.h:522-573 write pipeline,
  ECBackend.cc:1976-2030 sub-write fan-out, :2043 try_finish_rmw);
* reads pick the cheapest shard set via minimum_to_decode and reconstruct
  when degraded (ECBackend.cc:2284 objects_read_and_reconstruct, :1569
  get_min_avail_to_read_shards);
* every shard read cross-checks the stored per-shard crc32c
  (handle_sub_read, ECBackend.cc:1054-1076) and reports EIO on mismatch,
  which the primary treats as a missing shard;
* recovery reconstructs lost shards from the minimum available set and
  pushes them to the replacement OSD (continue_recovery_op,
  ECBackend.cc:535-700);
* client-class sub-writes carry the originating op's reqid (stamped by
  the shared ``PG._fanout_commit``), so every applying shard records a
  PG-log dup entry with the mutation itself -- the exactly-once replay
  guard across primary failover (docs/resilience.md).

Shard objects are stored as "<oid>@<shard>" in each OSD's store with the
HashInfo + logical size as xattrs.

Since round 5 the PG-generic machinery (versioning, locks, the metadata
plane, snapshots, scrub scheduling, peering, the recovery driver) lives
in ``ceph_tpu.osd.pg.PG`` -- the reference's PG / PGBackend layering
(src/osd/PG.h:1, src/osd/PGBackend.h:1) -- and this module holds only
the EC strategy.  The OSD daemon role moved to ``ceph_tpu.osd.shard``.
"""

from __future__ import annotations

import asyncio
import time
from typing import Dict, List, Optional

import numpy as np

from ceph_tpu.osd import ecutil
from ceph_tpu.osd.messenger import Messenger
from ceph_tpu.osd.pg import (  # noqa: F401  (compat re-exports)
    MCLOCK_DEFAULTS,
    OP_PRIORITY,
    PG,
    SIZE_KEY,
    SNAPSET_KEY,
    VERSION_KEY,
    WHITEOUT_KEY,
    ObjectIncomplete,
    WriteConflict,
    meta_vt,
    shard_oid,
    snap_oid,
    vt,
)
from ceph_tpu.osd.shard import OSDShard  # noqa: F401  (compat re-export)
from ceph_tpu.osd.types import ECSubWrite, LogEntry, Transaction
from ceph_tpu.utils import trace
from ceph_tpu.utils.perf import PerfCounters, stage_histogram


class ECBackend(PG):
    """EC primary engine: placement, write pipeline, read/reconstruct.

    Since round 3 this engine is HOSTED INSIDE the primary OSD daemon
    (``OSDShard.host_pool``) -- the reference architecture, where the
    client's Objecter sends one op to the primary OSD which owns the PG
    and fans out sub-ops (src/osd/PrimaryLogPG.cc, dispatch at
    src/osd/OSD.cc:6439, fan-out src/osd/ECBackend.cc:1976-2030).  A
    standalone client-side instance (``register=True``) remains possible
    and is what the multi-primary race tests exercise.
    """

    def __init__(
        self,
        ec,
        osds: List,
        messenger: Messenger,
        name: str = "client",
        placement=None,
        register: bool = True,
        tid_alloc=None,
        perf: Optional[PerfCounters] = None,
        min_size: Optional[int] = None,
        coalesce: Optional[bool] = None,
    ):
        self.ec = ec
        self.k = ec.get_data_chunk_count()
        self.km = ec.get_chunk_count()
        self.m = self.km - self.k
        #: write-acceptance floor: the reference defaults EC min_size to
        #: k + min(1, m-1) (OSDMonitor::prepare_new_pool pg_pool_t) --
        #: accepting a write with exactly k shards up would commit it
        #: with zero redundancy.  m == 1 keeps k (no redundancy exists
        #: to demand); an explicit pool min_size overrides.
        self.min_size = min_size if min_size is not None else (
            self.k + min(1, max(0, self.m - 1))
        )
        stripe_width = self.k * ec.get_chunk_size(1)
        self.sinfo = ecutil.StripeInfo(self.k, stripe_width)
        super().__init__(
            osds, messenger, name=name, placement=placement,
            register=register, tid_alloc=tid_alloc, perf=perf,
        )
        # per-PG codec coalescers: concurrent CLIENT ops gather their
        # encode/decode work into batched dispatches (recovery, scrub
        # and peering keep direct codec calls -- the client-op-only
        # scoping that keeps the batching deadlock-free, see
        # ceph_tpu/osd/coalescer.py)
        if coalesce is None:
            from ceph_tpu.utils.config import get_config

            coalesce = bool(get_config().get_val("osd_ec_op_coalesce"))
        from ceph_tpu.osd.coalescer import BatchCoalescer

        self._enc_coalescer = BatchCoalescer(
            self._encode_dispatch, perf=self.perf,
            counter="ec_encode_coalesce",
        ) if coalesce else None
        self._dec_coalescer = BatchCoalescer(
            self._decode_dispatch, perf=self.perf,
            counter="ec_decode_coalesce",
        ) if coalesce else None

    # -- batched codec dispatch (the stripe-batching pipeline seam) --------

    def _mesh_plane(self):
        """The process mesh data plane, iff gated on AND this pool's
        codec can ride it (matrix technique, w=8) -- the coalescer then
        dispatches its fused batches PG-sliced over the mesh instead of
        single-device (ceph_tpu/parallel/mesh_plane.py)."""
        from ceph_tpu.parallel import mesh_plane as mesh_mod

        plane = mesh_mod.current_plane()
        if plane is None or not plane.can_encode(self.ec):
            return None
        return plane

    def _encode_dispatch(self, items):
        """items: (shard-major block, want_resident, pgid) triples from
        :meth:`_encode_op`; one fused, bucketed pipeline dispatch covers
        the whole batch.  Returns (chunk_map, device_block) per item --
        the device block is the still-resident [k+m, bs] encode output
        for stripes the tier wants hot (promote-from-encode).  With the
        mesh data plane up, the batch instead rides ONE PG-sliced SPMD
        dispatch (each stripe placed on the mesh device owning its PG,
        parity scattered in-collective where the backend allows)."""
        blocks = [b for b, _keep, _pg in items]
        plane = self._mesh_plane()
        if plane is not None:
            # a coalesced batch belongs to THIS primary: encode it on
            # the primary's own mesh slot (different primaries' batches
            # land on different devices and overlap); an unbound
            # primary (client-side engine) spreads by PG ownership
            encs = plane.encode_shard_major_many(
                self.ec, blocks, [pg for _b, _keep, pg in items],
                slot=plane.slot_of(self.name))
            return [(enc, None) for enc in encs]
        keep = [keep for _b, keep, _pg in items]
        encs, devs = ecutil.encode_shard_major_many_resident(
            self.ec, blocks, range(self.km), keep)
        return list(zip(encs, devs))

    def _decode_dispatch(self, maps):
        plane = self._mesh_plane()
        if plane is not None:
            return plane.decode_concat_many(
                self.sinfo, self.ec, maps,
                slot=plane.slot_of(self.name))
        return ecutil.decode_concat_many(self.sinfo, self.ec, maps)

    def _pg_of(self, oid: str) -> int:
        """The object's PG id (the mesh plane's slice-ownership key);
        0 without CRUSH placement (mod-placement clusters slice by
        batch order instead)."""
        if self.placement is None:
            return 0
        return self.placement.pg_of(oid)

    async def _encode_op(self, buf, want_resident: bool = False,
                         oid: str = ""):
        """Client-op encode: the transpose runs per op (cheap host view
        work), the codec dispatch batches with every other client op in
        flight this tick.  Returns ``(chunk_map, device_block)`` --
        the device block is None unless ``want_resident`` and the codec
        composed one on device."""
        block = ecutil.to_shard_major(self.sinfo, self.k, buf)
        pgid = self._pg_of(oid) if oid else 0
        if self._enc_coalescer is None:
            # direct (uncoalesced) path: same timeline events as the
            # coalescer records, batch of one
            trace.event("encode_submit")
            items = [(block, want_resident, pgid)]
            out = self._encode_dispatch(items)[0]
            trace.event("encode_done")
            return out
        return await self._enc_coalescer.submit(
            (block, want_resident, pgid), block.nbytes)

    async def _decode_op(self, chunks) -> bytes:
        """Client-op decode: stripes sharing an erasure signature ride
        one fused reconstruction dispatch."""
        if self._dec_coalescer is None:
            trace.event("decode_submit")
            out = ecutil.decode_concat(self.sinfo, self.ec, chunks)
            trace.event("decode_done")
            return out
        nbytes = sum(c.nbytes for c in chunks.values())
        return await self._dec_coalescer.submit(chunks, nbytes)

    # -- device cache tier (ceph_tpu/tier/) --------------------------------

    def _tier_read(self, oid: str, offset: Optional[int] = None,
                   length: Optional[int] = None) -> Optional[bytes]:
        """Hit path: serve the logical bytes straight from the resident
        shard-major device block -- one D2H of the data rows + the
        logical transpose; no sub-read fan-out, no frombuffer ingest,
        and no decode even when the acting set is degraded (all km
        positions are resident).  With ``offset``/``length`` the column
        selection ALSO happens on device: only the covering stripes'
        chunk columns cross the bus, and the returned bytes are exactly
        the requested extent.  None = miss / tier off / stale."""
        tier = self._tier
        if tier is None or self.tier_mode not in ("writeback", "readproxy"):
            return None
        ent = tier.lookup(self.pool_name, oid)
        if ent is None:
            return None
        known = self._versions.get(oid)
        if known is not None and ent.version[0] < known:
            # this primary already assigned/learned a newer version:
            # the resident block predates it
            tier.invalidate(self.pool_name, oid)
            self.perf.inc("tier_stale_drop")
            return None
        from ceph_tpu.analysis.residency import (device_get,
                                                 resident_section)

        start = 0
        if offset is not None:
            if offset >= ent.logical_size:
                return b""
            length = min(length, ent.logical_size - offset)
            start, span = self.sinfo.offset_len_to_stripe_bounds(
                offset, length)
            chunk_off = self.sinfo.aligned_logical_offset_to_chunk_offset(
                start)
            chunk_len = (span // self.sinfo.stripe_width) * \
                self.sinfo.chunk_size
        pos = ecutil.data_positions(self.ec)
        # row (and, for extents, chunk-column) selection happens ON
        # DEVICE; the declared region pins the hit path's roofline
        # contract -- exactly one D2H (the seam below), of only the
        # bytes a read needs
        # cephlint: device-resident-section tier-hit-read
        with resident_section("tier-hit-read"):
            if pos == list(range(self.k)):
                # the common layout: data rows lead -- D2H only those
                dev_rows = ent.block[:self.k]
                remap = None
            else:
                dev_rows = ent.block  # remapped chunks: whole block
                remap = pos
            if offset is not None:
                hi = min(chunk_off + chunk_len, dev_rows.shape[1])
                dev_rows = dev_rows[:, chunk_off:hi]
        # cephlint: end-device-resident-section
        host = device_get(dev_rows)  # the hit path's ONE designed D2H
        rows = host if remap is None else np.stack([host[p] for p in remap])
        from ceph_tpu.tier.device_tier import reassemble_data_rows

        data = reassemble_data_rows(rows, self.sinfo.chunk_size)
        self.perf.inc("tier_hit_read")
        if offset is None:
            return data[:ent.logical_size]
        lo = offset - start
        return data[lo:lo + length]

    def _tier_hist(self, which: str):
        """Lazy tier read-latency observers (hit vs miss), shared per
        daemon name -- the ``ceph_hist_tier_read_{hit,miss}_usec``
        prometheus families."""
        attr = f"_h_tier_{which}"
        h = getattr(self, attr, None)
        if h is None:
            h = stage_histogram(f"{self.name}.tier_read_{which}_usec")
            setattr(self, attr, h)
        return h

    def _tier_hot(self, oid: str) -> bool:
        if self._hitset_temp is None:
            return False
        from ceph_tpu.utils.config import get_config

        return self._hitset_temp(oid) >= float(
            get_config().get_val("osd_tier_promote_temp")
        )

    def _promote_from_encode_on(self) -> bool:
        """Promote-from-encode toggle: hand the tier the still-resident
        encode output instead of re-uploading the host copy."""
        if self._tier is None or self.tier_mode != "writeback":
            return False
        from ceph_tpu.utils.config import get_config

        return bool(get_config().get_val("osd_tier_promote_from_encode"))

    def _want_resident(self, oid: str, logical: int) -> bool:
        """Should this write's encode keep its device block for the
        tier?  Mirrors :meth:`_tier_write_update`'s put predicate so the
        block is composed exactly when it will be inserted."""
        return bool(logical) and self._promote_from_encode_on() and (
            self._tier.contains(self.pool_name, oid) or self._tier_hot(oid)
        )

    def _tier_write_update(self, oid: str, encoded, version,
                           logical: int, dev_block=None) -> bool:
        """Write-through tier update: in writeback mode a hot (or
        already-resident) object's freshly encoded block -- the very
        arrays the coalescer's batched dispatch just produced -- replaces
        the resident copy, marked DIRTY until the fan-out commits
        (promote-on-write).  With ``dev_block`` (promote-from-encode)
        the insert is the encode pipeline's still-resident [k+m, bs]
        device output: ZERO re-upload -- otherwise the host arrays ride
        one device_put.  Any other resident copy is invalidated
        (readproxy/cold writes must not serve pre-write bytes)."""
        tier = self._tier
        if tier is None or self.tier_mode == "none":
            return False
        resident = tier.contains(self.pool_name, oid)
        if self.tier_mode == "writeback" and logical and (
            resident or self._tier_hot(oid)
        ):
            # resident blocks are keyed by the mesh slice owning the
            # object's PG (None off-plane): the tier's per-slice
            # accounting is how "which device holds what" stays a
            # ledger fact once the plane shards ownership
            plane = self._mesh_plane()
            mesh_slice = plane.owner_slot(self._pg_of(oid)) \
                if plane is not None else None
            if dev_block is not None:
                tier.put(self.pool_name, oid, dev_block, version, logical,
                         dirty=True, resident_origin=True,
                         mesh_slice=mesh_slice)
                return True
            block = np.stack([
                np.asarray(encoded[s], dtype=np.uint8)
                for s in range(self.km)
            ])
            tier.put(self.pool_name, oid, block, version, logical,
                     dirty=True, mesh_slice=mesh_slice)
            return True
        if resident:
            tier.invalidate(self.pool_name, oid)
        return False

    # -- write path --------------------------------------------------------

    async def _write_pinned(self, oid: str, data: bytes,
                            snapc=None) -> None:
        # a primary that has never touched this object must learn its
        # current version first: overwriting with a regressed version
        # would be refused by the shards' stale-write gate
        if oid not in self._versions or (
            snapc and oid not in self._snapsets
        ):
            await self._stat(oid)
        snapset, clone_id = self._snap_prepare(oid, snapc)
        version = self._next_version(oid)
        logical = len(data)
        padded_len = self.sinfo.logical_to_next_stripe_offset(logical)
        buf = np.zeros(padded_len, dtype=np.uint8)
        buf[:logical] = np.frombuffer(data, dtype=np.uint8)

        dev_block = None
        if padded_len:
            # decide promote-from-encode BEFORE dispatch so the pipeline
            # composes the [k+m, bs] device block exactly when the tier
            # will insert it (and exempts that granule from donation)
            encoded, dev_block = await self._encode_op(
                buf, self._want_resident(oid, logical), oid=oid)
        else:
            # zero-byte object (S3 markers, touch): no stripes to encode
            encoded = [np.zeros(0, dtype=np.uint8) for _ in range(self.km)]
        hinfo = ecutil.HashInfo(self.km)
        if padded_len:
            hinfo.append(0, encoded)

        acting = self.acting_set(oid)
        # min_size: write acceptance needs min_size live shards (commit
        # quorum below stays k -- acceptance, not completion, is gated)
        up = await self._up_for_write(oid, acting, self.min_size)
        tid = self._new_tid()
        entry = LogEntry(version=version[0], oid=oid, op="append",
                         prior_size=0)
        self.log.append(entry)
        subs = []
        for s in range(self.km):
            if acting[s] is None:
                continue  # CRUSH hole: no device for this position
            soid = shard_oid(oid, s)
            txn = Transaction()
            if clone_id is not None:
                txn.clone(soid, shard_oid(snap_oid(oid, clone_id), s))
            txn = (
                txn
                .write(soid, 0, encoded[s].tobytes())
                .truncate(soid, len(encoded[s]))
                .setattr(soid, ecutil.HINFO_KEY, hinfo.to_dict())
                .setattr(soid, SIZE_KEY, logical)
                .setattr(soid, VERSION_KEY, version)
            )
            txn.setattr(soid, WHITEOUT_KEY, None)
            self._pool_stamp(txn, soid)
            if snapset is not None:
                txn.setattr(soid, SNAPSET_KEY, snapset)
            subs.append((f"osd.{acting[s]}", ECSubWrite(
                from_shard=s,
                tid=tid,
                oid=oid,
                transaction=txn,
                at_version=version,
                log_entries=[entry],
            )))
        self.perf.inc("write")
        # write-through tier update BEFORE the fan-out: the block rides
        # dirty (unreadable) until the commit below confirms it
        tier_put = self._tier_write_update(oid, encoded, version, logical,
                                           dev_block)
        try:
            await self._fanout_commit(
                oid, tid, subs, {f"osd.{acting[s]}" for s in up},
                min_acks=self.k,
            )
            self._snap_committed(oid, snapset, logical)
            if tier_put:
                self._tier.mark_clean(self.pool_name, oid, version)
        except BaseException:
            if tier_put:
                # the fan-out failed: the device copy is unconfirmed
                self._tier.invalidate(self.pool_name, oid)
            raise

    # -- read path ---------------------------------------------------------

    async def read(self, oid: str) -> bytes:
        """objects_read_and_reconstruct: minimum shards, degraded
        fallback -- after consulting the device tier (a hit costs one
        D2H + transpose, no fan-out and no decode)."""
        if self._hitset_record is not None:
            # reads heat the hit sets too (the tier agent's temperature
            # source; write-only recording would never promote a
            # read-hot object)
            self._hitset_record(oid)
        t0 = time.monotonic()
        cached = self._tier_read(oid)
        if cached is not None:
            # tier-hit attribution: one D2H + transpose, no fan-out --
            # the histogram pair the mgr exposes as hit-vs-miss read
            self._tier_hist("hit").inc(
                (time.monotonic() - t0) * 1e6, len(cached))
            trace.event("tier_hit")
            self.perf.inc("read")
            return cached
        tiered = self.tier_mode in ("writeback", "readproxy")
        if tiered:
            trace.event("tier_miss")
        acting = self.acting_set(oid)
        up_shards = [
            s
            for s in range(self.km)
            if self._shard_up(acting, s)
        ]
        if len(up_shards) < self.k:
            # don't fail on a possibly-stale liveness view: probe the
            # down-looking holders once (the reference re-peers on
            # heartbeat recovery; a just-revived primary's messenger may
            # carry unreachable marks from boot-time connect races)
            up_shards = await self._reconfirm_up(acting, up_shards)
        want = ecutil.data_positions(self.ec)
        minimum = self.ec.minimum_to_decode(want, up_shards)
        chunks, logical_size, _, _ = await self._gather_consistent(
            oid, sorted(minimum.keys()), acting, up_shards=up_shards
        )
        if len(chunks) < self.k:
            raise IOError(f"cannot read {oid}: only {len(chunks)} shards")
        if logical_size is None:
            raise IOError(f"no size metadata for {oid}")
        data = await self._decode_op(chunks)
        if tiered:
            # miss attribution: the full fan-out + decode the resident
            # block would have saved
            self._tier_hist("miss").inc(
                (time.monotonic() - t0) * 1e6, logical_size)
        self.perf.inc("read")
        return data[:logical_size]

    # -- partial I/O (ECTransaction write plan + sub-chunk range reads) ----

    async def read_range(self, oid: str, offset: int, length: int) -> bytes:
        """Read only the stripes covering [offset, offset+length)
        (reference: get_write_plan stripe algebra + sub-chunk reads,
        ECBackend.cc:1021-1037 fragmented shard reads)."""
        if self._hitset_record is not None:
            self._hitset_record(oid)
        t0 = time.monotonic()
        cached = self._tier_read(oid, offset, length)
        if cached is not None:
            # whole-object residency serves any extent without a stat
            # round-trip; the stripe/chunk column selection happened ON
            # DEVICE, so only the covering stripes' bytes crossed the bus
            self._tier_hist("hit").inc(
                (time.monotonic() - t0) * 1e6, len(cached))
            trace.event("tier_hit")
            self.perf.inc("read_range")
            return cached
        size, _ = await self._stat(oid)
        if offset >= size:
            return b""
        length = min(length, size - offset)
        cached = self.extent_cache.get(oid, offset, length)
        if cached is not None:
            self.perf.inc("read_cache_hit")
            return cached
        start, span = self.sinfo.offset_len_to_stripe_bounds(offset, length)
        chunk_off = self.sinfo.aligned_logical_offset_to_chunk_offset(start)
        chunk_len = (span // self.sinfo.stripe_width) * self.sinfo.chunk_size

        acting = self.acting_set(oid)
        up = [
            s
            for s in range(self.km)
            if self._shard_up(acting, s)
        ]
        want = ecutil.data_positions(self.ec)
        minimum = self.ec.minimum_to_decode(want, up)
        chunks, _, _, _ = await self._gather_consistent(
            oid, sorted(minimum.keys()), acting,
            extents=[(chunk_off, chunk_len)], up_shards=up,
        )
        if len(chunks) < self.k:
            raise IOError(f"cannot range-read {oid}")
        data = await self._decode_op(chunks)
        lo = offset - start
        self.perf.inc("read_range")
        return data[lo : lo + length]

    def _pin_bounds(self, offset: int, length: int):
        """Extent-cache pin span for an RMW: whole covering stripes."""
        lo_pin, _ = self.sinfo.offset_len_to_stripe_bounds(
            offset, max(1, length)
        )
        hi_pin = self.sinfo.logical_to_next_stripe_offset(offset + length)
        return lo_pin, hi_pin

    async def _write_range_pinned(
        self, oid: str, offset: int, data: bytes, pin, snapc=None
    ) -> None:
        from ceph_tpu.osd.ectransaction import get_write_plan

        size, hinfo_d = await self._stat(oid)
        snapset, clone_id = self._snap_prepare(oid, snapc)
        # the version counter this RMW is computed on top of: shards not
        # on this base missed history and must skip the extent write
        base_version = self._versions.get(oid, 0)
        plan = get_write_plan(self.sinfo, size, offset, len(data))
        start, span = plan.will_write

        buf = np.zeros(span, dtype=np.uint8)
        if plan.to_read is not None:
            r_off, r_len = plan.to_read
            old = await self.read_range(oid, r_off, r_len)
            buf[r_off - start : r_off - start + len(old)] = np.frombuffer(
                old, dtype=np.uint8
            )
        buf[offset - start : offset - start + len(data)] = np.frombuffer(
            data, dtype=np.uint8
        )

        # an RMW's resident block is dropped below, so never keep one
        encoded, _dev = await self._encode_op(buf, oid=oid)
        chunk_off = self.sinfo.aligned_logical_offset_to_chunk_offset(start)

        if plan.is_append and hinfo_d is not None and chunk_off == (
            ecutil.HashInfo.from_dict(hinfo_d).get_total_chunk_size()
        ):
            hinfo = ecutil.HashInfo.from_dict(hinfo_d)
            hinfo.append(chunk_off, encoded)
        elif plan.is_append and hinfo_d is None and chunk_off == 0:
            hinfo = ecutil.HashInfo(self.km)
            hinfo.append(0, encoded)
        else:
            # overwrite: sizes only, hashes cleared (ec_overwrites semantics)
            hinfo = ecutil.HashInfo(0)
            hinfo.total_chunk_size = max(
                chunk_off + len(encoded[0]),
                ecutil.HashInfo.from_dict(hinfo_d).get_total_chunk_size()
                if hinfo_d
                else 0,
            )

        version = self._next_version(oid)
        # an RMW rewrites only the covered stripes: the resident block
        # cannot be refreshed in place, so drop it (reads fall back to
        # the shards; the agent re-promotes if the object stays hot)
        self._tier_invalidate(oid)
        acting = self.acting_set(oid)
        up = await self._up_for_write(oid, acting, self.min_size)
        tid = self._new_tid()
        entry = LogEntry(version=version[0], oid=oid, op="append",
                         prior_size=size)
        self.log.append(entry)
        subs = []
        for s in range(self.km):
            soid = shard_oid(oid, s)
            txn = Transaction()
            if clone_id is not None:
                txn.clone(soid, shard_oid(snap_oid(oid, clone_id), s))
            txn = (
                txn
                .write(soid, chunk_off, encoded[s].tobytes())
                .setattr(soid, ecutil.HINFO_KEY, hinfo.to_dict())
                .setattr(soid, SIZE_KEY, plan.new_size)
                .setattr(soid, VERSION_KEY, version)
                .setattr(soid, WHITEOUT_KEY, None)
            )
            self._pool_stamp(txn, soid)
            if snapset is not None:
                txn.setattr(soid, SNAPSET_KEY, snapset)
            subs.append((f"osd.{acting[s]}", ECSubWrite(
                from_shard=s, tid=tid, oid=oid, transaction=txn,
                at_version=version, log_entries=[entry],
                prev_version=base_version,
            )))
        self.perf.inc("write_range")
        await self._fanout_commit(
            oid, tid, subs, {f"osd.{acting[s]}" for s in up},
            min_acks=self.k,
        )
        self._snap_committed(oid, snapset, plan.new_size)
        # publish committed bytes for read-through (padding included: those
        # bytes are logically zero up to new_size and real data below it)
        pin.commit(start, buf.tobytes())

    # -- removal strategy --------------------------------------------------

    async def _destroy_object(self, oid: str, up, acting) -> None:
        """Plain (snap-less) removal: delete every shard object.

        Resurrection guard: a removal acked by fewer than m+1 shards
        could leave >= k same-version chunks on revived OSDs, making a
        "removed" object readable again.  m+1 deletions cap survivors
        at k-1 (the reference gets this from PG-log replay at peering)."""
        version = self._next_version(oid)
        tid = self._new_tid()
        subs = [
            (f"osd.{acting[s]}", ECSubWrite(
                from_shard=s, tid=tid, oid=oid,
                transaction=Transaction().remove(shard_oid(oid, s)),
                at_version=version,
            ))
            for s in up
        ]
        await self._fanout_commit(
            oid, tid, subs, {f"osd.{acting[s]}" for s in up},
            min_acks=self.m + 1,
        )

    # -- scrub / recovery strategy hooks -----------------------------------

    def _scrub_verify(self, chunks: Dict[int, np.ndarray],
                      report: dict) -> None:
        """Re-encode the data shards and compare the stored coding shards
        (the EC deep-scrub consistency check, reference ECBackend crc +
        parity verification)."""
        dpos = ecutil.data_positions(self.ec)
        if all(p in chunks for p in dpos):
            data = np.stack([chunks[p] for p in dpos])
            fresh = self.ec.encode(set(range(self.km)), data.reshape(-1))
            for s in range(self.km):
                if s in dpos:
                    continue
                if s in chunks and not np.array_equal(fresh[s], chunks[s]):
                    report["parity_mismatch"].append(s)

    def _min_sources(self, want_shards, up_shards):
        """Cheapest source set able to rebuild ``want_shards``
        (ECBackend.cc:1569 get_min_avail_to_read_shards)."""
        minimum = self.ec.minimum_to_decode(list(want_shards), up_shards)
        return sorted(minimum.keys())

    def _rebuild_shard(self, chunks: Dict[int, np.ndarray],
                       shard: int) -> bytes:
        """Reconstruct one shard's bytes from k source chunks."""
        rec = ecutil.decode_shards(self.ec, chunks, [shard])
        return rec[shard].tobytes()

    def _shard_bytes_total(self, logical_size: int) -> int:
        """Stored bytes per shard object: the stripe-rounded chunk span."""
        return self.sinfo.aligned_logical_offset_to_chunk_offset(
            self.sinfo.logical_to_next_stripe_offset(logical_size)
        )
