"""PG-generic primary engine: the machinery every pool type shares.

Reference layering: src/osd/PG.{h,cc} (peering, log, scrub scheduling,
snapshot bookkeeping) + src/osd/PrimaryLogPG.cc (client-op execution,
make_writeable, find_object_context) + the PGBackend seam
(src/osd/PGBackend.h:1, built per pool type by build_pg_backend,
src/osd/PGBackend.cc:533-570).  The storage *strategy* -- how object
bytes map onto per-OSD shard objects -- lives in the subclasses:

* ``ceph_tpu.osd.ecbackend.ECBackend`` -- k+m erasure-coded chunks
  (reference src/osd/ECBackend.cc);
* ``ceph_tpu.osd.replicated.ReplicatedBackend`` -- full copies on every
  acting replica (reference src/osd/ReplicatedBackend.cc).

Strategy hooks a subclass must provide (the PGBackend virtuals):

* ``_write_pinned(oid, data, snapc)`` -- full-object write fan-out;
* ``_write_range_pinned(oid, offset, data, pin, snapc)`` -- extent write;
* ``_pin_bounds(offset, length)`` -- extent-cache pin span for the above;
* ``read(oid)`` / ``read_range(oid, off, len)`` -- read paths;
* ``_min_sources(want_shards, up_shards)`` -- recovery source set;
* ``_rebuild_shard(chunks, shard)`` -- reconstruct one shard's bytes;
* ``_shard_bytes_total(logical_size)`` -- stored bytes per shard object;
* ``_scrub_verify(chunks, report)`` -- cross-shard consistency check;
* ``_destroy_object(oid, up, acting)`` -- plain (snap-less) removal.

plus the sizing attributes ``k`` (shards needed to assemble a version),
``km`` (placed positions), ``m`` (= km - k), ``min_size`` (write quorum
floor) and ``sinfo`` (stripe algebra; identity for replicated pools).
"""

from __future__ import annotations

import asyncio
import contextvars
import time
from contextlib import asynccontextmanager
from typing import Dict, List, Optional, Tuple

import numpy as np

from ceph_tpu.osd import ecutil
from ceph_tpu.osd.messenger import Messenger
from ceph_tpu.osd.types import (
    ECSubRead,
    ECSubReadReply,
    ECSubWrite,
    ECSubWriteReply,
    LogEntry,
    Transaction,
)
from ceph_tpu.utils import trace
from ceph_tpu.utils.perf import PerfCounters, stage_histogram

SIZE_KEY = "_size"
#: per-shard object version xattr (the object_info_t version role): every
#: write stamps it, reads drop shards whose version lags the newest seen,
#: so a shard that missed updates while down can never contribute a stale
#: chunk to a decode (the PG-log/peering consistency guarantee, reduced
#: to a read-time check)
VERSION_KEY = "_version"
#: per-object snapshot set xattr (the SnapSet role, src/osd/osd_types.h):
#: {"seq": newest snap context seen, "clones": [{"id", "size"}, ...]}
SNAPSET_KEY = "_snapset"
#: head deleted under a snap context but clones survive (the snapdir
#: object role, src/osd/PrimaryLogPG.cc)
WHITEOUT_KEY = "_whiteout"
#: pool-membership tag: multiple pools share one OSD's flat store (the
#: reference separates them by PG collection, pgid embedding the pool id,
#: src/osd/osd_types.h spg_t) -- the tag keeps one pool's scrub/peering
#: from "repairing" another pool's objects.  Absent on legacy/standalone
#: writes, which only exist in single-pool clusters.
POOL_KEY = "_pool"


def shard_oid(oid: str, shard: int) -> str:
    return f"{oid}@{shard}"


def snap_oid(oid: str, clone_id: int) -> str:
    """Clone object name; '~' is reserved so clones co-place with their
    head (placement strips the suffix, mirroring how the reference keeps
    clones in the head's PG via the ghobject snap field)."""
    return f"{oid}~{clone_id}"


def vt(v) -> tuple:
    """Order object/metadata versions.  Stored/wire form is
    ``(counter, writer)`` (legacy plain ints order as writer "").  The
    writer name breaks ties when two primaries race to the same counter:
    every shard/replica then picks the SAME winner and two writes can
    never share a version, so a read-time consistent cut cannot mix
    chunks from different writes (the role the reference gets from one
    primary OSD serializing the PG, src/osd/ECBackend.h:522-573)."""
    if v is None:
        return (0, "")
    if isinstance(v, int):
        return (v, "")
    return (v[0], v[1])


#: backward-compatible name (the metadata plane used this first)
meta_vt = vt


#: osd_client_op_priority / osd_recovery_op_priority defaults
OP_PRIORITY = {"client": 63, "recovery": 10, "scrub": 5}

#: client-op kinds whose OWN fan-out (sub-writes / meta applies) carries
#: the op's reqid, so every applying replica records the dup entry in
#: the same step as the mutation -- a zero-width dup-detection window.
#: These are exactly the kinds whose client-visible result is None or
#: rides the fan-out itself (omap_cas piggybacks its result on the
#: replication meta_apply).  ``exec`` and ``snap_trim`` compose several
#: internal mutations with a result known only at the end; their dups
#: are recorded by an explicit awaited ``dup_record`` fan-out instead
#: (see OSDShard._run_client_op_inner), so their internals stay
#: reqid-free -- an internal sub-op's dup must never masquerade as the
#: composite op's result.
REQID_FANOUT_KINDS = frozenset({
    "write", "write_range", "remove", "snap_rollback",
    "omap_set", "omap_rm", "omap_clear", "omap_cas",
})

#: the in-flight client op's reqid, visible to the fan-out helpers of
#: THIS task only (client ops run as separate tasks; contextvars keep
#: concurrent ops' reqids apart without threading a parameter through
#: every strategy signature)
_OP_REQID: "contextvars.ContextVar[Optional[tuple]]" = \
    contextvars.ContextVar("ceph_tpu_op_reqid", default=None)

#: the in-flight client op's QoS sub-class (gold/bulk/... from the
#: Objecter's qos_class; docs/qos.md), stamped onto the op's own
#: sub-ops so RECEIVING shards queue them under the same class --
#: end-to-end reservations need the replica hop, not just the
#: primary's admission, to honor the tags
_OP_QOS: "contextvars.ContextVar[Optional[str]]" = \
    contextvars.ContextVar("ceph_tpu_op_qos", default=None)

#: mclock_opclass-style defaults: (reservation, weight, limit) items/sec;
#: clients get a floor and most of the weight.  Recovery carries NO hard
#: limit since round 14: a degraded cluster must re-reach full
#: redundancy as fast as spare capacity allows (time degraded == the
#: data-loss risk window), so contention control is the 10:1
#: client:recovery weight here plus the primary-side BackgroundThrottle
#: (osd/recovery.py) backing batches off while the client queue is
#: saturated.  Scrub keeps its cap: it is periodic and never urgent.
MCLOCK_DEFAULTS = {
    "client": (1000.0, 100.0, 0.0),
    "recovery": (100.0, 10.0, 0.0),
    "scrub": (50.0, 5.0, 1000.0),
}


class WriteConflict(IOError):
    """A shard refused a client write as stale: a racing primary committed
    a newer version first.  Carries the winning version tuple."""

    def __init__(self, winner: tuple):
        super().__init__(f"write lost to concurrent version {winner}")
        self.winner = winner


class ObjectIncomplete(IOError):
    """The newest observed version might have been acked but cannot
    assemble k chunks from up shards — serving an older version would be a
    read-after-ack consistency violation (the reference's peering would
    block or mark the PG incomplete, src/osd/PG.cc)."""


class PG:
    """Pool-type-agnostic primary engine (hosted inside the primary OSD
    daemon via ``OSDShard.host_pool``, or standalone for race tests).

    Subclasses fill in the storage strategy; everything here -- version
    counters, per-object write serialization, commit-quorum accounting,
    the replicated metadata plane, watch/notify, snapshots, scrub
    scheduling, delta peering and the recovery driver -- is shared so the
    two pool types cannot drift apart (the reason the reference splits
    PG / PGBackend / {Replicated,EC}Backend, src/osd/PG.h:1)."""

    # sizing attributes set by subclasses before PG.__init__ runs:
    k: int
    km: int
    m: int
    min_size: int
    sinfo: ecutil.StripeInfo

    def __init__(
        self,
        osds: List,
        messenger: Messenger,
        name: str = "client",
        placement=None,
        register: bool = True,
        tid_alloc=None,
        perf: Optional[PerfCounters] = None,
    ):
        self.osds = osds
        self.messenger = messenger
        self.name = name
        #: pool this engine serves when hosted (set by OSDShard.host_pool);
        #: stamps every written shard with POOL_KEY and scopes peering
        self.pool_name: Optional[str] = None
        # a hosted engine shares its OSD's counter instance (one daemon,
        # one perf registry entry -- the reference's per-daemon logger)
        self.perf = perf if perf is not None else PerfCounters(name)
        self._tid = 0
        #: co-hosted backends on one OSD share a tid space so replies
        #: forwarded to every pool match exactly one pending op
        self._tid_alloc = tid_alloc
        self._pending: Dict[int, dict] = {}
        if register:
            messenger.register(name, self.dispatch)
        # per-object version counter (pg-log-lite); bounded: entries are
        # evicted LRU and relearned via _stat on the next touch
        from collections import OrderedDict

        self._versions: "OrderedDict[str, int]" = OrderedDict()
        #: high-water mark of every version ever assigned or learned --
        #: survives _versions eviction so the pg-wide counter (the
        #: eversion role) never regresses
        self._version_head = 0
        self.log: List[LogEntry] = []
        # in-flight RMW extent pinning + read-through byte cache
        # (reference src/osd/ExtentCache.h)
        from ceph_tpu.osd.extent_cache import ExtentCache

        self.extent_cache = ExtentCache()
        #: per-object write mutex: version-assignment + fan-out + commit
        #: wait run under it, so writes to one object from this primary
        #: complete in version order (the reference's in-order write
        #: pipeline, ECBackend.h:522-541).  Entries are refcounted and
        #: dropped when uncontended.
        self._oid_locks: Dict[str, asyncio.Lock] = {}
        self._oid_lock_refs: Dict[str, int] = {}
        #: replicated-metadata version sequence per oid (meta plane is
        #: versioned separately from the chunk plane)
        self._meta_versions: Dict[str, int] = {}
        #: oid -> callback for watch/notify events
        self._watch_callbacks: Dict[str, object] = {}
        # CRUSH placement engine (ceph_tpu.osd.placement.CrushPlacement);
        # None falls back to the seeded-permutation CRUSH-lite below.
        self.placement = placement
        #: placement epoch this engine last peered against: a CRUSH
        #: change (osd add/rm/out/reweight) moves acting sets without
        #: writing any pg log, so delta peering alone would never
        #: discover the re-placed (misplaced) objects -- an epoch skew
        #: forces the backfill scan exactly once per map change
        self._placement_epoch = getattr(placement, "epoch", None)
        # -- delta peering state (pg_missing_t / peer_info roles) ----------
        #: last log sequence processed per peer OSD; a peer whose head
        #: equals its watermark contributes zero peering traffic
        self._peer_seq: Dict[str, int] = {}
        #: last reqid-dup sequence fetched per peer OSD (dup sequences
        #: are per-OSD, so the watermark is too); peers whose dup head
        #: matches contribute zero dup traffic
        self._peer_dup_seq: Dict[str, int] = {}
        #: last incarnation nonce seen per peer (OSDShard.boot_id): a
        #: change invalidates both watermarks above -- see peering_pass
        self._peer_boot: Dict[str, str] = {}
        #: the hosting OSD's PGLog (OSDShard.host_pool wires it): where
        #: peering-fetched dup entries are merged so THIS OSD, once
        #: promoted primary, answers replayed ops from the log.  None
        #: for standalone engines (no daemon, no replay surface).
        self._host_pglog = None
        #: objects known to need attention (writes that missed shards,
        #: recoveries pending on down OSDs) -- the pg_missing_t analogue
        self._dirty: set = set()
        #: replicated-metadata objects in the same state
        self._dirty_meta: set = set()
        #: incremental per-PG statistics (pg_stat_t role): degraded /
        #: misplaced / state bits maintained at the mutation, peering
        #: and recovery-completion seams -- what MgrReport frames and
        #: the mgr's ClusterState read instead of scanning stores
        from ceph_tpu.osd.pg_stats import PGStats

        self.pg_stats = PGStats(self)
        #: last inconsistent deep-scrub reports (ScrubStore role);
        #: cleared when a re-scrub comes back clean
        self.scrub_errors: Dict[str, dict] = {}
        #: per-object SnapSet cache learned via _stat:
        #: {"seq", "clones", "exists", "size"}
        self._snapsets: Dict[str, dict] = {}
        # -- device cache tier hookup (ceph_tpu/tier/) ---------------------
        #: the hosting OSD's DeviceTierStore (OSDShard.host_pool wires
        #: it; a standalone engine keeps the tier off)
        self._tier = None
        #: per-pool cache mode: "writeback" | "readproxy" | "none"
        #: (flows from the mon's `osd tier cache-mode` via the osdmap,
        #: or ECCluster.set_tier_mode in-process)
        self.tier_mode = "none"
        #: hit-set feeds (late-bound to the hosting OSD's tracker so a
        #: test swapping shard.hitsets is picked up)
        self._hitset_record = None
        self._hitset_temp = None
        #: per-stage latency observers (lazy; shared per daemon name via
        #: perf.stage_histogram): sub-op round-trip wire time, measured
        #: fan-out-send -> commit-ack arrival on this primary
        self._h_wire_rtt = None

    # -- placement (CRUSH-lite) --------------------------------------------

    def acting_set(self, oid: str) -> List[int]:
        """Stable pseudorandom placement of the km shard positions over
        OSDs (full copies for replicated pools ride the same machinery:
        each "shard position" holds a whole copy).

        Clone objects ("oid~<cloneid>") place WITH their head object --
        the suffix is stripped before hashing -- so snapshots live in the
        head's PG exactly like the reference's ghobject snap ids.

        With a CrushPlacement attached this is the real thing: oid -> pg ->
        crush rule over the map (src/crush/mapper.c crush_choose_indep;
        src/osd/OSDMap.cc _pg_to_raw_osds).  The fallback is a
        deterministic permutation seeded by the object name."""
        oid = oid.split("~", 1)[0]
        if self.placement is not None:
            return self.placement.acting(oid)
        from ceph_tpu.osd.placement import fallback_acting

        # stable: down OSDs keep their slot (degraded) until recovery moves
        # the shard, mirroring up/acting set semantics
        return fallback_acting(oid, len(self.osds), self.km)

    def _pool_stamp(self, txn: Transaction, soid: str) -> Transaction:
        """Tag a written shard with its pool so co-hosted pools' scrub and
        peering never claim each other's objects (see POOL_KEY)."""
        if self.pool_name is not None:
            txn.setattr(soid, POOL_KEY, self.pool_name)
        return txn

    def _pool_match(self, tag) -> bool:
        """Does an object tagged ``tag`` belong to this engine's pool?
        Untagged objects (legacy / standalone writes) and un-pooled
        engines accept everything -- the single-pool behavior."""
        return tag is None or self.pool_name is None or tag == self.pool_name

    def _tier_invalidate(self, oid: str) -> None:
        """Drop any device-resident copy of ``oid`` (called by every
        mutation path the tier cannot refresh in place: RMW extents,
        removals, snapset restamps).  No-op without a tier."""
        if self._tier is not None:
            self._tier.invalidate(self.pool_name, oid)

    def _shard_up(self, acting, s: int) -> bool:
        """A shard position is usable iff it mapped (no CRUSH hole) and its
        OSD is not down."""
        return acting[s] is not None and not self.messenger.is_down(
            f"osd.{acting[s]}"
        )

    async def _reconfirm_up(self, acting, up_shards):
        """Probe down-looking acting holders (concurrently, at most once
        per second) and return the refreshed up set.  No-op on
        messengers without a probe (the in-process bus's is_down is
        authoritative).  A genuinely-dead cluster pays one probe round
        per second, not one per read."""
        probe = getattr(self.messenger, "probe", None)
        if probe is None:
            return up_shards
        now = asyncio.get_event_loop().time()
        if now - getattr(self, "_last_reconfirm", 0.0) < 1.0:
            # rate-limit the probe I/O only -- the liveness VIEW must
            # still be recomputed, or an op arriving just after another
            # op's probe round would fail on the stale argument even
            # though that round (or a background reprobe) healed it
            return [s for s in range(self.km)
                    if self._shard_up(acting, s)]
        self._last_reconfirm = now

        async def one(entity):
            try:
                # generous timeout: under host load this process's
                # event loop can stall past a short deadline while the
                # peer is perfectly alive
                await probe(entity, timeout=2.5)
            except TypeError:
                await probe(entity)
            except (OSError, asyncio.TimeoutError):
                pass

        await asyncio.gather(*(
            one(f"osd.{acting[s]}") for s in range(self.km)
            if s not in up_shards and acting[s] is not None
        ))
        return [s for s in range(self.km) if self._shard_up(acting, s)]

    # -- reply plumbing ----------------------------------------------------

    async def dispatch(self, src: str, msg) -> None:
        if isinstance(msg, dict):
            op = msg.get("op")
            if op in ("meta_get_reply", "meta_apply_reply",
                      "omap_cas_reply", "watch_reply", "notify_reply",
                      "pg_list_reply", "pg_log_info_reply",
                      "pg_log_entries_reply", "pg_rollback_reply",
                      "obj_versions_reply", "dup_record_reply",
                      "pg_dups_reply"):
                state = self._pending.get(msg.get("tid"))
                if state is not None:
                    state["replies"][src] = msg
                    state["outstanding"].discard(src)
                    if not state["outstanding"] and not state["done"].done():
                        state["done"].set_result(True)
                return
            if op == "notify_event":
                from ceph_tpu.osd.objecter import deliver_notify_event

                deliver_notify_event(
                    self.messenger, self.name, self._watch_callbacks,
                    src, msg,
                )
                return
            # monitor traffic (command replies, osdmap broadcasts)
            hook = getattr(self, "mon_hook", None)
            if hook is not None:
                await hook(msg)
            return
        if isinstance(msg, ECSubWriteReply):
            state = self._pending.get(msg.tid)
            if state is None:
                return
            t_sent = state.get("t_sent")
            if t_sent is not None:
                # per-sub-op wire round trip (send -> commit-ack here):
                # the "wire" attribution of the op timeline, exposed as
                # a prometheus histogram through the mgr module
                if self._h_wire_rtt is None:
                    self._h_wire_rtt = stage_histogram(
                        f"{self.name}.wire_rtt_usec")
                self._h_wire_rtt.inc(
                    (time.monotonic() - t_sent) * 1e6,
                    state.get("nbytes", 0))
            if msg.missed:
                # the shard skipped an incremental write (missed base):
                # degrade the fan-out as if it were down — it must not
                # count toward the quorum, and _await_commits verifies
                # enough real appliers remain
                state["expected"].discard(src)
                if (
                    state["committed"] >= state["expected"]
                    and not state["done"].done()
                ):
                    state["done"].set_result(True)
                return
            if not msg.committed and msg.current_version is not None:
                # stale-write refusal: a racing primary won this object.
                # Fail the op now so the writer retries at a higher
                # version; waiting out the commit quorum would hang.
                if not state["done"].done():
                    state["done"].set_exception(
                        WriteConflict(vt(msg.current_version))
                    )
                return
            if msg.committed:
                state["committed"].add(src)
            if state["committed"] >= state["expected"]:
                if not state["done"].done():
                    state["done"].set_result(True)
        elif isinstance(msg, ECSubReadReply):
            state = self._pending.get(msg.tid)
            if state is None:
                return
            state["replies"][msg.from_shard] = msg
            state["outstanding"].discard(msg.from_shard)
            if not state["outstanding"] and not state["done"].done():
                state["done"].set_result(True)

    def _new_tid(self) -> int:
        if self._tid_alloc is not None:
            return self._tid_alloc()
        self._tid += 1
        return self._tid

    @asynccontextmanager
    async def _object_lock(self, oid: str):
        """Acquire the per-object write mutex; the entry is dropped once
        no writer holds or waits for it (bounded state).  With the
        ``lockdep`` option on, acquisition order is tracked per lock
        class ("object:head" vs "object:clone" -- the legitimate nesting
        direction) and cycles raise before they can deadlock."""
        lock = self._oid_locks.get(oid)
        if lock is None:
            from ceph_tpu.utils import lockdep

            if lockdep.enabled():
                cls = "object:clone" if "~" in oid else "object:head"
                lock = self._oid_locks[oid] = lockdep.TrackedLock(cls)
            else:
                lock = self._oid_locks[oid] = asyncio.Lock()
        self._oid_lock_refs[oid] = self._oid_lock_refs.get(oid, 0) + 1
        try:
            async with lock:
                yield
        finally:
            refs = self._oid_lock_refs[oid] - 1
            if refs:
                self._oid_lock_refs[oid] = refs
            else:
                del self._oid_lock_refs[oid]
                self._oid_locks.pop(oid, None)

    #: bound on the per-object version cache; evicted oids are relearned
    #: from shard attrs by _stat on the next write
    _VERSION_CACHE_MAX = 8192

    def _next_version(self, oid: str) -> tuple:
        """pg-wide dense version counter + this primary's name: the
        eversion analogue with a writer tiebreak (see vt())."""
        self._version_head += 1
        self._versions[oid] = self._version_head
        self._versions.move_to_end(oid)
        while len(self._versions) > self._VERSION_CACHE_MAX:
            self._versions.popitem(last=False)
        return (self._version_head, self.name)

    def _learn_version(self, oid: str, seen: tuple) -> None:
        if seen[0] > self._versions.get(oid, 0):
            self._versions[oid] = seen[0]
            self._versions.move_to_end(oid)
            # the read/stat path inserts here too: enforce the cap on
            # every insert, not just on writes
            while len(self._versions) > self._VERSION_CACHE_MAX:
                self._versions.popitem(last=False)
        if seen[0] > self._version_head:
            self._version_head = seen[0]

    # -- write entry points (strategy does the fan-out) --------------------

    async def write(self, oid: str, data: bytes, snapc=None) -> None:
        """Full-object write (create or replace).

        ``snapc`` = {"seq": int, "snaps": [ids]} (librados SnapContext):
        when seq is newer than the object's SnapSet seq, the current head
        is cloned shard-by-shard in the SAME transaction before the new
        bytes land (PrimaryLogPG::make_writeable).

        A WriteConflict (a shard refused the version as stale) propagates
        to the caller; the Objecter retries once after the refusal
        teaches this primary the winning version."""
        # serialize writes per object (in-order pipeline) and conflict with
        # any in-flight RMW on the object via the whole-object pin
        async with self._object_lock(oid):
            async with self.extent_cache.pin(oid, 0, 1 << 62):
                try:
                    await self._write_pinned(oid, data, snapc)
                except WriteConflict as wc:
                    # adopt the winning version so a retry lands on top
                    self._learn_version(oid, wc.winner)
                    self.perf.inc("write_conflict")
                    raise
                finally:
                    # invalidate even on a partial/failed replace: some
                    # shards may have applied, so cached pre-replace
                    # bytes are stale
                    self.extent_cache.invalidate(oid)

    async def write_range(self, oid: str, offset: int, data: bytes,
                          snapc=None) -> None:
        """Partial write; the strategy decides between RMW (EC) and a
        direct extent fan-out (replicated)."""
        # serialize per object: version-assignment + fan-out + commit wait
        # must not interleave with another write's (in-order pipeline)
        async with self._object_lock(oid):
            # pin the write span: publishes committed bytes for read-through
            lo_pin, hi_pin = self._pin_bounds(offset, len(data))
            async with self.extent_cache.pin(oid, lo_pin, hi_pin) as pin:
                try:
                    await self._write_range_pinned(
                        oid, offset, data, pin, snapc
                    )
                except WriteConflict as wc:
                    # this primary's version view was cold (see write());
                    # learn the winner so the Objecter-level retry replays
                    # the WHOLE RMW (re-stat, re-read, re-merge) on top
                    self._learn_version(oid, wc.winner)
                    self.extent_cache.invalidate(oid)
                    self.perf.inc("write_conflict")
                    raise
                except Exception:
                    # a partially-acked write leaves shard state ahead
                    # of the cache: cached pre-write bytes would serve
                    # stale reads
                    self.extent_cache.invalidate(oid)
                    raise

    async def _await_commits(
        self, oid: str, tid: int, done: "asyncio.Future", min_acks: int
    ) -> None:
        """Wait for the fan-out's commit acks, pruning shards discovered
        dead during the send (e.g. a TCP connect refused) so the op
        completes on the surviving set.  Skipped shards hold stale bytes
        until recovered -- the VERSION_KEY read-time cut keeps them out of
        decodes.  If fewer than ``min_acks`` shard targets survive, the op
        fails.  A write that already fully committed (done resolved) is
        never failed by late deaths.  Shared by every fan-out path (full
        write, RMW write, recovery push)."""
        state = self._pending[tid]
        orig_expected = set(state["expected"])
        try:
            if not done.done():
                state["expected"] = {
                    n for n in state["expected"]
                    if not self.messenger.is_down(n)
                }
                if len(state["expected"]) < min_acks:
                    raise IOError(
                        f"write {oid} lost shards mid-flight: "
                        f"only {len(state['expected'])} up"
                    )
                if state["committed"] >= state["expected"]:
                    done.set_result(True)
            from ceph_tpu.utils.config import get_config as _gc

            await asyncio.wait_for(
                done, timeout=float(_gc().get_val(
                    "osd_client_op_commit_timeout"))
            )
            # shards may have dropped out mid-op (missed-base skips): the
            # write only durably exists if enough shards actually applied
            if len(state["committed"]) < min_acks:
                raise IOError(
                    f"write {oid}: only {len(state['committed'])} shards "
                    f"applied (need {min_acks})"
                )
        finally:
            # pg_missing_t bookkeeping: any fan-out that did not reach its
            # full expected set leaves a shard behind -- remember the
            # object so event-driven peering probes it without a scan
            if state["committed"] != orig_expected:
                self._dirty.add(oid)
            del self._pending[tid]

    async def _up_for_write(self, oid: str, acting, need: int):
        """Write-quorum gate shared by every mutation path: the up set,
        re-probed once if it looks too small (stale liveness), failing
        below ``need`` (min_size semantics); marks the object dirty when
        writing degraded (down holders will miss this version)."""
        up = [s for s in range(self.km) if self._shard_up(acting, s)]
        if len(up) < need:
            up = await self._reconfirm_up(acting, up)
        if len(up) < need:
            raise IOError(f"cannot write {oid}: only {len(up)} shards up")
        if len(up) < len(
            [s for s in range(self.km) if acting[s] is not None]
        ):
            self._dirty.add(oid)
        return up

    async def _fanout_commit(self, oid: str, tid: int, subs, expected,
                             min_acks: int) -> None:
        """Register the pending op, send every (target, sub) pair, and
        wait out the commit quorum -- the one fan-out/ack sequence every
        mutation shares, so commit accounting cannot drift between the
        pool strategies (the round-5 review's dedup finding)."""
        # exactly-once: stamp the in-flight client op's reqid onto its
        # own client-class sub-writes so every applying shard records
        # the dup entry in the same step as the mutation (recovery and
        # scrub pushes, and internal ops of composite kinds, stay bare)
        rid = _OP_REQID.get()
        if rid is not None:
            for _dst, sub in subs:
                if getattr(sub, "op_class", "client") == "client" and \
                        getattr(sub, "reqid", None) is None:
                    sub.reqid = rid
        # QoS: the op's client sub-class rides its own sub-writes so
        # the applying shards' op queues order them under it (trailing
        # optional field, like the reqid; scheduling only -- op_class
        # keeps the version-gate/dup semantics)
        qcls = _OP_QOS.get()
        if qcls is not None:
            for _dst, sub in subs:
                if getattr(sub, "op_class", "client") == "client" and \
                        getattr(sub, "qos_class", None) is None:
                    sub.qos_class = qcls
        # trace stitching: the in-flight op's wire context rides every
        # sub-op of its own fan-out (trailing optional field, like the
        # reqid), so the applying shards' sub-write spans join the
        # client's trace.  Unsampled ops stamp nothing.
        wire_ctx = trace.current_wire()
        if wire_ctx is not None:
            for _dst, sub in subs:
                if getattr(sub, "trace", None) is None:
                    sub.trace = wire_ctx
        done = asyncio.get_event_loop().create_future()
        self._pending[tid] = {
            "committed": set(),
            "expected": set(expected),
            "done": done,
            "t_sent": time.monotonic(),
            "nbytes": sum(
                len(top.data)
                for _dst, sub in subs
                for top in sub.transaction.ops
            ),
        }
        # mesh-local vs wire routing split (osd_mesh_data_plane,
        # ceph_tpu/parallel/mesh_plane.py), chosen per-chunk from CRUSH
        # placement: a sub-write whose destination OSD is bound to the
        # process mesh carries a delivery-board reference instead of
        # its chunk payload -- the bytes already live on the owner's
        # device slice (in-collective parity scatter / PG-sliced
        # placement), so the messenger frames only the envelope.  The
        # frame itself still rides the normal wire path: ordering,
        # acks, replay, and kill semantics are untouched, and
        # out-of-mesh peers keep the full payload frame.
        from ceph_tpu.parallel import mesh_plane as mesh_mod

        plane = mesh_mod.current_plane()
        if plane is not None:
            for dst, sub in subs:
                if dst != self.name and plane.covers(dst):
                    plane.detach_sub_write(sub)
        # one multi-destination submit for the whole k+m fan-out: the
        # TCP messenger's per-peer cork queues gather each peer's share
        # into a single scatter-gather burst (one writev + one drain per
        # peer instead of one per sub-op)
        await self.messenger.send_messages(self.name, subs)
        trace.event("fanout_sent")
        await self._await_commits(oid, tid, done, min_acks=min_acks)
        trace.event("commit")

    # -- shard read plumbing -----------------------------------------------

    async def _read_shards(
        self,
        oid: str,
        shards: List[int],
        acting: List[int],
        extents: Optional[List[Tuple[int, int]]] = None,
        op_class: str = "client",
    ) -> Dict[int, ECSubReadReply]:
        shards = [s for s in shards if acting[s] is not None]
        tid = self._new_tid()
        done = asyncio.get_event_loop().create_future()
        self._pending[tid] = {
            "replies": {},
            "outstanding": set(shards),
            "done": done,
        }
        # multi-destination submit: the sub-read fan-out corks per peer
        # exactly like the write fan-out.  The in-flight op's trace
        # context rides each sub-read so the serving shards' spans
        # stitch into the same trace.
        wire_ctx = trace.current_wire()
        qcls = _OP_QOS.get() if op_class == "client" else None
        await self.messenger.send_messages(self.name, [
            (f"osd.{acting[s]}", ECSubRead(
                from_shard=s,
                tid=tid,
                to_read={oid: list(extents) if extents else [(0, -1)]},
                attrs_to_read=[oid],
                op_class=op_class,
                trace=wire_ctx,
                qos_class=qcls,
            ))
            for s in shards
        ])
        trace.event("gather_sent")
        try:
            # config-driven (osd_op_thread_timeout role): give revived
            # stragglers the headroom the client op budget already allows
            from ceph_tpu.utils.config import get_config

            await asyncio.wait_for(done, timeout=float(
                get_config().get_val("osd_read_gather_timeout")))
        except asyncio.TimeoutError:
            pass  # missing shards handled by the caller
        trace.event("gather_done")
        state = self._pending.pop(tid)
        return state["replies"]

    @staticmethod
    def _collect_read(replies, oid, chunks, versions, sizes, failed,
                      attrmap=None) -> None:
        """Merge one _read_shards round into per-shard chunk/version/size
        maps (absent VERSION_KEY decodes as vt(0): pre-versioning or
        never-written objects).  ``attrmap`` additionally captures each
        shard's full attr dict (hinfo / snapset / whiteout) so recovery
        can re-stamp them on the rebuilt shard."""
        for s, reply in replies.items():
            if oid in reply.errors:
                failed.append(s)
                continue
            bufs = reply.buffers_read.get(oid)
            if bufs:
                chunks[s] = np.frombuffer(bufs[0][1], dtype=np.uint8)
            attrs = reply.attrs_read.get(oid) or {}
            if attrs.get(SIZE_KEY) is not None:
                sizes[s] = attrs[SIZE_KEY]
            if attrmap is not None and attrs:
                attrmap[s] = attrs
            versions[s] = vt(attrs.get(VERSION_KEY))

    async def _gather_consistent(
        self, oid, shards, acting, extents=None, op_class="client",
        up_shards=None, allow_incomplete=False,
    ):
        """Version-authoritative gather, shared by read / read_range /
        recovery so the staleness rules cannot diverge between them.

        Round 1 reads data from ``shards`` and, concurrently, version
        attrs from EVERY other up shard -- the minimum data set alone
        cannot establish the authoritative version (it might consist
        entirely of same-version stale shards that missed a degraded
        write).  Versions are tried newest first.  A version that cannot
        assemble k chunks is skipped ONLY if it provably was never acked
        (its up holders plus every unreachable shard still total < k
        commits — a write that died mid-flight below min_size; log
        rollback semantics).  If it MIGHT have been acked, the object is
        reported incomplete instead of silently serving older data — the
        read-after-ack guarantee.  Recovery passes ``allow_incomplete``
        to reconstruct the newest assemblable version (its job is exactly
        to repair such objects).

        Returns (chunks, size_hint, attrs_hint, version_tuple);
        attrs_hint is a full attr dict from one holder of the chosen
        version (hinfo / snapset / whiteout), or None."""
        if up_shards is None:
            up_shards = [
                s for s in range(self.km) if self._shard_up(acting, s)
            ]
        chunks: Dict[int, np.ndarray] = {}
        versions: Dict[int, tuple] = {}
        sizes: Dict[int, int] = {}
        attrmap: Dict[int, dict] = {}
        failed: List[int] = []
        others = [s for s in up_shards if s not in shards]
        data_coro = self._read_shards(
            oid, shards, acting, extents=extents, op_class=op_class
        )
        if others:
            attr_coro = self._read_shards(
                oid, others, acting, extents=[(0, 0)], op_class=op_class
            )
            data_replies, attr_replies = await asyncio.gather(
                data_coro, attr_coro
            )
        else:
            data_replies, attr_replies = await data_coro, {}
        self._collect_read(data_replies, oid, chunks, versions, sizes,
                           failed, attrmap)
        # attr-only round: versions/sizes/attrs, never chunk content
        attr_chunks: Dict[int, np.ndarray] = {}
        self._collect_read(attr_replies, oid, attr_chunks, versions, sizes,
                           failed, attrmap)

        counts: Dict[tuple, int] = {}
        for s, v in versions.items():
            if s not in failed:
                counts[v] = counts.get(v, 0) + 1
        if not counts:
            return {}, None, None, (0, "")
        # shards that might hold a newer version we cannot see: mapped
        # positions whose OSD is down/unreachable, plus shards that
        # errored (their stamp is unknown)
        unseen = sum(
            1 for s in range(self.km)
            if acting[s] is not None and s not in versions
        )

        ordered = sorted(counts, reverse=True)
        last = ordered[-1]
        for target in ordered:
            if counts[target] < self.k and target != last:
                if counts[target] + unseen >= self.k and not allow_incomplete:
                    # might have reached k commits (the missing holders
                    # may be among the unreachable shards): serving an
                    # older version could violate read-after-ack
                    raise ObjectIncomplete(
                        f"{oid}: newest version {target} has only "
                        f"{counts[target]} reachable holders (+{unseen} "
                        f"unreachable); refusing possibly-stale read"
                    )
                # provably never acked (< k commits possible): the write
                # died mid-flight below min_size — roll back to the
                # previous version
                self.perf.inc("rolled_back_version_skipped")
                continue
            holders = [
                s for s in up_shards
                if versions.get(s) == target and s not in failed
            ]
            need = [s for s in holders if s not in chunks]
            if need:
                self.perf.inc("degraded_read")
                more = await self._read_shards(
                    oid, need, acting, extents=extents, op_class=op_class
                )
                self._collect_read(more, oid, chunks, versions, sizes,
                                   failed, attrmap)
            have = {
                s: chunks[s] for s in holders
                if s in chunks and versions.get(s) == target
            }
            if len(have) >= self.k or target == last:
                if len(chunks) != len(have):
                    self.perf.inc("stale_shards_dropped")
                size = next(
                    (sizes[s] for s in holders if sizes.get(s) is not None),
                    None,
                )
                attrs = next(
                    (attrmap[s] for s in holders if s in attrmap), None
                )
                return have, size, attrs, target
            if not allow_incomplete:
                # the candidate had >= k stamped holders but fewer than k
                # produced chunks (read failures mid-gather): it may have
                # been acked, so do not fall through to older data
                raise ObjectIncomplete(
                    f"{oid}: version {target} assembled only "
                    f"{len(have)}/{self.k} chunks"
                )
        return {}, None, None, (0, "")  # unreachable: loop always returns

    async def _stat(self, oid: str) -> Tuple[int, Optional[dict]]:
        """(logical size, hinfo dict) from shard attrs; size 0 if absent.

        Queries every up shard's attrs in one parallel round and answers
        from the highest-versioned reply: a shard that was down during
        writes may hold stale size/hinfo, and planning an RMW from stale
        metadata would corrupt the object.  Also teaches this primary the
        object's current version (``self._versions``) so a fresh client
        process continues the version sequence instead of restarting it
        (which the shards' stale-write gate would silently discard)."""
        acting = self.acting_set(oid)
        up = [
            s
            for s in range(self.km)
            if self._shard_up(acting, s)
        ]
        replies = await self._read_shards(oid, up, acting, extents=[(0, 0)])
        best = None  # (version_tuple, size, hinfo, snapset, whiteout)
        for r in replies.values():
            attrs = r.attrs_read.get(oid) or {}
            if attrs.get(SIZE_KEY) is None:
                continue
            ver = vt(attrs.get(VERSION_KEY))
            if best is None or ver > best[0]:
                best = (ver, attrs[SIZE_KEY], attrs.get(ecutil.HINFO_KEY),
                        attrs.get(SNAPSET_KEY), attrs.get(WHITEOUT_KEY))
        if best is None:
            self._snapsets[oid] = {"seq": 0, "clones": [],
                                   "exists": False, "size": 0}
            return 0, None
        self._learn_version(oid, best[0])
        ss = best[3] or {"seq": 0, "clones": []}
        self._snapsets[oid] = {
            "seq": ss["seq"], "clones": list(ss["clones"]),
            "exists": not best[4], "size": best[1],
        }
        if best[4]:
            return 0, None  # whiteout head: absent to plain stat/readers
        return best[1], best[2]

    async def stat(self, oid: str):
        """Public stat: (logical size, hinfo dict | None) -- the same
        surface the Objecter exposes, so rbd/cls callers work against
        either a local engine or the remote-routed client."""
        return await self._stat(oid)

    # -- removal -----------------------------------------------------------

    async def remove_object(self, oid: str, snapc=None) -> None:
        """Delete every shard of an object (librados remove role).

        Under a snap context newer than the SnapSet seq the head is
        cloned first and then WHITEOUT'd (truncated to zero with the
        whiteout attr) instead of removed, so snap reads keep resolving
        through the head's SnapSet -- the reference's snapdir object.
        The whiteout disappears when snap_trim drops the last clone."""
        async with self._object_lock(oid):
            await self._remove_object_locked(oid, snapc)

    async def _remove_object_locked(self, oid: str, snapc=None) -> None:
        acting = self.acting_set(oid)
        up = [s for s in range(self.km) if self._shard_up(acting, s)]
        if not up:
            raise IOError(f"cannot remove {oid}: no shards up")
        if len(up) < len([s for s in range(self.km) if acting[s] is not None]):
            self._dirty.add(oid)  # down holders keep a doomed copy
        if oid not in self._versions or (
            snapc and oid not in self._snapsets
        ):
            await self._stat(oid)
        snapset, clone_id = self._snap_prepare(oid, snapc)
        if clone_id is not None:
            # snap-preserving delete: clone + whiteout in one transaction
            if len(up) < self.min_size:
                raise IOError(f"cannot remove {oid}: only {len(up)} up")
            version = self._next_version(oid)
            tid = self._new_tid()
            subs = []
            for s in up:
                soid = shard_oid(oid, s)
                txn = self._pool_stamp(
                    Transaction()
                    .clone(soid, shard_oid(snap_oid(oid, clone_id), s))
                    .truncate(soid, 0)
                    .setattr(soid, SIZE_KEY, 0)
                    .setattr(soid, VERSION_KEY, version)
                    .setattr(soid, WHITEOUT_KEY, True)
                    .setattr(soid, SNAPSET_KEY, snapset),
                    soid,
                )
                subs.append((f"osd.{acting[s]}", ECSubWrite(
                    from_shard=s, tid=tid, oid=oid,
                    transaction=txn, at_version=version)))
            await self._fanout_commit(
                oid, tid, subs, {f"osd.{acting[s]}" for s in up},
                min_acks=self.min_size,
            )
            self._snap_committed(oid, snapset, 0, exists=False)
            self.extent_cache.invalidate(oid)
            self._tier_invalidate(oid)
            return
        self._snapsets.pop(oid, None)
        # tombstone the meta twin BEFORE destroying data: if the
        # tombstone cannot land anywhere the remove fails cleanly with
        # the object intact, instead of leaving deleted data whose
        # stale omap resurrects at the next recovery pass (the
        # reference orders its delete the same way: the PG-log entry
        # is durable before the objects go)
        await self._meta_remove(oid)
        await self._destroy_object(oid, up, acting)
        self.extent_cache.invalidate(oid)
        self._tier_invalidate(oid)

    # -- metadata plane: replicated omap / CAS / watch-notify / cls --------
    #
    # The reference keeps object metadata (cls state, rbd headers, locks)
    # in omap on replicated pools and runs cls methods + watch/notify on
    # the primary OSD.  Here the metadata object "<oid>@meta" is fully
    # replicated to every up shard OSD (metadata is small; survival under
    # any k-available scenario matters more than space), versioned on its
    # own sequence; the acting[0] OSD is the atomicity (CAS) and
    # watch/notify authority.

    def _meta_targets(self, oid: str, mark_dirty: bool = False):
        acting = self.acting_set(oid)
        up = [
            f"osd.{acting[s]}"
            for s in range(self.km)
            if self._shard_up(acting, s)
        ]
        if not up:
            raise IOError(f"no up OSDs for {oid} metadata")
        if mark_dirty and len(up) < len(
            [s for s in range(self.km) if acting[s] is not None]
        ):
            self._dirty_meta.add(oid)  # down replicas miss this version
        return up

    async def _meta_roundtrip(self, targets, payload: dict,
                              timeout: float = 5.0) -> Dict[str, dict]:
        """Send one dict op to each target, gather replies by sender.
        Mutating meta ops carry this engine's pool so the stored twin is
        membership-tagged like any shard object (see POOL_KEY)."""
        if self.pool_name is not None and payload.get("op") in (
            "meta_apply", "omap_cas"
        ):
            payload = dict(payload, pool=self.pool_name)
        # exactly-once: metadata-plane mutations carry the client op's
        # reqid so every applying replica records the dup entry with the
        # mutation itself (see REQID_FANOUT_KINDS)
        rid = _OP_REQID.get()
        if rid is not None and payload.get("op") in (
            "meta_apply", "omap_cas"
        ) and "reqid" not in payload:
            payload = dict(payload, reqid=list(rid))
        tid = self._new_tid()
        done = asyncio.get_event_loop().create_future()
        self._pending[tid] = {
            "replies": {}, "outstanding": set(targets), "done": done,
        }
        for t in targets:
            await self.messenger.send_message(
                self.name, t, dict(payload, tid=tid)
            )
        try:
            await asyncio.wait_for(done, timeout=timeout)
        except asyncio.TimeoutError:
            pass
        state = self._pending.pop(tid)
        return state["replies"]

    async def _meta_read_full(self, oid: str):
        """(omap, version, removed) of the highest-versioned replica
        (+ learn the version).  A removed tombstone reads as empty."""
        targets = self._meta_targets(oid)
        replies = await self._meta_roundtrip(
            targets, {"op": "meta_get", "oid": oid}
        )
        best_ver, best, removed = 0, None, False
        for r in replies.values():
            if r.get("omap") is not None and r["version"] >= best_ver:
                best_ver, best = r["version"], r["omap"]
                removed = bool(r.get("removed"))
        if best_ver > self._meta_versions.get(oid, 0):
            self._meta_versions[oid] = best_ver
        if removed or best is None:
            return {}, best_ver, removed
        return best, best_ver, removed

    async def _meta_read(self, oid: str) -> Dict[str, bytes]:
        omap, _ver, _removed = await self._meta_read_full(oid)
        return omap

    async def _meta_write(self, oid: str, sets=None, rms=None,
                          clear=False) -> None:
        """Read-modify-write of the FULL replicated omap.  Full-state
        replication lets a replica that missed versions converge in one
        step; concurrent plain writers are last-writer-wins (atomic
        read-modify-write goes through omap_cas / cls methods, as in the
        reference)."""
        targets = self._meta_targets(oid, mark_dirty=True)
        omap = {} if clear else await self._meta_read(oid)
        if rms:
            for k in rms:
                omap.pop(k, None)
        if sets:
            omap.update(sets)
        ver = self._meta_versions.get(oid, 0) + 1
        self._meta_versions[oid] = ver
        replies = await self._meta_roundtrip(targets, {
            "op": "meta_apply", "oid": oid, "version": ver, "omap": omap,
        })
        if not replies:
            raise IOError(f"metadata write for {oid} reached no OSD")
        if len(replies) < len(targets):
            self._dirty_meta.add(oid)  # a replica missed this version

    #: tombstones jump a whole version GENERATION: a down replica whose
    #: solo-acked writes put it a few versions ahead of what the remover
    #: could read must still lose to the tombstone under highest-version
    #: recovery.  Packing the generation into the integer keeps every
    #: existing comparison (peering tuples included) working unchanged.
    TOMBSTONE_GEN = 1 << 32

    async def _meta_remove(self, oid: str) -> None:
        """Tombstone the meta twin on every replica (object removal).
        Versioned like any meta write so a replica that missed it is
        repaired by highest-version-wins recovery -- towards the
        tombstone, never back to the deleted keys."""
        targets = self._meta_targets(oid, mark_dirty=True)
        await self._meta_read(oid)  # learn the current version
        ver = self._meta_versions.get(oid, 0) + self.TOMBSTONE_GEN
        self._meta_versions[oid] = ver
        replies = await self._meta_roundtrip(targets, {
            "op": "meta_apply", "oid": oid, "version": ver,
            "remove": True, "omap": {},
        })
        if not replies:
            raise IOError(f"metadata remove for {oid} reached no OSD")
        if len(replies) < len(targets):
            self._dirty_meta.add(oid)  # a replica missed the tombstone

    async def omap_set(self, oid: str, kvs: Dict[str, bytes]) -> None:
        await self._meta_write(oid, sets=dict(kvs))

    async def omap_rm(self, oid: str, keys) -> None:
        await self._meta_write(oid, rms=list(keys))

    async def omap_clear(self, oid: str) -> None:
        await self._meta_write(oid, clear=True)

    async def omap_get(self, oid: str, keys=None) -> Dict[str, bytes]:
        omap = await self._meta_read(oid)
        if keys is None:
            return omap
        return {k: omap[k] for k in keys if k in omap}

    async def omap_cas(self, oid: str, key: str, expect, new):
        """Atomic compare-and-swap on the primary-shard OSD, then
        replicate the outcome to the remaining replicas."""
        acting = self.acting_set(oid)
        primary = None
        for s in range(self.km):
            if self._shard_up(acting, s):
                primary = f"osd.{acting[s]}"
                break
        if primary is None:
            raise IOError(f"no up OSDs for {oid} CAS")
        replies = await self._meta_roundtrip(
            [primary],
            {"op": "omap_cas", "oid": oid, "key": key,
             "expect": expect, "new": new},
        )
        r = replies.get(primary)
        if r is None:
            raise IOError(f"CAS on {oid} got no reply from {primary}")
        if r["success"]:
            # propagate the authority's full state to the other replicas
            self._meta_versions[oid] = r["version"]
            others = [t for t in self._meta_targets(oid) if t != primary]
            if others:
                # the CAS outcome rides the replication fan-out as a
                # dup result: any replica that may be promoted primary
                # can then answer a replayed CAS with the ORIGINAL
                # (success, current) instead of re-comparing against
                # post-apply state (which would report a false failure)
                await self._meta_roundtrip(others, {
                    "op": "meta_apply", "oid": oid,
                    "version": r["version"], "omap": r["omap"],
                    "dup_result": [r["success"], r["current"]],
                })
        return r["success"], r["current"]

    async def watch(self, oid: str, callback=None, watcher: str = None) -> None:
        """Register for notify events on oid (librados watch role).

        ``watcher`` names the entity that receives notify events; when a
        client routes its watch through the primary OSD (the reference
        path), it is the *client's* messenger name and events go to it
        directly, bypassing this engine."""
        targets = self._meta_targets(oid)[:1]
        watcher = watcher or self.name
        if watcher == self.name:
            self._watch_callbacks[oid] = callback
        replies = await self._meta_roundtrip(
            targets, {"op": "watch", "oid": oid, "watcher": watcher}
        )
        if not replies:
            self._watch_callbacks.pop(oid, None)
            raise IOError(f"watch {oid}: no reply")

    async def unwatch(self, oid: str, watcher: str = None) -> None:
        targets = self._meta_targets(oid)[:1]
        watcher = watcher or self.name
        if watcher == self.name:
            self._watch_callbacks.pop(oid, None)
        await self._meta_roundtrip(
            targets, {"op": "unwatch", "oid": oid, "watcher": watcher}
        )

    async def notify(self, oid: str, payload=None, timeout: float = 5.0):
        """Notify every watcher; returns {"acks": [...], "timeouts": [...]}
        once all ack or the timeout passes (librados notify role)."""
        targets = self._meta_targets(oid)[:1]
        replies = await self._meta_roundtrip(
            targets,
            {"op": "notify", "oid": oid, "payload": payload,
             "timeout": timeout},
            # the OSD gathers watcher acks for up to ``timeout`` before it
            # replies; give the round-trip headroom past that
            timeout=timeout + 2.0,
        )
        for r in replies.values():
            return {"acks": r["acks"], "timeouts": r["timeouts"]}
        raise IOError(f"notify {oid}: no reply")

    async def exec(self, oid: str, cls: str, method: str, inp: bytes = b""):
        """Run a server-side object class method (cls exec role).

        The reference dlopens cls plugins on the OSD (ClassHandler); our
        primary engine hosts the class registry and methods run against
        this backend's object surface, with omap_cas as the atomicity
        primitive where a method needs read-modify-write."""
        from ceph_tpu.cls import call_method

        return await call_method(self, oid, cls, method, inp)

    # -- snapshots (SnapMapper / make_writeable roles) ---------------------

    def _snap_prepare(self, oid: str, snapc):
        """(new snapset attr value, clone id) for a write under ``snapc``;
        (None, None) when no snap context.  Must run after _stat primed
        the SnapSet cache.  Reference: PrimaryLogPG::make_writeable."""
        if not snapc:
            return None, None
        cur = self._snapsets.get(oid) or {
            "seq": 0, "clones": [], "exists": False, "size": 0
        }
        snapset = {"seq": max(cur["seq"], snapc["seq"]),
                   "clones": list(cur["clones"])}
        clone_id = None
        if cur.get("exists") and snapc["seq"] > cur["seq"]:
            clone_id = snapc["seq"]
            snapset["clones"].append(
                {"id": clone_id, "size": cur.get("size", 0)}
            )
        return snapset, clone_id

    def _snap_committed(self, oid: str, snapset, new_size: int,
                        exists: bool = True) -> None:
        """Update the SnapSet cache after a committed snap-context op."""
        if snapset is None:
            ent = self._snapsets.get(oid)
            if ent is not None:
                ent["exists"] = exists
                ent["size"] = new_size
            return
        self._snapsets[oid] = {
            "seq": snapset["seq"], "clones": list(snapset["clones"]),
            "exists": exists, "size": new_size,
        }

    async def resolve_snap(self, oid: str, snap: int) -> str:
        """Object name serving reads at snap id ``snap``: the oldest clone
        whose id >= snap, else the head (librados snap read resolution,
        SnapSet::get_clone_bytes / PrimaryLogPG::find_object_context)."""
        if oid not in self._snapsets:
            await self._stat(oid)
        ss = self._snapsets.get(oid)
        if not ss or not ss["clones"]:
            return oid
        cands = sorted(c["id"] for c in ss["clones"] if c["id"] >= snap)
        return snap_oid(oid, cands[0]) if cands else oid

    async def list_snaps(self, oid: str) -> dict:
        """The object's SnapSet (rados listsnaps role)."""
        await self._stat(oid)  # refresh
        ss = self._snapsets.get(oid) or {"seq": 0, "clones": [],
                                         "exists": False}
        return {"seq": ss["seq"], "clones": list(ss["clones"]),
                "head_exists": bool(ss.get("exists"))}

    async def snap_rollback(self, oid: str, snap: int, snapc=None) -> None:
        """Restore the head to its state at ``snap`` (librados
        selfmanaged_snap_rollback; reference PrimaryLogPG::_rollback_to).
        Implemented as read-at-snap + write-as-new-version, so the
        rollback itself is snapshotted under ``snapc`` like any write."""
        src = await self.resolve_snap(oid, snap)
        if src == oid:
            return  # head already is the snap state
        data = await self.read(src)
        await self.write(oid, data, snapc=snapc)

    async def snap_trim(self, oid: str, live_snaps) -> int:
        """Drop clones no longer needed by any live snap (SnapMapper +
        snap trim role).  A clone with id C covers snaps in
        (previous clone id, C]; when none of those are alive the clone is
        removed and the head's SnapSet shrinks.  A whiteout head whose
        last clone goes is removed outright.  Returns clones dropped."""
        await self._stat(oid)
        cur = self._snapsets.get(oid)
        if not cur or not cur["clones"]:
            return 0
        live = sorted(live_snaps)
        keep, drop = [], []
        prev = 0
        for c in sorted(cur["clones"], key=lambda c: c["id"]):
            if any(prev < sn <= c["id"] for sn in live):
                keep.append(c)
            else:
                drop.append(c)
            prev = c["id"]
        if not drop:
            return 0
        # the whole read-modify-write of the SnapSet runs under the head's
        # object lock so a concurrent snap-context write cannot append a
        # clone entry that the stale stamp below would erase
        async with self._object_lock(oid):
            cur = self._snapsets.get(oid) or cur  # re-read under the lock
            keep = [c for c in cur["clones"]
                    if not any(d["id"] == c["id"] for d in drop)]
            for c in drop:
                try:
                    await self.remove_object(snap_oid(oid, c["id"]))
                except IOError:
                    pass  # already gone; peering will converge
            self.perf.inc("snap_trim", len(drop))
            if not keep and not cur.get("exists"):
                # whiteout head, no clones left: the object is fully dead
                await self._remove_object_locked(oid)
                self._snapsets.pop(oid, None)
                return len(drop)
            new_ss = {"seq": cur["seq"], "clones": keep}
            await self._set_snapset_locked(oid, new_ss)
        return len(drop)

    async def _set_snapset_locked(self, oid: str, snapset: dict) -> None:
        """Attr-only fan-out updating the head's SnapSet (version-stamped
        so the stale gates order it like any write).  Caller holds the
        object lock."""
        acting = self.acting_set(oid)
        up = [s for s in range(self.km) if self._shard_up(acting, s)]
        if len(up) < self.min_size:
            raise IOError(f"cannot update snapset of {oid}")
        version = self._next_version(oid)
        tid = self._new_tid()
        subs = []
        for s in up:
            soid = shard_oid(oid, s)
            txn = (
                Transaction()
                .setattr(soid, SNAPSET_KEY, snapset)
                .setattr(soid, VERSION_KEY, version)
            )
            subs.append((f"osd.{acting[s]}", ECSubWrite(
                from_shard=s, tid=tid, oid=oid,
                transaction=txn, at_version=version)))
        await self._fanout_commit(
            oid, tid, subs, {f"osd.{acting[s]}" for s in up},
            min_acks=self.min_size,
        )
        ent = self._snapsets.get(oid)
        if ent is not None:
            ent["seq"] = snapset["seq"]
            ent["clones"] = list(snapset["clones"])
        # the bytes are unchanged but the version moved: a resident tier
        # copy would read as stale forever, so drop it now
        self._tier_invalidate(oid)

    # -- scrub -------------------------------------------------------------

    async def deep_scrub(self, oid: str) -> dict:
        """Read every shard, verify per-shard crc32c and cross-shard
        consistency (``_scrub_verify``: parity re-encode for EC, copy
        comparison for replicated) -- the deep-scrub role (reference: PG
        scrub + backend-specific checks; inconsistency report shape
        follows ScrubStore's per-object errors).  Since round 14 the
        reads ride the batched background lane with a chunked cursor
        (see :meth:`deep_scrub_many`)."""
        return (await self.deep_scrub_many([oid]))[oid]

    async def deep_scrub_many(self, oids: List[str]) -> Dict[str, dict]:
        """Batched deep scrub: every object's shard reads ride the
        chunked background cursor (``osd_scrub_chunk_max`` bytes per
        shard per round, one corked multi-read burst per round for the
        WHOLE set -- osd/recovery.py scrub_read_many) instead of one
        whole-shard fan-out per object; verification is per object as
        before.  Returns {oid: report}."""
        from ceph_tpu.osd.recovery import scrub_read_many

        gathered = await scrub_read_many(self, list(oids))
        reports = {}
        for oid in oids:
            acting = self.acting_set(oid)
            up = [
                s for s in range(self.km) if self._shard_up(acting, s)
            ]
            reports[oid] = self._scrub_report(
                oid, up, gathered.get(oid, {}))
        return reports

    def _scrub_report(self, oid: str, up: List[int],
                      shards: Dict[int, dict]) -> dict:
        """Classify one object's gathered shard cuts into the scrub
        report (shared by the batched and single-object entry points)."""
        report = {
            "oid": oid,
            "crc_errors": [],
            "missing": [],
            "parity_mismatch": [],
            "ok": True,
        }
        chunks: Dict[int, np.ndarray] = {}
        seen_versions = set()
        for s in up:
            slot = shards.get(s)
            if slot is None:
                report["missing"].append(s)  # the shard never answered
                continue
            if slot.get("error") is not None:
                report["crc_errors"].append(s)
                continue
            seen_versions |= slot.get("versions") or {vt(None)}
            if slot.get("had_buf"):
                chunks[s] = np.frombuffer(slot["data"], dtype=np.uint8)
            else:
                report["missing"].append(s)
        if len(seen_versions) > 1:
            # mixed versions: an in-flight write or a stale shard --
            # that is peering's jurisdiction, not a scrub inconsistency;
            # report clean-with-deferral instead of a false parity error
            # (the reference scrubber blocks on in-progress writes)
            self.perf.inc("scrub_deferred")
            report["deferred"] = True
            self.scrub_errors.pop(oid, None)
            return report
        self._scrub_verify(chunks, report)
        report["ok"] = not (
            report["crc_errors"] or report["missing"] or report["parity_mismatch"]
        )
        if report["ok"]:
            self.scrub_errors.pop(oid, None)
        else:
            self.scrub_errors[oid] = report
            self.perf.inc("scrub_inconsistent")
        self.perf.inc("deep_scrub")
        return report

    async def scrub_repair(self, oid: str, report: dict) -> int:
        """Repair every shard a deep scrub flagged (crc error / missing /
        parity mismatch) by reconstructing it from the consistent set and
        pushing it back -- the scrub-driven auto-repair loop (reference:
        PG repair + qa/standalone/erasure-code/test-erasure-eio.sh)."""
        acting = self.acting_set(oid)
        bad = sorted(
            set(report["crc_errors"]) | set(report["missing"])
            | set(report["parity_mismatch"])
        )
        repaired = 0
        for s in bad:
            if not self._shard_up(acting, s):
                continue
            try:
                await self.recover_shard(oid, s, acting[s], rollback=True)
                repaired += 1
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 -- a failed repair stays in
                # scrub_errors/_dirty; the next scrub or peering retries
                self.perf.inc("scrub_repair_failed")
                self._dirty.add(oid)
        if repaired:
            self.perf.inc("scrub_repair", repaired)
            # confirm: a clean re-scrub clears the error record
            report2 = await self.deep_scrub(oid)
            if report2["ok"]:
                self.scrub_errors.pop(oid, None)
        return repaired

    # -- recovery ----------------------------------------------------------

    def _recovery(self):
        """Lazy per-PG RecoveryCoalescer (the batched background data
        plane, osd/recovery.py); shared by recovery and the scrub
        cursor so one throttle governs all background I/O."""
        rc = getattr(self, "_recovery_coalescer", None)
        if rc is None:
            from ceph_tpu.osd.recovery import RecoveryCoalescer

            rc = self._recovery_coalescer = RecoveryCoalescer(self)
        return rc

    def _use_batched_recovery(self) -> bool:
        """Batched recovery serves EC engines (the codec's fused decode
        is the win); replicated pools keep the per-object path."""
        from ceph_tpu.utils.config import get_config

        return getattr(self, "ec", None) is not None and bool(
            get_config().get_val("osd_recovery_batched"))

    async def _recovery_pace(self) -> None:
        """Awaited pacing between background recovery windows
        (osd_recovery_sleep; 0 still yields so client ops interleave
        -- the async-background-unthrottled discipline)."""
        from ceph_tpu.utils.config import get_config

        await asyncio.sleep(
            float(get_config().get_val("osd_recovery_sleep")))

    async def recover_shard(
        self, oid: str, shard: int, target_osd: int, rollback: bool = False,
        sources: Optional[Dict[int, int]] = None,
    ) -> None:
        """Reconstruct one lost/stale shard and push it to the target OSD
        in bounded windows (the READING->WRITING recovery state machine,
        ECBackend.h:256-300, chunked like get_recovery_chunk_size :213 so
        a 64 MiB object never needs 64 MiB of primary memory).  A client
        write landing mid-recovery changes the object version; that is
        detected at the next window's gather and the recovery restarts.
        ``rollback=True`` lets the final stamp overwrite a torn
        higher-versioned copy (peering's divergent-entry rollback).

        The whole recovery holds the object's write lock, so client
        writes to a HOT object queue briefly behind the push instead of
        restarting it forever (the reference pins the object context for
        the duration of the push, src/osd/ECBackend.cc:535-700).  The
        version-moved restart loop remains as a safety net for writes
        from a racing primary, which does not share this lock.

        ``sources`` maps shard position -> OSD id holding that shard's
        authoritative copy on a NON-acting OSD (a remap leftover): the
        gather reads those positions from the named holders instead of
        the acting slots.  This is the backfill/relocation data path of
        elastic membership -- after a CRUSH remap the acting set may
        hold fewer than k shards, so reconstruction must read from
        wherever the copies actually are."""
        from ceph_tpu.utils.config import get_config

        window = max(1, int(get_config().get_val("osd_recovery_max_chunk")))
        async with self._object_lock(oid):
            for attempt in range(3):
                if await self._recover_shard_once(
                    oid, shard, target_osd, window, rollback,
                    sources=sources,
                ):
                    self.perf.inc("recover")
                    return
                self.perf.inc("recover_restart")
        raise IOError(
            f"recovery of {oid}@{shard} kept losing to concurrent writes"
        )

    async def _recover_shard_once(
        self, oid: str, shard: int, target_osd: int, window: int,
        rollback: bool, sources: Optional[Dict[int, int]] = None,
    ) -> bool:
        """One windowed recovery attempt; False = restart (the object's
        version moved under us)."""
        acting = self.acting_set(oid)
        if sources:
            # relocation gather: read positions from the remap-leftover
            # holders, not the (possibly empty) acting slots
            acting = list(acting)
            for pos, holder in sources.items():
                acting[pos] = holder
        up_shards = [
            s
            for s in range(self.km)
            if (s != shard or (sources and s in sources))
            and self._shard_up(acting, s)
        ]
        src = self._min_sources([shard], up_shards)
        cs = self.sinfo.chunk_size
        # per-source-chunk bytes per round, whole per-stripe chunks only
        # (a stripe decodes independently for every technique)
        win = max(cs, (window // self.k) // cs * cs)
        chunks, logical_size, attrs_hint, vmax = await self._gather_consistent(
            oid, src, acting, extents=[(0, win)], op_class="recovery",
            up_shards=up_shards, allow_incomplete=True,
        )
        if len(chunks) < self.k:
            raise IOError(f"cannot recover {oid}@{shard}: too few sources")
        if logical_size is None:
            raise IOError(f"cannot recover {oid}@{shard}: no size metadata")
        chunk_total = self._shard_bytes_total(logical_size)
        soid = shard_oid(oid, shard)
        off = 0
        while True:
            piece = self._rebuild_shard(chunks, shard)
            last = off + len(piece) >= chunk_total
            if not last and not piece:
                # sources hold less data than the size metadata claims
                # (inconsistent mid-write state): restart, don't spin
                return False
            txn = Transaction().write(soid, off, piece)
            if last:
                # attrs (incl. the version stamp) land ONLY on the final
                # window: a half-recovered shard must never claim the
                # authoritative version.  Truncate drops any longer stale
                # tail from a shrinking overwrite the target missed.
                # SnapSet/whiteout re-stamp from the sources so a
                # recovered shard keeps serving snap resolution.
                attrs_hint = attrs_hint or {}
                txn = self._pool_stamp(
                    txn.truncate(soid, chunk_total)
                    .setattr(soid, ecutil.HINFO_KEY,
                             attrs_hint.get(ecutil.HINFO_KEY))
                    .setattr(soid, SIZE_KEY, logical_size)
                    .setattr(soid, VERSION_KEY, vmax)
                    .setattr(soid, SNAPSET_KEY,
                             attrs_hint.get(SNAPSET_KEY))
                    .setattr(soid, WHITEOUT_KEY,
                             attrs_hint.get(WHITEOUT_KEY)),
                    soid,
                )
            tid = self._new_tid()
            sub = ECSubWrite(
                from_shard=shard,
                tid=tid,
                oid=oid,
                transaction=txn,
                # the consistent sources' version, NOT this primary's
                # possibly cold _versions map: a lower number would be
                # silently no-op'd by the target's stale-write gate
                at_version=vmax,
                op_class="recovery",
                rollback=rollback,
            )
            # min_acks=1: the push has exactly one target; if it died,
            # fail loudly instead of reporting a recovery that never ran
            await self._fanout_commit(
                oid, tid, [(f"osd.{target_osd}", sub)],
                {f"osd.{target_osd}"}, min_acks=1,
            )
            self.perf.inc("recover_window")
            if sources:
                # relocation pushes are backfill data movement by
                # definition: account them for the elastic bench's
                # data-moved gate
                self.perf.inc("recovery_backfill_bytes", len(piece))
            if last:
                return True
            await self._recovery_pace()
            off += len(piece)
            chunks, _, _, v2 = await self._gather_consistent(
                oid, src, acting, extents=[(off, win)], op_class="recovery",
                up_shards=up_shards, allow_incomplete=True,
            )
            if v2 != vmax or len(chunks) < self.k:
                return False

    # -- peering (PG.h:2122 Peering + start_recovery_ops role) -------------

    def _peering_authoritative(self, counts: Dict[tuple, int],
                               unseen: int,
                               counts_any: Optional[Dict[tuple, int]] = None,
                               all_visible: bool = False,
                               ) -> Optional[tuple]:
        """Pick the version to recover toward from placed-copy counts.

        Newest version with >= k placed holders wins (assemblable).  A
        newer version with fewer holders is either *possibly acked*
        (holders + unreporting placed positions could reach k) -- then we
        must NOT recover toward older data, return None and wait -- or
        *provably torn* (could never have reached k commits), in which
        case its copies are divergent log entries to roll back.  This is
        the log-authority computation of peering
        (doc/dev/osd_internals/log_based_pg.rst).  For replicated pools
        k == 1, so any visible copy of the newest version is immediately
        authoritative (a full copy is always assemblable)."""
        for v in sorted(counts, reverse=True):
            if counts[v] >= self.k:
                return v
            if counts[v] + unseen >= self.k:
                return None  # possibly acked, unassemblable now: wait
        # No acting version is assemblable.  Before declaring the object
        # absent, consult copies on up-but-NON-acting holders (remap
        # leftovers): if any version could have reached k commits counting
        # those, the write was real -- wait for remap recovery instead of
        # destroying the surviving copies.
        if counts_any:
            for v, n in counts_any.items():
                if n + unseen >= self.k:
                    return None
        if not all_visible:
            # an unreporting OSD anywhere in the cluster could hide
            # committed copies (e.g. remap sources that died): the torn
            # proof is incomplete -- wait, never destroy
            return None
        # every observed version is PROVABLY torn (could not have reached
        # k commits even counting non-acting holders and unreporting
        # placed holders, with every cluster OSD visible): the object's
        # authoritative state is "absent".  Divergent creates and remove
        # leftovers roll back / get removed (the reference rolls back
        # divergent log entries the same way).
        return (0, "")

    def _remap_sources(
        self, shardmap: Dict[int, Dict[str, tuple]], reporting,
    ) -> Tuple[Optional[tuple], Dict[int, int]]:
        """Newest version visible on any up holder and, per shard
        position, one up holder of that version -- the read-source map
        for remap relocation.  Holders that stopped reporting are
        excluded (their copies cannot be read)."""
        vstar = None
        for holders in shardmap.values():
            for holder, v in holders.items():
                if holder in reporting and (vstar is None or v > vstar):
                    vstar = v
        src: Dict[int, int] = {}
        if vstar is not None:
            for s, holders in shardmap.items():
                for holder in sorted(holders):
                    if holder in reporting and holders[holder] == vstar:
                        src[s] = int(holder.split(".", 1)[1])
                        break
        return vstar, src

    async def peering_pass(self, max_active: int = None,
                           backfill: bool = False) -> int:
        """One event/delta-driven peering + recovery round for objects
        whose PRIMARY this engine's OSD currently is.

        Three stages mirroring the reference peering state machine
        (src/osd/PG.cc GetInfo -> GetLog -> GetMissing -> recovery):

        1. **GetInfo**: poll every up OSD's pg-log head/tail (O(1) each).
           Peers whose head equals this primary's watermark contribute
           nothing further -- a clean, quiet cluster costs one tiny
           round-trip per OSD and NO object traffic.
        2. **GetLog**: for peers that advanced, fetch only the log entries
           above the watermark; the named objects (plus the engine's own
           missing-set of writes that skipped down shards) are the only
           candidates.  A watermark below the peer's log tail means the
           history was trimmed: fall back to a full ``pg_list`` scan --
           the reference's log-recovery vs backfill distinction.
        3. **GetMissing/recover**: probe versions for candidate objects
           only (``obj_versions``), compute the authoritative version,
           then roll back divergent (torn) entries via the target's own
           PG log where possible and push full shards otherwise.

        Returns the number of recovery actions attempted (0 == clean from
        this primary's perspective)."""
        from ceph_tpu.utils.config import get_config

        if max_active is None:
            max_active = int(get_config().get_val("osd_recovery_max_active"))
        n_osds = len(self.osds)
        up_osds = [
            f"osd.{i}" for i in range(n_osds)
            if not self.messenger.is_down(f"osd.{i}")
        ]

        # -- stage 1: GetInfo ---------------------------------------------
        infos = await self._meta_roundtrip(
            up_osds, {"op": "pg_log_info"}, timeout=3.0
        )
        self.perf.inc("peering_info_poll")
        # incarnation check BEFORE any watermark is consulted (dup
        # watermarks included): a peer whose boot_id changed is a
        # RESTARTED process -- its log/dup sequence spaces are new, so
        # our per-peer watermarks against the old incarnation are
        # meaningless.  Reset them and force the backfill path; a
        # memstore daemon revived empty would otherwise read as a
        # "quiet peer" (head 0 <= watermark) and its lost shards would
        # never be discovered (the multi-process wipe case).
        restarted = False
        for osd_name, info in infos.items():
            bid = info.get("boot_id")
            if bid is None:
                continue  # pre-boot-id peer: legacy watermark rules
            known = self._peer_boot.get(osd_name)
            if known is not None and known != bid:
                self._peer_seq.pop(osd_name, None)
                self._peer_dup_seq.pop(osd_name, None)
                restarted = True
                self.perf.inc("peering_peer_restarted")
            self._peer_boot[osd_name] = bid
        # reqid-dup exchange rides GetInfo (both the delta and backfill
        # flows pass through here): fetch peers' dup entries above our
        # per-peer watermark so a just-promoted primary answers replayed
        # client ops with their original results (pg_log_dup_t exchange)
        await self._sync_dups(infos)
        candidates = set(self._dirty)
        meta_candidates = set(self._dirty_meta)
        pre_heads: Dict[str, int] = {}
        need_backfill = backfill or restarted
        # CRUSH epoch skew: the map changed since this engine last
        # peered (osd add/rm/out/reweight remapped acting sets with NO
        # log traffic) -- only a full scan finds the misplaced objects
        placement_epoch = getattr(self.placement, "epoch", None)
        if placement_epoch != self._placement_epoch:
            need_backfill = True
        fetches = []
        for osd_name, info in infos.items():
            head, tail = info["head_seq"], info["tail_seq"]
            pre_heads[osd_name] = head
            last = self._peer_seq.get(osd_name)
            if last is not None and head <= last:
                continue  # quiet peer
            if last is None:
                if head == 0 and not info.get("nonempty"):
                    self._peer_seq[osd_name] = 0  # brand-new empty OSD
                    continue
                need_backfill = True  # unknown history (daemon restart on
                continue              # a persistent store, revived peer)
            if last < tail:
                need_backfill = True  # log trimmed past the watermark
                continue
            fetches.append((osd_name, last))

        # -- stage 2: GetLog deltas (independent peers, one round-trip) ---
        if not need_backfill and fetches:
            results = await asyncio.gather(*(
                self._meta_roundtrip(
                    [osd_name],
                    {"op": "pg_log_entries", "from_seq": last},
                    timeout=3.0,
                )
                for osd_name, last in fetches
            ))
            for (osd_name, last), r in zip(fetches, results):
                rep = r.get(osd_name)
                if rep is None:
                    continue  # peer died mid-pass; the event retries
                if not rep["complete"]:
                    need_backfill = True
                    break
                maxseq = last
                for seq, base, tag, ver in rep["entries"]:
                    if tag == "meta":
                        meta_candidates.add(base)
                    else:
                        candidates.add(base)
                    maxseq = max(maxseq, seq)
                self._peer_seq[osd_name] = maxseq
                self.perf.inc("peering_delta_entries", len(rep["entries"]))

        if need_backfill:
            n = await self._peering_backfill(up_osds, max_active, pre_heads)
            # the scan covered the re-placed objects for THIS epoch;
            # advance only after it completes so a failed pass rescans.
            # Deliberately the CAPTURED epoch, not the live one: remaps
            # committed during the scan were not covered, and writing
            # the stale value forces the next pass to rescan them
            self._placement_epoch = placement_epoch  # cephlint: disable=async-rmw-across-await
            return n

        if not candidates and not meta_candidates:
            self.perf.inc("peering_pass")
            return 0

        # -- stage 3: targeted probe --------------------------------------
        oids = sorted(candidates | meta_candidates)
        replies = await self._meta_roundtrip(
            up_osds, {"op": "obj_versions", "oids": oids, "km": self.km},
            timeout=3.0,
        )
        self.perf.inc("peering_probe")
        have: Dict[str, Dict[int, Dict[str, tuple]]] = {}
        meta: Dict[str, Dict[str, int]] = {}
        for osd_name, r in replies.items():
            for base, info in r.get("objects", {}).items():
                if not self._pool_match(info.get("pool")):
                    continue  # another co-hosted pool's object
                for sh, ver in info["shards"].items():
                    have.setdefault(base, {}).setdefault(int(sh), {})[
                        osd_name
                    ] = vt(tuple(ver))
                if info["meta"] is not None and base in meta_candidates:
                    meta.setdefault(base, {})[osd_name] = info["meta"]
        # candidate objects with no copies anywhere (e.g. fully removed)
        for base in candidates:
            have.setdefault(base, {})
        return await self._peering_apply(
            have, meta, set(replies), max_active,
            tracked=candidates, tracked_meta=meta_candidates,
        )

    async def _sync_dups(self, infos: Dict[str, dict]) -> int:
        """Fetch and merge peers' reqid-dup entries newer than our
        per-peer watermarks into the hosting OSD's PG log (the peering
        dup exchange; reference: pg_log_dup_t travels with the log in
        GetLog, src/osd/PGLog.cc merge_log).  Returns entries merged."""
        if self._host_pglog is None:
            return 0
        fetches = [
            (osd_name, self._peer_dup_seq.get(osd_name, 0))
            for osd_name, info in infos.items()
            if osd_name != self.name
            and int(info.get("dup_head", 0)) >
            self._peer_dup_seq.get(osd_name, 0)
        ]
        if not fetches:
            return 0
        results = await asyncio.gather(*(
            self._meta_roundtrip(
                [osd_name], {"op": "pg_dups", "from_seq": last},
                timeout=3.0,
            )
            for osd_name, last in fetches
        ))
        merged = 0
        # merge + watermark advance are one indivisible step: a
        # concurrent peering pass that observes the advanced
        # _peer_dup_seq must be able to rely on these entries already
        # sitting in the host log -- a task switch between them would
        # let that pass skip (and never re-fetch) the gap.
        # cephlint: atomic-section peering-dup-merge
        for (osd_name, last), r in zip(fetches, results):
            rep = r.get(osd_name)
            if rep is None:
                continue  # peer died mid-pass; the next event retries
            maxseq = last
            for seq, reqid, result, d_oid, version in rep["dups"]:
                self._host_pglog.merge_dup(
                    tuple(reqid), result, d_oid,
                    tuple(version) if version is not None else None,
                )
                maxseq = max(maxseq, seq)
                merged += 1
            self._peer_dup_seq[osd_name] = max(
                maxseq, int(rep.get("head", 0)))
        # cephlint: end-atomic-section
        if merged:
            self.perf.inc("dup_entries_merged", merged)
        return merged

    async def _peering_backfill(self, up_osds, max_active,
                                pre_heads: Dict[str, int]) -> int:
        """Full-scan peering (the backfill path): every up OSD serializes
        its holdings via ``pg_list``.  Needed when the log cannot prove
        completeness -- primary restart, revived peer, trimmed log.  On
        success the per-peer watermarks jump to the pre-scan log heads, so
        subsequent passes are delta-driven again."""
        self.perf.inc("peering_backfill")
        self.pg_stats.backfilling = True
        replies = await self._meta_roundtrip(
            up_osds, {"op": "pg_list"}, timeout=3.0
        )
        have: Dict[str, Dict[int, Dict[str, tuple]]] = {}
        meta: Dict[str, Dict[str, int]] = {}
        for osd_name, r in replies.items():
            for ent in r.get("objects", []):
                # (base, shard, ver) pre-round-5 / (base, shard, ver, pool)
                base, shard, ver = ent[0], ent[1], ent[2]
                if len(ent) > 3 and not self._pool_match(ent[3]):
                    continue  # another co-hosted pool's object
                if shard == -1:
                    meta.setdefault(base, {})[osd_name] = ver[0]
                else:
                    have.setdefault(base, {}).setdefault(shard, {})[
                        osd_name
                    ] = vt(tuple(ver))
        try:
            n = await self._peering_apply(
                have, meta, set(replies), max_active,
                tracked=set(have) | self._dirty,
                tracked_meta=set(meta) | self._dirty_meta,
            )
        finally:
            self.pg_stats.backfilling = False
        # entries at or below the pre-scan heads are covered by the scan
        for osd_name in replies:
            h = pre_heads.get(osd_name)
            if h is not None:
                self._peer_seq[osd_name] = max(
                    self._peer_seq.get(osd_name, 0), h
                )
        return n

    async def _peering_apply(self, have, meta, reporting, max_active,
                             tracked=frozenset(),
                             tracked_meta=frozenset()) -> int:
        """Authoritative-version election + recovery execution over the
        gathered shard/meta version maps; maintains the engine's dirty
        sets (objects in ``tracked``/``tracked_meta`` that end the pass
        clean are dropped; unfinished ones are kept for the next event)."""

        def is_my_object(acting) -> bool:
            for s in range(self.km):
                if self._shard_up(acting, s):
                    return f"osd.{acting[s]}" == self.name
            return False

        actions = []  # (oid, shard, target_osd, authoritative, rollback)
        # relocation actions carry a 6th element: {position: holder_osd}
        # read-source overrides for shards living on non-acting OSDs
        reloc_actions = []
        unfinished: set = set()
        for oid in sorted(have):
            acting = self.acting_set(oid)
            if not is_my_object(acting):
                continue  # another OSD is this object's primary
            shardmap = have[oid]
            # placed copies only: a copy on a non-acting OSD (remap
            # leftover) cannot feed _gather_consistent
            counts: Dict[tuple, int] = {}
            unseen = 0
            placed: Dict[int, Optional[tuple]] = {}
            placed_down = False
            for s in range(self.km):
                if acting[s] is None:
                    continue
                holder = f"osd.{acting[s]}"
                if holder not in reporting:
                    unseen += 1
                    placed_down = True
                    continue
                v = shardmap.get(s, {}).get(holder)
                placed[s] = v
                if v is not None:
                    counts[v] = counts.get(v, 0) + 1
            # every copy anywhere (incl. non-acting remap leftovers), one
            # per distinct shard position, for the absent-object proof
            counts_any: Dict[tuple, int] = {}
            for s, holders in shardmap.items():
                best = max(holders.values(), default=None)
                if best is not None:
                    counts_any[best] = counts_any.get(best, 0) + 1
            if placed_down:
                unfinished.add(oid)  # probe again when the holder returns
            if not counts and not counts_any:
                continue
            authoritative = None
            if counts:
                authoritative = self._peering_authoritative(
                    counts, unseen, counts_any,
                    all_visible=len(reporting) >= len(self.osds),
                )
            # remap relocation (the backfill data plane of elastic
            # membership): the acting set cannot assemble the newest
            # version, but every up holder anywhere -- including
            # non-acting remap leftovers -- can.  With no acting holder
            # unreachable (nothing newer can be hiding), recover toward
            # that version reading from wherever the shards actually
            # are.  Without this, an object whose CRUSH placement moved
            # >= m+1 slots in one map change waits forever: the election
            # keeps answering "wait for remap recovery" and no such
            # mechanism would exist.
            relocate_src: Optional[Dict[int, int]] = None
            if authoritative is None and not placed_down:
                vstar, src = self._remap_sources(shardmap, reporting)
                if vstar is not None and len(src) >= self.k:
                    authoritative = vstar
                    relocate_src = src
            if authoritative is None:
                self.perf.inc("peering_wait")
                unfinished.add(oid)
                continue
            for s, cur in placed.items():
                if cur == authoritative:
                    continue
                if cur is None and tuple(authoritative) == (0, ""):
                    continue  # absent object, absent copy: nothing to do
                if cur is None and any(
                    holder not in (f"osd.{acting[s]}",)
                    for holder in shardmap.get(s, {})
                ):
                    # the acting slot lost the shard but a copy still
                    # exists on a non-acting holder (remap leftover):
                    # data is safe, just in the wrong place -- the
                    # pg_stat_t misplaced (not degraded) distinction
                    self.pg_stats.misplaced.add(oid)
                if relocate_src is not None:
                    reloc_actions.append(
                        (oid, s, acting[s], authoritative,
                         cur is not None and cur > authoritative,
                         relocate_src)
                    )
                else:
                    actions.append(
                        (oid, s, acting[s], authoritative,
                         cur is not None and cur > authoritative)
                    )

        meta_actions = []  # (oid, stale_targets)
        unfinished_meta: set = set()
        for oid, holders in meta.items():
            acting = self.acting_set(oid)
            if not is_my_object(acting):
                continue
            newest = max(holders.values())
            try:
                targets = self._meta_targets(oid)
            except IOError:
                unfinished_meta.add(oid)
                continue
            if any(
                acting[s] is not None and not self._shard_up(acting, s)
                for s in range(self.km)
            ):
                unfinished_meta.add(oid)  # a down replica will need this
            stale = [t for t in targets if holders.get(t, 0) < newest]
            if stale:
                meta_actions.append((oid, stale))

        # in-flight rebuild accounting: the action objects count as
        # degraded from here until their recovery completes (the
        # per-object note_recovered calls below and in osd/recovery.py
        # drain the count monotonically while a rebuild runs)
        action_oids = {a[0] for a in actions} | \
            {a[0] for a in reloc_actions} | \
            {m[0] for m in meta_actions}
        self.pg_stats.note_recovering(action_oids)
        failed: set = set()
        if actions and self._use_batched_recovery():
            # the batched background data plane (osd/recovery.py):
            # corked multi-read gather, fused decode, corked multi-push
            # -- throttled against client traffic; objects it cannot
            # prove consistent fall back to the per-object path inside
            failed |= await self._recovery().recover_actions(actions)
            actions = []
        if actions or reloc_actions or meta_actions:
            sem = asyncio.Semaphore(max_active)

            async def recover_one(oid, s, target, authoritative, rb,
                                  sources=None):
                async with sem:
                    try:
                        if rb and await self._try_log_rollback(
                            oid, s, target, authoritative
                        ):
                            self.pg_stats.note_recovered(oid)
                            return
                        if tuple(authoritative) == (0, ""):
                            # no assemblable object behind the torn copy:
                            # nothing to reconstruct, just drop it
                            await self._remove_shard_copy(oid, s, target)
                            self.pg_stats.note_recovered(oid)
                            return
                        await self.recover_shard(
                            oid, s, target, rollback=rb, sources=sources
                        )
                        self.pg_stats.note_recovered(oid)
                    except asyncio.CancelledError:
                        raise
                    except Exception:  # noqa: BLE001 -- a failed recovery
                        # stays pending; the next peering pass retries
                        self.perf.inc("recover_failed")
                        failed.add(oid)

            async def recover_meta(oid, stale):
                async with sem:
                    try:
                        # full-state re-apply: replicas converge in one
                        # step; a removal tombstone propagates AS a
                        # tombstone (re-applying it as a plain write
                        # would resurrect the deleted name)
                        omap, ver, removed = await self._meta_read_full(oid)
                        await self._meta_roundtrip(stale, {
                            "op": "meta_apply", "oid": oid,
                            "version": ver, "omap": omap,
                            "remove": removed,
                        })
                        self.pg_stats.note_recovered(oid)
                    except asyncio.CancelledError:
                        raise
                    except Exception:  # noqa: BLE001
                        self.perf.inc("recover_failed")
                        failed.add(oid)

            await asyncio.gather(
                *(recover_one(*a) for a in actions),
                *(recover_one(*a) for a in reloc_actions),
                *(recover_meta(*m) for m in meta_actions),
            )

        # dirty-set maintenance (pg_missing_t bookkeeping)
        for oid in tracked:
            if oid in unfinished or oid in failed:
                self._dirty.add(oid)
            else:
                self._dirty.discard(oid)
        for oid in tracked_meta:
            if oid in unfinished_meta or oid in failed:
                self._dirty_meta.add(oid)
            else:
                self._dirty_meta.discard(oid)
        # pg-stat epilogue mirroring the dirty maintenance: tracked
        # objects that ended the pass clean drop their degraded
        # markings (liveness victims included); unfinished ones stay
        self.pg_stats.end_pass(
            set(tracked) | set(tracked_meta) | action_oids,
            unfinished | unfinished_meta | failed,
        )
        self.perf.inc("peering_pass")
        return len(actions) + len(reloc_actions) + len(meta_actions)

    async def _remove_shard_copy(self, oid: str, s: int,
                                 target: int) -> None:
        """Remove a provably-torn or leftover shard copy whose object has
        no assemblable authoritative version (divergent create / remove
        leftover): the rollback target is non-existence."""
        soid = shard_oid(oid, s)
        tid = self._new_tid()
        sub = ECSubWrite(
            from_shard=s, tid=tid, oid=oid,
            transaction=Transaction().remove(soid),
            at_version=(0, ""), op_class="recovery", rollback=True,
        )
        await self._fanout_commit(
            oid, tid, [(f"osd.{target}", sub)], {f"osd.{target}"},
            min_acks=1,
        )
        self.perf.inc("remove_torn_copy")

    async def _try_log_rollback(self, oid: str, s: int, target: int,
                                to_version: tuple) -> bool:
        """Ask the divergent shard's OSD to roll its torn entries back
        from its own PG log (truncate + attr restore); True on success.
        False (missing/trimmed/overwrite history) -> caller re-pushes the
        shard.  Reference: divergent-entry rollback,
        src/osd/PGLog.h / ECTransaction rollback records."""
        r = await self._meta_roundtrip(
            [f"osd.{target}"],
            {"op": "pg_rollback", "soid": shard_oid(oid, s),
             "to_version": tuple(to_version)},
            timeout=3.0,
        )
        rep = r.get(f"osd.{target}")
        return bool(rep and rep.get("ok"))

    # -- client-op service (the PrimaryLogPG do_op role) -------------------

    async def client_op(self, msg: dict):
        """Execute one client op routed here by an Objecter.

        Reference: PrimaryLogPG::do_op (src/osd/PrimaryLogPG.cc:1844) --
        the primary OSD owns the PG and executes the op, fanning sub-ops
        to the acting set.  Returns the op's wire-encodable result."""
        kind = msg["kind"]
        reqid = msg.get("reqid")
        qtoken = None
        if msg.get("qos_class"):
            # the op's QoS sub-class travels to its own fan-outs (every
            # kind: reads schedule under the class too)
            qtoken = _OP_QOS.set(msg["qos_class"])
        try:
            if reqid is not None and kind in REQID_FANOUT_KINDS:
                # visible to this op's own fan-outs only (task-scoped);
                # composite kinds (exec/snap_trim) run reqid-free
                # internals
                token = _OP_REQID.set(tuple(reqid))
                try:
                    return await self._client_op_inner(msg)
                finally:
                    _OP_REQID.reset(token)
            return await self._client_op_inner(msg)
        finally:
            if qtoken is not None:
                _OP_QOS.reset(qtoken)

    async def _client_op_inner(self, msg: dict):
        kind = msg["kind"]
        oid = msg.get("oid", "")
        snap = msg.get("snap")
        if snap is not None and kind in ("read", "read_range", "stat"):
            # snap reads resolve to the serving clone (find_object_context)
            oid = await self.resolve_snap(oid, snap)
        if kind == "write":
            await self.write(oid, msg["data"], snapc=msg.get("snapc"))
        elif kind == "read":
            return await self.read(oid)
        elif kind == "write_range":
            await self.write_range(oid, msg["offset"], msg["data"],
                                   snapc=msg.get("snapc"))
        elif kind == "read_range":
            return await self.read_range(oid, msg["offset"], msg["length"])
        elif kind == "remove":
            await self.remove_object(oid, snapc=msg.get("snapc"))
        elif kind == "stat":
            size, hinfo = await self._stat(oid)
            return (size, hinfo)
        elif kind == "snap_rollback":
            await self.snap_rollback(oid, msg["snapid"],
                                     snapc=msg.get("snapc"))
        elif kind == "snap_trim":
            return await self.snap_trim(oid, msg["live_snaps"])
        elif kind == "list_snaps":
            return await self.list_snaps(oid)
        elif kind == "scrub":
            return await self.deep_scrub(oid)
        elif kind == "recover":
            await self.recover_shard(oid, msg["shard"], msg["target"])
        elif kind == "omap_set":
            await self.omap_set(oid, msg["kvs"])
        elif kind == "omap_get":
            return await self.omap_get(oid, msg.get("keys"))
        elif kind == "omap_rm":
            await self.omap_rm(oid, msg["keys"])
        elif kind == "omap_clear":
            await self.omap_clear(oid)
        elif kind == "omap_cas":
            ok, cur = await self.omap_cas(
                oid, msg["key"], msg["expect"], msg["new"]
            )
            return (ok, cur)
        elif kind == "exec":
            ret, out = await self.exec(
                oid, msg["cls"], msg["method"], msg["inp"]
            )
            return (ret, out)
        elif kind == "watch":
            await self.watch(oid, watcher=msg["watcher"])
        elif kind == "unwatch":
            await self.unwatch(oid, watcher=msg["watcher"])
        elif kind == "notify":
            return await self.notify(
                oid, msg.get("payload"),
                msg.get("timeout_ms", 5000) / 1000.0,
            )
        else:
            raise ValueError(f"unknown client op {kind!r}")
        return None
