"""Replicated (primary-copy) storage strategy for the PG engine.

Reference: src/osd/ReplicatedBackend.{h,cc} + the TYPE_REPLICATED arm of
build_pg_backend (src/osd/PGBackend.cc:533-570).  Every acting position
holds a FULL copy of the object; writes fan the same bytes to every up
replica and commit at ``min_size`` acks (pool min_size semantics,
src/osd/osd_types.h pg_pool_t); reads are served from one replica with
the shared version-authoritative gather falling back to newer holders
when the chosen copy is stale.

The machinery -- version gates, per-object write serialization, the
replicated metadata plane, snapshots, scrub scheduling, delta peering,
windowed recovery -- is ``ceph_tpu.osd.pg.PG``, shared with ECBackend,
parameterized by ``k = 1`` (any single full copy is assemblable): the
peering authority election then degenerates to newest-visible-copy-wins,
which is sound precisely because a full copy needs no quorum to decode.

Removal uses a version-stamped WHITEOUT tombstone ("removed") instead of
a bare delete: with k = 1 a single stale surviving copy would otherwise
win the authority election and resurrect the object (the EC strategy
caps survivors below k via its m+1 delete quorum; a replicated pool has
no such arithmetic, so the tombstone IS the guard -- the role the
reference's logged delete + PG-log replay plays, src/osd/PGLog.cc).

Exactly-once replay protection is inherited whole from the shared PG
engine: full-copy sub-writes and tombstone fan-outs are stamped with the
client op's reqid by ``PG._fanout_commit`` exactly like EC sub-writes,
so every applying replica records the PG-log dup entry with the
mutation and a replayed op after primary failover is answered from the
log on whichever replica is promoted (docs/resilience.md)."""

from __future__ import annotations

import asyncio
from typing import Dict, List, Optional

import numpy as np

from ceph_tpu.osd import ecutil
from ceph_tpu.osd.messenger import Messenger
from ceph_tpu.osd.pg import (
    PG,
    SIZE_KEY,
    SNAPSET_KEY,
    VERSION_KEY,
    WHITEOUT_KEY,
    ObjectIncomplete,
    shard_oid,
    snap_oid,
)
from ceph_tpu.osd.types import ECSubWrite, LogEntry, Transaction
from ceph_tpu.utils.perf import PerfCounters

#: WHITEOUT_KEY value marking a plain removal (vs True: a snap whiteout
#: that keeps clones readable).  Any truthy value reads as absent.
REMOVED = "removed"


class ReplicatedBackend(PG):
    """Primary engine for replicated pools: ``size`` full copies."""

    def __init__(
        self,
        size: int,
        osds: List,
        messenger: Messenger,
        name: str = "client",
        placement=None,
        register: bool = True,
        tid_alloc=None,
        perf: Optional[PerfCounters] = None,
        min_size: Optional[int] = None,
    ):
        assert size >= 1
        self.size = size
        self.k = 1          # one full copy assembles the object
        self.km = size      # placed positions
        self.m = size - 1
        # pool min_size default: size - size/2 (reference
        # OSDMonitor::prepare_new_pool / pg_pool_t), i.e. 2 for size=3
        self.min_size = min_size if min_size is not None else max(
            1, size - size // 2
        )
        # identity stripe algebra: a replica stores logical bytes as-is
        self.sinfo = ecutil.StripeInfo(1, 1)
        super().__init__(
            osds, messenger, name=name, placement=placement,
            register=register, tid_alloc=tid_alloc, perf=perf,
        )

    # -- write path --------------------------------------------------------

    def _full_copy_hinfo(self, buf: np.ndarray) -> ecutil.HashInfo:
        """Per-replica crc32c of the full copy (every position stores the
        same bytes, so every cumulative hash is the same)."""
        hinfo = ecutil.HashInfo(self.km)
        if len(buf):
            hinfo.append(0, {s: buf for s in range(self.km)})
        return hinfo

    async def _write_pinned(self, oid: str, data: bytes,
                            snapc=None) -> None:
        """Full-object write: the same bytes to every up replica
        (ReplicatedBackend::submit_transaction -> MOSDRepOp fan-out,
        src/osd/ReplicatedBackend.cc:1 issue_op)."""
        if oid not in self._versions or (
            snapc and oid not in self._snapsets
        ):
            await self._stat(oid)
        snapset, clone_id = self._snap_prepare(oid, snapc)
        version = self._next_version(oid)
        buf = np.frombuffer(data, dtype=np.uint8)
        hinfo = self._full_copy_hinfo(buf)

        acting = self.acting_set(oid)
        up = await self._up_for_write(oid, acting, self.min_size)
        tid = self._new_tid()
        entry = LogEntry(version=version[0], oid=oid, op="write",
                         prior_size=0)
        self.log.append(entry)
        payload = buf.tobytes()
        subs = []
        for s in range(self.km):
            if acting[s] is None:
                continue  # CRUSH hole
            soid = shard_oid(oid, s)
            txn = Transaction()
            if clone_id is not None:
                txn.clone(soid, shard_oid(snap_oid(oid, clone_id), s))
            txn = (
                txn
                .write(soid, 0, payload)
                .truncate(soid, len(payload))
                .setattr(soid, ecutil.HINFO_KEY, hinfo.to_dict())
                .setattr(soid, SIZE_KEY, len(data))
                .setattr(soid, VERSION_KEY, version)
                .setattr(soid, WHITEOUT_KEY, None)
            )
            self._pool_stamp(txn, soid)
            if snapset is not None:
                txn.setattr(soid, SNAPSET_KEY, snapset)
            subs.append((f"osd.{acting[s]}", ECSubWrite(
                from_shard=s, tid=tid, oid=oid, transaction=txn,
                at_version=version, log_entries=[entry])))
        self.perf.inc("write")
        await self._fanout_commit(
            oid, tid, subs, {f"osd.{acting[s]}" for s in up},
            min_acks=self.min_size,
        )
        self._snap_committed(oid, snapset, len(data))

    # -- read path ---------------------------------------------------------

    def _read_quorum_check(self, oid: str, acting, up) -> None:
        """Read-after-ack guard for k=1 (the review r5 finding): every
        acked write reached >= min_size placed replicas, so a NEWER acked
        version can hide entirely among the unreachable holders only if
        >= min_size of them are unreachable.  In that regime the newest
        visible copy may be stale -- refuse, like the reference's PG
        going inactive below min_size, instead of serving silently."""
        placed = sum(1 for s in range(self.km) if acting[s] is not None)
        unseen = placed - len(up)
        if unseen >= self.min_size:
            raise ObjectIncomplete(
                f"{oid}: {unseen} of {placed} replicas unreachable "
                f"(>= min_size {self.min_size}); the newest acked write "
                "may be invisible -- refusing possibly-stale read"
            )

    async def read(self, oid: str) -> bytes:
        """Serve from one replica; the shared gather falls back to newer
        holders if the chosen copy is stale (the primary-read role,
        src/osd/PrimaryLogPG.cc do_osd_ops CEPH_OSD_OP_READ)."""
        acting = self.acting_set(oid)
        up = [s for s in range(self.km) if self._shard_up(acting, s)]
        if not up:
            up = await self._reconfirm_up(acting, up)
        if not up:
            raise IOError(f"cannot read {oid}: no replicas up")
        self._read_quorum_check(oid, acting, up)
        chunks, logical_size, attrs, _ = await self._gather_consistent(
            oid, up[:1], acting, up_shards=up
        )
        if not chunks:
            raise IOError(f"cannot read {oid}: only 0 replicas")
        if (attrs or {}).get(WHITEOUT_KEY) == REMOVED:
            raise IOError(f"cannot read {oid}: removed")
        if logical_size is None:
            raise IOError(f"no size metadata for {oid}")
        data = next(iter(chunks.values())).tobytes()
        self.perf.inc("read")
        return data[:logical_size]

    async def read_range(self, oid: str, offset: int, length: int) -> bytes:
        """Extent read from one replica -- no stripe algebra, the copy IS
        the logical byte stream."""
        size, _ = await self._stat(oid)
        if offset >= size:
            return b""
        length = min(length, size - offset)
        cached = self.extent_cache.get(oid, offset, length)
        if cached is not None:
            self.perf.inc("read_cache_hit")
            return cached
        acting = self.acting_set(oid)
        up = [s for s in range(self.km) if self._shard_up(acting, s)]
        if not up:
            raise IOError(f"cannot range-read {oid}: no replicas up")
        self._read_quorum_check(oid, acting, up)
        chunks, _, _, _ = await self._gather_consistent(
            oid, up[:1], acting, extents=[(offset, length)], up_shards=up,
        )
        if not chunks:
            raise IOError(f"cannot range-read {oid}")
        self.perf.inc("read_range")
        return next(iter(chunks.values())).tobytes()[:length]

    def _pin_bounds(self, offset: int, length: int):
        return offset, offset + max(1, length)

    async def _write_range_pinned(
        self, oid: str, offset: int, data: bytes, pin, snapc=None
    ) -> None:
        """Direct extent fan-out: replicas apply the same extent, gated on
        the base version so a replica that missed history skips (and is
        later recovered) instead of patching stale bytes -- no RMW read
        needed, the defining efficiency of replicated pools."""
        size, hinfo_d = await self._stat(oid)
        snapset, clone_id = self._snap_prepare(oid, snapc)
        base_version = self._versions.get(oid, 0)
        new_size = max(size, offset + len(data))
        buf = np.frombuffer(data, dtype=np.uint8)
        if offset == size and hinfo_d is not None and \
                ecutil.HashInfo.from_dict(hinfo_d).has_chunk_hash():
            hinfo = ecutil.HashInfo.from_dict(hinfo_d)
            hinfo.append(size, {s: buf for s in range(self.km)})
        elif offset == 0 and size == 0:
            hinfo = self._full_copy_hinfo(buf)
        else:
            # overwrite / gap: sizes only, hashes cleared (the
            # ec_overwrites-style reduction the EC strategy also uses)
            hinfo = ecutil.HashInfo(0)
            hinfo.total_chunk_size = new_size

        version = self._next_version(oid)
        acting = self.acting_set(oid)
        up = await self._up_for_write(oid, acting, self.min_size)
        tid = self._new_tid()
        entry = LogEntry(version=version[0], oid=oid, op="write",
                         prior_size=size)
        self.log.append(entry)
        subs = []
        for s in range(self.km):
            if acting[s] is None:
                continue
            soid = shard_oid(oid, s)
            txn = Transaction()
            if clone_id is not None:
                txn.clone(soid, shard_oid(snap_oid(oid, clone_id), s))
            txn = (
                txn
                .write(soid, offset, data)
                .setattr(soid, ecutil.HINFO_KEY, hinfo.to_dict())
                .setattr(soid, SIZE_KEY, new_size)
                .setattr(soid, VERSION_KEY, version)
                .setattr(soid, WHITEOUT_KEY, None)
            )
            self._pool_stamp(txn, soid)
            if snapset is not None:
                txn.setattr(soid, SNAPSET_KEY, snapset)
            subs.append((f"osd.{acting[s]}", ECSubWrite(
                from_shard=s, tid=tid, oid=oid, transaction=txn,
                at_version=version, log_entries=[entry],
                prev_version=base_version)))
        self.perf.inc("write_range")
        await self._fanout_commit(
            oid, tid, subs, {f"osd.{acting[s]}" for s in up},
            min_acks=self.min_size,
        )
        self._snap_committed(oid, snapset, new_size)
        pin.commit(offset, data)

    # -- removal strategy --------------------------------------------------

    async def _destroy_object(self, oid: str, up, acting) -> None:
        """Plain removal via version-stamped tombstone (see module
        docstring): truncate to zero + WHITEOUT "removed" at a NEW
        version, so a revived replica's stale full copy loses the
        authority election to the tombstone instead of resurrecting the
        object.  Recovery then propagates the tombstone (whiteout attr
        included) to stale replicas like any newest-version state."""
        version = self._next_version(oid)
        hinfo = ecutil.HashInfo(self.km)
        tid = self._new_tid()
        subs = []
        for s in up:
            soid = shard_oid(oid, s)
            txn = self._pool_stamp(
                Transaction()
                .truncate(soid, 0)
                .setattr(soid, ecutil.HINFO_KEY, hinfo.to_dict())
                .setattr(soid, SIZE_KEY, 0)
                .setattr(soid, VERSION_KEY, version)
                .setattr(soid, WHITEOUT_KEY, REMOVED)
                .setattr(soid, SNAPSET_KEY, None),
                soid,
            )
            subs.append((f"osd.{acting[s]}", ECSubWrite(
                from_shard=s, tid=tid, oid=oid,
                transaction=txn, at_version=version)))
        await self._fanout_commit(
            oid, tid, subs, {f"osd.{acting[s]}" for s in up},
            min_acks=self.min_size,
        )

    # -- scrub / recovery strategy hooks -----------------------------------

    def _scrub_verify(self, chunks: Dict[int, np.ndarray],
                      report: dict) -> None:
        """Copies must be byte-identical; replicas differing from the
        majority content are flagged (the replicated deep-scrub
        object-compare, reference src/osd/PG.cc scrub_compare_maps /
        be_select_auth_object)."""
        if len(chunks) < 2:
            return
        votes: Dict[bytes, list] = {}
        for s, arr in chunks.items():
            votes.setdefault(arr.tobytes(), []).append(s)
        if len(votes) == 1:
            return
        # majority wins; ties break toward the group containing the
        # lowest shard position (a deterministic auth pick, like the
        # reference's auth-object selection)
        auth = max(votes.values(), key=lambda g: (len(g), -min(g)))
        for group in votes.values():
            if group is not auth:
                report["parity_mismatch"].extend(group)
        report["parity_mismatch"].sort()

    def _min_sources(self, want_shards, up_shards):
        """Any single up replica rebuilds any other."""
        return list(up_shards[:1])

    def _rebuild_shard(self, chunks: Dict[int, np.ndarray],
                       shard: int) -> bytes:
        return next(iter(chunks.values())).tobytes()

    def _shard_bytes_total(self, logical_size: int) -> int:
        """A replica stores exactly the logical bytes."""
        return logical_size
