"""Elastic-membership benchmark stage (bench.py ``elastic_path``).

Online expansion and contraction end-to-end, under concurrent client
load, with every step of the control plane live: ``osd add`` / ``osd
rm`` / ``osd out`` are mon commands that commit paxos osdmap
incrementals whose broadcasts drive CRUSH growth through
``apply_map_view``'s epoch gate -- data only moves once the committed
map says so.

The measured sequence is a +2-OSD expansion: the movement set (the
diff of the pg->acting snapshots around the map change) must stay
within ``moved_ratio_bound`` of the theoretical minimum for the weight
change (straw2's minimal-movement contract), the misplaced census must
peak at map-commit time and drain monotonically (at most
``uptick_bound`` transient upticks -- primary-handoff double counts),
and the cluster must reach HEALTH_OK on a fresh mgr fold with every
object reading back bit-exact.

Three chaos stages then gate the same convergence contract under
churn:

* ``target_kill`` -- a freshly added backfill TARGET dies
  mid-migration; the mon outs it, movement re-plans, and an
  exactly-once write audit must stay exact (no lost or phantom acks).
* ``primary_rm`` -- ``osd rm`` of a LIVE primary under client load:
  graceful drain, zero client-visible errors, the daemon retires only
  after its PGs hand off.
* ``flap`` -- add-then-immediately-rm before any backfill ran: the
  epoch gate resolves the race and no misplaced residue sticks.

Used by bench.py (fields ``elastic_path_*``) and
``tools/ec_benchmark.py --workload elastic-path``; the tier-1 smoke
runs the same code at a tiny shape in tests/test_elastic.py.
"""

from __future__ import annotations

import asyncio
import time
from typing import Dict, List, Optional

import numpy as np

#: k=2/m=1 at 10 OSDs / 128 PGs: a shape whose measured pg-level
#: movement ratio for a +2 expansion sits comfortably under the 1.25x
#: gate (EC positions re-draw independently, so per-position movement
#: compounds above the per-draw straw2 minimum on small clusters)
PROFILE = {"k": "2", "m": "1", "plugin": "jerasure"}
N_OSDS = 10


def _pct(samples: List[float], q: float) -> float:
    if not samples:
        return 0.0
    s = sorted(samples)
    return s[min(len(s) - 1, int(q * len(s)))]


def _upticks(timeline: List[int]) -> int:
    """Transient increases after the census peak -- the monotone-drain
    gate tolerates a couple (primary handoff re-marks an object on the
    new primary one pass before the old primary's entry drains)."""
    if not timeline:
        return 0
    peak_at = timeline.index(max(timeline))
    return sum(
        1 for i in range(peak_at + 1, len(timeline))
        if timeline[i] > timeline[i - 1]
    )


class _Harness:
    """One booted mon-backed cluster + client load + mgr fold loop."""

    def __init__(self, cluster, payload_bytes: int):
        from ceph_tpu.mgr.pgmap import PGMap

        self.cluster = cluster
        self.payload_bytes = payload_bytes
        self.pgmap = PGMap(expected=[o.name for o in cluster.osds])
        self._seq = 0
        self.read_lat: List[float] = []
        self.client_errors: List[str] = []
        #: exactly-once audit ledger: oid -> last ACKED payload
        self.acked: Dict[str, bytes] = {}
        self._stop = asyncio.Event()
        self._tasks: List[asyncio.Task] = []

    # -- mgr fold ----------------------------------------------------------

    def fold_reports(self) -> None:
        """Fold a fresh MgrReport from every live daemon (the in-process
        stand-in for the daemons' report ticks)."""
        from ceph_tpu.mgr.report import MgrReport

        self._seq += 1
        for osd in self.cluster.osds:
            if self.cluster.messenger.is_down(osd.name):
                continue
            if osd.name not in self.pgmap.expected:
                continue  # retired daemon: no longer part of the map
            self.pgmap.apply(MgrReport(
                osd.name, self._seq, 1.0, osd.mgr_report_stats(),
                lag_ms=0.0,
            ))

    def forget_daemon(self, name: str) -> None:
        """Drop a RETIRED daemon from the mgr view (the reference purges
        rm'd osds from the osdmap; a stale entry would read as OSD_DOWN
        forever)."""
        self.pgmap.expected.discard(name)
        self.pgmap.daemons.pop(name, None)
        for by_daemon in self.pgmap.pgs.values():
            by_daemon.pop(name, None)

    def health_status(self) -> str:
        self.fold_reports()
        return self.pgmap.health()["status"]

    # -- ground truth ------------------------------------------------------

    def misplaced_total(self) -> int:
        return sum(
            len(b.pg_stats.misplaced)
            for osd in self.cluster.osds
            for b in osd.pools.values()
        )

    def backfill_bytes(self) -> int:
        return sum(
            osd.perf.snapshot().get("recovery_backfill_bytes", 0)
            for osd in self.cluster.osds
        )

    # -- client load -------------------------------------------------------

    def start_load(self, hot: List[str], payloads: Dict[str, bytes],
                   n_clients: int, writer_oids: List[str]) -> None:
        cluster = self.cluster

        async def reader(idx: int):
            i = idx
            while not self._stop.is_set():
                oid = hot[i % len(hot)]
                t0 = time.perf_counter()
                try:
                    got = await cluster.read(oid)
                    if got != payloads[oid]:
                        self.client_errors.append(f"read {oid}: mismatch")
                except Exception as exc:  # noqa: BLE001
                    self.client_errors.append(f"read {oid}: {exc}")
                self.read_lat.append(time.perf_counter() - t0)
                i += n_clients
                await asyncio.sleep(0)

        async def writer():
            rng = np.random.RandomState(4242)
            i = 0
            while not self._stop.is_set():
                oid = writer_oids[i % len(writer_oids)]
                data = rng.randint(
                    0, 256, size=self.payload_bytes, dtype=np.uint8
                ).tobytes()
                try:
                    await cluster.write(oid, data)
                    # the ack ledger records only COMMITTED payloads:
                    # after any chaos, each oid must read back as
                    # exactly its last acked write (exactly-once audit)
                    self.acked[oid] = data
                except Exception as exc:  # noqa: BLE001
                    self.client_errors.append(f"write {oid}: {exc}")
                i += 1
                await asyncio.sleep(0)

        loop = asyncio.get_event_loop()
        self._tasks = [
            loop.create_task(reader(i)) for i in range(n_clients)
        ]
        if writer_oids:
            self._tasks.append(loop.create_task(writer()))

    async def stop_load(self) -> None:
        self._stop.set()
        for t in self._tasks:
            await t
        self._tasks = []

    # -- convergence -------------------------------------------------------

    async def converge(self, max_rounds: int = 40,
                       mid_round_hook=None) -> Dict:
        """Drive peering to clean: rounds of per-engine passes until two
        consecutive rounds report zero actions AND zero misplaced.  The
        misplaced timeline is sampled after every engine pass (plus the
        census value going in) for the monotone-drain gate.

        ``mid_round_hook()`` fires after each engine pass until it
        returns True -- the chaos stages use it to kill a backfill
        target literally mid-migration (it watches the moved-bytes
        counter, since the batched recovery lane absorbs its actions
        and reports them through counters, not the pass return)."""
        cluster = self.cluster
        timeline = [self.misplaced_total()]
        zero = 0
        rounds = 0
        while rounds < max_rounds:
            n = 0
            for osd in list(cluster.osds):
                if cluster.messenger.is_down(osd.name):
                    continue
                for backend in osd.pools.values():
                    n += await backend.peering_pass()
                timeline.append(self.misplaced_total())
                if mid_round_hook is not None and mid_round_hook():
                    mid_round_hook = None  # fired: re-census sample
                    timeline.append(self.misplaced_total())
            rounds += 1
            if n == 0 and timeline[-1] == 0:
                zero += 1
                if zero >= 2:
                    break
            else:
                zero = 0
        return {
            "rounds": rounds,
            "timeline": timeline,
            "peak": max(timeline),
            "upticks": _upticks(timeline),
            "final": timeline[-1],
        }


def _gate(ok: bool, msg: str) -> None:
    if not ok:
        raise AssertionError(f"elastic-path: {msg}")


async def _run(*, n_objects: int, obj_bytes: int, n_hot: int,
               n_clients: int, moved_ratio_bound: float,
               uptick_bound: int, client_p99_bound_ms: float,
               seed: int) -> Dict:
    from ceph_tpu.osd.cluster import ECCluster
    from ceph_tpu.osd.placement import theoretical_min_moved
    from ceph_tpu.utils.perf import PerfCounters

    PerfCounters.reset_all()
    rng = np.random.RandomState(seed)

    def payload() -> bytes:
        return rng.randint(0, 256, size=obj_bytes, dtype=np.uint8).tobytes()

    cluster = await ECCluster.create_with_mons(
        N_OSDS, dict(PROFILE), pool="elastic",
    )
    h: Optional[_Harness] = None
    try:
        km = cluster.backend.km
        payloads: Dict[str, bytes] = {}
        cold = [f"eo{i}" for i in range(n_objects)]
        hot = [f"hot{i}" for i in range(n_hot)]
        for oid in cold + hot:
            payloads[oid] = payload()
            await cluster.write(oid, payloads[oid])
        shard_bytes = cluster.primary_backend(
            cold[0]
        )._shard_bytes_total(obj_bytes)

        h = _Harness(cluster, obj_bytes)
        writer_oids = [f"cw{i}" for i in range(4)]
        h.start_load(hot, payloads, n_clients, writer_oids)

        async def wait_weight(osd_id: int, nonzero: bool) -> None:
            for _ in range(200):
                w = (cluster.placement.weights[osd_id]
                     if osd_id < len(cluster.placement.weights) else 0)
                if bool(w) == nonzero:
                    return
                await asyncio.sleep(0.02)
            raise AssertionError(
                f"elastic-path: broadcast for osd.{osd_id} never applied")

        # ---- stage 1: measured +2 expansion under load ------------------
        weights_before = list(cluster.placement.weights)
        n_pre_objects = len(payloads)  # all writes before the map change
        new_ids = []
        for _ in range(2):
            osd_id = cluster.add_osd(update_placement=False)
            h.pgmap.expected.add(f"osd.{osd_id}")
            new_ids.append(osd_id)
            rc, out = await cluster.mon_command(
                {"prefix": "osd add", "osd": osd_id})
            _gate(rc == 0, f"osd add {osd_id} failed: {out}")
        for osd_id in new_ids:
            await wait_weight(osd_id, True)
        weights_after = list(cluster.placement.weights)

        t0 = time.perf_counter()
        lat_mark = len(h.read_lat)
        expansion = await h.converge()
        time_to_clean = time.perf_counter() - t0
        expansion_lat = h.read_lat[lat_mark:]

        moved_bytes = h.backfill_bytes()
        min_bytes = theoretical_min_moved(
            weights_before, weights_after, n_pre_objects * km,
        ) * shard_bytes
        ratio = moved_bytes / max(min_bytes, 1.0)
        _gate(expansion["peak"] > 0,
              "expansion produced no misplaced peak (census regressed)")
        _gate(expansion["final"] == 0,
              f"misplaced residue after expansion: {expansion['final']}")
        _gate(expansion["upticks"] <= uptick_bound,
              f"misplaced drained non-monotonically "
              f"({expansion['upticks']} upticks > {uptick_bound}): "
              f"{expansion['timeline']}")
        _gate(moved_bytes > 0, "expansion moved no bytes")
        _gate(ratio <= moved_ratio_bound,
              f"expansion moved {ratio:.3f}x the theoretical minimum "
              f"(bound {moved_ratio_bound}x): {moved_bytes}B vs "
              f"{min_bytes:.0f}B")
        _gate(h.health_status() == "HEALTH_OK",
              f"not HEALTH_OK after expansion: {h.pgmap.health()}")
        p99_ms = _pct(expansion_lat, 0.99) * 1e3
        _gate(p99_ms <= client_p99_bound_ms,
              f"client p99 {p99_ms:.1f}ms breached the "
              f"{client_p99_bound_ms}ms bound during expansion")

        # ---- stage 2: chaos -- kill the backfill target mid-migration ---
        target = cluster.add_osd(update_placement=False)
        h.pgmap.expected.add(f"osd.{target}")
        rc, out = await cluster.mon_command(
            {"prefix": "osd add", "osd": target})
        _gate(rc == 0, f"osd add {target} failed: {out}")
        await wait_weight(target, True)
        killed = {}
        bytes_mark = h.backfill_bytes()

        def kill_target() -> bool:
            moved = h.backfill_bytes() - bytes_mark
            if moved <= 0:
                return False
            # migration toward the new target is in flight RIGHT NOW
            cluster.kill_osd(target)
            killed["at_bytes"] = moved
            return True

        # bounded: with the target dead its objects cannot finish --
        # convergence is gated AFTER the mon outs it and movement
        # re-plans
        chaos_a = await h.converge(max_rounds=2,
                                   mid_round_hook=kill_target)
        # the mon outs the dead target: movement re-plans off it
        rc, out = await cluster.mon_command(
            {"prefix": "osd out", "osd": target})
        _gate(rc == 0, f"osd out {target} failed: {out}")
        await wait_weight(target, False)
        # back up but still OUT (weight 0): its engine rejoins peering
        # -- the forced backfill pass on the new epoch drains the stale
        # misplaced entries it accumulated as a primary before dying
        cluster.revive_osd(target)
        chaos_a2 = await h.converge()
        _gate(killed.get("at_bytes", 0) > 0,
              "target-kill chaos never caught a migration in flight")
        _gate(chaos_a2["final"] == 0,
              f"misplaced residue after target-kill re-plan: "
              f"{chaos_a2['final']}")
        _gate(chaos_a2["upticks"] <= uptick_bound,
              f"non-monotone drain after target-kill: "
              f"{chaos_a2['timeline']}")
        _gate(h.health_status() == "HEALTH_OK",
              f"not HEALTH_OK after target-kill: {h.pgmap.health()}")

        # ---- stage 3: chaos -- osd rm of a live primary under load ------
        victim = cluster.placement.acting(hot[0])[0]
        _gate(victim is not None, "hot primary unmapped")
        rc, out = await cluster.mon_command(
            {"prefix": "osd rm", "osd": victim})
        _gate(rc == 0, f"osd rm {victim} failed: {out}")
        await wait_weight(victim, False)
        chaos_b = await h.converge()
        _gate(chaos_b["peak"] > 0,
              "primary-rm produced no misplaced peak")
        _gate(chaos_b["final"] == 0,
              f"misplaced residue after primary rm: {chaos_b['final']}")
        _gate(chaos_b["upticks"] <= uptick_bound,
              f"non-monotone drain after primary rm: "
              f"{chaos_b['timeline']}")
        # drained clean: NOW the daemon may retire (graceful contraction)
        cluster.retire_osd(victim)
        h.forget_daemon(f"osd.{victim}")
        _gate(h.health_status() == "HEALTH_OK",
              f"not HEALTH_OK after primary rm: {h.pgmap.health()}")

        # ---- stage 4: chaos -- add-then-immediately-rm flap -------------
        flap = cluster.add_osd(update_placement=False)
        h.pgmap.expected.add(f"osd.{flap}")
        rc, out = await cluster.mon_command(
            {"prefix": "osd add", "osd": flap})
        _gate(rc == 0, f"osd add {flap} failed: {out}")
        rc, out = await cluster.mon_command(
            {"prefix": "osd rm", "osd": flap})
        _gate(rc == 0, f"osd rm {flap} failed: {out}")
        # both broadcasts (add epoch, then rm epoch) must land before
        # the residue check means anything; the epoch gate orders them
        await asyncio.sleep(0.3)
        await wait_weight(flap, False)
        chaos_c = await h.converge()
        _gate(chaos_c["final"] == 0,
              f"flap left stuck misplaced residue: {chaos_c['timeline']}")
        _gate(h.health_status() == "HEALTH_OK",
              f"not HEALTH_OK after flap: {h.pgmap.health()}")

        # ---- final audits -----------------------------------------------
        await h.stop_load()
        _gate(not h.client_errors,
              f"{len(h.client_errors)} client-visible errors: "
              f"{h.client_errors[:5]}")
        for oid, data in payloads.items():
            got = await cluster.read(oid)
            _gate(got == data, f"{oid} not bit-exact after the run")
        # exactly-once: every acked write reads back as its LAST ack
        for oid, data in h.acked.items():
            got = await cluster.read(oid)
            _gate(got == data,
                  f"exactly-once audit: {oid} diverged from last ack")

        return {
            "n_osds": N_OSDS,
            "n_objects": n_objects,
            "obj_bytes": obj_bytes,
            "n_clients": n_clients,
            "data_moved_ratio": round(ratio, 4),
            "data_moved_bytes": moved_bytes,
            "theoretical_min_bytes": round(min_bytes),
            "time_to_clean_s": round(time_to_clean, 4),
            "client_p99_during_expansion_ms": round(p99_ms, 3),
            "client_ops_total": len(h.read_lat),
            "misplaced_peak": expansion["peak"],
            "misplaced_upticks": expansion["upticks"],
            "expansion_rounds": expansion["rounds"],
            "audited_writes": len(h.acked),
            "bit_exact": True,  # the gates raised otherwise
            "chaos": {
                "target_kill": {
                    "killed_mid_migration": True,
                    "rounds": chaos_a["rounds"] + chaos_a2["rounds"],
                    "upticks": chaos_a2["upticks"],
                },
                "primary_rm": {
                    "victim": victim,
                    "rounds": chaos_b["rounds"],
                    "peak": chaos_b["peak"],
                    "upticks": chaos_b["upticks"],
                },
                "flap": {
                    "rounds": chaos_c["rounds"],
                    "residue": chaos_c["final"],
                },
            },
        }
    finally:
        if h is not None:
            await h.stop_load()
        await cluster.shutdown()


def run_elastic_path_bench(*, smoke: bool = False,
                           moved_ratio_bound: float = 1.25,
                           uptick_bound: int = 2,
                           client_p99_bound_ms: float = 2000.0,
                           seed: int = 99) -> Dict:
    """Boot, expand, contract, converge; returns the metric dict or
    raises AssertionError on any gate.  ``smoke`` shrinks object count,
    size and client fan-out for the tier-1 run -- same topology, same
    code paths, same gates."""
    kwargs = dict(
        n_objects=24 if smoke else 72,
        obj_bytes=(4 << 10) if smoke else (12 << 10),
        n_hot=8 if smoke else 16,
        n_clients=8 if smoke else 24,
        moved_ratio_bound=moved_ratio_bound,
        uptick_bound=uptick_bound,
        client_p99_bound_ms=client_p99_bound_ms,
        seed=seed,
    )
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(_run(**kwargs))
    finally:
        loop.close()
