"""Wire/value types for the EC storage backend (ECMsgTypes equivalents).

Reference: src/osd/ECMsgTypes.h -- ECSubWrite (:23-89), ECSubWriteReply
(:91-103), ECSubRead (:105), ECSubReadReply (:118); ObjectStore::Transaction
(src/os/Transaction.cc) reduced to the op set the EC path uses.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import numpy as np


@dataclasses.dataclass
class TxnOp:
    """A single ObjectStore transaction op (append/write/setattr/remove)."""

    op: str  # "write" | "setattr" | "remove" | "truncate"
    oid: str = ""
    offset: int = 0
    data: bytes = b""
    attr_name: str = ""
    attr_value: object = None


@dataclasses.dataclass
class Transaction:
    ops: List[TxnOp] = dataclasses.field(default_factory=list)

    def write(self, oid: str, offset: int, data: bytes) -> "Transaction":
        self.ops.append(TxnOp("write", oid=oid, offset=offset, data=bytes(data)))
        return self

    def setattr(self, oid: str, name: str, value) -> "Transaction":
        self.ops.append(
            TxnOp("setattr", oid=oid, attr_name=name, attr_value=value)
        )
        return self

    def remove(self, oid: str) -> "Transaction":
        self.ops.append(TxnOp("remove", oid=oid))
        return self

    def truncate(self, oid: str, offset: int) -> "Transaction":
        self.ops.append(TxnOp("truncate", oid=oid, offset=offset))
        return self

    def clone(self, oid: str, dst_oid: str) -> "Transaction":
        """Copy ``oid`` (data + xattrs) to ``dst_oid`` -- the COW-clone
        primitive snapshots ride on (reference: ObjectStore clone,
        PrimaryLogPG::make_writeable cloning the head before a write
        under a newer SnapContext)."""
        self.ops.append(TxnOp("clone", oid=oid, attr_name=dst_oid))
        return self

    # -- omap (reference: ObjectStore omap_setkeys/rmkeys/clear; the
    # per-object sorted key->value map cls/mds/rbd metadata lives in) ----

    def omap_setkeys(self, oid: str, kvs: Dict[str, bytes]) -> "Transaction":
        self.ops.append(
            TxnOp("omap_set", oid=oid,
                  attr_value={k: bytes(v) for k, v in kvs.items()})
        )
        return self

    def omap_rmkeys(self, oid: str, keys: List[str]) -> "Transaction":
        self.ops.append(TxnOp("omap_rm", oid=oid, attr_value=list(keys)))
        return self

    def omap_clear(self, oid: str) -> "Transaction":
        self.ops.append(TxnOp("omap_clear", oid=oid))
        return self


@dataclasses.dataclass
class LogEntry:
    """Minimal pg-log entry: enough for rollback-aware appends
    (reference: ECSubWrite carries log entries + rollback versions,
    doc/dev/osd_internals/erasure_coding/ecbackend.rst:9-27)."""

    version: int
    oid: str
    op: str  # "append" | "touch" | "delete"
    prior_size: int = 0  # for append rollback


@dataclasses.dataclass
class ECSubWrite:
    from_shard: int
    tid: int
    oid: str
    transaction: Transaction
    #: object version tuple ``(counter, writer)`` — the eversion analogue
    #: with a writer tiebreak so two primaries racing the same counter
    #: produce *distinct, totally ordered* versions (no same-version mix)
    at_version: tuple
    log_entries: List[LogEntry] = dataclasses.field(default_factory=list)
    #: QoS class for the OSD op queue ("client" | "recovery" | "scrub")
    op_class: str = "client"
    #: peering-authorized rollback: lets a recovery push OVERWRITE a
    #: higher-versioned shard copy.  Set only when the primary's peering
    #: pass proved the newer version a torn write (held by < k shards
    #: with every mapped shard reporting) — the PG-log divergent-entry
    #: rollback role (reference doc/dev/osd_internals/log_based_pg.rst)
    rollback: bool = False
    #: base-version gate for INCREMENTAL writes (RMW extent writes): the
    #: version counter this write was computed on top of.  A shard whose
    #: applied counter differs missed history (e.g. it was down and
    #: revived hollow) — applying just the extent would stamp the new
    #: version over an object mostly made of stale/absent bytes, so the
    #: shard must skip the write and wait for recovery instead (the PG
    #: missing-set role, reference src/osd/PG.h pg_missing_t).  None for
    #: full-rewrite transactions, which are safe on any base.
    prev_version: object = None
    #: originating client op's reqid ``(client, incarnation, tid)`` for
    #: client-class sub-ops (the osd_reqid_t role): the applying shard
    #: records a PG-log dup entry so a replayed op after primary
    #: failover is answered from the log instead of re-executed.  None
    #: for recovery/scrub pushes and legacy senders.
    reqid: object = None
    #: originating op's trace context ``[trace_id, parent_span_id]``
    #: (utils/trace.py): the applying shard's sub-write span joins the
    #: client op's trace so one op stitches client -> primary ->
    #: sub-write across daemons.  None for unsampled ops and pre-trace
    #: senders (trailing optional wire field, msg/wire.py).
    trace: object = None
    #: originating client's QoS sub-class (gold/bulk/...; docs/qos.md):
    #: the RECEIVING shard's op queue orders this sub-write under that
    #: class, so end-to-end reservations hold through the replica hop,
    #: not just at the primary's admission.  Distinct from ``op_class``
    #: on purpose -- the version-gate/dup semantics key on op_class and
    #: must not change with scheduling class.  None = plain "client"
    #: (trailing optional wire field).
    qos_class: object = None


@dataclasses.dataclass
class ECSubWriteReply:
    from_shard: int
    tid: int
    committed: bool = False
    applied: bool = False
    #: set when a client-class write was refused as stale: the shard's
    #: currently-applied version tuple, so the writer can detect the
    #: conflict and retry at a higher version instead of believing a
    #: commit that never applied
    current_version: object = None
    #: the shard skipped an incremental write because its base version
    #: did not match ``prev_version`` (it missed history): it must NOT be
    #: counted toward the write's k-commit quorum, and it stays on the
    #: old version until peering recovers it
    missed: bool = False


@dataclasses.dataclass
class ECSubRead:
    from_shard: int
    tid: int
    # oid -> list of (offset, length) chunk-space extents
    to_read: Dict[str, List[Tuple[int, int]]] = dataclasses.field(
        default_factory=dict
    )
    attrs_to_read: List[str] = dataclasses.field(default_factory=list)
    subchunks: Dict[str, List[Tuple[int, int]]] = dataclasses.field(
        default_factory=dict
    )
    #: QoS class for the OSD op queue ("client" | "recovery" | "scrub")
    op_class: str = "client"
    #: originating op's trace context (see ECSubWrite.trace); trailing
    #: optional wire field, None for unsampled ops / pre-trace senders
    trace: object = None
    #: originating client's QoS sub-class (see ECSubWrite.qos_class);
    #: trailing optional wire field
    qos_class: object = None
    #: regenerating-code repair lane (plugins/regen.py): oid -> the
    #: GF(2^8) helper coefficients (phi_f).  The serving shard does NOT
    #: return raw extents for these oids -- it dots its own stored
    #: sub-chunks with the coefficients and replies the beta-sized
    #: helper symbol.  Trailing optional wire field, None for classic
    #: extent reads / pre-regen senders.
    regen: object = None


@dataclasses.dataclass
class ECSubReadReply:
    from_shard: int
    tid: int
    buffers_read: Dict[str, List[Tuple[int, bytes]]] = dataclasses.field(
        default_factory=dict
    )
    attrs_read: Dict[str, Dict[str, object]] = dataclasses.field(
        default_factory=dict
    )
    errors: Dict[str, int] = dataclasses.field(default_factory=dict)
