"""OSD daemon shard: op queue, sub-op service, ticks (the OSD role).

Reference: src/osd/OSD.{h,cc} -- ShardedOpWQ dispatch (OSD.h:1566), the
tick loop (OSD::tick), scrub scheduling, heartbeat fast-dispatch -- plus
the replica-side sub-op handlers (ECBackend::handle_sub_write/:922,
handle_sub_read/:987, which are strategy-agnostic here: a replicated
pool's full-copy sub-ops ride the same version-gated transaction apply).

Split out of ecbackend.py in round 5 so the primary-engine strategies
(EC / replicated) and the daemon role can evolve independently -- the
reference's OSD vs PG/PGBackend layering (src/osd/PGBackend.cc:533).
"""

from __future__ import annotations

import asyncio
import os
import time
from typing import Dict

from ceph_tpu.osd import ecutil
from ceph_tpu.osd.messenger import Messenger
from ceph_tpu.osd.pg import (
    MCLOCK_DEFAULTS,
    OP_PRIORITY,
    POOL_KEY,
    SIZE_KEY,
    SNAPSET_KEY,
    VERSION_KEY,
    WHITEOUT_KEY,
    shard_oid,
    vt,
)
from ceph_tpu.osd.types import (
    ECSubRead,
    ECSubReadReply,
    ECSubWrite,
    ECSubWriteReply,
    Transaction,
)
from ceph_tpu.native.gf_native import crc32c
from ceph_tpu.profiling import ledger as _profiler
from ceph_tpu.utils import trace
from ceph_tpu.utils.perf import PerfCounters, stage_histogram

#: wire-tax cost centers for the OSD execution seam (fetched once; a
#: global-bool branch when profiling is off).  ``osd.op_exec`` bills the
#: per-op bookkeeping sections of the one-at-a-time path;
#: ``osd.batch_exec`` bills the array passes of the batched fast path --
#: the pair is what the bench's OSD-exec share A/B compares.  Markers
#: never span an await (exclusive-time protocol).
_PS_OP = _profiler.stage("osd.op_exec")
_PS_BATCH = _profiler.stage("osd.batch_exec")

#: client-op kinds subject to reqid dup detection: every kind that
#: mutates state (re-executing a replay would double-apply or return a
#: post-apply answer).  Reads and watch bookkeeping stay dedup-free,
#: like the reference (only logged ops get pg_log_dup_t entries).
MUTATING_KINDS = frozenset({
    "write", "write_range", "remove", "snap_rollback", "snap_trim",
    "omap_set", "omap_rm", "omap_clear", "omap_cas", "exec",
})

#: composite kinds whose result is only known at completion: their dup
#: entries are pushed to the acting set by an explicit awaited
#: ``dup_record`` fan-out before the reply (everything else records
#: dups on the fan-out that performs the mutation -- zero extra RTT)
_RESULT_FANOUT_KINDS = frozenset({"exec", "snap_trim"})


class OSDShard:
    """One OSD daemon holding one shard position per object it stores.

    Incoming EC sub-ops pass through a QoS op queue served by a worker
    loop — the ShardedOpWQ role (reference src/osd/OSD.h:1566), with the
    queue discipline selected like ``osd_op_queue``: ``wpq`` (default) or
    ``mclock`` (src/osd/mClockOpClassQueue).  Heartbeat pings bypass the
    queue (the reference's fast-dispatch path).
    """

    def __init__(self, osd_id: int, messenger: Messenger,
                 op_queue: str = "wpq", objectstore: str = "memstore",
                 data_path: str = ""):
        from ceph_tpu.osd.opqueue import MClockQueue, WeightedPriorityQueue
        from ceph_tpu.osd.pglog import PGLog
        from ceph_tpu.utils.optracker import OpTracker

        self.osd_id = osd_id
        self.name = f"osd.{osd_id}"
        #: per-incarnation nonce (the reference's up_from epoch role):
        #: carried in pg_log_info replies so peers detect a RESTARTED
        #: daemon -- its in-memory log sequence space is new, so their
        #: watermarks against the old incarnation are meaningless and
        #: peering must take the backfill path (the multi-process
        #: kill+revive wipe case: a memstore daemon revives empty with
        #: head_seq 0, which would otherwise read as "quiet peer")
        self.boot_id = os.urandom(8).hex()
        # reference ObjectStore::create (src/os/ObjectStore.cc:63): backend
        # chosen by name, data under the osd's own dir.  An empty data_path
        # propagates as-is so the factory rejects pathless persistent
        # backends instead of writing under the filesystem root.
        from ceph_tpu import objectstore as os_mod

        self.store = os_mod.create(
            objectstore, f"{data_path}/osd.{osd_id}" if data_path else ""
        )
        self.messenger = messenger
        self.perf = PerfCounters(f"osd.{osd_id}")
        self.pglog = PGLog()
        #: per-shard-object applied version tuple (counter, writer): the
        #: QoS queue may legally reorder a low-priority recovery push
        #: behind a newer client write, and racing primaries may deliver
        #: writes out of version order, so applies are version-gated
        #: (reference: recovery pushes carry the object version and PG
        #: logic discards stale ones; primaries racing is impossible in
        #: the reference because one primary OSD serializes a PG)
        self._applied_version: Dict[str, tuple] = {}
        #: watch/notify state (reference src/osd/Watch.cc): oid -> watchers
        self.watches: Dict[str, Dict[str, bool]] = {}
        self._notify_seq = 0
        self._notify_pending: Dict[int, tuple] = {}
        #: OSD-side meta_apply fan-out acks (CAS replication authority)
        self._meta_tid = 0
        self._meta_pending: Dict[int, tuple] = {}
        self.optracker = OpTracker(perf=self.perf, name=self.name)
        #: peer name -> last heartbeat pong time (handle_osd_ping role)
        self.hb_pongs: Dict[str, float] = {}
        #: entity -> OSDCap; entities absent here run with the open
        #: default (client.admin allow *).  Populated via
        #: set_client_caps from keyring "caps osd" strings.
        self.client_caps: Dict[str, object] = {}
        # 2D latency x size grid (PerfHistogram<2>, dumped by the
        # admin-socket `perf histogram dump` like l_osd_op_*_lat_*)
        from ceph_tpu.utils.perf import HistogramAxis, PerfHistogram

        self.op_hist = PerfHistogram(
            f"osd.{osd_id}.op_latency_size",
            HistogramAxis("latency_usec", 0, 64, 32, "log2"),
            HistogramAxis("size_bytes", 0, 512, 24, "log2"),
        )
        # per-stage latency attribution (docs/observability.md): these
        # feed the prometheus _bucket/_sum/_count series the mgr module
        # exposes, and mirror the trace-span segments
        self.h_queue_wait = stage_histogram(
            f"osd.{osd_id}.op_queue_wait_usec")
        self.h_dispatch = stage_histogram(
            f"osd.{osd_id}.op_dispatch_usec")
        # object-access temperature tracking (src/osd/HitSet.h; feeds
        # the tiering-agent role and the admin-socket hit_set commands)
        from ceph_tpu.osd.hitset import HitSetTracker

        self.hitsets = HitSetTracker()
        # device-resident cache tier (ceph_tpu/tier/): hot objects'
        # encoded shards stay in device memory, byte-budgeted against
        # the process-wide HBM ledger; the agent (tier_tick) promotes /
        # flushes / evicts by hit-set temperature.  temp_fn late-binds
        # through self so a swapped tracker is picked up.
        from ceph_tpu.tier.device_tier import DeviceTierStore

        self.tier = DeviceTierStore(
            perf=self.perf,
            temp_fn=lambda pool, oid: self.hitsets.temperature(oid),
        )
        self.tier_agent = None  # built lazily on the first active tick
        self.op_queue_type = op_queue
        if op_queue == "mclock":
            # the base classes keep their legacy 4KiB-unit rates; the
            # osd_qos_profile's EXTRA classes (client sub-classes like
            # gold/bulk) join scaled from MiB/s to 4KiB units so one
            # profile string governs both the op queue and the unified
            # admission layer
            from ceph_tpu.osd.qos import parse_profile

            classes = dict(MCLOCK_DEFAULTS)
            for kname, (res, wgt, lim) in parse_profile().items():
                if kname not in classes:
                    classes[kname] = (res * 256.0, wgt, lim * 256.0)
            self.opq = MClockQueue(classes)
        else:
            self.opq = WeightedPriorityQueue()
        # unified QoS admission (osd/qos.py, osd_qos_unified): the
        # dmClock tags become the data plane's admission stage --
        # ``qos`` grants BATCH dispatches (coalesced client encodes,
        # recovery cycles, scrub rounds; counted per batch for the
        # recovery/scrub classes), ``qos_ops`` grants client-op
        # execution slots in tag order by the op's qos_class (counted
        # per op).  Two slot pools, one profile: an op holding an
        # execution slot may wait on a batch slot but never the other
        # way around, so no admission cycle can form.
        from ceph_tpu.utils.config import get_config as _get_config

        self.qos = None
        self.qos_ops = None
        if bool(_get_config().get_val("osd_qos_unified")):
            from ceph_tpu.osd.qos import (QoSAdmission, parse_profile,
                                          profile_bytes_per_s)

            qclasses = profile_bytes_per_s(parse_profile())
            self.qos = QoSAdmission(
                classes=qclasses, perf=self.perf,
                perf_classes={"recovery", "scrub"},
            )
            self.qos_ops = QoSAdmission(
                slots=int(_get_config().get_val("osd_qos_op_slots")),
                classes=qclasses, perf=self.perf,
                perf_classes=set(qclasses) - {"recovery", "scrub"},
            )
        self._op_event = asyncio.Event()
        #: background-scrub rotating cursor (PG scrub scheduling role)
        self._scrub_cursor = 0
        #: simulates a hung daemon: alive on the wire but never responding
        #: (what OSD heartbeats exist to catch, reference OSD.cc:4612
        #: handle_osd_ping / HeartbeatMap suicide timeouts)
        self.frozen = False
        #: pools this OSD can act as PRIMARY for: pool name -> hosted
        #: ECBackend engine (the PrimaryLogPG role; reference
        #: src/osd/PGBackend.cc:533 build_pg_backend per PG)
        self.pools: Dict[str, "ECBackend"] = {}
        #: per-pool PG activity state ("active" | "peering"): while a
        #: pool is peering after a liveness event, client ops get an
        #: explicit ``backoff`` reply instead of queueing (the RADOS PG
        #: backoff protocol, src/osd/osd_types.h Backoff); engaged only
        #: while the background tick loop runs (see request_peering)
        self.pg_states: Dict[str, str] = {}
        #: pool -> client entities holding a backoff, released with one
        #: ``backoff_release`` each when the pool reactivates
        self._backoffs: Dict[str, set] = {}
        #: shared tid space across hosted backends so a forwarded reply
        #: matches exactly one engine's pending op
        self._host_tid = 0
        #: bound on concurrently executing client ops (the osd_op_tp
        #: thread-count role)
        self._cop_sem = asyncio.Semaphore(64)
        self._cop_seq = 0
        #: array-batched client-op execution (osd_op_batch_exec): the
        #: worker drains same-kind client-op RUNS off the queue and runs
        #: their bookkeeping as batch passes -- one optracker request,
        #: one dups-registry scan, per-class amortized QoS admission,
        #: one corked reply burst (resolved once per daemon; the bench
        #: builds a fresh harness per A/B mode)
        self._batch_exec = bool(_get_config().get_val("osd_op_batch_exec"))
        self._batch_max = max(1, int(_get_config().get_val(
            "osd_op_batch_max")))
        #: queued-or-executing client ops (the background throttle's
        #: saturation signal: recovery/scrub batches back off while
        #: this is high -- osd/recovery.py BackgroundThrottle)
        self._client_ops_queued = 0
        messenger.register(self.name, self.dispatch)
        messenger.adopt_task(
            f"{self.name}.opwq",
            asyncio.get_event_loop().create_task(self._op_worker()),
        )

    def _next_host_tid(self) -> int:
        self._host_tid += 1
        return self._host_tid

    def host_pool(self, pool: str, ec, n_osds: int, placement=None,
                  pool_type: str = "erasure", size: int = 3,
                  min_size=None):
        """Attach a primary engine for ``pool`` to this OSD.  Every OSD in
        the cluster hosts one; clients route each op to the object's
        current primary (first up shard of the acting set).

        ``pool_type`` selects the PGBackend strategy like the reference's
        build_pg_backend switch (src/osd/PGBackend.cc:533-570):
        "erasure" -> ECBackend driven by the ``ec`` codec;
        "replicated" -> ReplicatedBackend with ``size`` full copies
        (``ec`` is ignored)."""
        if pool_type == "replicated":
            from ceph_tpu.osd.replicated import ReplicatedBackend

            backend = ReplicatedBackend(
                size, list(range(n_osds)), self.messenger, name=self.name,
                placement=placement, register=False,
                tid_alloc=self._next_host_tid, perf=self.perf,
                min_size=min_size,
            )
        else:
            from ceph_tpu.osd.ecbackend import ECBackend

            backend = ECBackend(
                ec, list(range(n_osds)), self.messenger, name=self.name,
                placement=placement, register=False,
                tid_alloc=self._next_host_tid, perf=self.perf,
                min_size=min_size,
            )
        backend.pool_name = pool
        # exactly-once hookup: the engine's peering pass merges peers'
        # reqid-dup entries into THIS daemon's PG log, so a promotion
        # to primary answers replayed client ops from the log
        backend._host_pglog = self.pglog
        self.pg_states[pool] = "active"
        # cache-tier hookup: the engine serves tier hits / write-through
        # updates against this OSD's store, and feeds the hit sets the
        # agent ranks temperature from (late-bound lambdas: replacing
        # self.hitsets mid-test must redirect the feeds too)
        backend._tier = self.tier
        backend._hitset_record = lambda oid: self.hitsets.record(oid)
        backend._hitset_temp = lambda oid: self.hitsets.temperature(oid)
        # background-throttle hookup: the engine's recovery/scrub
        # batches consult THIS daemon's client-queue depth to back off
        # under saturation (osd/recovery.py BackgroundThrottle)
        backend._host_shard = self
        # unified QoS hookup: the engine's codec coalescers admit each
        # fused batch through this daemon's dmClock slots (the
        # batching-and-QoS-as-one-layer fusion, osd/qos.py); the
        # recovery/scrub paths reach the same admission via
        # _host_shard.qos inside the BackgroundThrottle
        if self.qos is not None:
            for co in (getattr(backend, "_enc_coalescer", None),
                       getattr(backend, "_dec_coalescer", None)):
                if co is not None:
                    co.admission = self.qos
        # mesh data plane membership (osd_mesh_data_plane): bind this
        # daemon to a mesh device slot so its PG-shard slice lives on
        # (and its inbound chunks are delivered through) the device
        # plane; daemons past the device count stay out-of-mesh and
        # keep the wire path
        from ceph_tpu.parallel import mesh_plane as mesh_mod

        plane = mesh_mod.current_plane()
        if plane is not None:
            plane.bind(self.name)
        self.pools[pool] = backend
        return backend

    def set_client_caps(self, entity: str, caps: str) -> None:
        """Confine ``entity``'s client ops to an OSDCap string (the
        keyring 'caps osd' line, ref src/osd/OSDCap.h)."""
        from ceph_tpu.auth.caps import OSDCap

        self.client_caps[entity] = OSDCap.parse(caps)

    # -- background tick: peering-driven recovery (OSD::tick role) ---------

    def start_tick(self, interval: float = None) -> None:
        """Start the background tick loop (reference OSD::tick,
        src/osd/OSD.cc): each tick runs a peering pass over the hosted
        pools, auto-recovering missing/stale shards.  Idempotent."""
        if getattr(self, "_tick_task", None) is not None:
            return
        if interval is None:
            from ceph_tpu.utils.config import get_config

            interval = float(get_config().get_val("osd_tick_interval"))
        self._tick_interval = interval
        self._peer_event = asyncio.Event()
        self._tick_task = asyncio.get_event_loop().create_task(
            self._tick_loop()
        )
        self.messenger.adopt_task(f"{self.name}.tick", self._tick_task)

    def request_peering(self) -> None:
        """Wake the peering loop NOW (event-driven peering: OSDMap epoch
        change, OSD up/down -- the reference re-peers on every map change,
        src/osd/PG.cc peering state machine, instead of waiting out a
        timer).  No-op until start_tick has run.

        While the loop is running, a liveness event also flips every
        hosted pool to "peering": client ops arriving before the next
        pass completes get an explicit backoff instead of racing the
        role handoff (the RADOS PG backoff protocol; a replayed op must
        not be served until the dup exchange and divergent-entry
        rollback of peering have run)."""
        ev = getattr(self, "_peer_event", None)
        if ev is not None:
            for pool in self.pools:
                if self.pg_states.get(pool) != "peering":
                    self.pg_states[pool] = "peering"
                    self.perf.inc("pg_peering")
            ev.set()

    async def _tick_loop(self) -> None:
        while True:
            try:
                await self.peering_tick()
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 -- a failed pass must not
                # kill the loop; state is retried next tick
                import sys
                import traceback

                traceback.print_exc(file=sys.stderr)
            # sleep until the next scheduled tick OR a peering event
            # (up/down/map change) -- whichever comes first
            try:
                await asyncio.wait_for(
                    self._peer_event.wait(), timeout=self._tick_interval
                )
            except asyncio.TimeoutError:
                pass
            self._peer_event.clear()

    async def peering_tick(self) -> int:
        """One peering round over every hosted pool, then a rate-limited
        background deep-scrub slice; returns the number of recovery
        actions attempted."""
        if self.frozen or self.messenger.is_down(self.name):
            return 0
        total = 0
        for pool, backend in list(self.pools.items()):
            total += await backend.peering_pass()
            # the pass completed (dup exchange + authority election +
            # recovery kickoff): the pool is active again -- release
            # every client parked on a backoff so their ops resend the
            # moment the PG is serviceable (RADOS backoff_release)
            if self.pg_states.get(pool) == "peering":
                await self._activate_pool(pool)
        total += await self.scrub_tick()
        total += await self.tier_tick()
        return total

    async def _activate_pool(self, pool: str) -> None:
        self.pg_states[pool] = "active"
        for client in sorted(self._backoffs.pop(pool, ())):
            await self.messenger.send_message(self.name, client, {
                "op": "backoff_release", "pool": pool, "from": self.name,
            })
            self.perf.inc("backoff_release_sent")

    def _scrub_base_list(self):
        """Base-oid list for the scrub cursor; rebuilt only when the
        cursor wraps (a fresh listing every tick would pay O(objects)
        to pick osd_scrub_objects_per_tick of them)."""
        cached = getattr(self, "_scrub_bases", None)
        if cached is None or self._scrub_cursor == 0 or                 self._scrub_cursor >= len(cached):
            bases = set()
            tags: Dict[str, object] = {}
            for stored in self.store.list_objects():
                base, _, tag = stored.rpartition("@")
                if base and tag.isdigit():
                    bases.add(base)
                    if base not in tags:
                        # pool membership of the base (co-hosted pools
                        # must not scrub each other's objects)
                        tags[base] = self.store.getattr(stored, POOL_KEY)
            cached = sorted(bases)
            self._scrub_bases = cached
            self._scrub_pool_tags = tags
            self._scrub_cursor = min(self._scrub_cursor, len(cached))                 if cached else 0
        return cached

    async def scrub_tick(self) -> int:
        """Background deep-scrub scheduler (reference: PG scrub
        reservation/scheduling, src/osd/PG.cc): each tick deep-scrubs up
        to ``osd_scrub_objects_per_tick`` objects this OSD is currently
        PRIMARY for (rotating cursor over the local store), tagged with
        the mClock ``scrub`` op class, and feeds any inconsistency
        straight into shard recovery -- the cluster heals silent
        corruption with no manual call (qa test-erasure-eio role)."""
        from ceph_tpu.utils.config import get_config

        limit = int(get_config().get_val("osd_scrub_objects_per_tick"))
        if limit <= 0 or not self.pools:
            return 0
        # error records for objects this OSD no longer leads pin mgr
        # health forever (the new primary re-detects real damage): drop
        for backend in self.pools.values():
            for e_oid in list(backend.scrub_errors):
                e_acting = backend.acting_set(e_oid)
                lead = None
                for sh in range(backend.km):
                    if backend._shard_up(e_acting, sh):
                        lead = f"osd.{e_acting[sh]}"
                        break
                if lead != self.name:
                    backend.scrub_errors.pop(e_oid, None)
        bases = self._scrub_base_list()
        if not bases:
            return 0
        repaired = 0
        scanned = 0
        n = len(bases)
        # phase 1 -- candidate collection (no awaits: the cursor walk
        # stays consistent); the slice's objects then ride ONE batched
        # chunk-cursor read per backend instead of one whole-shard
        # fan-out each (the round-14 background data plane)
        slices: Dict[object, list] = {}
        for _ in range(n):
            if scanned >= limit:
                break
            base = bases[self._scrub_cursor % n]
            self._scrub_cursor = (self._scrub_cursor % n + 1) % n
            base_tag = getattr(self, "_scrub_pool_tags", {}).get(base)
            for backend in self.pools.values():
                if not backend._pool_match(base_tag):
                    continue  # another co-hosted pool's object
                acting = backend.acting_set(base)
                primary = None
                for sh in range(backend.km):
                    if backend._shard_up(acting, sh):
                        primary = f"osd.{acting[sh]}"
                        break
                if primary != self.name:
                    continue
                scanned += 1
                slices.setdefault(id(backend), (backend, []))[1].append(
                    base)
                break
        # phase 2 -- batched scrub + repair per backend
        for backend, oids in slices.values():
            try:
                reports = await backend.deep_scrub_many(oids)
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 -- scrub must not kill
                # the tick (e.g. a degraded object mid-recovery)
                self.perf.inc("scrub_failed")
                continue
            for base in oids:
                report = reports.get(base)
                if report is None or report["ok"]:
                    continue
                try:
                    repaired += await backend.scrub_repair(base, report)
                except asyncio.CancelledError:
                    raise
                except Exception:  # noqa: BLE001 -- a failed repair
                    # stays in scrub_errors; the next slice retries
                    self.perf.inc("scrub_failed")
        return repaired

    async def tier_tick(self) -> int:
        """Cache-tier agent slice (peer of scrub_tick; the reference's
        agent_work runs on the same background cadence): flush abandoned
        dirty entries, promote hot objects this OSD leads in one batched
        device transfer, evict back under osd_tier_hbm_bytes.  No-op
        until some hosted pool's cache mode is writeback/readproxy.
        Returns objects promoted (the tick's action count)."""
        if not any(
            getattr(b, "tier_mode", "none") != "none"
            for b in self.pools.values()
        ):
            return 0
        if self.tier_agent is None:
            from ceph_tpu.tier.agent import TierAgent

            self.tier_agent = TierAgent(self)
        try:
            stats = await self.tier_agent.tick()
        except asyncio.CancelledError:
            raise
        except Exception:  # noqa: BLE001 -- a failed agent round must
            # not kill the tick loop; the next tick retries
            self.perf.inc("tier_agent_failed")
            return 0
        return int(stats.get("promoted", 0))

    def mgr_report_stats(self) -> dict:
        """The MgrReport payload for this daemon (mgr/report.py schema).

        Everything here is O(counters): store totals are maintained
        incrementally by the object stores, per-PG degraded/misplaced
        counts by the pg_stats seams -- building a report NEVER walks
        the object store (the regression tests/test_telemetry.py pins).
        """
        from ceph_tpu.mgr.report import (REPORT_SCHEMA_VERSION,
                                         filter_counters)
        from ceph_tpu.utils.perf import histogram_marginals

        tier = self.tier.status()
        stats = {
            "v": REPORT_SCHEMA_VERSION,
            "kind": "osd",
            "boot_id": self.boot_id,
            "store": dict(self.store.stats()),
            "perf": filter_counters(self.perf.snapshot()),
            "pgs": {
                pool: backend.pg_stats.pg_stat()
                for pool, backend in self.pools.items()
            },
            "ops_in_flight": self.optracker.num_inflight(),
            # scalar tier residency only (the full per-object listing
            # stays an admin-socket affair)
            "tier": {key: tier[key] for key in
                     ("resident_bytes", "budget", "entries", "dirty",
                      "hit", "miss")},
            "hist": histogram_marginals(f"osd.{self.osd_id}."),
        }
        try:
            # residency-ledger deltas ride along; co-located daemons
            # share one process ledger (documented in
            # docs/observability.md), so the mgr labels but does not
            # sum these across daemons of one process
            from ceph_tpu.analysis import residency

            stats["residency"] = dict(residency.counters().snapshot())
        except Exception:  # noqa: BLE001 -- reports must never fail
            pass
        try:
            # wire-tax profiler slice (ceph_tpu/profiling/): per-stage
            # ns + loop/GC scalars; None (omitted) when profile_mode is
            # off.  Same one-ledger-per-process caveat as residency.
            from ceph_tpu import profiling

            prof_slice = profiling.report_slice()
            if prof_slice is not None:
                stats["profile"] = prof_slice
        except Exception:  # noqa: BLE001 -- reports must never fail
            pass
        return stats

    def _op_cost(self, msg) -> int:
        if isinstance(msg, ECSubWrite):
            return max(
                1,
                sum(len(op.data) for op in msg.transaction.ops) // 4096,
            )
        return 1

    async def dispatch(self, src: str, msg) -> None:
        if self.frozen:
            return
        if msg == "ping":
            # fast dispatch: heartbeats never sit behind the op queue
            await self.messenger.send_message(self.name, src, ("pong", self.name))
            return
        if isinstance(msg, tuple) and msg and msg[0] == "pong":
            # peer heartbeat answer (the mon-integrated daemon's
            # heartbeat loop reads these timestamps)
            self.hb_pongs[msg[1]] = asyncio.get_event_loop().time()
            return
        if isinstance(msg, (ECSubWriteReply, ECSubReadReply)):
            # this OSD is acting as a primary: forward sub-op replies to
            # the hosted engines (shared tid space -> exactly one matches)
            for backend in self.pools.values():
                await backend.dispatch(src, msg)
            return
        if isinstance(msg, dict) and "op" in msg:
            op = msg["op"]
            if op == "client_op":
                # RADOS PG backoff: while the pool is peering after a
                # liveness event, answer with an explicit backoff frame
                # instead of queueing -- the client parks the op and
                # resends on our backoff_release, rather than burning
                # probe slices against a PG mid-role-handoff (reference
                # src/osd/PrimaryLogPG.cc maybe_add_backoff).  The
                # dispatch-throttle budget is never claimed here, so the
                # transport's own release path returns it.
                pool = msg.get("pool") or ""
                if pool not in self.pools and self.pools:
                    pool = next(iter(self.pools))
                if self.pg_states.get(pool) == "peering":
                    self._backoffs.setdefault(pool, set()).add(src)
                    self.perf.inc("backoff_sent")
                    await self.messenger.send_message(self.name, src, {
                        "op": "backoff", "tid": msg.get("tid"),
                        "pool": pool, "from": self.name,
                    })
                    return
                # a client op lands in the QoS queue like any other work
                # (reference: ms_fast_dispatch -> enqueue_op, OSD.cc:6439)
                claim = msg.pop("_budget_claim", None)
                if claim is not None:
                    # keep the messenger's dispatch-throttle budget held
                    # until the op EXECUTES (released in _run_client_op)
                    # so queued bytes stay under the daemon's cap
                    claim()
                cost = max(1, len(msg.get("data") or b"") // 4096)
                # queue-entry stamp only (no allocation): the TrackedOp
                # and its span are minted at dequeue BACKDATED to this
                # stamp, so queue wait is attributed per op without a
                # tracker object per queued message
                msg["_queued_mono"] = time.monotonic()
                if self.op_queue_type == "mclock":
                    # client sub-class (gold/bulk/... from the op's
                    # qos_class field) when the profile names it;
                    # plain "client" otherwise
                    klass = msg.get("qos_class") or "client"
                    if klass not in self.opq.classes:
                        klass = "client"
                    self.opq.enqueue(klass, cost, (src, msg))
                else:
                    self.opq.enqueue(
                        OP_PRIORITY["client"], cost, (src, msg)
                    )
                self._client_ops_queued += 1
                msg["_client_gauge"] = True
                self.perf.inc("queued_client_op")
                self._op_event.set()
                return
            if op.endswith("_reply"):
                # meta-plane replies for a hosted primary engine
                for backend in self.pools.values():
                    await backend.dispatch(src, msg)
                return
            await self._handle_meta_op(src, msg)
            return
        if isinstance(msg, dict):
            # monitor traffic (command replies, osdmap broadcasts): a
            # mon-integrated daemon wires its MonClient handler here
            hook = getattr(self, "mon_hook", None)
            if hook is not None:
                await hook(src, msg)
            return
        if isinstance(msg, (ECSubWrite, ECSubRead)):
            klass = getattr(msg, "op_class", "client")
            # a client sub-op carrying its originating op's QoS
            # sub-class queues under THAT class (end-to-end tags: the
            # replica hop honors the same reservation/weight/limit
            # triple as the primary's admission); unknown classes ride
            # the base op_class
            qcls = getattr(msg, "qos_class", None)
            cost = self._op_cost(msg)
            # queue-entry stamp (see the client-op path above)
            msg._queued_mono = time.monotonic()
            if self.op_queue_type == "mclock":
                if qcls is not None and klass == "client" and \
                        qcls in self.opq.classes:
                    klass = qcls
                self.opq.enqueue(klass, cost, (src, msg))
            else:
                self.opq.enqueue(OP_PRIORITY.get(klass, 63), cost, (src, msg))
            self.perf.inc(f"queued_{klass}")
            self._op_event.set()

    async def _handle_meta_op(self, src: str, msg: dict) -> None:
        """Metadata-plane ops served fast-dispatch (single-threaded, so
        compare-and-swap is atomic without extra locking):

        * ``omap_cas`` -- the atomicity primitive cls_lock-style classes
          need: this OSD (the object's primary-shard holder) is the CAS
          authority (the reference runs cls methods on the primary OSD,
          src/osd/ClassHandler.cc; our primary engine is client-side, so
          atomic read-modify-write is delegated here).
        * ``watch`` / ``unwatch`` / ``notify`` -- watch/notify semantics
          (reference src/osd/Watch.cc): watchers register here; notify
          fans an event to every watcher and gathers acks.
        * ``meta_get`` -- omap + xattrs + meta version for the replicated
          metadata object.
        """
        op = msg["op"]
        oid = msg.get("oid", "")
        soid = f"{oid}@meta"
        if op == "pg_log_info":
            # O(1) peering poll: log head/tail only.  A primary whose
            # watermark is current skips this OSD entirely (reference
            # GetInfo, src/osd/PG.cc peering).  "nonempty" distinguishes a
            # brand-new OSD from one RESTARTED on a persistent store whose
            # in-memory log is empty but whose holdings need a backfill
            # comparison (memoized once true; a stale true only costs an
            # extra backfill).
            if not getattr(self, "_store_nonempty", False):
                self._store_nonempty = bool(self.store.list_objects())
            self.perf.inc("pg_log_info_serve")
            await self.messenger.send_message(self.name, src, {
                "op": "pg_log_info_reply", "tid": msg["tid"],
                "from": self.name,
                "head_seq": self.pglog.head_seq,
                "tail_seq": self.pglog.tail_seq,
                "dup_head": self.pglog.dup_head_seq,
                "nonempty": self._store_nonempty,
                # incarnation nonce: pre-boot-id peers just .get() None
                "boot_id": self.boot_id,
            })
            return
        if op == "pg_dups":
            # peering dup exchange: reqid dup entries above the
            # requester's per-peer watermark (bounded by
            # osd_pg_log_dups_tracked, so worst case is one small full
            # sweep per new primary)
            ents = [
                (d.seq, list(d.reqid), d.result, d.oid,
                 list(d.version) if d.version is not None else None)
                for d in self.pglog.dups_after(int(msg.get("from_seq", 0)))
            ]
            self.perf.inc("pg_dups_serve")
            await self.messenger.send_message(self.name, src, {
                "op": "pg_dups_reply", "tid": msg["tid"],
                "from": self.name, "dups": ents,
                "head": self.pglog.dup_head_seq,
            })
            return
        if op == "dup_record":
            # a primary pushing a completed composite op's result
            # (exec/snap_trim) into our log before it replies to the
            # client -- the awaited leg of the exactly-once protocol
            self.pglog.record_dup(
                tuple(msg["reqid"]), msg.get("result"),
                oid=msg.get("oid", ""),
            )
            self.perf.inc("dup_record")
            await self.messenger.send_message(self.name, src, {
                "op": "dup_record_reply", "tid": msg["tid"],
                "from": self.name, "ok": True,
            })
            return
        if op == "pg_log_entries":
            # delta peering: entries above the requester's watermark
            # (reference GetLog / missing-set computation).  complete=False
            # means the log was trimmed past the gap -> backfill.
            from_seq = int(msg.get("from_seq", 0))
            complete = self.pglog.covers(from_seq)
            ents = []
            if complete:
                for e in self.pglog.entries_after(from_seq):
                    base, _, tag = e.oid.rpartition("@")
                    ents.append((e.seq, base, tag, tuple(e.obj_version)))
            self.perf.inc("pg_log_entries_serve")
            await self.messenger.send_message(self.name, src, {
                "op": "pg_log_entries_reply", "tid": msg["tid"],
                "from": self.name, "complete": complete,
                "head_seq": self.pglog.head_seq, "entries": ents,
            })
            return
        if op == "pg_rollback":
            # divergent-entry rollback: undo this shard's torn entries
            # locally from the log instead of re-pushing the whole shard
            # (reference PGLog rollback via EC transaction rollback info,
            # src/osd/ECTransaction.cc:97).
            target_soid = msg["soid"]
            to_version = vt(tuple(msg["to_version"]))
            ok = self.pglog.rollback_object_to(
                target_soid, to_version, self.store
            )
            # a rolled-back shard invalidates any resident copy of its
            # base object (the device block was built pre-rollback)
            base = target_soid.rpartition("@")[0] or target_soid
            self.tier.invalidate_oid(base)
            if ok:
                try:
                    self.store.stat(target_soid)
                    self._applied_version[target_soid] = to_version
                except FileNotFoundError:
                    self._applied_version.pop(target_soid, None)
                self.perf.inc("pglog_rollback")
            await self.messenger.send_message(self.name, src, {
                "op": "pg_rollback_reply", "tid": msg["tid"],
                "from": self.name, "ok": ok,
            })
            return
        if op == "obj_versions":
            # targeted peering probe: versions for NAMED objects only
            # (per-object GetInfo; the clean-path replacement for the
            # pg_list full scan).
            out = {}
            for base in msg.get("oids", []):
                shards = {}
                pool_tag = None
                for s in range(msg.get("km", 0)):
                    so = shard_oid(base, s)
                    try:
                        self.store.stat(so)
                    except FileNotFoundError:
                        continue
                    # string key: the wire encoder (utils/encoding
                    # value()) rejects int dict keys, so an int here
                    # crashed every delta-peering probe REPLY on the
                    # real-TCP path (in-process delivery hid it); the
                    # consumer int()s the key either way
                    shards[str(s)] = tuple(
                        vt(self.store.getattr(so, VERSION_KEY)))
                    if pool_tag is None:
                        pool_tag = self.store.getattr(so, POOL_KEY)
                mv = None
                try:
                    self.store.stat(f"{base}@meta")
                    mv = self.store.getattr(f"{base}@meta", "_meta_version") or 0
                    if pool_tag is None:
                        pool_tag = self.store.getattr(
                            f"{base}@meta", POOL_KEY)
                except FileNotFoundError:
                    pass
                out[base] = {"shards": shards, "meta": mv,
                             "pool": pool_tag}
            self.perf.inc("obj_versions_serve")
            await self.messenger.send_message(self.name, src, {
                "op": "obj_versions_reply", "tid": msg["tid"],
                "from": self.name, "objects": out,
            })
            return
        if op == "pg_list":
            self.perf.inc("pg_list_serve")
            # peering scan: report every shard object this OSD holds with
            # its version stamp (the role of the peering Query/log+missing
            # exchange, reference src/osd/PG.cc GetInfo/GetLog).  Shard
            # entries are (oid, shard, (counter, writer)); meta replicas
            # report shard -1 with their meta version.
            objects = []
            for stored in self.store.list_objects():
                base, _, tag = stored.rpartition("@")
                if not base:
                    continue
                if tag == "meta":
                    mv = self.store.getattr(stored, "_meta_version") or 0
                    objects.append((base, -1, (mv, ""),
                                    self.store.getattr(stored, POOL_KEY)))
                else:
                    try:
                        shard = int(tag)
                    except ValueError:
                        continue
                    ver = vt(self.store.getattr(stored, VERSION_KEY))
                    objects.append((base, shard, tuple(ver),
                                    self.store.getattr(stored, POOL_KEY)))
            await self.messenger.send_message(self.name, src, {
                "op": "pg_list_reply", "tid": msg["tid"],
                "from": self.name, "objects": objects,
            })
        elif op == "meta_get":
            try:
                omap = self.store.omap_get(soid)
                ver = self.store.getattr(soid, "_meta_version") or 0
                removed = bool(self.store.getattr(soid, "_meta_removed"))
            except FileNotFoundError:
                omap, ver, removed = None, 0, False
            await self.messenger.send_message(self.name, src, {
                "op": "meta_get_reply", "tid": msg["tid"],
                "omap": omap, "version": ver, "removed": removed,
                "from": self.name,
            })
        elif op == "meta_apply":
            # replicated metadata write: the message carries the FULL
            # resulting omap, not a delta, so a replica that missed any
            # number of earlier versions (it was down) converges to the
            # complete state in one application -- a delta under a
            # version-gap gate would either be rejected forever or stamp
            # a newer version over incomplete contents
            ver = msg["version"]
            if msg.get("reqid") is not None:
                # exactly-once: the originating client op's dup entry
                # lands with the replicated state (recorded even when
                # the version gate below refuses a stale re-apply --
                # the op itself DID happen cluster-wide).  dup_result
                # carries the client-visible outcome where one exists
                # (a replicated CAS); plain omap writes answer None.
                # version stays None: meta versions live on their own
                # sequence and must never be pruned by a CHUNK-plane
                # rollback of the same base oid.
                self.pglog.record_dup(
                    tuple(msg["reqid"]), msg.get("dup_result"), oid=oid,
                )
            try:
                cur = self.store.getattr(soid, "_meta_version") or 0
            except FileNotFoundError:
                cur = 0
            if msg.get("remove"):
                # object removal leaves a VERSIONED TOMBSTONE (cleared
                # omap + removed flag), not a bare delete: a replica
                # that missed the remove holds the old keys at a lower
                # version, and highest-version-wins recovery must
                # propagate the removal, never resurrect the keys.
                # Written even when no twin exists here: the removal
                # record must survive somewhere, or a down replica's
                # stale keys would be the only (hence winning) state
                # when it revives.
                if ver >= cur:
                    self.pglog.append(soid, "remove", (ver, ""),
                                      rollbackable=False)
                    self.pglog.maybe_trim()
                    txn = (
                        Transaction()
                        .omap_clear(soid)
                        .setattr(soid, "_meta_version", ver)
                        .setattr(soid, "_meta_removed", True)
                    )
                    if msg.get("pool") is not None:
                        txn.setattr(soid, POOL_KEY, msg["pool"])
                    self.store.queue_transaction(txn)
                await self.messenger.send_message(self.name, src, {
                    "op": "meta_apply_reply", "tid": msg["tid"],
                    "from": self.name, "applied": ver >= cur,
                })
                return
            if ver >= cur:
                txn = (
                    Transaction()
                    .omap_clear(soid)
                    .omap_setkeys(soid, msg["omap"])
                    .setattr(soid, "_meta_version", ver)
                    .setattr(soid, "_meta_removed", False)
                )
                if msg.get("pool") is not None:
                    txn.setattr(soid, POOL_KEY, msg["pool"])
                # log the apply so delta peering discovers meta staleness
                # the same way it does chunk staleness (full-state omap
                # replication is not log-rollbackable; peering re-applies
                # the newest replica instead)
                self.pglog.append(
                    soid, "write", (ver, ""), rollbackable=False,
                )
                self.pglog.maybe_trim()
                self.store.queue_transaction(txn)
            await self.messenger.send_message(self.name, src, {
                "op": "meta_apply_reply", "tid": msg["tid"],
                "from": self.name, "applied": ver >= cur,
            })
        elif op == "omap_cas":
            key, expect, new = msg["key"], msg["expect"], msg["new"]
            try:
                omap = self.store.omap_get(soid)
            except FileNotFoundError:
                omap = {}
            reqid = msg.get("reqid")
            if reqid is not None:
                hit = self.pglog.lookup_dup(reqid)
                if hit is not None and hit.result is not None:
                    # replayed CAS: the compare already ran and (maybe)
                    # swapped -- re-comparing against post-apply state
                    # would report a false failure.  Answer the original
                    # outcome; the current full state rides along for
                    # the caller's replication fan-out as usual.
                    self.perf.inc("dup_op_hit")
                    ver = (self.store.getattr(soid, "_meta_version") or 0
                           if self.store.exists(soid) else 0)
                    await self.messenger.send_message(self.name, src, {
                        "op": "omap_cas_reply", "tid": msg["tid"],
                        "success": hit.result[0],
                        "current": hit.result[1],
                        "version": ver, "omap": omap,
                    })
                    return
            # The PR-5 exactly-once invariant, machine-enforced: the
            # compare, the dup record, the swap and the transaction
            # queue are ONE indivisible step (the "zero-width
            # dup-detection window").  An await slipped inside lets a
            # replayed CAS re-run the compare against post-apply state
            # (false failure) or apply twice before the dup lands.
            # cephlint: atomic-section omap-cas-dup-with-apply
            cur = omap.get(key)
            success = cur == expect
            ver = (self.store.getattr(soid, "_meta_version") or 0
                   if self.store.exists(soid) else 0)
            if reqid is not None:
                # recorded with the compare itself; the result is
                # final whether or not the swap applied
                self.pglog.record_dup(reqid, [success, cur], oid=oid)
            if success:
                ver += 1
                if new is None:
                    omap.pop(key, None)
                else:
                    omap[key] = new
                txn = (
                    Transaction()
                    .omap_clear(soid)
                    .omap_setkeys(soid, omap)
                    .setattr(soid, "_meta_version", ver)
                )
                if msg.get("pool") is not None:
                    txn.setattr(soid, POOL_KEY, msg["pool"])
                self.store.queue_transaction(txn)
            # cephlint: end-atomic-section
            await self.messenger.send_message(self.name, src, {
                "op": "omap_cas_reply", "tid": msg["tid"],
                "success": success, "current": cur, "version": ver,
                # full state for replication fan-out by the caller
                "omap": omap,
            })
        elif op == "watch":
            self.watches.setdefault(oid, {})[msg["watcher"]] = True
            await self.messenger.send_message(self.name, src, {
                "op": "watch_reply", "tid": msg["tid"], "ok": True,
            })
        elif op == "unwatch":
            self.watches.get(oid, {}).pop(msg["watcher"], None)
            await self.messenger.send_message(self.name, src, {
                "op": "watch_reply", "tid": msg["tid"], "ok": True,
            })
        elif op == "notify":
            self._notify_seq += 1
            notify_id = self._notify_seq
            watchers = list(self.watches.get(oid, {}))
            if not watchers:
                await self.messenger.send_message(self.name, src, {
                    "op": "notify_reply", "tid": msg["tid"],
                    "acks": [], "timeouts": [],
                })
                return
            pending = set(watchers)
            acked: list = []
            fut = asyncio.get_event_loop().create_future()
            self._notify_pending[notify_id] = (pending, acked, fut)
            for w in watchers:
                await self.messenger.send_message(self.name, w, {
                    "op": "notify_event", "oid": oid,
                    "payload": msg.get("payload"),
                    "notify_id": notify_id, "notifier": self.name,
                })

            async def gather_acks(tid=msg["tid"]):
                # runs as its own task: the dispatch loop must stay free
                # to deliver the very notify_acks being awaited here
                try:
                    await asyncio.wait_for(
                        fut, timeout=msg.get("timeout", 5.0)
                    )
                except asyncio.TimeoutError:
                    pass
                self._notify_pending.pop(notify_id, None)
                await self.messenger.send_message(self.name, src, {
                    "op": "notify_reply", "tid": tid,
                    "acks": list(acked), "timeouts": sorted(pending),
                })

            self.messenger.adopt_task(
                f"{self.name}.notify{notify_id}",
                asyncio.get_event_loop().create_task(gather_acks()),
            )
        elif op == "notify_ack":
            state = self._notify_pending.get(msg["notify_id"])
            if state is not None:
                pending, acked, fut = state
                if msg["watcher"] in pending:
                    pending.discard(msg["watcher"])
                    acked.append(msg["watcher"])
                if not pending and not fut.done():
                    fut.set_result(True)

    async def _op_worker(self) -> None:
        """Dequeue-and-execute loop (the osd_op_tp worker thread role)."""
        while True:
            await self._op_event.wait()
            self._op_event.clear()
            while True:
                if self.op_queue_type == "mclock":
                    item = self.opq.dequeue()
                    if item is None:
                        # next_ready-based idle wakeup: sleep until the
                        # earliest queued tag comes due OR a new arrival
                        # (whose reservation may be eligible right away)
                        # -- the queue's OWN injected clock times both
                        # sides, so no mixed-domain drift can strand a
                        # tag (the polling fallback is gone)
                        delay = self.opq.idle_for()
                        if delay is None:
                            break
                        try:
                            await asyncio.wait_for(
                                self._op_event.wait(), timeout=delay,
                            )
                            self._op_event.clear()
                        except asyncio.TimeoutError:
                            pass
                        continue
                else:
                    if self.opq.empty():
                        break
                    item = self.opq.dequeue()
                # a daemon frozen or marked down after enqueue must not
                # execute (a "hung" OSD mutating its store would defeat
                # the fault model the flag simulates)
                if self.frozen or self.messenger.is_down(self.name):
                    # a dropped op must still return its claimed
                    # dispatch-throttle budget or repeated freeze cycles
                    # would shrink the messenger's byte cap forever
                    dropped = item[1]
                    if isinstance(dropped, dict):
                        release = dropped.pop("_budget_release", None)
                        if release is not None:
                            release()
                        if dropped.pop("_client_gauge", None):
                            self._client_ops_queued -= 1
                    continue
                src, msg = item
                singles = [(src, msg)]
                if (self._batch_exec and isinstance(msg, dict)
                        and msg.get("op") == "client_op"
                        and not self.client_caps):
                    # batched fast path: the decoded burst's client ops
                    # are all buffered in the queue already (the
                    # dispatch loop drains a corked burst before this
                    # worker wakes), so the RUN gathered here is real.
                    # Entities with registered caps keep the per-op
                    # path (op_capable stays per-op audited).
                    batch, spill = self._gather_client_run(src, msg)
                    if len(batch) > 1:
                        # ONE task for the whole batch (vs one per op):
                        # the gathered backend calls still land in the
                        # same event-loop tick, so the codec coalescer
                        # sees the identical fan-in
                        self._cop_seq += 1
                        self.messenger.adopt_task(
                            f"{self.name}.cob{self._cop_seq}",
                            asyncio.get_event_loop().create_task(
                                self._run_client_op_batch(batch)),
                        )
                        singles = []
                    else:
                        singles = batch
                    if spill is not None:
                        singles.append(spill)
                for one_src, one_msg in singles:
                    try:
                        await self._execute_op(one_src, one_msg)
                    except asyncio.CancelledError:
                        raise
                    except Exception:  # noqa: BLE001 — op failure must
                        # not kill the worker; log and keep serving (the
                        # reference logs and drops misbehaving ops too)
                        import sys
                        import traceback

                        traceback.print_exc(file=sys.stderr)

    def _gather_client_run(self, src: str, msg: dict):
        """Drain the client-op RUN already buffered behind ``msg`` (up
        to ``osd_op_batch_max``).  Sync -- no awaits, so no state
        (frozen / mark_down / caps) can change mid-gather.  Returns
        ``(batch, spill)``: the first non-client item dequeued ends the
        run and is handed back for ordinary execution."""
        batch = [(src, msg)]
        spill = None
        while len(batch) < self._batch_max:
            if self.op_queue_type == "mclock":
                nxt = self.opq.dequeue()
            else:
                nxt = None if self.opq.empty() else self.opq.dequeue()
            if nxt is None:
                break
            nmsg = nxt[1]
            if isinstance(nmsg, dict) and nmsg.get("op") == "client_op":
                batch.append(nxt)
            else:
                spill = nxt
                break
        return batch, spill

    async def _run_client_op_batch(self, items) -> None:
        """Array-batched client-op execution (osd_op_batch_exec, the
        round-22 post-codec fast path): the per-op bookkeeping the
        wire-tax profiler ranked as the residual wall -- optracker
        stamping, reqid/dup lookups, QoS slot admission, perf/hitset
        accounting, reply sends -- runs as BATCH passes over the run
        instead of per-op dict walks:

        * one tracked request + one trace span for the batch (queue-wait
          attribution stays per op);
        * the dups registry is scanned in ONE pass over the batch's
          reqids; hits answer with the original result exactly like the
          per-op path (exactly-once unchanged);
        * QoS execution slots are claimed once per (class) group with
          the SUMMED byte cost -- the coalescer's admission discipline;
        * the backend calls run CONCURRENTLY (gather), so the codec
          coalescer gathers the same one-tick fan-in as per-op tasks;
        * counters, the latency grid, hit sets and budget releases fold
          into one array pass; replies go out as one corked burst.

        Semantics are the per-op path's exactly: dup answers, typed
        error replies, composite-kind dup fan-out, apply-window kills
        (a fired kill marks this daemon down and suppresses the batch's
        replies -- the client resends and is answered from the dups
        registry)."""
        t_exec = time.monotonic()
        n = len(items)
        with _PS_BATCH:
            qats = [m.pop("_queued_mono", None) for _, m in items]
            t0 = min((q for q in qats if q is not None), default=t_exec)
            op = self.optracker.create_request(
                f"client_op_batch(n={n})",
                span=trace.join(None, "osd:client_op_batch", t0=t0),
                t0=t0,
            )
            sizes = [len(m.get("data") or b"") for _, m in items]
            self.h_queue_wait.inc_pairs([
                ((t_exec - (qat if qat is not None else t_exec)) * 1e6, sz)
                for qat, sz in zip(qats, sizes)])
            op.mark_event("dequeued")
            replies = [{"op": "client_reply", "tid": m["tid"]}
                       for _, m in items]
            default_pool = next(iter(self.pools)) if self.pools else None
            backends = []
            for _, m in items:
                b = self.pools.get(m.get("pool") or "")
                if b is None and default_pool is not None:
                    b = self.pools[default_pool]
                backends.append(b)
            kinds = [m.get("kind", "") for _, m in items]
            reqids = [m.get("reqid") for _, m in items]
            dedupable = [r is not None and k in MUTATING_KINDS
                         for r, k in zip(reqids, kinds)]
            # one-pass batch dup scan (the per-op path pays a lookup per
            # op; here the registry dict is touched once per batch row)
            hits = self.pglog.lookup_dups_batch(
                [reqids[i] if dedupable[i] else None for i in range(n)])
            run = []
            dup_hits = 0
            for i in range(n):
                if backends[i] is None:
                    replies[i].update(
                        ok=False, etype="IOError",
                        error=f"{self.name} hosts no pool")
                elif hits[i] is not None:
                    replies[i].update(ok=True, result=hits[i].result)
                    dup_hits += 1
                else:
                    run.append(i)
            if dup_hits:
                self.perf.inc("dup_op_hit", dup_hits)
            groups: Dict[str, list] = {}
            for i in run:
                klass = items[i][1].get("qos_class") or "client"
                if self.qos_ops is None or \
                        klass not in self.qos_ops.classes:
                    klass = "client"
                groups.setdefault(klass, []).append(i)
        op.mark_event("started")

        async def _exec_one(i):
            reply = replies[i]
            try:
                reply.update(
                    ok=True, result=await backends[i].client_op(items[i][1]))
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 -- every failure
                # travels back to the client as a typed error
                reply.update(ok=False, etype=type(e).__name__, error=str(e))

        async def _exec_group(klass, idxs):
            # amortized admission: ONE slot claim per class with the
            # summed cost (per-op pays a tag + slot round trip each)
            cost = sum(max(4096, sizes[i]) for i in idxs)
            if self.qos_ops is not None and klass in self.qos_ops.classes:
                guard = self.qos_ops.slot(klass, cost)
            else:
                guard = self._cop_sem
            async with guard:
                await asyncio.gather(*(_exec_one(i) for i in idxs))

        try:
            with trace.use_span(op.span):
                if groups:
                    await asyncio.gather(*(
                        _exec_group(k, idxs) for k, idxs in groups.items()))
                for i in run:
                    if not (dedupable[i] and replies[i].get("ok")):
                        continue
                    m = items[i][1]
                    if kinds[i] in _RESULT_FANOUT_KINDS:
                        # composite kinds keep the awaited acting-set
                        # dup fan-out (result only exists at completion)
                        await self._record_op_dup(
                            backends[i], m, replies[i].get("result"))
                    else:
                        self.pglog.record_dup(
                            reqids[i], replies[i].get("result"),
                            oid=m.get("oid", ""))
            op.mark_event("replied")
        finally:
            dur_us = (time.monotonic() - t_exec) * 1e6
            with _PS_BATCH:
                n_ok = wr = rd = 0
                oids = []
                for (_, m), reply, size in zip(items, replies, sizes):
                    if reply.get("ok"):
                        n_ok += 1
                        wr += size
                        result = reply.get("result")
                        if isinstance(result, (bytes, bytearray)):
                            rd += len(result)
                    if m.get("oid"):
                        oids.append(m["oid"])
                    release = m.pop("_budget_release", None)
                    if release is not None:
                        release()
                    if m.pop("_client_gauge", None):
                        self._client_ops_queued -= 1
                # the latency grids take the whole run in one locked
                # pass each; the hit set rolls once for the run
                self.op_hist.inc_many(dur_us, sizes)
                self.h_dispatch.inc_many(dur_us, sizes)
                if oids:
                    self.hitsets.record_many(oids)
                if n_ok:
                    self.perf.inc("client_ops", n_ok)
                if wr:
                    self.perf.inc("client_wr_bytes", wr)
                if rd:
                    self.perf.inc("client_rd_bytes", rd)
            op.finish()
        fault = getattr(self.messenger, "fault", None)
        if fault is not None:
            for i in range(n):
                if (replies[i].get("ok") and dedupable[i]
                        and fault.kill_after_apply_fire(kinds[i])):
                    # injected dup-detection window: the whole batch
                    # applied (dup entries recorded above) but this
                    # primary dies before its reply burst -- the client
                    # resends and is answered from a surviving PG log
                    self.messenger.mark_down(self.name)
                    return
        if self.frozen or self.messenger.is_down(self.name):
            return
        await self.messenger.send_messages(
            self.name, [(items[i][0], replies[i]) for i in range(n)])

    async def _execute_op(self, src: str, msg) -> None:
        if isinstance(msg, dict):
            # client op: runs as its own task -- it awaits sub-ops that
            # this very worker loop must stay free to execute (the
            # reference gets the same effect from multiple osd_op_tp
            # threads; concurrency is bounded by _cop_sem)
            self._cop_seq += 1
            task = asyncio.get_event_loop().create_task(
                self._run_client_op(src, msg)
            )
            self.messenger.adopt_task(f"{self.name}.cop{self._cop_seq}", task)
            return
        kind = "sub_write" if isinstance(msg, ECSubWrite) else "sub_read"
        t_exec = time.monotonic()
        cost_bytes = self._op_cost(msg) * 4096
        qat = getattr(msg, "_queued_mono", None)
        # the span joins the originating op's trace (trailing wire
        # field) so the cross-daemon timeline stitches client ->
        # primary -> sub-op; the op backdates to queue entry
        op = self.optracker.create_request(
            f"{kind}(tid={msg.tid} oid={next(iter(msg.to_read), '?') if isinstance(msg, ECSubRead) else msg.oid} shard={msg.from_shard})",
            span=trace.join(getattr(msg, "trace", None),
                            f"{self.name}:{kind}", t0=qat),
            t0=qat,
        )
        # queue wait = enqueue stamp -> here
        self.h_queue_wait.inc(
            (t_exec - (qat if qat is not None else t_exec)) * 1e6,
            cost_bytes)
        op.mark_event("dequeued")
        try:
            with trace.use_span(op.span):
                if isinstance(msg, ECSubWrite):
                    await self.handle_sub_write(src, msg)
                else:
                    await self.handle_sub_read(src, msg)
            op.mark_event("replied")
        finally:
            self.h_dispatch.inc(
                (time.monotonic() - t_exec) * 1e6, cost_bytes)
            op.finish()

    async def _run_client_op(self, src: str, msg: dict) -> None:
        """Execute one client op on the hosted primary engine and reply.

        Reference: the osd_op_tp worker calling PrimaryLogPG::do_request
        -> do_op -> execute_ctx, with the MOSDOpReply back to the client
        (src/osd/OSD.cc:9072, src/osd/PrimaryLogPG.cc:1649)."""
        t_exec = time.monotonic()
        qat = msg.pop("_queued_mono", None)
        with _PS_OP:
            # the op backdates to its queue-entry stamp; its span (when
            # the client's trace context rode the op) starts there too,
            # so the timeline's first segment is the true queue wait
            op = self.optracker.create_request(
                f"client_op({msg.get('kind')} oid={msg.get('oid')} "
                f"from={src})",
                span=trace.join(msg.get("trace"), f"osd:{msg.get('kind')}",
                                t0=qat),
                t0=qat,
            )
            self.h_queue_wait.inc(
                (t_exec - (qat if qat is not None else t_exec)) * 1e6,
                len(msg.get("data") or b""))
            op.mark_event("dequeued")
            reply = {"op": "client_reply", "tid": msg["tid"]}
        try:
            # the op span is task-current for the whole execution: the
            # engine's fan-outs stamp it onto sub-ops and the coalescer
            # links its batch fan-in span to it
            with trace.use_span(op.span):
                await self._run_client_op_inner(src, msg, op, reply)
        finally:
            with _PS_OP:
                self.h_dispatch.inc(
                    (time.monotonic() - t_exec) * 1e6,
                    len(msg.get("data") or b""))
                release = msg.pop("_budget_release", None)
                if release is not None:
                    release()  # claimed dispatch-throttle budget
                if msg.pop("_client_gauge", None):
                    self._client_ops_queued -= 1
                op.finish()

    async def _run_client_op_inner(self, src: str, msg: dict, op,
                                   reply: dict) -> None:
        # execution-slot admission: under unified QoS the op claims its
        # slot in dmClock tag order for its client class (the op's
        # qos_class field, plain "client" otherwise) with cost = payload
        # bytes (4 KiB floor for metadata ops) -- freed slots go to the
        # class the tags elect, not to semaphore-FIFO order.  Fallback:
        # the legacy _cop_sem (osd_qos_unified=false).
        klass = msg.get("qos_class") or "client"
        if self.qos_ops is not None and \
                klass not in self.qos_ops.classes:
            klass = "client"  # unknown sub-class rides the base class
        if self.qos_ops is not None and klass in self.qos_ops.classes:
            guard = self.qos_ops.slot(
                klass, max(4096, len(msg.get("data") or b"")),
            )
        else:
            guard = self._cop_sem
        async with guard:
            # the sync bookkeeping head is a declared wire-tax cost
            # center (osd.op_exec): what the batched fast path amortizes
            with _PS_OP:
                op.mark_event("started")
                pool_name = msg.get("pool") or ""
                backend = self.pools.get(pool_name)
                if backend is None and self.pools:
                    # fall back to the hosted pool -- and make the cap
                    # check below use the pool the op will actually RUN
                    # on, never the requested name (a grant on an
                    # unhosted name must not leak onto the hosted pool)
                    pool_name = next(iter(self.pools))
                    backend = self.pools[pool_name]
                cap = self.client_caps.get(src.split("[")[0])
                if cap is not None and backend is not None:
                    # OSDCap enforcement (PrimaryLogPG
                    # op_has_sufficient_caps): an entity with registered
                    # caps is confined to them; unregistered entities
                    # keep the open-cluster default (client.admin
                    # allow *)
                    from ceph_tpu.auth.caps import op_capable

                    if not op_capable(cap, pool_name, msg.get("oid", ""),
                                      msg.get("kind", "")):
                        reply.update(
                            ok=False, etype="PermissionError",
                            error=f"{src} caps do not permit "
                                  f"{msg.get('kind')} on {msg.get('oid')}",
                        )
                        backend = None
                        self.perf.inc("cap_denied")
                kind = msg.get("kind", "")
                reqid = msg.get("reqid")
                dedupable = reqid is not None and kind in MUTATING_KINDS
                execute = False
                if backend is None and "etype" not in reply:
                    reply.update(
                        ok=False, etype="IOError",
                        error=f"{self.name} hosts no pool",
                    )
                elif backend is not None and dedupable and (
                    (hit := self.pglog.lookup_dup(reqid)) is not None
                ):
                    # replay of an op this PG already applied (the
                    # client resent after a failover): answer with the
                    # ORIGINAL result from the log instead of
                    # re-executing -- the exactly-once guarantee
                    # (reference: PrimaryLogPG::do_op eversion/reqid
                    # check via pg_log_dup_t, src/osd/osd_types.h)
                    reply.update(ok=True, result=hit.result)
                    self.perf.inc("dup_op_hit")
                elif backend is not None:
                    execute = True
            if execute:
                try:
                    reply.update(ok=True, result=await backend.client_op(msg))
                except asyncio.CancelledError:
                    raise
                except Exception as e:  # noqa: BLE001 -- every failure
                    # travels back to the client as a typed error
                    reply.update(
                        ok=False, etype=type(e).__name__, error=str(e)
                    )
                if dedupable and reply.get("ok"):
                    await self._record_op_dup(
                        backend, msg, reply.get("result"))
            op.mark_event("replied")
        op.finish()
        with _PS_OP:
            self.op_hist.inc(op.duration * 1e6,
                             len(msg.get("data") or b""))
            if reply.get("ok"):
                # rate-engine feed (mgr/pgmap.py): consecutive MgrReport
                # deltas of these become the `ceph -s` io block (client
                # ops/s + throughput, distinct from recovery_bytes)
                self.perf.inc("client_ops")
                wr = len(msg.get("data") or b"")
                if wr:
                    self.perf.inc("client_wr_bytes", wr)
                result = reply.get("result")
                if isinstance(result, (bytes, bytearray)):
                    self.perf.inc("client_rd_bytes", len(result))
            if msg.get("oid"):
                self.hitsets.record(msg["oid"])
        fault = getattr(self.messenger, "fault", None)
        if (
            fault is not None and reply.get("ok") and dedupable
            and fault.kill_after_apply_fire(kind)
        ):
            # injected dup-detection window: the op applied (and its
            # dup entries reached the acting set above) but this
            # primary dies before the reply frame -- the client must
            # resend and be answered from a surviving PG log
            self.messenger.mark_down(self.name)
            return
        if self.frozen or self.messenger.is_down(self.name):
            return
        await self.messenger.send_message(self.name, src, reply)

    async def _record_op_dup(self, backend, msg: dict, result) -> None:
        """Persist a completed client op's reqid + result as a PG-log
        dup entry on this primary, and -- for composite kinds whose
        result only exists at completion (exec, snap_trim) -- push it to
        the rest of the acting set with an AWAITED ``dup_record``
        fan-out before the client reply can go out.  Single-fan-out
        kinds already recorded their dups on the mutating sub-ops
        themselves (see pg.REQID_FANOUT_KINDS), so they pay no extra
        round trip here."""
        reqid = msg.get("reqid")
        oid = msg.get("oid", "")
        # None-result upgrade: the fan-out-recorded entry learns the
        # final client-visible result (exec's (ret, out), snap_trim's
        # dropped-clone count, omap_cas's (success, current))
        self.pglog.record_dup(reqid, result, oid=oid)
        if msg.get("kind") not in _RESULT_FANOUT_KINDS:
            return
        try:
            acting = backend.acting_set(oid)
        except Exception:  # noqa: BLE001 -- placement failure: the
            # local record above still covers the common replay path
            return
        targets = [
            f"osd.{acting[s]}"
            for s in range(backend.km)
            if backend._shard_up(acting, s)
            and f"osd.{acting[s]}" != self.name
        ]
        if not targets:
            return
        await backend._meta_roundtrip(targets, {
            "op": "dup_record", "reqid": list(reqid),
            "result": result, "oid": oid,
        }, timeout=3.0)

    async def handle_sub_write(self, src: str, msg: ECSubWrite) -> None:
        """reference ECBackend::handle_sub_write (:922): log the operation,
        then apply the transaction (log_operation + queue_transactions)."""
        if any(op.op == "write_ref" for op in msg.transaction.ops):
            # mesh-delivered payload (osd_mesh_data_plane): the chunk
            # bytes rode the device plane and the frame carried board
            # references -- claim them back (crc-checked) before the
            # version gate sees the transaction.  A failed claim
            # (evicted / foreign reference) refuses the sub-write:
            # no ack, no apply; peering recovery repairs the shard.
            from ceph_tpu.parallel import mesh_plane as mesh_mod

            plane = mesh_mod.current_plane()
            if plane is None or \
                    not plane.resolve_transaction(msg.transaction):
                self.perf.inc("mesh_claim_miss")
                await self.messenger.send_message(
                    self.name, src, ECSubWriteReply(
                        from_shard=msg.from_shard, tid=msg.tid,
                        committed=False, applied=False,
                    ))
                return
        soid = shard_oid(msg.oid, msg.from_shard)
        new_vt = vt(msg.at_version)
        cur_vt = self._applied_version.get(soid)
        if cur_vt is None:
            # fresh process (daemon restart): the applied version lives in
            # the object's xattr, not just this map — the gate must
            # survive restarts on persistent stores
            try:
                cur_vt = vt(self.store.getattr(soid, VERSION_KEY))
            except FileNotFoundError:
                cur_vt = vt(None)
        if (
            msg.prev_version is not None
            and cur_vt[0] != vt(msg.prev_version)[0]
            and new_vt >= cur_vt
        ):
            # incremental (RMW extent) write, but this shard is not on the
            # base version it was computed against: it missed history
            # (down/revived hollow).  Applying just the extent would stamp
            # the new version over mostly-stale bytes.  Skip; the shard
            # stays behind until peering recovers it (pg_missing_t role).
            self.perf.inc("sub_write_missed_base")
            await self.messenger.send_message(self.name, src, ECSubWriteReply(
                from_shard=msg.from_shard, tid=msg.tid,
                committed=False, applied=False, missed=True,
            ))
            return
        if msg.rollback and msg.op_class == "recovery":
            # peering proved this shard's newer copy a torn write (held by
            # < k shards): the primary rolls it back to the authoritative
            # version, bypassing the stale gate (divergent-entry rollback)
            self.perf.inc("sub_write_rollback")
        elif new_vt < cur_vt:
            # dequeued behind a newer write to the same object (priority
            # reordering or a racing primary).  Applying would clobber
            # newer bytes with stale ones.
            self.perf.inc("sub_write_stale")
            if msg.op_class == "client":
                # a racing client write lost: refuse loudly so the writer
                # retries at a higher version instead of believing a
                # commit that never applied (split-brain fix)
                reply = ECSubWriteReply(
                    from_shard=msg.from_shard, tid=msg.tid,
                    committed=False, applied=False,
                    current_version=cur_vt,
                )
            else:
                # a recovery/scrub push made obsolete by a newer client
                # write is genuinely done: the shard holds newer data
                reply = ECSubWriteReply(
                    from_shard=msg.from_shard, tid=msg.tid,
                    committed=True, applied=False,
                )
            await self.messenger.send_message(self.name, src, reply)
            return
        # From the version stamp to queue_transaction is ONE indivisible
        # apply step: the stale gate above was evaluated against
        # _applied_version, and a task switch before the transaction
        # lands would let a racing sub-write interleave between gate
        # and apply (clobbering newer bytes) or observe the version
        # advanced with the dup entry/log append missing.
        # cephlint: atomic-section sub-write-apply
        self._applied_version[soid] = new_vt
        # device-tier coherence: an applied sub-write proves any resident
        # copy stale UNLESS it belongs to this very write (the primary's
        # own write-through put carries the same version and survives;
        # a racing primary's write carries a different one and evicts).
        # A same-versioned RECOVERY push is a refresh, not a mutation:
        # the shard is being rebuilt toward the version the resident
        # copy already holds, so the copy stays valid AND in-flight
        # promotions of the rebuilt object must not be dropped (the
        # rebuilt-object-goes-cold bug: the unconditional invalidate
        # notified the agent's watchers even when the entry survived)
        if not (msg.op_class in ("recovery", "scrub")
                and self.tier.recovery_refresh(msg.oid, new_vt)):
            self.tier.invalidate_oid(msg.oid, keep_version=new_vt)
        # log_operation before queue_transactions (reference order,
        # ECBackend.cc:922): snapshot the pre-apply state so a torn write
        # can be rolled back locally (divergent-entry rollback) and give
        # the entry this OSD's monotonic sequence for delta peering.
        try:
            prior = self.store.stat(soid)
            existed = True
        except FileNotFoundError:
            prior = 0
            existed = False
        prior_attrs: Dict[str, object] = {}
        rollbackable = True
        for top in msg.transaction.ops:
            if top.op == "setattr" and top.oid == soid:
                prior_attrs[top.attr_name] = (
                    self.store.getattr(soid, top.attr_name) if existed
                    else None
                )
            elif existed and top.op == "write" and top.offset < prior:
                rollbackable = False  # overwrites prior bytes: needs push
            elif existed and top.op == "truncate" and top.offset < prior:
                rollbackable = False
            elif top.op in ("remove", "omap_set", "omap_rm", "omap_clear"):
                rollbackable = False
        self.pglog.append(
            soid, "write", new_vt,
            existed=existed, prior_size=prior,
            prior_attrs=prior_attrs or None, rollbackable=rollbackable,
        )
        if msg.reqid is not None and msg.op_class == "client":
            # exactly-once: the dup entry lands in the SAME step as the
            # mutation, so there is no window in which this shard holds
            # the write but could not detect its replay (the reference
            # writes pg_log_dup_t with the log entry).  Result None is
            # exact for every reqid-carrying fan-out kind; composite
            # ops upgrade it via dup_record (see _record_op_dup).
            self.pglog.record_dup(msg.reqid, None, oid=msg.oid,
                                  version=new_vt)
        self.pglog.maybe_trim()
        self.store.queue_transaction(msg.transaction)
        # cephlint: end-atomic-section
        self.perf.inc("sub_write")
        reply = ECSubWriteReply(
            from_shard=msg.from_shard, tid=msg.tid, committed=True, applied=True
        )
        await self.messenger.send_message(self.name, src, reply)

    def _serve_regen_helpers(
        self, msg: ECSubRead, regen: Dict[str, list],
        reply: ECSubReadReply,
    ) -> None:
        """Regenerating-code repair lane (plugins/regen.py): for each
        ``regen`` oid, dot our stored shard's alpha sub-chunks with the
        wire-carried phi_f coefficients and reply the beta-sized helper
        symbol instead of raw extents -- d helpers of chunk/alpha bytes
        replace k whole-chunk reads at the primary.  All oids of the
        message sharing a coefficient signature fuse into ONE batched
        GF(2^8) matmul dispatch."""
        import numpy as np

        from ceph_tpu.plugins import regen as regen_mod

        groups: Dict[tuple, list] = {}
        for oid, coeffs in regen.items():
            soid = shard_oid(oid, msg.from_shard)
            try:
                data = self.store.read(soid)
                # same integrity gate as the extent path: a full-shard
                # helper computed from silently-corrupt bytes would
                # poison the regenerated shard undetectably
                hinfo_d = self.store.getattr(soid, ecutil.HINFO_KEY)
            except FileNotFoundError:
                reply.errors[oid] = -2  # ENOENT
                continue
            if hinfo_d is not None:
                hinfo = ecutil.HashInfo.from_dict(hinfo_d)
                if (hinfo.has_chunk_hash()
                        and len(data) == hinfo.get_total_chunk_size()
                        and crc32c(data) != hinfo.get_chunk_hash(
                            msg.from_shard)):
                    self.perf.inc("read_crc_error")
                    reply.errors[oid] = -5  # EIO
                    continue
            key = (tuple(int(c) for c in coeffs), len(data))
            groups.setdefault(key, []).append(
                (oid, np.frombuffer(data, dtype=np.uint8)))
        for (coeffs, _nbytes), members in groups.items():
            try:
                helpers = regen_mod.compute_helpers(
                    coeffs, [arr for _, arr in members],
                    slot_name=self.name)
            except ValueError:
                for oid, _ in members:
                    reply.errors[oid] = -22  # EINVAL: shard/coeff shape
                continue
            for (oid, _), h in zip(members, helpers):
                reply.buffers_read[oid] = [(0, h.tobytes())]
            self.perf.inc("regen_helpers_served", len(members))

    async def handle_sub_read(self, src: str, msg: ECSubRead) -> None:
        """reference ECBackend::handle_sub_read (:987): serve extents and
        crc-verify full-shard reads against HashInfo."""
        reply = ECSubReadReply(from_shard=msg.from_shard, tid=msg.tid)
        regen = msg.regen if isinstance(msg.regen, dict) else {}
        if regen:
            self._serve_regen_helpers(msg, regen, reply)
        for oid, extents in msg.to_read.items():
            if oid in regen:
                continue  # served as a helper symbol, never raw extents
            soid = shard_oid(oid, msg.from_shard)
            try:
                bufs = []
                for off, length in extents:
                    data = self.store.read(soid, off, length)
                    bufs.append((off, data))
                # full-shard read -> verify cumulative crc (ECBackend.cc:1054)
                hinfo_d = self.store.getattr(soid, ecutil.HINFO_KEY)
                if hinfo_d is not None:
                    hinfo = ecutil.HashInfo.from_dict(hinfo_d)
                    # overwrites clear chunk hashes (ec_overwrites mode):
                    # only crc-check shards that still track them
                    if hinfo.has_chunk_hash():
                        full = self.store.read(soid)
                        if len(full) == hinfo.get_total_chunk_size():
                            if crc32c(full) != hinfo.get_chunk_hash(
                                msg.from_shard
                            ):
                                self.perf.inc("read_crc_error")
                                reply.errors[oid] = -5  # EIO
                                continue
                reply.buffers_read[oid] = bufs
            except FileNotFoundError:
                reply.errors[oid] = -2  # ENOENT
        for oid in msg.attrs_to_read:
            soid = shard_oid(oid, msg.from_shard)
            try:
                reply.attrs_read[oid] = {
                    ecutil.HINFO_KEY: self.store.getattr(soid, ecutil.HINFO_KEY),
                    SIZE_KEY: self.store.getattr(soid, SIZE_KEY),
                    VERSION_KEY: self.store.getattr(soid, VERSION_KEY),
                    SNAPSET_KEY: self.store.getattr(soid, SNAPSET_KEY),
                    WHITEOUT_KEY: self.store.getattr(soid, WHITEOUT_KEY),
                    POOL_KEY: self.store.getattr(soid, POOL_KEY),
                }
            except FileNotFoundError:
                pass
        self.perf.inc("sub_read")
        await self.messenger.send_message(self.name, src, reply)
