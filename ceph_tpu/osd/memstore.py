"""In-memory ObjectStore (MemStore equivalent).

Reference: src/os/memstore/MemStore.cc -- the in-RAM ObjectStore used by
unit tests; transactions apply atomically (reference ObjectStore semantics:
a queued transaction either fully commits or not at all).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

from ceph_tpu.osd.types import Transaction


class MemObject:
    def __init__(self):
        self.data = bytearray()
        self.xattrs: Dict[str, object] = {}
        self.omap: Dict[str, bytes] = {}


class MemStore:
    def __init__(self):
        self._objects: Dict[str, MemObject] = {}
        self._lock = threading.Lock()
        # incremental usage totals (the ObjectStore statfs role):
        # maintained at the transaction swap so stats() is O(1) -- the
        # mgr report loop reads it every interval and MUST NOT pay
        # O(objects) per report (tests/test_telemetry.py pins this)
        self._n_shards = 0
        self._n_metas = 0
        self._bytes = 0

    # -- transactions ------------------------------------------------------

    def queue_transaction(self, txn: Transaction) -> None:
        """Apply atomically (all ops under one lock, staged then swapped)."""
        with self._lock:
            staged: Dict[str, Optional[MemObject]] = {}

            def obj_for(oid: str) -> MemObject:
                if oid not in staged:
                    existing = self._objects.get(oid)
                    clone = MemObject()
                    if existing is not None:
                        clone.data = bytearray(existing.data)
                        clone.xattrs = dict(existing.xattrs)
                        clone.omap = dict(existing.omap)
                    staged[oid] = clone
                return staged[oid]  # type: ignore[return-value]

            for op in txn.ops:
                if op.op == "write":
                    o = obj_for(op.oid)
                    end = op.offset + len(op.data)
                    if len(o.data) < end:
                        o.data.extend(b"\0" * (end - len(o.data)))
                    o.data[op.offset : end] = op.data
                elif op.op == "setattr":
                    obj_for(op.oid).xattrs[op.attr_name] = op.attr_value
                elif op.op == "truncate":
                    o = obj_for(op.oid)
                    if op.offset < len(o.data):
                        del o.data[op.offset :]
                    else:
                        o.data.extend(b"\0" * (op.offset - len(o.data)))
                elif op.op == "clone":
                    src = staged[op.oid] if op.oid in staged \
                        else self._objects.get(op.oid)
                    if src is None:
                        raise FileNotFoundError(op.oid)
                    dst = MemObject()
                    dst.data = bytearray(src.data)
                    dst.xattrs = dict(src.xattrs)
                    staged[op.attr_name] = dst
                elif op.op == "remove":
                    staged[op.oid] = None
                elif op.op == "omap_set":
                    obj_for(op.oid).omap.update(op.attr_value)
                elif op.op == "omap_rm":
                    o = obj_for(op.oid)
                    for k in op.attr_value:
                        o.omap.pop(k, None)
                elif op.op == "omap_clear":
                    obj_for(op.oid).omap.clear()
                else:
                    raise ValueError(f"unknown op {op.op}")
            for oid, obj in staged.items():
                prior = self._objects.get(oid)
                is_meta = oid.endswith("@meta")
                if prior is not None:
                    self._bytes -= len(prior.data)
                    if is_meta:
                        self._n_metas -= 1
                    else:
                        self._n_shards -= 1
                if obj is None:
                    self._objects.pop(oid, None)
                else:
                    self._objects[oid] = obj
                    self._bytes += len(obj.data)
                    if is_meta:
                        self._n_metas += 1
                    else:
                        self._n_shards += 1

    # -- reads -------------------------------------------------------------

    def read(self, oid: str, offset: int = 0, length: int = -1) -> bytes:
        with self._lock:
            obj = self._objects.get(oid)
            if obj is None:
                raise FileNotFoundError(oid)
            if length < 0:
                return bytes(obj.data[offset:])
            return bytes(obj.data[offset : offset + length])

    def getattr(self, oid: str, name: str):
        with self._lock:
            obj = self._objects.get(oid)
            if obj is None:
                raise FileNotFoundError(oid)
            return obj.xattrs.get(name)

    def omap_get(self, oid: str, keys: Optional[List[str]] = None
                 ) -> Dict[str, bytes]:
        with self._lock:
            obj = self._objects.get(oid)
            if obj is None:
                raise FileNotFoundError(oid)
            if keys is None:
                return dict(obj.omap)
            return {k: obj.omap[k] for k in keys if k in obj.omap}

    def stat(self, oid: str) -> int:
        with self._lock:
            obj = self._objects.get(oid)
            if obj is None:
                raise FileNotFoundError(oid)
            return len(obj.data)

    def exists(self, oid: str) -> bool:
        with self._lock:
            return oid in self._objects

    def list_objects(self) -> List[str]:
        with self._lock:
            return sorted(self._objects.keys())

    def stats(self) -> Dict[str, int]:
        """O(1) usage totals (statfs role): stored names split into
        data/parity shard objects ("oid@N") and replicated meta twins
        ("oid@meta"), plus total data bytes."""
        with self._lock:
            return {
                "objects": self._n_shards + self._n_metas,
                "shards": self._n_shards,
                "metas": self._n_metas,
                "bytes": self._bytes,
            }

    # test hook: corrupt a byte (scrub/EIO-path tests)
    def corrupt(self, oid: str, offset: int) -> None:
        with self._lock:
            self._objects[oid].data[offset] ^= 0xFF
