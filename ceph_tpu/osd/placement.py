"""Object -> PG -> acting-set placement via CRUSH.

Reference: the OSD maps hobject_t -> pg (ceph_str_hash + pg_num mask,
src/osd/osd_types.h raw_pg_to_pg) and pg -> up/acting osds via
OSDMap::pg_to_up_acting_osds -> crush->do_rule with the pool's rule in
'indep' mode for EC pools (src/osd/OSDMap.cc:_pg_to_raw_osds).  Devices
marked *out* get weight 0 and are remapped; *down* devices keep their
acting position (degraded) until marked out — the same up/acting split the
reference has.  Unmappable indep positions come back as ``None`` (the
CRUSH_ITEM_NONE hole): the pg stays usable as long as >= k positions map.
"""

from __future__ import annotations

import hashlib
from typing import Dict, List, Optional, Sequence, Tuple

from ceph_tpu.crush import CrushMap, Tunables, build_flat_map, build_hierarchy, do_rule
from ceph_tpu.crush.hash import crush_hash32
from ceph_tpu.crush.map import ITEM_NONE, erasure_rule, weight_fp


def fallback_acting(oid: str, n_osds: int, km: int) -> List[int]:
    """CRUSH-lite: deterministic permutation seeded by the object name.
    Used when no CrushPlacement is attached (unit-test clusters)."""
    if n_osds < km:
        raise RuntimeError("not enough OSDs for the acting set")
    seed = int.from_bytes(
        hashlib.blake2b(oid.encode(), digest_size=8).digest(), "big"
    )
    order = sorted(
        range(n_osds), key=lambda i: (seed * (i + 1)) % (2**61 - 1)
    )
    return order[:km]


class CrushPlacement:
    """CRUSH-backed acting-set computation for an EC pool."""

    def __init__(
        self,
        n_osds: int,
        km: int,
        pg_num: int = 128,
        hosts: Optional[Sequence[Sequence[int]]] = None,
    ):
        if hosts is not None:
            all_osds = sorted(o for h in hosts for o in h)
            if all_osds != list(range(n_osds)):
                raise ValueError(
                    f"hosts layout covers osds {all_osds}, "
                    f"expected exactly 0..{n_osds - 1}"
                )
            self.map, root = build_hierarchy(hosts)
            domain = 2  # host
        else:
            self.map, root = build_flat_map(n_osds)
            domain = 0
        self._root = root
        self._domain = domain
        self.ruleno = self.map.add_rule(
            erasure_rule(root, failure_domain_type=domain)
        )
        self.km = km
        self.pg_num = pg_num
        self.weights = [0x10000] * n_osds
        self.tunables = Tunables()
        self.epoch = 1  # bumped on every weight/map mutation
        # pg -> acting, valid for the current epoch only (the reference
        # equivalent is OSDMapMapping's precomputed pg->osds cache).
        self._cache: Dict[int, List[Optional[int]]] = {}
        self._cache_epoch = self.epoch
        # oid -> pg is pure hashing, independent of the epoch; the data
        # path asks for the same object's acting set dozens of times per
        # op (_shard_up loops), so the hash must not re-run each time.
        # Bounded: cleared wholesale when it grows past ~64k names.
        self._pg_cache: Dict[str, int] = {}

    def pg_of(self, oid: str) -> int:
        pg = self._pg_cache.get(oid)
        if pg is None:
            if len(self._pg_cache) >= (1 << 16):
                self._pg_cache.clear()
            h = crush_hash32(
                int.from_bytes(
                    hashlib.blake2b(oid.encode(),
                                    digest_size=4).digest(), "big"
                )
            )
            pg = self._pg_cache[oid] = int(h) % self.pg_num
        return pg

    def acting_for_pg(self, pg: int) -> List[Optional[int]]:
        """km entries; ``None`` marks an unmappable position (hole).
        Raises only when fewer positions map than the caller can ever
        decode from is *not* known here — callers enforce k/min_size."""
        if self._cache_epoch != self.epoch:
            self._cache.clear()
            self._cache_epoch = self.epoch
        cached = self._cache.get(pg)
        if cached is not None:
            return cached
        out = do_rule(
            self.map, self.ruleno, pg, self.km, self.weights, self.tunables
        )
        acting: List[Optional[int]] = [
            None if v == ITEM_NONE else v for v in out
        ]
        acting += [None] * (self.km - len(acting))
        self._cache[pg] = acting
        return acting

    def acting(self, oid: str) -> List[Optional[int]]:
        return self.acting_for_pg(self.pg_of(oid))

    # -- osdmap mutations --------------------------------------------------

    def mark_out(self, osd_id: int) -> None:
        self.weights[osd_id] = 0
        self.epoch += 1

    def mark_in(self, osd_id: int, weight: float = 1.0) -> None:
        self.weights[osd_id] = weight_fp(weight)
        self.epoch += 1

    def reweight(self, osd_id: int, weight: float) -> None:
        self.weights[osd_id] = weight_fp(weight)
        self.epoch += 1

    # -- elastic membership (online osd add/rm) ----------------------------

    @property
    def n_osds(self) -> int:
        return len(self.weights)

    def ensure_osd(self, osd_id: int, weight_fp16: int = 0) -> bool:
        """Grow the placement so ``osd_id`` is a known device, initially
        at the given 16.16 weight (default 0 = out, so a growth driven
        by a map broadcast only moves data once the weight lands too).
        Idempotent; returns True when the crush map actually grew.

        Flat maps get the device appended to the root straw2 bucket;
        hierarchies get a fresh single-osd host bucket (the smallest
        failure-domain-preserving expansion).  straw2 makes either
        growth minimal-movement by construction: only PGs whose draw
        now favours the new item move.
        """
        if osd_id < len(self.weights):
            return False
        # fill any id gap with weight-0 devices NOT in the crush tree --
        # do_rule treats ids past the weight vector as out, and a hole
        # id never wins a straw2 draw at weight 0.
        while len(self.weights) < osd_id:
            self.weights.append(0)
        self.weights.append(weight_fp16)
        root = self.map.buckets[self._root]
        if self._domain == 0:
            root.add_item(osd_id, 0x10000)
        else:
            hb = self.map.new_bucket(
                type=2, name=f"host-osd{osd_id}"
            )
            hb.add_item(osd_id, 0x10000)
            root.add_item(hb.id, hb.weight)
        self.map.note_device(osd_id)
        self.epoch += 1
        return True

    def add_osd(self, osd_id: int, weight: float = 1.0) -> None:
        """Grow the map AND bring the osd in, in one epoch step."""
        if not self.ensure_osd(osd_id, weight_fp(weight)):
            self.weights[osd_id] = weight_fp(weight)
            self.epoch += 1

    def remove_osd(self, osd_id: int) -> None:
        """Contract: weight drops to 0 so CRUSH remaps away from the
        device (straw2 touches only the PGs that mapped there).  The
        crush bucket entry stays -- a departed id never wins a draw at
        weight 0, and keeping the tree append-only keeps every other
        PG's draw (and hence the movement set) untouched."""
        if osd_id < len(self.weights):
            self.weights[osd_id] = 0
            self.epoch += 1

    # -- movement accounting (expansion/contraction planning) --------------

    def pg_actings(self) -> Dict[int, List[Optional[int]]]:
        """Full pg -> acting snapshot at the current epoch (O(pg_num);
        the expansion planner diffs two of these to find the minimal
        movement set)."""
        return {pg: list(self.acting_for_pg(pg)) for pg in range(self.pg_num)}


def movement_plan(
    before: Dict[int, List[Optional[int]]],
    after: Dict[int, List[Optional[int]]],
) -> List[Tuple[int, int, Optional[int], Optional[int]]]:
    """Diff two pg->acting snapshots into the minimal movement set:
    one (pg, position, src_osd, dst_osd) entry per acting-set slot
    whose holder changed.  Unchanged positions never appear -- only
    moved shards migrate."""
    plan: List[Tuple[int, int, Optional[int], Optional[int]]] = []
    for pg, old in before.items():
        new = after.get(pg, old)
        for pos, (src, dst) in enumerate(zip(old, new)):
            if src != dst:
                plan.append((pg, pos, src, dst))
    return plan


def theoretical_min_moved(
    weights_before: Sequence[int],
    weights_after: Sequence[int],
    total_positions: int,
) -> float:
    """Lower bound on acting-set positions that MUST move for the
    weight change: every osd whose capacity share grew must end up
    holding its new share, so at least sum(max(0, share_after -
    share_before)) of all positions migrate.  A perfectly minimal
    placement (straw2's design goal) moves exactly this."""
    tb = float(sum(weights_before)) or 1.0
    ta = float(sum(weights_after)) or 1.0
    gained = 0.0
    n = max(len(weights_before), len(weights_after))
    for i in range(n):
        wb = weights_before[i] if i < len(weights_before) else 0
        wa = weights_after[i] if i < len(weights_after) else 0
        gained += max(0.0, wa / ta - wb / tb)
    return gained * total_positions
