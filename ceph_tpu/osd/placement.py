"""Object -> PG -> acting-set placement via CRUSH.

Reference: the OSD maps hobject_t -> pg (ceph_str_hash + pg_num mask,
src/osd/osd_types.h raw_pg_to_pg) and pg -> up/acting osds via
OSDMap::pg_to_up_acting_osds -> crush->do_rule with the pool's rule in
'indep' mode for EC pools (src/osd/OSDMap.cc:_pg_to_raw_osds).  Devices
marked *out* get weight 0 and are remapped; *down* devices keep their
acting position (degraded) until marked out — the same up/acting split the
reference has.  Unmappable indep positions come back as ``None`` (the
CRUSH_ITEM_NONE hole): the pg stays usable as long as >= k positions map.
"""

from __future__ import annotations

import hashlib
from typing import Dict, List, Optional, Sequence, Tuple

from ceph_tpu.crush import CrushMap, Tunables, build_flat_map, build_hierarchy, do_rule
from ceph_tpu.crush.hash import crush_hash32
from ceph_tpu.crush.map import ITEM_NONE, erasure_rule, weight_fp


def fallback_acting(oid: str, n_osds: int, km: int) -> List[int]:
    """CRUSH-lite: deterministic permutation seeded by the object name.
    Used when no CrushPlacement is attached (unit-test clusters)."""
    if n_osds < km:
        raise RuntimeError("not enough OSDs for the acting set")
    seed = int.from_bytes(
        hashlib.blake2b(oid.encode(), digest_size=8).digest(), "big"
    )
    order = sorted(
        range(n_osds), key=lambda i: (seed * (i + 1)) % (2**61 - 1)
    )
    return order[:km]


class CrushPlacement:
    """CRUSH-backed acting-set computation for an EC pool."""

    def __init__(
        self,
        n_osds: int,
        km: int,
        pg_num: int = 128,
        hosts: Optional[Sequence[Sequence[int]]] = None,
    ):
        if hosts is not None:
            all_osds = sorted(o for h in hosts for o in h)
            if all_osds != list(range(n_osds)):
                raise ValueError(
                    f"hosts layout covers osds {all_osds}, "
                    f"expected exactly 0..{n_osds - 1}"
                )
            self.map, root = build_hierarchy(hosts)
            domain = 2  # host
        else:
            self.map, root = build_flat_map(n_osds)
            domain = 0
        self.ruleno = self.map.add_rule(
            erasure_rule(root, failure_domain_type=domain)
        )
        self.km = km
        self.pg_num = pg_num
        self.weights = [0x10000] * n_osds
        self.tunables = Tunables()
        self.epoch = 1  # bumped on every weight/map mutation
        # pg -> acting, valid for the current epoch only (the reference
        # equivalent is OSDMapMapping's precomputed pg->osds cache).
        self._cache: Dict[int, List[Optional[int]]] = {}
        self._cache_epoch = self.epoch
        # oid -> pg is pure hashing, independent of the epoch; the data
        # path asks for the same object's acting set dozens of times per
        # op (_shard_up loops), so the hash must not re-run each time.
        # Bounded: cleared wholesale when it grows past ~64k names.
        self._pg_cache: Dict[str, int] = {}

    def pg_of(self, oid: str) -> int:
        pg = self._pg_cache.get(oid)
        if pg is None:
            if len(self._pg_cache) >= (1 << 16):
                self._pg_cache.clear()
            h = crush_hash32(
                int.from_bytes(
                    hashlib.blake2b(oid.encode(),
                                    digest_size=4).digest(), "big"
                )
            )
            pg = self._pg_cache[oid] = int(h) % self.pg_num
        return pg

    def acting_for_pg(self, pg: int) -> List[Optional[int]]:
        """km entries; ``None`` marks an unmappable position (hole).
        Raises only when fewer positions map than the caller can ever
        decode from is *not* known here — callers enforce k/min_size."""
        if self._cache_epoch != self.epoch:
            self._cache.clear()
            self._cache_epoch = self.epoch
        cached = self._cache.get(pg)
        if cached is not None:
            return cached
        out = do_rule(
            self.map, self.ruleno, pg, self.km, self.weights, self.tunables
        )
        acting: List[Optional[int]] = [
            None if v == ITEM_NONE else v for v in out
        ]
        acting += [None] * (self.km - len(acting))
        self._cache[pg] = acting
        return acting

    def acting(self, oid: str) -> List[Optional[int]]:
        return self.acting_for_pg(self.pg_of(oid))

    # -- osdmap mutations --------------------------------------------------

    def mark_out(self, osd_id: int) -> None:
        self.weights[osd_id] = 0
        self.epoch += 1

    def mark_in(self, osd_id: int, weight: float = 1.0) -> None:
        self.weights[osd_id] = weight_fp(weight)
        self.epoch += 1

    def reweight(self, osd_id: int, weight: float) -> None:
        self.weights[osd_id] = weight_fp(weight)
        self.epoch += 1
