"""ceph_tpu: a TPU-native erasure-coding framework.

From-scratch implementation of the capabilities of Ceph's erasure-code
subsystem (reference: justincmoy/ceph 13.0.1, src/erasure-code/), redesigned
TPU-first: codec math is expressed as GF(2) / GF(2^w) matrix products that
run on the MXU via XLA and Pallas, with bit-exact CPU oracles and the
reference's plugin/benchmark/test surface around them.
"""

__version__ = "0.1.0"
