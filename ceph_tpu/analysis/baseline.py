"""Baseline file: accepted legacy findings + the inline-disable audit.

The baseline lets the tier-1 gate enforce "no NEW findings" without
requiring every legacy finding to be fixed in the PR that introduces a
rule.  Entries match on ``(rule, path, hash of the stripped source
line)`` rather than line numbers, so unrelated edits above a baselined
site don't invalidate it; each entry carries a count, so N accepted
instances of the same line text cover exactly N findings and the N+1st
is NEW.

Workflow:
  * ``tools/cephlint.py --write-baseline`` regenerates the file from the
    current findings (review the diff -- every added entry is a finding
    you are accepting instead of fixing);
  * the checked-in file also carries ``suppressions``: an audit listing
    of every inline ``# cephlint: disable`` in the tree, regenerated on
    every --write-baseline, so accepted escapes are reviewable in one
    place.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Dict, List, Tuple

from ceph_tpu.analysis.core import Finding

FORMAT_VERSION = 1


def _line_text(lines: List[str], lineno: int) -> str:
    if 1 <= lineno <= len(lines):
        return lines[lineno - 1].strip()
    return ""


def finding_key(f: Finding, lines: List[str]) -> Tuple[str, str, str]:
    digest = hashlib.sha1(
        _line_text(lines, f.line).encode("utf-8", "replace")
    ).hexdigest()[:12]
    return (f.rule, f.path, digest)


def load(path: str) -> Dict[Tuple[str, str, str], int]:
    """Baseline as key -> accepted count; {} when absent."""
    if not path or not os.path.exists(path):
        return {}
    with open(path) as fh:
        data = json.load(fh)
    out: Dict[Tuple[str, str, str], int] = {}
    for e in data.get("findings", []):
        key = (e["rule"], e["path"], e["line_hash"])
        out[key] = out.get(key, 0) + int(e.get("count", 1))
    return out


def write(path: str, findings: List[Finding],
          file_lines: Dict[str, List[str]],
          suppression_audit: List[dict]) -> None:
    counted: Dict[Tuple[str, str, str], int] = {}
    for f in findings:
        key = finding_key(f, file_lines.get(f.path, []))
        counted[key] = counted.get(key, 0) + 1
    entries = [
        {"rule": r, "path": p, "line_hash": h, "count": c}
        for (r, p, h), c in sorted(counted.items())
    ]
    with open(path, "w") as fh:
        json.dump(
            {
                "format_version": FORMAT_VERSION,
                "comment": "accepted legacy cephlint findings; regenerate "
                           "with tools/cephlint.py --write-baseline and "
                           "review the diff",
                "findings": entries,
                "suppressions": suppression_audit,
            },
            fh, indent=2, sort_keys=False,
        )
        fh.write("\n")


def split(findings: List[Finding],
          file_lines: Dict[str, List[str]],
          accepted: Dict[Tuple[str, str, str], int]
          ) -> Tuple[List[Finding], List[Finding]]:
    """(new, baselined) -- consumes ``accepted`` counts in order."""
    budget = dict(accepted)
    new: List[Finding] = []
    old: List[Finding] = []
    for f in findings:
        key = finding_key(f, file_lines.get(f.path, []))
        if budget.get(key, 0) > 0:
            budget[key] -= 1
            old.append(f)
        else:
            new.append(f)
    return new, old
