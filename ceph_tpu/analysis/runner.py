"""cephlint runner: collect files, run rules, apply suppressions and
the baseline, format results."""

from __future__ import annotations

import ast
import json
import os
import time
from typing import Dict, Iterable, List, Optional, Tuple

from ceph_tpu.analysis import baseline as baseline_mod
from ceph_tpu.analysis import suppress as suppress_mod
from ceph_tpu.analysis.core import (SEV_ERROR, FileContext, Finding, Rule,
                                    all_rules)

#: paths skipped by default: the lint fixtures are DELIBERATE findings
#: (each rule's positive examples) and would otherwise fail the gate
DEFAULT_EXCLUDES = ("tests/fixtures/lint",)

#: native-extension sources the ``native`` pack scans (everything else
#: runs the Python-AST packs)
NATIVE_EXTS = (".c", ".cpp", ".cc", ".h")


def _is_native(path: str) -> bool:
    return path.endswith(NATIVE_EXTS)


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def collect_files(paths: Iterable[str], root: Optional[str] = None,
                  excludes: Tuple[str, ...] = DEFAULT_EXCLUDES
                  ) -> List[str]:
    """Expand files/directories into a sorted list of repo-relative
    posix paths to .py and native (.c/.cpp) files."""
    root = root or repo_root()
    out = set()
    for p in paths:
        full = p if os.path.isabs(p) else os.path.join(root, p)
        if os.path.isfile(full):
            out.add(os.path.relpath(full, root))
        else:
            for dirpath, dirnames, filenames in os.walk(full):
                dirnames[:] = [d for d in dirnames
                               if d not in ("__pycache__", ".git")]
                for fn in filenames:
                    if fn.endswith(".py") or _is_native(fn):
                        out.add(os.path.relpath(
                            os.path.join(dirpath, fn), root))
    rel = sorted(p.replace(os.sep, "/") for p in out)
    return [p for p in rel
            if not any(p.startswith(e) for e in excludes)]


class ScanResult:
    def __init__(self):
        self.new: List[Finding] = []          # unsuppressed, not baselined
        self.suppressed: List[Finding] = []   # inline-disabled
        self.baselined: List[Finding] = []    # accepted legacy
        self.files_scanned = 0
        self.suppression_audit: List[dict] = []
        #: raw per-file lines (baseline hashing)
        self.file_lines: Dict[str, List[str]] = {}
        #: analysis wall time (bench.py's lint_runtime_secs metric)
        self.runtime_secs = 0.0
        #: names of the rules this scan ran (all, or a --rule subset)
        self.rules_run: List[str] = []

    @property
    def all_findings(self) -> List[Finding]:
        return self.new + self.suppressed + self.baselined

    def to_dict(self) -> dict:
        counts: Dict[str, int] = {}
        for f in self.new:
            counts[f.rule] = counts.get(f.rule, 0) + 1
        return {
            "lint_findings_total": len(self.new),
            "lint_findings_by_rule": dict(sorted(counts.items())),
            "lint_runtime_secs": round(self.runtime_secs, 3),
            "files_scanned": self.files_scanned,
            "suppressed": len(self.suppressed),
            "baselined": len(self.baselined),
            "rules_run": list(self.rules_run),
            # legacy spelling kept for older consumers of the JSON
            "counts_by_rule": dict(sorted(counts.items())),
            "findings": [f.to_dict() for f in self.new],
        }


def resolve_rules(names: Optional[Iterable[str]] = None) -> Dict[str, Rule]:
    """The rule set a scan runs: every registered rule, or the ``--rule``
    subset (unknown names raise with the valid spellings listed)."""
    registry = all_rules()
    if not names:
        return registry
    out: Dict[str, Rule] = {}
    for name in names:
        if name not in registry:
            known = ", ".join(sorted(registry))
            raise ValueError(f"unknown rule {name!r}; known rules: {known}")
        out[name] = registry[name]
    return out


def scan_file(path: str, source: str,
              rules: Optional[Dict[str, Rule]] = None) -> List[Finding]:
    """All raw findings for one file (no suppression/baseline yet).
    Native (.c/.cpp) sources run the ``native`` pack against the C
    model; Python sources run every other pack against the AST."""
    rule_set = rules if rules is not None else all_rules()
    findings: List[Finding] = []
    if _is_native(path):
        from ceph_tpu.analysis.rules_native import NativeFileContext

        nctx = NativeFileContext(path, source)
        for r in rule_set.values():
            if r.pack == "native":
                findings.extend(r.check(nctx))
        findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
        return findings
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        return [Finding("parse-error", path, e.lineno or 1, 0,
                        f"file does not parse: {e.msg}", SEV_ERROR)]
    ctx = FileContext(path, source, tree)
    for r in rule_set.values():
        if r.pack == "native":
            continue
        findings.extend(r.check(ctx))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def run_paths(paths: Iterable[str], root: Optional[str] = None,
              baseline_path: Optional[str] = None,
              excludes: Tuple[str, ...] = DEFAULT_EXCLUDES,
              rules: Optional[Iterable[str]] = None) -> ScanResult:
    root = root or repo_root()
    t0 = time.monotonic()
    rule_set = resolve_rules(rules)
    result = ScanResult()
    result.rules_run = sorted(rule_set)
    accepted = baseline_mod.load(baseline_path) if baseline_path else {}
    for rel in collect_files(paths, root, excludes):
        try:
            with open(os.path.join(root, rel), encoding="utf-8") as fh:
                source = fh.read()
        except OSError:
            continue
        result.files_scanned += 1
        result.file_lines[rel] = source.splitlines()
        raw = scan_file(rel, source, rule_set)
        result.suppression_audit.extend(suppress_mod.audit(rel, source))
        if not raw:
            continue
        sup = suppress_mod.parse_suppressions(source)
        live = []
        for f in raw:
            if suppress_mod.is_suppressed(sup, f.rule, f.line):
                result.suppressed.append(f)
            else:
                live.append(f)
        new, old = baseline_mod.split(live, result.file_lines, accepted)
        result.new.extend(new)
        result.baselined.extend(old)
    result.runtime_secs = time.monotonic() - t0
    return result


def changed_files(root: Optional[str] = None) -> List[str]:
    """Repo-relative .py and native .c/.cpp files differing from HEAD
    (staged, unstaged, and untracked) -- the ``--changed`` scan scope.
    Empty when git is unavailable (callers fall back to a full scan or
    a no-op)."""
    import subprocess

    root = root or repo_root()
    out: set = set()
    for args in (["git", "diff", "--name-only", "HEAD", "--"],
                 ["git", "ls-files", "--others", "--exclude-standard"]):
        try:
            proc = subprocess.run(args, cwd=root, capture_output=True,
                                  text=True, timeout=30)
        except (OSError, subprocess.TimeoutExpired):
            return []
        if proc.returncode != 0:
            return []
        for line in proc.stdout.splitlines():
            line = line.strip()
            if (line.endswith(".py") or _is_native(line)) and \
                    os.path.exists(os.path.join(root, line)):
                out.add(line.replace(os.sep, "/"))
    return sorted(out)


def to_sarif(result: ScanResult) -> dict:
    """SARIF 2.1.0 document for CI diff annotation (GitHub code
    scanning et al. ingest this directly).  Only NEW findings are
    results -- suppressed/baselined debt is the text/json surface's
    business, a diff annotator wants exactly what fails the gate."""
    registry = all_rules()
    used = sorted({f.rule for f in result.new})
    rules_meta = []
    for name in used:
        r = registry.get(name)
        rules_meta.append({
            "id": name,
            "shortDescription": {
                "text": (r.description if r is not None
                         else "cephlint finding")},
            "defaultConfiguration": {
                "level": ("error" if (r and r.severity == SEV_ERROR)
                          else "warning")},
        })
    results = []
    for f in result.new:
        results.append({
            "ruleId": f.rule,
            "level": "error" if f.severity == SEV_ERROR else "warning",
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path,
                                         "uriBaseId": "SRCROOT"},
                    "region": {"startLine": max(1, f.line),
                               "startColumn": f.col + 1},
                }
            }],
        })
    return {
        "$schema": ("https://raw.githubusercontent.com/oasis-tcs/"
                    "sarif-spec/master/Schemata/sarif-schema-2.1.0.json"),
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "cephlint",
                "informationUri": "docs/cephlint.md",
                "rules": rules_meta,
            }},
            "columnKind": "utf16CodeUnits",
            "results": results,
        }],
    }


def run(paths: Iterable[str], fmt: str = "text",
        baseline_path: Optional[str] = None,
        root: Optional[str] = None,
        excludes: Tuple[str, ...] = DEFAULT_EXCLUDES,
        rules: Optional[Iterable[str]] = None) -> Tuple[int, str]:
    """(exit_code, rendered_output); exit 0 iff no new findings."""
    result = run_paths(paths, root=root, baseline_path=baseline_path,
                       excludes=excludes, rules=rules)
    if fmt == "json":
        out = json.dumps(result.to_dict(), indent=2)
    elif fmt == "sarif":
        out = json.dumps(to_sarif(result), indent=2)
    else:
        lines = [f.format() for f in result.new]
        lines.append(
            f"cephlint: {len(result.new)} finding(s) in "
            f"{result.files_scanned} files "
            f"({len(result.suppressed)} inline-suppressed, "
            f"{len(result.baselined)} baselined, "
            f"{result.runtime_secs:.2f}s)"
        )
        out = "\n".join(lines)
    return (1 if result.new else 0), out
